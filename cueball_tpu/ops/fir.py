"""Batched EMA/low-pass FIR filtering.

The pool damps shrinkage with a 128-tap EMA FIR sampled at 5 Hz
(reference lib/pool.js:37-100; tc -0.2 -> pass band ~0.25 Hz, -10 dB at
0.5 Hz, -20 dB at 2.5 Hz). These are the [pools, taps] batched forms:

- :func:`fir_apply` — one filter output per pool from its current
  ring-buffer window (the FIRFilter.get() analogue), a [P,K]x[K] matvec
  that XLA maps straight onto the MXU.
- :func:`fir_smooth` — full filtered history for offline analysis.
- :func:`fir_apply_pallas` — the same matvec as a pallas TPU kernel
  (VMEM-blocked over pools; K=128 lands exactly on the lane width).
  A round-4 capture (archived as BENCH_TPU_r04.json) measured it at
  1.29x the XLA einsum on TPU v5 lite, but that artifact predates the
  code-hash guard and is NOT verified against the current measured
  path — bench.py refuses to cite it until tools/chip_bench.py
  re-captures with a hash. It remains the telemetry default on TPU
  (parallel/telemetry.py _default_fir) pending re-measurement;
  off-TPU it only runs interpreted and the einsum is the default.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def gen_taps(count: int = 128, tc: float = -0.2) -> jax.Array:
    """Normalized EMA taps (reference lib/pool.js:50-76). taps[0] weights
    the newest sample."""
    taps = jnp.exp(tc * jnp.arange(count, dtype=jnp.float32))
    return taps / jnp.sum(taps)


@jax.jit
def fir_apply(windows: jax.Array, taps: jax.Array) -> jax.Array:
    """Filter output for each pool.

    windows: [P, K] with windows[:, -1] the newest sample (ordered
    oldest->newest); taps: [K] with taps[0] the newest-sample weight.
    Returns [P].
    """
    return windows[:, ::-1] @ taps


@jax.jit
def fir_smooth(series: jax.Array, taps: jax.Array) -> jax.Array:
    """Causal filtered sequence for each pool: series [P, T] -> [P, T],
    zero-padded history at t<K."""
    k = taps.shape[0]
    padded = jnp.pad(series, ((0, 0), (k - 1, 0)))
    # Sliding windows: out[:, t] = sum_j taps[j] * series[:, t-j]
    windows = jax.vmap(
        lambda i: jax.lax.dynamic_slice_in_dim(padded, i, k, axis=1),
        out_axes=2)(jnp.arange(series.shape[1]))      # [P, K, T]
    return jnp.einsum('pkt,k->pt', windows[:, ::-1, :], taps)


def _fir_kernel(w_ref, t_ref, o_ref):
    # One block of pools: [B, K] x [K] -> [B, 1]
    o_ref[:, :] = jnp.dot(
        w_ref[:, :], t_ref[:, :].T,
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=('block',))
def fir_apply_pallas(windows: jax.Array, taps: jax.Array,
                     block: int = 256) -> jax.Array:
    """Pallas form of :func:`fir_apply`: grid over pool blocks, window
    block and taps resident in VMEM. Interpreted automatically on
    non-TPU backends."""
    from jax.experimental import pallas as pl

    p, k = windows.shape
    rev = windows[:, ::-1]
    pad = (-p) % block
    if pad:
        rev = jnp.pad(rev, ((0, pad), (0, 0)))
    pp = rev.shape[0]
    interpret = jax.default_backend() != 'tpu'

    out = pl.pallas_call(
        _fir_kernel,
        grid=(pp // block,),
        in_specs=[
            pl.BlockSpec((block, k), lambda i: (i, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((pp, 1), jnp.float32),
        interpret=interpret,
    )(rev, taps[None, :])
    return out[:p, 0]

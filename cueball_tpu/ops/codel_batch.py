"""Batched CoDel control law as a lax.scan.

The same controlled-delay algorithm the pool runs per claim queue
(reference lib/codel.js, cueball_tpu/codel.py), restructured for TPU:
Q queues advance in lockstep through T dequeue events, carrying
(first_above_time, drop_next, count, dropping) as dense state. All
branching is jnp.where — no data-dependent Python control flow — so the
whole scan compiles to one fused loop.
"""

from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

CODEL_INTERVAL = 100.0  # ms (reference lib/codel.js:16)


class CodelState(typing.NamedTuple):
    first_above: jax.Array  # [Q] ms timestamp, 0 = unset
    drop_next: jax.Array    # [Q] ms timestamp
    count: jax.Array        # [Q] drops in current dropping run
    dropping: jax.Array     # [Q] bool


def codel_init(num_queues: int) -> CodelState:
    # Three separate allocations, NOT one aliased zeros array: the
    # live sampler donates this state through its jitted step, and XLA
    # rejects donating the same underlying buffer twice.
    def z():
        return jnp.zeros((num_queues,), jnp.float32)
    return CodelState(z(), z(), z(), jnp.zeros((num_queues,), bool))


def _step(target: jax.Array, state: CodelState, inputs):
    now, sojourn = inputs  # now: scalar ms; sojourn: [Q] ms

    below = sojourn < target
    first_unset = state.first_above == 0.0
    # can_drop per reference lib/codel.js:34-46
    new_first = jnp.where(
        below, 0.0,
        jnp.where(first_unset, now + CODEL_INTERVAL, state.first_above))
    ok_to_drop = (~below) & (~first_unset) & (now >= state.first_above)

    # dropping branch (reference lib/codel.js:62-68)
    leave_dropping = state.dropping & ~ok_to_drop
    drop_in_run = state.dropping & ok_to_drop & (now >= state.drop_next)
    count_a = jnp.where(drop_in_run, state.count + 1, state.count)

    # enter-dropping branch (reference lib/codel.js:69-85)
    recent = (now - state.drop_next) < CODEL_INTERVAL
    long_above = (now - state.first_above) >= CODEL_INTERVAL
    enter = (~state.dropping) & ok_to_drop & (recent | long_above)
    count_b = jnp.where(
        enter,
        jnp.where(recent & (count_a > 2), count_a - 2, 1.0),
        count_a)
    # drop_next moves only on entering a dropping run; an in-run drop
    # bumps count but NOT drop_next (reference lib/codel.js:62-68 —
    # deliberately not classic CoDel, which would reschedule here).
    drop_next = jnp.where(
        enter,
        now + CODEL_INTERVAL / jnp.sqrt(jnp.maximum(count_b, 1.0)),
        state.drop_next)

    dropping = (state.dropping & ~leave_dropping) | enter
    drop = drop_in_run | enter

    return CodelState(new_first, drop_next, count_b, dropping), drop


def codel_scan(sojourns: jax.Array, times: jax.Array,
               target: float,
               state: CodelState | None = None):
    """Run CoDel over a trace.

    sojourns: [T, Q] queue sojourn times (ms) at each dequeue event;
    times: [T] monotonic ms clock; target: ms. Returns (final_state,
    drops [T, Q] bool).
    """
    if state is None:
        state = codel_init(sojourns.shape[1])
    tgt = jnp.float32(target)
    return jax.lax.scan(
        lambda s, x: _step(tgt, s, x), state, (times, sojourns))

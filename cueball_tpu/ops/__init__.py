"""Batched JAX/TPU implementations of the framework's numeric control
algorithms.

The per-pool runtime uses the scalar Python forms (pool.FIRFilter,
codel.ControlledDelay, utils.gen_delay) — one pool's control math is a
handful of flops and belongs on the host next to the event loop. These
modules are the fleet-scale forms: a TPU-host process supervising
telemetry for thousands of pools/queues batches the same control laws
into dense [pools, ...] arrays where XLA can fuse and tile them.

- ops.fir: the 128-tap EMA low-pass filter (reference lib/pool.js:37-100)
- ops.backoff: exponential backoff schedules with randomized spread
  (reference lib/connection-fsm.js:361-394, lib/utils.js:446-461)
- ops.codel_batch: the CoDel control law as a lax.scan
  (reference lib/codel.js)
"""

from .fir import gen_taps, fir_apply, fir_smooth, fir_apply_pallas
from .backoff import backoff_schedule, spread_delays
from .codel_batch import codel_scan, CodelState

__all__ = ['gen_taps', 'fir_apply', 'fir_smooth', 'fir_apply_pallas',
           'backoff_schedule', 'spread_delays', 'codel_scan',
           'CodelState']

"""Vectorized exponential-backoff schedules.

The SocketMgr doubles delay/timeout per attempt with caps and a
randomized +/- spread/2 jitter to decorrelate retry herds (reference
lib/connection-fsm.js:361-394, lib/utils.js:446-461). Computing the
whole schedule for a fleet of [N] connections (or the full [N, R]
attempt table) is a couple of fused elementwise ops on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=('retries',))
def backoff_schedule(delay, max_delay, retries: int):
    """Per-attempt base delays [N, R]: delay * 2^r clamped to max_delay
    (the deterministic part of the SocketMgr backoff ladder)."""
    delay = jnp.asarray(delay, jnp.float32)
    max_delay = jnp.asarray(max_delay, jnp.float32)
    growth = jnp.exp2(jnp.arange(retries, dtype=jnp.float32))
    return jnp.minimum(delay[:, None] * growth[None, :],
                       max_delay[:, None])


@jax.jit
def backoff_at(delay, max_delay, attempt):
    """Current base delay after `attempt` backoff entries:
    min(delay * 2^attempt, max_delay) — exactly the value
    SocketMgrFSM.sm_delay holds after entering state_backoff `attempt`
    times with finite retries (reference lib/connection-fsm.js:372-380;
    cueball_tpu/connection_fsm.py state_backoff doubles-and-caps).
    Elementwise over [N] fleets of slots/pools."""
    delay = jnp.asarray(delay, jnp.float32)
    max_delay = jnp.asarray(max_delay, jnp.float32)
    attempt = jnp.asarray(attempt, jnp.float32)
    return jnp.minimum(delay * jnp.exp2(attempt), max_delay)


@jax.jit
def spread_delays(base, spread, uniforms):
    """Apply the randomized spread: base * (1 - spread/2 + u * spread),
    u ~ U(0,1) supplied by the caller (reference lib/utils.js:446-461;
    randomness is passed in so the op stays a pure function)."""
    base = jnp.asarray(base, jnp.float32)
    spread = jnp.asarray(spread, jnp.float32)
    return jnp.round(base * (1.0 - spread / 2.0 + uniforms * spread))

"""Claim-path span tracing: where did a claim's latency actually go?

The kang snapshot and the fleet sampler expose *structure* (FSM states,
queue depths); this module records *behavior*. When tracing is enabled,
every pool claim carries a `ClaimTrace` — a flat list of spans with
OTLP-compatible field names (trace_id / span_id / parent_span_id /
start / end / attrs) — decomposing its life into queue wait, CoDel
admission decisions, slot selection, connect + handshake, lease-held
time and release/requeue. DNS lookups get their own `DnsTrace` with one
child span per resolver attempt.

Completed traces land in a bounded per-process ring (O(1) append,
oldest dropped) and surface three ways:

  * `GET /kang/traces` on the debug HTTP server (NDJSON, one span per
    line — see http_server.py);
  * the SIGUSR2 dump (`debug.dump_fsm_histories()` folds in the slowest
    claims next to the FSM histories);
  * histograms / counters / gauges on an attached metrics Collector,
    served through the existing `/metrics` endpoint.

Zero dependencies and hot-path neutral when disabled: the only cost a
disabled tracer adds to the claim cycle is a module-global load plus a
None check (the same discipline as the pool's empty-tuple telemetry
walk), guarded by the bench A/B stage (`bench.py --host-only`) and
`tests/test_bench_guard.py`.

All span timestamps are monotonic milliseconds (`utils.current_millis`),
the same clock as `ch_started` and the FSM history ring — durations are
exact; absolute values are process-relative, not wall-clock.
"""

from __future__ import annotations

import collections
import json
import threading

from . import runq as mod_runq
from . import utils as mod_utils
from .events import _native

DEFAULT_RING_SIZE = 512

# With the C engine loaded, the hot path doesn't build these objects at
# all: trace.claim_begin hands the claim a NativeTrace token whose
# methods append fixed-width slots to a preallocated C ring
# (native/emitter.c, "Native trace recorder"), and the Python objects
# below are assembled lazily at export by replaying the ring through
# the SAME classes — which is what keeps the NDJSON byte-identical to
# the pure-Python recorder. The event ring is sized as a multiple of
# the completed-trace ring (a claim emits ~6 events).
NATIVE_EVENTS_PER_TRACE = 16

_NATIVE_TRACE_OK = _native is not None and \
    hasattr(_native, 'trace_claim_begin')

# Event codes — must match the TREV_* defines in native/emitter.c.
_EV_CLAIM_BEGIN = 1
_EV_CODEL = 2
_EV_SLOT = 3
_EV_CLAIMING = 4
_EV_CLAIMED = 5
_EV_REQUEUED = 6
_EV_RELEASED = 7
_EV_FAILED = 8
_EV_CANCELLED = 9
_EV_DNS_BEGIN = 10
_EV_DNS_QBEGIN = 11
_EV_DNS_QEND = 12
_EV_DNS_DONE = 13

# Reserved wire-event codes (WEV_* in the future native transport):
# the fixed slots a native data path appends per Transport seam for
# the wiretap ledger. They share the event ring with the TREV_* codes
# above but are NOT trace events — _drain_native skips them without
# touching the pending map or the truncation counter. The mapping is
# part of the NativeTransport conformance contract and follows
# transport.SEAM_METHODS / wiretap.SEAMS order.
_EV_WIRE_FIRST = 14
WIRE_EVENT_CODES = {
    'connector': 14,
    'create_stream': 15,
    'serve': 16,
    'dns_udp': 17,
    'dns_tcp': 18,
}

# Cap on traces whose begin event has drained but whose terminal event
# hasn't: protects the assembler against claims that never finish.
_PENDING_MAX = 4096

# Histograms the runtime feeds from completed spans (all milliseconds).
TRACE_HISTOGRAMS = {
    'cueball_claim_wait_ms':
        'Time a claim spent queued before a slot was assigned (ms)',
    'cueball_connect_ms':
        'TCP connect + constructor time per backend connect (ms)',
    'cueball_handshake_ms':
        'Slot claim handshake time, claiming to claimed (ms)',
    'cueball_lease_held_ms':
        'Time a claimed connection was held before release (ms)',
    'cueball_dns_lookup_ms':
        'DNS lookup round-trip time (ms)',
}

# Per-phase claim cost, fed from the profile module's phase ledger at
# completion time (labelled, so declared separately from the plain
# histograms above).
PHASE_HISTOGRAM = 'cueball_claim_phase_ms'
PHASE_HISTOGRAM_HELP = ('Per-claim time attributed to one claim-path '
                        'phase by the profile ledger (ms)')

SHED_COUNTER = 'cueball_codel_shed_total'
SHED_HELP = 'Claims shed by CoDel admission control, by reason'

# Per-pool gauges refreshed lazily at scrape time from the same
# mark_dirty() hooks that drive the fleet sampler's TelemetryRowHandle.
POOL_GAUGES = {
    'cueball_queue_depth': 'Claims waiting in the pool claim queue',
    'cueball_open_slots': 'Connection slots open (all states)',
    'cueball_idle_slots': 'Connection slots idle (claimable)',
    'cueball_busy_slots': 'Connection slots busy (claimed)',
    'cueball_pending_slots': 'Connection slots still connecting',
}

# Self-observability of the recorder itself: the flight recorder must
# say when it is dropping its own film.
RING_DROPPED_COUNTER = 'cueball_trace_ring_dropped_total'
RING_DROPPED_HELP = \
    'Native trace-ring event slots overwritten before export'
RING_GAUGES = {
    'cueball_trace_ring_highwater':
        'Peak undrained event slots in the native trace ring',
    'cueball_pump_queue_depth':
        'Callbacks waiting in the engine run-queue pump',
}


# -- shard identity ---------------------------------------------------------
# Each FleetRouter shard thread/process stamps its id here at bootstrap;
# spans record it so the merged export surfaces keep a per-shard
# breakdown. Thread-local because thread-backend shards share this
# module; the native recorder mirrors it into a C thread-local so slots
# written without any Python payload still carry the shard (flags bits
# 8+, biased by +1 so 0 keeps meaning "no shard").

_SHARD_TLS = threading.local()
_SHARD_FROM_TLS = object()  # sentinel: "read the caller's TLS"
_SHARD_FLAG_SHIFT = 8


def set_shard_id(shard_id: int | None) -> None:
    """Tag the calling thread (and, through the C TLS, every native
    trace slot it writes) with a FleetRouter shard id. None clears."""
    _SHARD_TLS.shard = None if shard_id is None else int(shard_id)
    if _NATIVE_TRACE_OK and hasattr(_native, 'trace_set_shard'):
        _native.trace_set_shard(-1 if shard_id is None else int(shard_id))


def get_shard_id() -> int | None:
    return getattr(_SHARD_TLS, 'shard', None)


def _shard_from_flags(flags: int) -> int | None:
    sid = ((int(flags) >> _SHARD_FLAG_SHIFT) & 0xFFF) - 1
    return sid if sid >= 0 else None


# -- backend identity -------------------------------------------------------
# Stable small integers for backend keys, shared by every attribution
# surface: the native recorder stamps the index into slot flags (bits
# 20+, biased by +1 like the shard field) and the health engine's
# BackendTable uses the same index as its row number, so a claim
# attributed by the C ring and one attributed by the Python recorder
# land in the same per-backend column. Index 0 is RESERVED for the
# unattributed bucket (key ''): claims that never reached a backend.

_BACKEND_LOCK = threading.Lock()
_BACKEND_KEYS: list = ['']
_BACKEND_IDS: dict = {'': 0}
_BACKEND_FLAG_SHIFT = 20
#: 12 flag bits, biased by +1: indexes past this fall back to row 0.
BACKEND_INDEX_MAX = 0xFFE


def backend_index(key) -> int:
    """The stable row index for a backend key (registering it on first
    sight). Falls back to 0 (unattributed) when the registry is full,
    so the flag stamp can never alias two real backends."""
    key = str(key or '')
    idx = _BACKEND_IDS.get(key)
    if idx is not None:
        return idx
    with _BACKEND_LOCK:
        idx = _BACKEND_IDS.get(key)
        if idx is None:
            if len(_BACKEND_KEYS) > BACKEND_INDEX_MAX:
                return 0
            idx = len(_BACKEND_KEYS)
            _BACKEND_KEYS.append(key)
            _BACKEND_IDS[key] = idx
    return idx


def backend_key_for(index: int) -> str | None:
    """Reverse lookup; None for indexes never registered."""
    if not 0 <= index < len(_BACKEND_KEYS):
        return None
    return _BACKEND_KEYS[index]


def backend_known(key) -> bool:
    """True when the backend key has ever been registered (seen by a
    trace or telemetry path) — lets /kang/traces reject filters naming
    backends that never existed instead of returning an empty body."""
    return str(key or '') in _BACKEND_IDS


def _backend_from_flags(flags: int) -> str | None:
    idx = ((int(flags) >> _BACKEND_FLAG_SHIFT) & 0xFFF) - 1
    return backend_key_for(idx) if idx >= 0 else None


# Attribution sinks (the health engine's BackendTable): every finished
# claim and every CoDel shed is offered to each sink with its backend
# key, on whatever thread completed it. Copy-on-write tuple like
# _EXPORT_SOURCES so the hot path pays one load when empty.
_BACKEND_SINKS: tuple = ()


def add_backend_sink(sink) -> None:
    """Register an attribution sink: an object with
    ``observe(key, service_ms, claim_ms, ok)`` and
    ``observe_shed(key)``."""
    global _BACKEND_SINKS
    _BACKEND_SINKS = _BACKEND_SINKS + (sink,)


def remove_backend_sink(sink) -> None:
    global _BACKEND_SINKS
    _BACKEND_SINKS = tuple(
        s for s in _BACKEND_SINKS if s is not sink)


# External NDJSON producers merged into export_ndjson() — the seam the
# FleetRouter's spawn backend uses to fold child-process trace rings
# into the parent's /kang/traces view. Each source is a zero-arg
# callable returning an NDJSON string ('' when it has nothing).
_EXPORT_SOURCES: tuple = ()


def add_export_source(fn) -> None:
    global _EXPORT_SOURCES
    _EXPORT_SOURCES = _EXPORT_SOURCES + (fn,)


def remove_export_source(fn) -> None:
    global _EXPORT_SOURCES
    _EXPORT_SOURCES = tuple(f for f in _EXPORT_SOURCES if f is not fn)


def _new_trace_id() -> str:
    return '%032x' % mod_utils.get_rng().getrandbits(128)


def _new_span_id() -> str:
    return '%016x' % mod_utils.get_rng().getrandbits(64)


_M64 = (1 << 64) - 1


def _span_id_from(seed: int, index: int) -> str:
    """Deterministic span id: splitmix64 of (trace seed, span index).

    Span ids used to be independent RNG draws, which would make the
    native recorder's lazily-assembled spans diverge from the pure
    recorder's (the draws happen at different times). Deriving them
    from the trace id — itself still one RNG draw — makes the id a
    pure function of (trace, position), so both recorders emit
    byte-identical NDJSON while consuming identical RNG streams."""
    z = (seed + (index + 1) * 0x9E3779B97F4A7C15) & _M64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    z ^= z >> 31
    return '%016x' % z


class Span:
    """One timed operation. `end is None` means still open; event spans
    are recorded with end == start."""

    __slots__ = ('name', 'span_id', 'parent_span_id', 'start', 'end',
                 'attrs')

    def __init__(self, name: str, parent_span_id: str | None,
                 start: float, attrs: dict | None = None,
                 span_id: str | None = None):
        self.name = name
        self.span_id = _new_span_id() if span_id is None else span_id
        self.parent_span_id = parent_span_id
        self.start = start
        self.end = None
        self.attrs = dict(attrs or {})

    def duration(self) -> float | None:
        if self.end is None:
            return None
        return self.end - self.start


class Trace:
    """A flat span list sharing one trace_id; spans[0] is the root."""

    __slots__ = ('trace_id', 'spans', 'tr_runtime', 'tr_sid_seed')

    root_name = 'trace'

    def __init__(self, runtime: '_TraceRuntime', attrs: dict | None = None,
                 start: float | None = None,
                 trace_id_int: int | None = None):
        if trace_id_int is None:
            trace_id_int = mod_utils.get_rng().getrandbits(128)
        self.trace_id = '%032x' % trace_id_int
        self.tr_sid_seed = trace_id_int & _M64
        self.tr_runtime = runtime
        if start is None:
            start = mod_utils.current_millis()
        self.spans = []
        self._new_span(self.root_name, None, start, attrs)

    @property
    def root(self) -> Span:
        return self.spans[0]

    def _new_span(self, name: str, parent_span_id: str | None,
                  start: float, attrs: dict | None = None) -> Span:
        span = Span(name, parent_span_id, start, attrs,
                    span_id=_span_id_from(self.tr_sid_seed,
                                          len(self.spans)))
        self.spans.append(span)
        return span

    def begin_span(self, name: str, attrs: dict | None = None,
                   start: float | None = None) -> Span:
        if start is None:
            start = mod_utils.current_millis()
        return self._new_span(name, self.root.span_id, start, attrs)

    def end_span(self, span: Span, end: float | None = None) -> None:
        if span.end is None:
            span.end = mod_utils.current_millis() if end is None else end

    def add_event(self, name: str, attrs: dict | None = None,
                  now: float | None = None) -> Span:
        """A zero-duration decision/event span (end == start)."""
        span = self.begin_span(name, attrs, start=now)
        span.end = span.start
        return span

    def span_totals(self) -> dict:
        """Sum of closed-span durations per span name (ms)."""
        totals: dict = {}
        for span in self.spans[1:]:
            d = span.duration()
            if d is not None:
                totals[span.name] = totals.get(span.name, 0.0) + d
        return totals

    def finish(self, outcome: str, end: float | None = None) -> None:
        """Close the root span and hand the trace to the ring; safe to
        call more than once (terminal FSM states can chain, e.g.
        released -> closed)."""
        root = self.root
        if root.end is not None:
            return
        root.attrs['outcome'] = outcome
        root.end = mod_utils.current_millis() if end is None else end
        for span in self.spans[1:]:
            if span.end is None:
                span.end = root.end
        self.tr_runtime.completed(self)

    def ndjson_lines(self) -> list:
        out = []
        for span in self.spans:
            out.append(json.dumps({
                'trace_id': self.trace_id,
                'span_id': span.span_id,
                'parent_span_id': span.parent_span_id,
                'name': span.name,
                'start': span.start,
                'end': span.end,
                'attrs': span.attrs,
            }, sort_keys=True))
        return out


class ClaimTrace(Trace):
    """Spans for one pool/set claim. The claim handle calls exactly one
    guarded method per FSM transition; every method tolerates arriving
    in unexpected orders (terminal states finish idempotently)."""

    __slots__ = ('ct_queue_span', 'ct_handshake_span', 'ct_lease_span',
                 'ct_backend')

    root_name = 'claim'

    def __init__(self, runtime: '_TraceRuntime', pool,
                 start: float | None = None,
                 trace_id_int: int | None = None,
                 ident: tuple | None = None):
        # 'pool' may be a ConnectionPool or a ConnectionSet standing in
        # as one (cset claims hand the set itself down), so everything
        # here is getattr-guarded. Replay passes the (pool, domain[,
        # shard]) identity captured at emit time instead of the live
        # object. Pools owned by a FleetRouter shard carry p_shard and
        # stamp it on the span; plain pools produce the exact
        # pre-sharding attrs (no 'shard' key), keeping unsharded
        # exports byte-identical.
        if ident is None:
            uuid = getattr(pool, 'p_uuid', None) or \
                getattr(pool, 'cs_uuid', None) or ''
            domain = getattr(pool, 'p_domain', None) or \
                getattr(pool, 'cs_domain', None) or ''
            shard = getattr(pool, 'p_shard', None)
            if shard is None:
                shard = getattr(pool, 'cs_shard', None)
            ident = (str(uuid), str(domain))
            if shard is not None:
                ident += (int(shard),)
        attrs = {
            'kind': 'claim',
            'pool': ident[0],
            'domain': ident[1],
        }
        if len(ident) > 2 and ident[2] is not None:
            attrs['shard'] = ident[2]
        Trace.__init__(self, runtime, attrs,
                       start=start, trace_id_int=trace_id_int)
        self.ct_queue_span = self.begin_span('queue_wait',
                                             start=self.root.start)
        self.ct_handshake_span = None
        self.ct_lease_span = None
        self.ct_backend = ''

    def codel_decision(self, decision: str, sojourn_ms: float,
                       target_ms: float, now: float | None = None) -> None:
        self.add_event('codel', {
            'decision': decision,
            'sojourn_ms': round(float(sojourn_ms), 3),
            'target_ms': float(target_ms),
        }, now=now)

    def slot_selected(self, source: str, now: float | None = None) -> None:
        self.add_event('slot_select', {'source': source}, now=now)

    def claiming(self, slot) -> None:
        """Queue wait is over; the claim handshake with `slot` begins.
        The serving slot's last connect is attached as a child span so
        the trace shows where connect time went even when the connect
        predates the claim (attrs.during_claim says which)."""
        backend = ''
        last = None
        smgr = None
        get_smgr = getattr(slot, 'get_socket_mgr', None)
        if get_smgr is not None:
            smgr = get_smgr()
        if smgr is not None:
            be = getattr(smgr, 'sm_backend', None) or {}
            backend = str(be.get('key') or '')
            last = getattr(smgr, 'sm_last_connect', None)
            if last is not None:
                cstart, cend = last
                last = (cstart, cend)
        self._claiming_at(backend, last, mod_utils.current_millis())

    def _claiming_at(self, backend: str, last: tuple | None,
                     now: float) -> None:
        self.ct_backend = backend or ''
        self.end_span(self.ct_queue_span, now)
        if last is not None:
            cstart, cend = last
            span = self._new_span(
                'connect', self.root.span_id, cstart,
                {'backend': backend,
                 'during_claim': cend >= self.root.start})
            span.end = cend
        self.ct_handshake_span = self.begin_span(
            'handshake', {'backend': backend}, start=now)

    def claimed(self, now: float | None = None) -> None:
        if now is None:
            now = mod_utils.current_millis()
        if self.ct_handshake_span is not None:
            self.end_span(self.ct_handshake_span, now)
        self.ct_lease_span = self.begin_span('lease', start=now)

    def requeued(self, now: float | None = None) -> None:
        """The slot rejected the handshake; the claim is back in the
        queue. Only meaningful when a handshake was open."""
        if self.ct_handshake_span is None:
            return
        if now is None:
            now = mod_utils.current_millis()
        if self.ct_handshake_span.end is None:
            self.ct_handshake_span.attrs['outcome'] = 'rejected'
            self.end_span(self.ct_handshake_span, now)
        self.ct_handshake_span = None
        self.add_event('requeue', now=now)
        self.ct_queue_span = self.begin_span(
            'queue_wait', {'requeue': True}, start=now)

    def released(self, how: str, now: float | None = None) -> None:
        if now is None:
            now = mod_utils.current_millis()
        if self.ct_lease_span is not None:
            self.end_span(self.ct_lease_span, now)
        if self.root.end is None:
            self.add_event('release', {'how': how}, now=now)
        self.finish('released' if how == 'release' else 'closed',
                    end=now)

    def failed(self, err) -> None:
        self._fail_named(type(err).__name__ if err is not None else None)

    def _fail_named(self, errname: str | None,
                    now: float | None = None) -> None:
        if errname is not None:
            self.root.attrs['error'] = errname
        self.finish('failed', end=now)

    def cancelled(self, now: float | None = None) -> None:
        self.finish('cancelled', end=now)


class DnsTrace(Trace):
    """Spans for one DNS resolution: a root lookup span plus one
    `dns_query` child per resolver attempt (dns_client)."""

    __slots__ = ()

    root_name = 'dns_lookup'

    def __init__(self, runtime: '_TraceRuntime', domain: str, rtype: str,
                 start: float | None = None,
                 trace_id_int: int | None = None,
                 shard=_SHARD_FROM_TLS):
        # Live construction reads the caller's shard id off the thread
        # local (a DNS lookup has no pool to carry it); native replay
        # passes the shard decoded from the slot's flags explicitly —
        # including None — so the drain thread's own TLS never leaks
        # into replayed traces.
        if shard is _SHARD_FROM_TLS:
            shard = get_shard_id()
        attrs = {
            'kind': 'dns',
            'domain': str(domain),
            'type': str(rtype),
        }
        if shard is not None:
            attrs['shard'] = int(shard)
        Trace.__init__(self, runtime, attrs,
                       start=start, trace_id_int=trace_id_int)

    def query_begin(self, resolver: str,
                    now: float | None = None) -> Span:
        return self.begin_span('dns_query', {'resolver': str(resolver)},
                               start=now)

    def query_end(self, span: Span, outcome: str,
                  now: float | None = None) -> None:
        span.attrs['outcome'] = outcome
        self.end_span(span, end=now)

    def done(self, outcome: str, err=None) -> None:
        self._done_named(outcome,
                         type(err).__name__ if err is not None else None)

    def _done_named(self, outcome: str, errname: str | None,
                    now: float | None = None) -> None:
        if errname is not None:
            self.root.attrs['error'] = errname
        self.finish(outcome, end=now)


class _GaugeRow:
    """Speaks the pool's telemetry_attach protocol (the same hooks that
    drive the fleet sampler's TelemetryRowHandle): the pool marks the
    row dirty on every state-moving event, and the runtime re-reads the
    pool's gauges only on scrapes where something changed."""

    __slots__ = ('gr_pool', 'gr_labels', 'gr_dirty')

    def __init__(self, pool, labels: dict):
        self.gr_pool = pool
        self.gr_labels = labels
        self.gr_dirty = True

    def mark_dirty(self) -> None:
        self.gr_dirty = True


class _TraceRuntime:
    """Process-global tracer state: the completed-trace ring, the
    sampling decision, and the optional metric aggregation."""

    def __init__(self, ring_size: int = DEFAULT_RING_SIZE,
                 sample_rate: float = 1.0, collector=None,
                 native: bool | None = None):
        if ring_size < 1:
            raise ValueError('ring_size must be >= 1')
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError('sample_rate must be within [0, 1]')
        if native is None:
            native = _NATIVE_TRACE_OK
        self.tr_native = bool(native) and _NATIVE_TRACE_OK
        self.tr_ring: collections.deque = collections.deque(
            maxlen=int(ring_size))
        self.tr_sample = float(sample_rate)
        self.tr_collector = collector
        self.tr_seen = 0
        self.tr_sampled = 0
        self.tr_rows: dict = {}
        self.tr_generation = None
        # Traces whose begin event has drained but whose terminal event
        # hasn't: serial -> [trace, dns_query_token_map_or_None].
        self.tr_pending: dict = {}
        self.tr_truncated = 0
        self.tr_evicted = 0
        self.tr_dropped_reported = 0
        if collector is not None:
            for name, help_ in TRACE_HISTOGRAMS.items():
                collector.histogram(name, help=help_)
            collector.histogram(PHASE_HISTOGRAM, help=PHASE_HISTOGRAM_HELP)
            collector.counter(SHED_COUNTER, help=SHED_HELP)
            collector.counter(RING_DROPPED_COUNTER,
                              help=RING_DROPPED_HELP)
            for name, help_ in POOL_GAUGES.items():
                collector.gauge(name, help=help_)
            for name, help_ in RING_GAUGES.items():
                collector.gauge(name, help=help_)
            collector.add_collect_hook(self.refresh_gauges)
        if self.tr_native:
            _native.trace_ring_configure(
                int(ring_size) * NATIVE_EVENTS_PER_TRACE)
            _sync_native_clock()
            # Bound module functions cached for the per-claim path.
            self.tr_nclaim = _native.trace_claim_begin
            self.tr_ndns = _native.trace_dns_begin

    # -- sampling ---------------------------------------------------------

    def _sampled(self) -> bool:
        self.tr_seen += 1
        rate = self.tr_sample
        if rate >= 1.0:
            sampled = True
        elif rate <= 0.0:
            sampled = False
        else:
            sampled = mod_utils.get_rng().random() < rate
        if sampled:
            self.tr_sampled += 1
        return sampled

    # -- claim-path hooks (called from pool / connection_fsm / cset) ------

    def claim_begin(self, handle, pool) -> None:
        # _sampled() inlined: this runs once per claim at rate 1.0.
        self.tr_seen += 1
        rate = self.tr_sample
        if rate < 1.0:
            if rate <= 0.0 or \
                    not mod_utils.get_rng().random() < rate:
                return
        self.tr_sampled += 1
        start = getattr(handle, 'ch_started', None)
        if self.tr_native:
            try:
                ident = pool._tr_claim_ident
            except AttributeError:
                ident = self._claim_ident(pool)
            if start is None:
                start = mod_utils.current_millis()
            handle.ch_trace = self.tr_nclaim(
                (mod_utils.get_rng().getrandbits(128), ident), start)
        else:
            handle.ch_trace = ClaimTrace(self, pool, start=start)

    def _claim_ident(self, pool) -> tuple:
        """(pool uuid, domain[, shard]) as strings (shard an int),
        cached on the pool so the native fast path pays one attribute
        load instead of four. Shard-owned pools (FleetRouter sets
        p_shard right after construction, before any claim) get the
        3-tuple; plain pools keep the 2-tuple so their exports are
        bit-for-bit what they were before sharding existed."""
        uuid = getattr(pool, 'p_uuid', None) or \
            getattr(pool, 'cs_uuid', None) or ''
        domain = getattr(pool, 'p_domain', None) or \
            getattr(pool, 'cs_domain', None) or ''
        ident = (str(uuid), str(domain))
        shard = getattr(pool, 'p_shard', None)
        if shard is None:
            shard = getattr(pool, 'cs_shard', None)
        if shard is not None:
            ident += (int(shard),)
        try:
            pool._tr_claim_ident = ident
        except (AttributeError, TypeError):
            pass
        return ident

    def connect_done(self, backend_key, start: float, end: float) -> None:
        self.observe('cueball_connect_ms', end - start)

    def codel_shed(self, handle, reason: str, sojourn_ms: float,
                   target_ms: float) -> None:
        if self.tr_collector is not None:
            self.tr_collector.counter(SHED_COUNTER, help=SHED_HELP) \
                .increment({'reason': reason})
        trace = getattr(handle, 'ch_trace', None)
        sinks = _BACKEND_SINKS
        if sinks:
            # Sheds strike queued claims, so most are unattributed
            # (row 0); a requeued claim keeps its last backend.
            key = getattr(trace, 'ct_backend', '') or ''
            for sink in sinks:
                sink.observe_shed(key)
        if trace is not None:
            trace.codel_decision('shed-' + reason, sojourn_ms, target_ms)

    def dns_begin(self, domain: str, rtype: str):
        if not self._sampled():
            return None
        if self.tr_native:
            return self.tr_ndns(
                (mod_utils.get_rng().getrandbits(128),
                 str(domain), str(rtype)),
                mod_utils.current_millis())
        return DnsTrace(self, domain, rtype)

    def observe(self, name: str, value_ms: float) -> None:
        if self.tr_collector is not None and value_ms is not None:
            self.tr_collector.histogram(
                name, help=TRACE_HISTOGRAMS.get(name, '')) \
                .observe(value_ms)

    # -- completion -------------------------------------------------------

    def completed(self, trace: Trace) -> None:
        if len(self.tr_ring) == self.tr_ring.maxlen:
            self.tr_evicted += 1
        self.tr_ring.append(trace)
        sinks = _BACKEND_SINKS
        if sinks and isinstance(trace, ClaimTrace):
            outcome = trace.root.attrs.get('outcome')
            if outcome != 'cancelled':
                lease = trace.ct_lease_span
                service = (lease.duration()
                           if lease is not None else None)
                ok = outcome in ('released', 'closed')
                claim_ms = trace.root.duration()
                for sink in sinks:
                    sink.observe(trace.ct_backend, service,
                                 claim_ms, ok)
        if self.tr_collector is None:
            return
        totals = trace.span_totals()
        if isinstance(trace, ClaimTrace):
            if 'queue_wait' in totals:
                self.observe('cueball_claim_wait_ms', totals['queue_wait'])
            if 'handshake' in totals:
                self.observe('cueball_handshake_ms', totals['handshake'])
            if 'lease' in totals:
                self.observe('cueball_lease_held_ms', totals['lease'])
            from . import profile as mod_profile
            led = mod_profile.claim_ledger(trace)
            if led is not None:
                hist = self.tr_collector.histogram(
                    PHASE_HISTOGRAM, help=PHASE_HISTOGRAM_HELP)
                for phase, ms in led['phases'].items():
                    if ms > 0.0:
                        hist.observe(ms, labels={'phase': phase})
        elif isinstance(trace, DnsTrace):
            self.observe('cueball_dns_lookup_ms', trace.root.duration())

    # -- native ring drain ------------------------------------------------

    def _drain_native(self) -> None:
        """Replay the C event ring through the real trace classes.

        This is the lazy half of the native recorder: the hot path
        wrote fixed-width slots; here — only at export/scrape — those
        slots are replayed through the SAME ClaimTrace/DnsTrace methods
        the pure recorder drives inline, with the recorded timestamps
        passed as now=/start=. Byte-identical NDJSON by construction.

        Terminal events deliberately do NOT remove the pending entry:
        terminal states can chain (released -> closed) and finish() is
        idempotent, so a later terminal event on the same serial must
        still find its trace. Entries age out of the bounded pending
        map instead; an unfinished trace evicted that way (or an event
        whose begin slot was already overwritten) counts as truncated."""
        if not self.tr_native:
            return
        events = _native.trace_ring_drain()
        if not events:
            return
        pending = self.tr_pending
        for code, serial, t, a, b, obj, flags in events:
            if code >= _EV_WIRE_FIRST:
                # Reserved wire-event slot (native transport wiretap
                # counters): not a trace event, never truncation.
                continue
            if code == _EV_CLAIM_BEGIN:
                tid, ident = obj
                pending[serial] = [
                    ClaimTrace(self, None, start=t,
                               trace_id_int=tid, ident=ident),
                    None,
                ]
            elif code == _EV_DNS_BEGIN:
                tid, domain, rtype = obj
                pending[serial] = [
                    DnsTrace(self, domain, rtype, start=t,
                             trace_id_int=tid,
                             shard=_shard_from_flags(flags)),
                    None,
                ]
            else:
                ent = pending.get(serial)
                if ent is None:
                    self.tr_truncated += 1
                    # The begin slot was overwritten, but terminal
                    # claim events still carry the backend index in
                    # their flags: attribution survives truncation.
                    sinks = _BACKEND_SINKS
                    if sinks and code in (_EV_RELEASED, _EV_FAILED):
                        key = _backend_from_flags(flags) or ''
                        for sink in sinks:
                            sink.observe(key, None, None,
                                         code == _EV_RELEASED)
                    continue
                trace = ent[0]
                if code == _EV_CODEL:
                    trace.codel_decision(obj, a, b, now=t)
                elif code == _EV_SLOT:
                    trace.slot_selected(obj, now=t)
                elif code == _EV_CLAIMING:
                    trace._claiming_at(
                        obj, (a, b) if flags & 1 else None, t)
                elif code == _EV_CLAIMED:
                    trace.claimed(now=t)
                elif code == _EV_REQUEUED:
                    trace.requeued(now=t)
                elif code == _EV_RELEASED:
                    trace.released(obj, now=t)
                elif code == _EV_FAILED:
                    trace._fail_named(obj, now=t)
                elif code == _EV_CANCELLED:
                    trace.cancelled(now=t)
                elif code == _EV_DNS_QBEGIN:
                    qmap = ent[1]
                    if qmap is None:
                        qmap = ent[1] = {}
                    qmap[int(a)] = trace.query_begin(obj, now=t)
                elif code == _EV_DNS_QEND:
                    qmap = ent[1]
                    span = qmap.pop(int(a), None) if qmap else None
                    if span is not None:
                        trace.query_end(span, obj, now=t)
                elif code == _EV_DNS_DONE:
                    outcome, errname = obj
                    trace._done_named(outcome, errname, now=t)
                continue
            if len(pending) > _PENDING_MAX:
                ent = pending.pop(next(iter(pending)))
                if ent[0].root.end is None:
                    self.tr_truncated += 1

    def _refresh_ring_health(self) -> None:
        """Scrape-time ring self-observability: dropped-slot counter
        (delta-exported from the C ring's monotonic total), undrained
        high-water gauge, and the run-queue pump depth."""
        if self.tr_collector is None:
            return
        highwater = 0
        if self.tr_native:
            stats = _native.trace_ring_stats()
            dropped = stats['dropped']
            delta = dropped - self.tr_dropped_reported
            if delta > 0:
                self.tr_dropped_reported = dropped
                self.tr_collector.counter(
                    RING_DROPPED_COUNTER, help=RING_DROPPED_HELP) \
                    .increment(value=delta)
            highwater = stats['highwater']
        self.tr_collector.gauge(
            'cueball_trace_ring_highwater',
            help=RING_GAUGES['cueball_trace_ring_highwater']) \
            .set(highwater)
        self.tr_collector.gauge(
            'cueball_pump_queue_depth',
            help=RING_GAUGES['cueball_pump_queue_depth']) \
            .set(mod_runq.pump_depth())

    # -- per-pool gauges --------------------------------------------------

    def refresh_gauges(self) -> None:
        """Collect-time refresh: reconcile the pool roster (via the
        monitor's generation counter, as the sampler does), then re-read
        gauges only for pools whose telemetry row was marked dirty."""
        if self.tr_collector is None:
            return
        self._drain_native()
        self._refresh_ring_health()
        from . import monitor as mod_monitor
        mon = mod_monitor.pool_monitor
        gen = mon.pm_generation
        if gen != self.tr_generation:
            self.tr_generation = gen
            live = dict(mon.pm_pools)
            for uuid in list(self.tr_rows):
                if uuid not in live:
                    self._drop_row(uuid)
            for uuid, pool in live.items():
                if uuid in self.tr_rows:
                    continue
                if getattr(pool, 'telemetry_attach', None) is None:
                    continue
                labels = {
                    'pool': str(uuid),
                    'domain': str(getattr(pool, 'p_domain', '')),
                }
                shard = getattr(pool, 'p_shard', None)
                if shard is not None:
                    labels['shard'] = str(shard)
                row = _GaugeRow(pool, labels)
                self.tr_rows[uuid] = row
                pool.telemetry_attach(row)
        for row in self.tr_rows.values():
            if not row.gr_dirty:
                continue
            row.gr_dirty = False
            stats = row.gr_pool.get_stats()
            total = stats['totalConnections']
            idle = stats['idleConnections']
            pending = stats['pendingConnections']
            values = {
                'cueball_queue_depth': stats['waiterCount'],
                'cueball_open_slots': total,
                'cueball_idle_slots': idle,
                'cueball_busy_slots': max(total - idle - pending, 0),
                'cueball_pending_slots': pending,
            }
            for name, v in values.items():
                self.tr_collector.gauge(
                    name, help=POOL_GAUGES[name]).set(v, row.gr_labels)

    def _drop_row(self, uuid) -> None:
        row = self.tr_rows.pop(uuid, None)
        if row is None:
            return
        detach = getattr(row.gr_pool, 'telemetry_detach', None)
        if detach is not None:
            detach(row)
        for name in POOL_GAUGES:
            self.tr_collector.gauge(
                name, help=POOL_GAUGES[name]).remove(row.gr_labels)

    def shutdown(self) -> None:
        for uuid in list(self.tr_rows):
            self._drop_row(uuid)
        if self.tr_collector is not None:
            self.tr_collector.remove_collect_hook(self.refresh_gauges)
        if self.tr_native:
            mod_utils.remove_clock_hook(_sync_native_clock)
            _native.trace_ring_configure(0)
            _native.trace_set_clock(None)
            self.tr_pending.clear()


# The one per-process runtime; None when tracing is off. Hot-path call
# sites read this module global directly and branch on None — keep it a
# simple attribute so the disabled cost stays one load + one check.
_runtime: _TraceRuntime | None = None


def _sync_native_clock(*_clock) -> None:
    """Keep the C recorder on the same clock as utils.current_millis():
    under the real SystemClock the C side reads CLOCK_MONOTONIC
    directly (no Python in the hot path); any substituted clock
    (netsim's VirtualClock) routes through a Python callback so
    virtual-time traces stay parity-exact. Registered as a
    utils.add_clock_hook so mid-run set_clock() switches follow."""
    if not _NATIVE_TRACE_OK:
        return
    if isinstance(mod_utils.get_clock(), mod_utils.SystemClock):
        _native.trace_set_clock(None)
    else:
        _native.trace_set_clock(mod_utils.current_millis)


def enable_tracing(ring_size: int = DEFAULT_RING_SIZE,
                   sample_rate: float = 1.0,
                   collector=None,
                   native: bool | None = None) -> _TraceRuntime:
    """Turn on claim-path tracing process-wide. `collector` (a
    metrics.Collector) is optional: without one, traces land in the
    ring and on /kang/traces but no histograms/gauges are fed.
    `native` selects the C event-ring recorder (None = use it whenever
    the C engine is loaded; False forces the pure-Python recorder)."""
    global _runtime
    if _runtime is not None:
        disable_tracing()
    _runtime = _TraceRuntime(ring_size, sample_rate, collector, native)
    if _runtime.tr_native:
        mod_utils.add_clock_hook(_sync_native_clock)
    return _runtime


def disable_tracing() -> None:
    """Turn tracing off and detach every pool hook it installed."""
    global _runtime
    runtime = _runtime
    _runtime = None
    if runtime is not None:
        runtime.shutdown()


def tracing_enabled() -> bool:
    return _runtime is not None


def active_collector():
    """The enabled runtime's Collector (or None): lets other metric
    producers (e.g. the fleet sampler) publish onto the same canonical
    surface without plumbing a collector of their own."""
    runtime = _runtime
    return runtime.tr_collector if runtime is not None else None


def trace_ring() -> list:
    """Completed traces, oldest first (a copy; safe to iterate)."""
    runtime = _runtime
    if runtime is None:
        return []
    runtime._drain_native()
    return list(runtime.tr_ring)


def export_ndjson() -> str:
    """All ring spans as NDJSON, one span per line, oldest trace first
    (the /kang/traces payload), followed by any registered external
    sources (child-process shard rings). Empty string when tracing is
    off and no source has anything."""
    runtime = _runtime
    lines: list = []
    if runtime is not None:
        runtime._drain_native()
        for trace in runtime.tr_ring:
            lines.extend(trace.ndjson_lines())
    out = '\n'.join(lines) + '\n' if lines else ''
    for fn in _EXPORT_SOURCES:
        try:
            extra = fn()
        except Exception:
            extra = ''
        if extra:
            out += extra if extra.endswith('\n') else extra + '\n'
    return out


def filter_ndjson(text: str, limit: int | None = None,
                  backend: str | None = None) -> str:
    """Filter an NDJSON span export by trace: keep only traces with at
    least one span attributed to `backend` (handshake/connect spans
    carry attrs.backend), then only the LAST `limit` traces — newest
    claims are what an operator chasing a flagged backend wants. With
    neither filter the text passes through untouched (the default
    /kang/traces behaviour, byte-identical to the pre-filter surface).
    Whole traces are kept or dropped; span lines are never split up."""
    if not text or (limit is None and backend is None):
        return text
    groups: dict = {}
    order: list = []
    matched: set = set()
    for line in text.splitlines():
        if not line.strip():
            continue
        try:
            span = json.loads(line)
            tid = span.get('trace_id')
        except ValueError:
            tid = None
        if tid is None:
            continue
        if tid not in groups:
            groups[tid] = []
            order.append(tid)
        groups[tid].append(line)
        attrs = span.get('attrs')
        if backend is not None and isinstance(attrs, dict) and \
                attrs.get('backend') == backend:
            matched.add(tid)
    if backend is not None:
        order = [tid for tid in order if tid in matched]
    if limit is not None:
        limit = max(int(limit), 0)
        order = order[len(order) - limit:] if limit else []
    lines = [line for tid in order for line in groups[tid]]
    return '\n'.join(lines) + '\n' if lines else ''


# Identity of the current netsim scenario run (seed, name, schedule),
# attached by netsim.scenario so every export surface — summary(),
# the SIGUSR2 dump, kang snapshots — names the exact replayable run
# its numbers came from. Empty outside simulation.
_run_metadata: dict = {}


def set_run_metadata(meta: dict | None) -> None:
    global _run_metadata
    _run_metadata = dict(meta or {})


def get_run_metadata() -> dict:
    return dict(_run_metadata)


def summary() -> dict:
    runtime = _runtime
    if runtime is None:
        out = {'enabled': False}
    else:
        runtime._drain_native()
        out = {
            'enabled': True,
            'ring': len(runtime.tr_ring),
            'ring_size': runtime.tr_ring.maxlen,
            'sample_rate': runtime.tr_sample,
            'seen': runtime.tr_seen,
            'sampled': runtime.tr_sampled,
            'native': runtime.tr_native,
            'evicted': runtime.tr_evicted,
            'truncated': runtime.tr_truncated,
        }
        if runtime.tr_native:
            out['native_ring'] = dict(_native.trace_ring_stats())
    routers = _active_fleet_routers()
    if routers:
        out['shards'] = [r.snapshot() for r in routers]
    if _run_metadata:
        out['run'] = dict(_run_metadata)
    return out


def _active_fleet_routers() -> list:
    """Started FleetRouters, without importing the shard package until
    one could actually exist (it registers on start)."""
    import sys
    mod = sys.modules.get('cueball_tpu.shard.router')
    if mod is None:
        return []
    return mod.active_routers()


def dump_traces(limit: int = 8) -> str:
    """Human-oriented section for the SIGUSR2 dump: the `limit` slowest
    completed traces with their per-span breakdown. '' when tracing is
    off or the ring is empty."""
    runtime = _runtime
    if runtime is None:
        return ''
    runtime._drain_native()
    if not runtime.tr_ring:
        return ''
    traces = sorted(runtime.tr_ring,
                    key=lambda t: t.root.duration() or 0.0,
                    reverse=True)[:limit]
    out = ['-- claim traces (%d slowest of %d in ring; '
           'sample_rate=%g) --' %
           (len(traces), len(runtime.tr_ring), runtime.tr_sample)]
    if runtime.tr_native:
        stats = _native.trace_ring_stats()
        out.append('  native ring: cap=%d pending=%d dropped=%d '
                   'highwater=%d truncated=%d' %
                   (stats['capacity'], stats['pending'],
                    stats['dropped'], stats['highwater'],
                    runtime.tr_truncated))
    for trace in traces:
        root = trace.root
        parts = ['%s=%.1f' % (name, ms)
                 for name, ms in sorted(trace.span_totals().items())]
        out.append('  %s %-10s %8.1fms %-9s %s' % (
            trace.trace_id[:8], root.name, root.duration() or 0.0,
            root.attrs.get('outcome', '?'), ' '.join(parts)))
    return '\n'.join(out) + '\n'

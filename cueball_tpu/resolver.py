"""Resolvers: service discovery for pools and sets.

Rebuild of reference `lib/resolver.js`. Three pieces:

- :class:`ResolverFSM` — the thin public 5-state wrapper
  (stopped/starting/running/failed/stopping) over an inner resolver that
  emits ``updated(err?)`` and ``added``/``removed``
  (reference lib/resolver.js:66-150; exported "for testing only" there,
  and used by the static resolver and test fixtures).
- :class:`StaticIpResolver` — emits a fixed backend list once on start
  (reference lib/resolver.js:1380-1456).
- :class:`DNSResolver` — full DNS SRV→AAAA→A service-discovery machine
  with TTL-driven refresh (reference lib/resolver.js:152-1377); defined
  in dns_resolver.py and re-exported here.

Plus the backend-identity hash (srv_key, reference lib/resolver.js:1157-1171),
DNS error types (lib/resolver.js:1173-1208), and the
``resolver_for_ip_or_domain`` user-input factory (lib/resolver.js:1459-1573).

Resolver interface contract (reference docs/api.adoc:354-453): methods
``start() stop() count() list() getLastError()``; events ``added(key,
backend)``, ``removed(key)``; FSM states stopped→starting→running⇄failed.
"""

from __future__ import annotations

import base64
import hashlib
import ipaddress
import logging

from . import utils as mod_utils
from .errors import CueBallError
from .events import EventEmitter
from .fsm import FSM


def _is_ip(s: str) -> int:
    """net.isIP equivalent: 4, 6 or 0."""
    try:
        addr = ipaddress.ip_address(s)
    except ValueError:
        return 0
    return addr.version


def srv_key(srv: dict) -> str:
    """Stable unique backend id: base64 SHA-1 of name||port||normalized-ip
    (reference lib/resolver.js:1157-1171). Used as the key in every
    resolver/pool/set backend map."""
    h = hashlib.sha1()
    h.update(str(srv['name']).encode())
    h.update(b'||')
    h.update(str(srv['port']).encode())
    h.update(b'||')
    addr = ipaddress.ip_address(srv['address'])
    if addr.version == 6:
        # ipaddr.js toNormalizedString(): all eight hextets, lowercase,
        # no zero-compression, no leading zeros ('2001:db8:0:0:0:0:0:1').
        norm = ':'.join('%x' % int(p, 16)
                        for p in addr.exploded.split(':'))
        h.update(norm.encode())
    else:
        h.update(str(addr).encode())
    return base64.b64encode(h.digest()).decode()


srvKey = srv_key


# ---------------------------------------------------------------------------
# DNS lookup error types (reference lib/resolver.js:1173-1208)

class NoNameError(CueBallError):
    """NXDOMAIN: the name does not exist."""

    def __init__(self, name: str, cause=None):
        self.dns_name = name
        super().__init__('No records returned for name %s' % name, cause)


class NoRecordsError(CueBallError):
    """NODATA: name exists but has no records of this type; carries the
    SOA minimum TTL when known so re-checks can be scheduled."""

    def __init__(self, name: str, rtype: str, ttl=None):
        self.dns_name = name
        self.dns_type = rtype
        self.ttl = ttl
        super().__init__('No records returned for name %s of type %s' % (
            name, rtype))


class TimeoutError_(CueBallError):
    """All nameservers timed out for this lookup."""

    def __init__(self, name: str):
        self.dns_name = name
        super().__init__(
            'Timeout while contacting resolvers for name %s' % name)


# ---------------------------------------------------------------------------
# Public wrapper FSM (reference lib/resolver.js:66-150)

class ResolverFSM(FSM):
    """Wraps an inner resolver (EventEmitter with start/stop/count/list
    emitting 'updated'/'added'/'removed') in the public 5-state resolver
    contract."""

    def __init__(self, inner, options: dict | None = None):
        options = options or {}
        self.r_fsm = inner
        self.r_last_error = None
        self.r_log = mod_utils.make_child_logger(
            options.get('log') or logging.getLogger('cueball.resolver'),
            component='CueBallResolver')
        super().__init__('stopped')
        # Always-on forwarding, independent of wrapper state
        # (reference lib/resolver.js:72-73).
        inner.on('added', lambda key, backend:
                 self.emit('added', key, backend))
        inner.on('removed', lambda key: self.emit('removed', key))

    # -- public interface ------------------------------------------------

    def start(self) -> None:
        self.emit('startAsserted')

    def stop(self) -> None:
        self.emit('stopAsserted')

    def count(self) -> int:
        return self.r_fsm.count()

    def list(self) -> dict:
        return self.r_fsm.list()

    def get_last_error(self):
        return self.r_last_error

    getLastError = get_last_error

    # -- states ----------------------------------------------------------

    def state_stopped(self, S):
        S.validTransitions(['starting'])
        S.goto_state_on(self, 'startAsserted', 'starting')

    def state_starting(self, S):
        S.validTransitions(['failed', 'running', 'stopping'])
        # Listener registered before start(): the reference relies on
        # inner resolvers deferring their 'updated' emission
        # (lib/resolver.js:113-116 starts first), but an inner that
        # emits synchronously from start() must not be missed.
        def on_updated(err=None):
            if err:
                self.r_last_error = err
                S.gotoState('failed')
            else:
                S.gotoState('running')
        S.on(self.r_fsm, 'updated', on_updated)
        S.goto_state_on(self, 'stopAsserted', 'stopping')
        self.r_fsm.start()

    def state_running(self, S):
        S.validTransitions(['stopping'])
        S.goto_state_on(self, 'stopAsserted', 'stopping')

    def state_failed(self, S):
        S.validTransitions(['running', 'stopping'])

        def on_updated(err=None):
            if not err:
                S.gotoState('running')
        S.on(self.r_fsm, 'updated', on_updated)
        S.goto_state_on(self, 'stopAsserted', 'stopping')

    def state_stopping(self, S):
        S.validTransitions(['stopped'])
        self.r_fsm.stop()
        S.immediate(lambda: S.gotoState('stopped'))


# ---------------------------------------------------------------------------
# Static IP resolver (reference lib/resolver.js:1380-1456)

class _StaticInner(EventEmitter):
    def __init__(self, options: dict):
        super().__init__()
        if not isinstance(options, dict):
            raise AssertionError('options must be a dict')
        default_port = options.get('defaultPort')
        if default_port is not None and not isinstance(default_port, int):
            raise AssertionError('options.defaultPort must be a number')
        backends = options.get('backends')
        if not isinstance(backends, list) or \
                not all(isinstance(b, dict) for b in backends):
            raise AssertionError('options.backends must be a list of dicts')

        self.sr_backends = []
        for i, backend in enumerate(backends):
            addr = backend.get('address')
            if not isinstance(addr, str):
                raise AssertionError(
                    'options.backends[%d].address must be a string' % i)
            if _is_ip(addr) == 0:
                raise AssertionError(
                    'options.backends[%d].address must be an IP address' % i)
            port = backend.get('port')
            if port is None:
                port = default_port
            if not isinstance(port, int) or isinstance(port, bool):
                raise AssertionError(
                    'options.backends[%d].port must be a number' % i)
            self.sr_backends.append({
                'name': '%s:%d' % (addr, port),
                'address': addr,
                'port': port,
            })
        self.sr_state = 'idle'

    def start(self) -> None:
        if self.sr_state != 'idle':
            raise AssertionError(
                'cannot call start() again without calling stop()')
        self.sr_state = 'started'

        def emit_all():
            for be in self.sr_backends:
                self.emit('added', srv_key(be), be)
            self.emit('updated')
        from .runq import defer
        defer(emit_all)

    def stop(self) -> None:
        if self.sr_state != 'started':
            raise AssertionError(
                'cannot call stop() again without calling start()')
        self.sr_state = 'idle'

    def count(self) -> int:
        return len(self.sr_backends)

    def list(self) -> dict:
        return {srv_key(be): be for be in self.sr_backends}


def StaticIpResolver(options: dict) -> ResolverFSM:
    """Build a resolver that emits a fixed IP list once on start().

    Mirrors the reference's constructor-returns-wrapper pattern
    (lib/resolver.js:1413): you get a ResolverFSM whose inner resolver is
    the static list."""
    return ResolverFSM(_StaticInner(options), options)


# ---------------------------------------------------------------------------
# User-input factory (reference lib/resolver.js:1459-1573)

def parse_ip_or_domain(s: str):
    """Parse 'HOSTNAME[:PORT]' into a resolver spec, or return (not raise)
    an Error for well-formed-but-invalid input
    (reference lib/resolver.js:1530-1573)."""
    if not isinstance(s, str):
        raise AssertionError('input must be a string')
    colon = s.rfind(':')
    if colon == -1:
        first = s
        port = None
    else:
        first = s[:colon]
        try:
            port = int(s[colon + 1:], 10)
        except ValueError:
            return ValueError('unsupported port in input: ' + s)
        if port < 0 or port > 65535:
            return ValueError('unsupported port in input: ' + s)

    ret = {}
    if _is_ip(first) == 0:
        ret['kind'] = 'dns'
        ret['cons'] = DNSResolver
        ret['config'] = {'domain': first}
        if port is not None:
            ret['config']['defaultPort'] = port
    else:
        ret['kind'] = 'static'
        ret['cons'] = StaticIpResolver
        ret['config'] = {'backends': [{'address': first, 'port': port}]}
    return ret


def config_for_ip_or_domain(args: dict):
    """Merge user resolverConfig with the parsed spec
    (reference lib/resolver.js:1502-1528)."""
    if not isinstance(args, dict):
        raise AssertionError('args must be a dict')
    if not isinstance(args.get('input'), str):
        raise AssertionError('args.input must be a string')
    rconfig = args.get('resolverConfig')
    if rconfig is not None and not isinstance(rconfig, dict):
        raise AssertionError('args.resolverConfig must be a dict')

    rcfg = dict(rconfig or {})
    spec = parse_ip_or_domain(args['input'])
    if isinstance(spec, Exception):
        return spec
    rcfg.update(spec['config'])
    spec['mergedConfig'] = rcfg
    return spec


def resolver_for_ip_or_domain(args: dict):
    """Build the right resolver (static for IPs, DNS otherwise) from a
    user-supplied 'HOSTNAME[:PORT]' string; returns an Error instance on
    invalid input (reference lib/resolver.js:1485-1500)."""
    spec = config_for_ip_or_domain(args)
    if isinstance(spec, Exception):
        return spec
    return spec['cons'](spec['mergedConfig'])


def pool_resolver(host: str, port: int, *, service: str,
                  recovery: dict, resolvers=None, log=None,
                  max_dns_concurrency: int = 3):
    """The default per-pool resolver, constructed the way the agent
    does it (reference lib/agent.js:117-139) — shared by the agent and
    the httpx/aiohttp integration layers so resolver configuration has
    one owner. Raises (rather than returns) on invalid host input."""
    res = resolver_for_ip_or_domain({
        'input': '%s:%d' % (host, port),
        'resolverConfig': {
            'resolvers': resolvers,
            'service': service,
            'maxDNSConcurrency': max_dns_concurrency,
            'recovery': recovery,
            'log': log,
        }})
    if isinstance(res, Exception):
        raise res
    return res


resolverForIpOrDomain = resolver_for_ip_or_domain
configForIpOrDomain = config_for_ip_or_domain
parseIpOrDomain = parse_ip_or_domain


# DNSResolver lives in its own module (the largest single component,
# reference lib/resolver.js:152-1377); import at the bottom to avoid a
# cycle (dns_resolver imports srv_key and error types from here).
from .dns_resolver import DNSResolver  # noqa: E402

# Pre-0.4 compatibility naming: the public "Resolver" IS the DNS resolver
# (reference lib/resolver.js:9-13).
Resolver = DNSResolver

"""The byte-moving seam: every real socket the framework touches.

Before this module, raw asyncio socket plumbing was scattered across
five call sites — the DNS wire client opened datagram endpoints and
TCP streams itself (dns_client.py), the HTTP agent called
``loop.create_connection`` and set keep-alive sockopts (agent.py), the
kang debug server called ``asyncio.start_server`` (http_server.py),
the pool monitor read the host ident straight off the socket module
(monitor.py), and netsim substituted each seam ad hoc. Following the
policy/data-path separation of "An Extensible Software Transport
Layer for GPU Networking" (PAPERS.md), the protocol decisions stay
where they were (sans-io cores: ``dns_client.DnsQueryCore``, the FSM
engines, the HTTP parsers) and everything that actually moves bytes
lands here, behind one ``Transport`` interface:

- :class:`AsyncioTransport` — the default; today's behavior, and the
  ONE place in the package (outside ``netsim/``) allowed to import
  ``socket`` or touch loop socket APIs (``make check`` enforces this
  via the cblint C110 layering rule).
- :class:`FabricTransport` — netsim's virtual data plane as a
  transport: the pool constructor seam is ``fabric.constructor``, the
  DNS seam is a ``SimWire``; no real socket exists anywhere. The
  parity gate (tests/test_transport_parity.py) runs the full pool and
  cset soaks on both transports and pins byte-identical FSM
  transition traces plus matching phase ledgers.
- :class:`NativeTransport` — the stub surface a ``native/`` C
  transport plugs into next: the method set IS the plug-in contract.

Pool/FSM semantics do not live here and do not move: a transport
supplies connections, streams, servers and DNS byte exchanges; who
claims what, when, is the pool's business. See docs/transport.md.
"""

from __future__ import annotations

import asyncio
import socket as mod_socket
import struct

from . import utils as mod_utils
from . import wiretap as mod_wiretap
from .errors import TransportNotAvailableError
from .events import EventEmitter

#: The five seam method names, in wiretap display order. This tuple
#: and wiretap.SEAMS are the same contract stated twice — cbflow rule
#: A006 (make check) fails if they drift from each other or from the
#: Transport class's actual method set.
SEAM_METHODS = ('connector', 'create_stream', 'serve', 'dns_udp',
                'dns_tcp')


class Transport:
    """Abstract byte-mover. Subclasses implement the five seams:

    - ``connector(backend)`` — the pool/cset ``options['constructor']``
      fallback: build one connection-contract object (emits
      'connect'/'error'/'close', has destroy/ref/unref) for a backend.
    - ``create_stream(...)`` — one outbound stream (the HTTP agent's
      socket seam); returns ``(transport, protocol)``.
    - ``serve(...)`` — one listening server (the kang debug endpoint).
    - ``dns_udp`` / ``dns_tcp`` — one DNS byte exchange: payload out,
      raw response bytes back (the sans-io ``DnsQueryCore`` decides
      what the bytes mean).
    - ``host_ident()`` — the identity stamped on kang snapshots.
    """

    name = 'abstract'
    #: False on registered-but-stubbed backends (the native stub):
    #: get_transport refuses them at resolution time with
    #: TransportNotAvailableError instead of letting the first I/O
    #: blow up deep inside a pool.
    available = True

    # -- pool constructor seam -------------------------------------------

    def connector(self, backend: dict):
        raise NotImplementedError(
            '%s does not supply pool connections' % type(self).__name__)

    # -- stream seam ------------------------------------------------------

    async def create_stream(self, protocol_factory, host, port,
                            ssl=None, server_hostname=None):
        raise NotImplementedError(
            '%s does not open streams' % type(self).__name__)

    def configure_keepalive(self, stream_transport,
                            delay_ms: float | None = None) -> int | None:
        """Enable TCP keep-alive on an established stream; returns the
        local port when one exists (None on non-socket transports)."""
        return None

    # -- server seam ------------------------------------------------------

    async def serve(self, client_connected_cb, host, port):
        raise NotImplementedError(
            '%s does not listen' % type(self).__name__)

    # -- DNS wire seam ----------------------------------------------------

    async def dns_udp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        raise NotImplementedError(
            '%s does not move DNS datagrams' % type(self).__name__)

    async def dns_tcp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        raise NotImplementedError(
            '%s does not move DNS streams' % type(self).__name__)

    # -- identity ---------------------------------------------------------

    def host_ident(self) -> str:
        return mod_socket.gethostname()


class WatchedStreamProtocol(asyncio.StreamReaderProtocol):
    """StreamReaderProtocol that reports connection loss to an owner
    even while the stream sits idle in a pool. Node's net.Socket emits
    'close' on FIN regardless of reads; plain asyncio streams only
    surface EOF at the next read, which would leave dead idle
    connections undetected until claimed. The owner implements
    ``_on_connection_lost(exc)``."""

    def __init__(self, reader, owner, loop):
        super().__init__(reader, loop=loop)
        self._owner = owner
        # Wire-ledger hooks: connection_made stamps the kernel
        # readiness time (the wiretap socket_wait decomposition reads
        # it as the kernel_wait/loop_dispatch boundary); _wt_stats is
        # a SeamStats fed per data_received, or None when wiretap is
        # off (one attribute load + None check per read).
        self._wt_stats = None
        self._wt_ready = None

    def connection_made(self, transport):
        self._wt_ready = mod_utils.current_millis()
        super().connection_made(transport)

    def data_received(self, data):
        st = self._wt_stats
        if st is not None:
            st.reads += 1
            st.bytes_in += len(data)
        super().data_received(data)

    def eof_received(self):
        super().eof_received()
        # Close on FIN rather than lingering half-open (node's
        # allowHalfOpen=false default) so connection_lost fires and
        # the pool learns the backend hung up.
        return False

    def connection_lost(self, exc):
        super().connection_lost(exc)
        self._owner._on_connection_lost(exc)


class TcpStreamConnection(EventEmitter):
    """Connection-contract object over a transport stream: the default
    ``AsyncioTransport.connector`` product, and the real-socket twin
    of netsim's SimConnection (the parity soaks run one pool on each).
    Emits 'connect' once the stream is up, 'error'/'close' on loss;
    ``reader``/``writer`` are live after 'connect'."""

    def __init__(self, transport: Transport, backend: dict):
        super().__init__()
        self.transport = transport
        self.backend = backend
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None
        self.destroyed = False
        # (kernel-ready, dispatched) wire marks for the wiretap
        # socket_wait decomposition; stamped by _connect. wt_transport
        # is the ledger label connection_fsm uses for wire records.
        self.wt_marks = None
        self.wt_transport = transport.name
        self._task = asyncio.ensure_future(self._connect())

    def _on_connection_lost(self, exc):
        if self.destroyed:
            return
        if exc is not None:
            self.emit('error', exc)
        else:
            self.emit('close')

    async def _connect(self):
        try:
            loop = asyncio.get_running_loop()
            reader = asyncio.StreamReader(loop=loop)
            # Pool connects account to the 'connector' seam, so route
            # around the instrumented create_stream wrapper when the
            # transport has the raw opener (otherwise every pool
            # connect would double-count as a create_stream event).
            st = mod_wiretap.seam_stats(self.transport.name,
                                        'connector')
            opener = getattr(self.transport, '_open_stream', None)
            if opener is None:
                opener = self.transport.create_stream

            def factory():
                proto = WatchedStreamProtocol(reader, self, loop)
                proto._wt_stats = st
                return proto

            stream, protocol = await opener(
                factory, self.backend['address'], self.backend['port'])
            ready = getattr(protocol, '_wt_ready', None)
            if ready is not None:
                self.wt_marks = (ready, mod_utils.current_millis())
            self.reader = reader
            self.writer = asyncio.StreamWriter(
                stream, protocol, reader, loop)
            if st is not None:
                mod_wiretap.instrument_writer(st, self.writer)
            self.emit('connect')
        except OSError as e:
            # No direct error count here: the connector seam's watch()
            # listeners count the 'error' emit (same path netsim's
            # SimConnection takes), keeping the two backends' ledgers
            # comparable.
            self.emit('error', e)
        except asyncio.CancelledError:
            pass

    def destroy(self):
        self.destroyed = True
        if self.writer is not None:
            self.writer.close()
        elif not self._task.done():
            self._task.cancel()

    def ref(self):
        pass

    def unref(self):
        pass


class _UdpQuery(asyncio.DatagramProtocol):
    """One-shot DNS datagram exchange. Datagrams whose transaction ID
    doesn't match the query are dropped: qid randomization is the
    anti-spoofing entropy and is useless unless checked on receive."""

    def __init__(self, fut: asyncio.Future, qid: int):
        self.fut = fut
        self.qid = qid

    def datagram_received(self, data, addr):
        if len(data) < 2 or \
                struct.unpack('>H', data[:2])[0] != self.qid:
            return
        if not self.fut.done():
            self.fut.set_result(data)

    def error_received(self, exc):
        if not self.fut.done():
            self.fut.set_exception(exc)


class AsyncioTransport(Transport):
    """The default transport: real sockets on the running asyncio
    loop. All raw plumbing formerly inlined in dns_client.query_udp /
    query_tcp, agent.HttpSocket._connect and http_server.serve_monitor
    lives here now."""

    name = 'asyncio'

    def connector(self, backend: dict) -> TcpStreamConnection:
        conn = TcpStreamConnection(self, backend)
        st = mod_wiretap.seam_stats(self.name, 'connector')
        if st is not None:
            st.events += 1
            mod_wiretap.watch(st, conn)
        return conn

    async def create_stream(self, protocol_factory, host, port,
                            ssl=None, server_hostname=None):
        st = mod_wiretap.seam_stats(self.name, 'create_stream')
        if st is not None:
            st.events += 1
        try:
            result = await self._open_stream(
                protocol_factory, host, port, ssl=ssl,
                server_hostname=server_hostname)
        except OSError:
            if st is not None:
                st.errors += 1
            raise
        if st is not None:
            st.connects += 1
        return result

    async def _open_stream(self, protocol_factory, host, port,
                           ssl=None, server_hostname=None):
        """The raw opener behind create_stream: same signature, no
        wiretap accounting (the connector seam uses it so pool
        connects land in their own ledger row)."""
        loop = asyncio.get_running_loop()
        kwargs = {}
        if ssl is not None:
            kwargs['ssl'] = ssl
            kwargs['server_hostname'] = server_hostname
        return await loop.create_connection(
            protocol_factory, host, port, **kwargs)

    def configure_keepalive(self, stream_transport,
                            delay_ms: float | None = None) -> int | None:
        sock = stream_transport.get_extra_info('socket')
        if sock is None:
            return None
        # Keep-alive is always on (reference lib/agent.js:52,188-191);
        # the optional delay maps to TCP_KEEPIDLE.
        sock.setsockopt(mod_socket.SOL_SOCKET,
                        mod_socket.SO_KEEPALIVE, 1)
        if delay_ms is not None and hasattr(mod_socket, 'TCP_KEEPIDLE'):
            sock.setsockopt(mod_socket.IPPROTO_TCP,
                            mod_socket.TCP_KEEPIDLE,
                            max(1, int(delay_ms / 1000)))
        return sock.getsockname()[1]

    async def serve(self, client_connected_cb, host, port):
        st = mod_wiretap.seam_stats(self.name, 'serve')
        if st is not None:
            st.events += 1
            inner_cb = client_connected_cb

            def client_connected_cb(reader, writer):
                st.connects += 1
                return inner_cb(reader, writer)

        return await asyncio.start_server(
            client_connected_cb, host, port)

    async def dns_udp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        st = mod_wiretap.seam_stats(self.name, 'dns_udp')
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        qid = struct.unpack('>H', payload[:2])[0]
        stream, _ = await loop.create_datagram_endpoint(
            lambda: _UdpQuery(fut, qid), remote_addr=(resolver, port))
        if st is not None:
            st.events += 1
            st.writes += 1
            st.bytes_out += len(payload)
        try:
            stream.sendto(payload)
            data = await asyncio.wait_for(fut, timeout_s)
        except Exception:
            if st is not None:
                st.errors += 1
            raise
        finally:
            stream.close()
        if st is not None:
            st.reads += 1
            st.bytes_in += len(data)
        return data

    async def dns_tcp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        st = mod_wiretap.seam_stats(self.name, 'dns_tcp')
        if st is not None:
            st.events += 1
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(resolver, port), timeout_s)
        except Exception:
            if st is not None:
                st.errors += 1
            raise
        if st is not None:
            st.connects += 1
        try:
            writer.write(struct.pack('>H', len(payload)) + payload)
            await writer.drain()
            if st is not None:
                st.writes += 1
                st.bytes_out += len(payload) + 2
            ln = struct.unpack('>H', await asyncio.wait_for(
                reader.readexactly(2), timeout_s))[0]
            body = await asyncio.wait_for(
                reader.readexactly(ln), timeout_s)
            if st is not None:
                st.reads += 2
                st.bytes_in += ln + 2
            return body
        except Exception:
            if st is not None:
                st.errors += 1
            raise
        finally:
            writer.close()


class FabricTransport(Transport):
    """netsim's virtual data plane as a transport. ``fabric`` is a
    ``cueball_tpu.netsim.Fabric`` (duck-typed — this module never
    imports netsim); ``wire`` is an optional ``SimWire``-shaped DNS
    byte mover. No real socket exists anywhere: connections are
    SimConnections on virtual timers, so the same pool workload runs
    byte-identically from a seed."""

    name = 'fabric'

    def __init__(self, fabric, wire=None, ident: str = 'netsim'):
        self.fabric = fabric
        self.wire = wire
        self._ident = ident

    def connector(self, backend: dict):
        conn = self.fabric.constructor(backend)
        st = mod_wiretap.seam_stats(self.name, 'connector')
        if st is not None:
            st.events += 1
            mod_wiretap.watch(st, conn)
        return conn

    async def dns_udp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        if self.wire is None:
            raise NotImplementedError(
                'FabricTransport has no SimWire attached')
        st = mod_wiretap.seam_stats(self.name, 'dns_udp')
        if st is not None:
            st.events += 1
            st.writes += 1
            st.bytes_out += len(payload)
        try:
            data = await self.wire.udp(resolver, port, payload,
                                       timeout_s)
        except Exception:
            if st is not None:
                st.errors += 1
            raise
        if st is not None:
            st.reads += 1
            st.bytes_in += len(data)
        return data

    async def dns_tcp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        if self.wire is None:
            raise NotImplementedError(
                'FabricTransport has no SimWire attached')
        st = mod_wiretap.seam_stats(self.name, 'dns_tcp')
        if st is not None:
            st.events += 1
        try:
            data = await self.wire.tcp(resolver, port, payload,
                                       timeout_s)
        except Exception:
            if st is not None:
                st.errors += 1
            raise
        if st is not None:
            # Mirror the asyncio seam's syscall-equivalent shape: one
            # framed write out, length-prefix + body reads back.
            st.connects += 1
            st.writes += 1
            st.bytes_out += len(payload) + 2
            st.reads += 2
            st.bytes_in += len(data) + 2
        return data

    def host_ident(self) -> str:
        return self._ident


class NativeTransport(Transport):
    """The plug-in surface for the C data path (native/transport, next
    PR): a registered-but-stubbed backend so the dispatch plumbing,
    the registry name, the docs contract and the wiretap conformance
    counters (trace.WIRE_EVENT_CODES) all exist before the first
    native byte moves. Every seam raises a typed
    :class:`TransportNotAvailableError` carrying the seam name, and
    ``available = False`` makes ``get_transport('native')`` refuse at
    resolution time rather than at first I/O; a real native module
    replaces this via :func:`register_transport`."""

    name = 'native'
    available = False

    def _unavailable(self, seam: str):
        raise TransportNotAvailableError(seam, transport=self.name)

    def connector(self, backend: dict):
        self._unavailable('connector')

    async def create_stream(self, protocol_factory, host, port,
                            ssl=None, server_hostname=None):
        self._unavailable('create_stream')

    async def serve(self, client_connected_cb, host, port):
        self._unavailable('serve')

    async def dns_udp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        self._unavailable('dns_udp')

    async def dns_tcp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        self._unavailable('dns_tcp')


# -- registry ---------------------------------------------------------------

_REGISTRY: dict = {'asyncio': AsyncioTransport, 'native': NativeTransport}
_default: Transport | None = None


def register_transport(name: str, factory) -> None:
    """Register a transport factory (a zero-arg callable returning a
    Transport) under ``name`` for ``get_transport(name)`` / the pool's
    ``options['transport']`` string form."""
    _REGISTRY[name] = factory


def get_transport(spec=None) -> Transport:
    """Resolve a transport: None -> the process-default
    AsyncioTransport singleton, a string -> the registry, a Transport
    instance -> itself."""
    global _default
    if spec is None:
        if _default is None:
            _default = AsyncioTransport()
        return _default
    if isinstance(spec, str):
        if spec == 'native' and _REGISTRY.get('native') is NativeTransport:
            # Upgrade the stub to the real C data plane lazily, the
            # first time anyone asks for it: native_transport imports
            # the extension and registers itself when the transport
            # symbols are present; otherwise the stub's typed
            # resolution refusal below stands.
            try:
                from . import native_transport as _nt
            except ImportError:
                _nt = None
            if _nt is not None and _nt.native_available():
                register_transport('native', _nt.RealNativeTransport)
        factory = _REGISTRY.get(spec)
        if factory is None:
            raise ValueError('unknown transport %r (registered: %s)' % (
                spec, ', '.join(sorted(_REGISTRY))))
        t = factory()
        if not getattr(t, 'available', True):
            # Fail at resolution time, not first I/O: a pool handed a
            # stub transport would otherwise come up healthy and die
            # on its first connect.
            raise TransportNotAvailableError('resolve',
                                             transport=t.name)
        return t
    if isinstance(spec, Transport):
        return spec
    raise TypeError('transport must be None, a name or a Transport, '
                    'got %r' % (spec,))


def host_ident() -> str:
    """The default transport's host identity (what monitor.py stamps
    on kang snapshots instead of touching the socket module)."""
    return get_transport().host_ident()


__all__ = ['Transport', 'AsyncioTransport', 'FabricTransport',
           'NativeTransport', 'TcpStreamConnection',
           'WatchedStreamProtocol', 'TransportNotAvailableError',
           'SEAM_METHODS', 'register_transport',
           'get_transport', 'host_ident']

"""The byte-moving seam: every real socket the framework touches.

Before this module, raw asyncio socket plumbing was scattered across
five call sites — the DNS wire client opened datagram endpoints and
TCP streams itself (dns_client.py), the HTTP agent called
``loop.create_connection`` and set keep-alive sockopts (agent.py), the
kang debug server called ``asyncio.start_server`` (http_server.py),
the pool monitor read the host ident straight off the socket module
(monitor.py), and netsim substituted each seam ad hoc. Following the
policy/data-path separation of "An Extensible Software Transport
Layer for GPU Networking" (PAPERS.md), the protocol decisions stay
where they were (sans-io cores: ``dns_client.DnsQueryCore``, the FSM
engines, the HTTP parsers) and everything that actually moves bytes
lands here, behind one ``Transport`` interface:

- :class:`AsyncioTransport` — the default; today's behavior, and the
  ONE place in the package (outside ``netsim/``) allowed to import
  ``socket`` or touch loop socket APIs (``make check`` enforces this
  via the cblint C110 layering rule).
- :class:`FabricTransport` — netsim's virtual data plane as a
  transport: the pool constructor seam is ``fabric.constructor``, the
  DNS seam is a ``SimWire``; no real socket exists anywhere. The
  parity gate (tests/test_transport_parity.py) runs the full pool and
  cset soaks on both transports and pins byte-identical FSM
  transition traces plus matching phase ledgers.
- :class:`NativeTransport` — the stub surface a ``native/`` C
  transport plugs into next: the method set IS the plug-in contract.

Pool/FSM semantics do not live here and do not move: a transport
supplies connections, streams, servers and DNS byte exchanges; who
claims what, when, is the pool's business. See docs/transport.md.
"""

from __future__ import annotations

import asyncio
import socket as mod_socket
import struct

from .events import EventEmitter


class Transport:
    """Abstract byte-mover. Subclasses implement the five seams:

    - ``connector(backend)`` — the pool/cset ``options['constructor']``
      fallback: build one connection-contract object (emits
      'connect'/'error'/'close', has destroy/ref/unref) for a backend.
    - ``create_stream(...)`` — one outbound stream (the HTTP agent's
      socket seam); returns ``(transport, protocol)``.
    - ``serve(...)`` — one listening server (the kang debug endpoint).
    - ``dns_udp`` / ``dns_tcp`` — one DNS byte exchange: payload out,
      raw response bytes back (the sans-io ``DnsQueryCore`` decides
      what the bytes mean).
    - ``host_ident()`` — the identity stamped on kang snapshots.
    """

    name = 'abstract'

    # -- pool constructor seam -------------------------------------------

    def connector(self, backend: dict):
        raise NotImplementedError(
            '%s does not supply pool connections' % type(self).__name__)

    # -- stream seam ------------------------------------------------------

    async def create_stream(self, protocol_factory, host, port,
                            ssl=None, server_hostname=None):
        raise NotImplementedError(
            '%s does not open streams' % type(self).__name__)

    def configure_keepalive(self, stream_transport,
                            delay_ms: float | None = None) -> int | None:
        """Enable TCP keep-alive on an established stream; returns the
        local port when one exists (None on non-socket transports)."""
        return None

    # -- server seam ------------------------------------------------------

    async def serve(self, client_connected_cb, host, port):
        raise NotImplementedError(
            '%s does not listen' % type(self).__name__)

    # -- DNS wire seam ----------------------------------------------------

    async def dns_udp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        raise NotImplementedError(
            '%s does not move DNS datagrams' % type(self).__name__)

    async def dns_tcp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        raise NotImplementedError(
            '%s does not move DNS streams' % type(self).__name__)

    # -- identity ---------------------------------------------------------

    def host_ident(self) -> str:
        return mod_socket.gethostname()


class WatchedStreamProtocol(asyncio.StreamReaderProtocol):
    """StreamReaderProtocol that reports connection loss to an owner
    even while the stream sits idle in a pool. Node's net.Socket emits
    'close' on FIN regardless of reads; plain asyncio streams only
    surface EOF at the next read, which would leave dead idle
    connections undetected until claimed. The owner implements
    ``_on_connection_lost(exc)``."""

    def __init__(self, reader, owner, loop):
        super().__init__(reader, loop=loop)
        self._owner = owner

    def eof_received(self):
        super().eof_received()
        # Close on FIN rather than lingering half-open (node's
        # allowHalfOpen=false default) so connection_lost fires and
        # the pool learns the backend hung up.
        return False

    def connection_lost(self, exc):
        super().connection_lost(exc)
        self._owner._on_connection_lost(exc)


class TcpStreamConnection(EventEmitter):
    """Connection-contract object over a transport stream: the default
    ``AsyncioTransport.connector`` product, and the real-socket twin
    of netsim's SimConnection (the parity soaks run one pool on each).
    Emits 'connect' once the stream is up, 'error'/'close' on loss;
    ``reader``/``writer`` are live after 'connect'."""

    def __init__(self, transport: Transport, backend: dict):
        super().__init__()
        self.transport = transport
        self.backend = backend
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None
        self.destroyed = False
        self._task = asyncio.ensure_future(self._connect())

    def _on_connection_lost(self, exc):
        if self.destroyed:
            return
        if exc is not None:
            self.emit('error', exc)
        else:
            self.emit('close')

    async def _connect(self):
        try:
            loop = asyncio.get_running_loop()
            reader = asyncio.StreamReader(loop=loop)
            stream, protocol = await self.transport.create_stream(
                lambda: WatchedStreamProtocol(reader, self, loop),
                self.backend['address'], self.backend['port'])
            self.reader = reader
            self.writer = asyncio.StreamWriter(
                stream, protocol, reader, loop)
            self.emit('connect')
        except OSError as e:
            self.emit('error', e)
        except asyncio.CancelledError:
            pass

    def destroy(self):
        self.destroyed = True
        if self.writer is not None:
            self.writer.close()
        elif not self._task.done():
            self._task.cancel()

    def ref(self):
        pass

    def unref(self):
        pass


class _UdpQuery(asyncio.DatagramProtocol):
    """One-shot DNS datagram exchange. Datagrams whose transaction ID
    doesn't match the query are dropped: qid randomization is the
    anti-spoofing entropy and is useless unless checked on receive."""

    def __init__(self, fut: asyncio.Future, qid: int):
        self.fut = fut
        self.qid = qid

    def datagram_received(self, data, addr):
        if len(data) < 2 or \
                struct.unpack('>H', data[:2])[0] != self.qid:
            return
        if not self.fut.done():
            self.fut.set_result(data)

    def error_received(self, exc):
        if not self.fut.done():
            self.fut.set_exception(exc)


class AsyncioTransport(Transport):
    """The default transport: real sockets on the running asyncio
    loop. All raw plumbing formerly inlined in dns_client.query_udp /
    query_tcp, agent.HttpSocket._connect and http_server.serve_monitor
    lives here now."""

    name = 'asyncio'

    def connector(self, backend: dict) -> TcpStreamConnection:
        return TcpStreamConnection(self, backend)

    async def create_stream(self, protocol_factory, host, port,
                            ssl=None, server_hostname=None):
        loop = asyncio.get_running_loop()
        kwargs = {}
        if ssl is not None:
            kwargs['ssl'] = ssl
            kwargs['server_hostname'] = server_hostname
        return await loop.create_connection(
            protocol_factory, host, port, **kwargs)

    def configure_keepalive(self, stream_transport,
                            delay_ms: float | None = None) -> int | None:
        sock = stream_transport.get_extra_info('socket')
        if sock is None:
            return None
        # Keep-alive is always on (reference lib/agent.js:52,188-191);
        # the optional delay maps to TCP_KEEPIDLE.
        sock.setsockopt(mod_socket.SOL_SOCKET,
                        mod_socket.SO_KEEPALIVE, 1)
        if delay_ms is not None and hasattr(mod_socket, 'TCP_KEEPIDLE'):
            sock.setsockopt(mod_socket.IPPROTO_TCP,
                            mod_socket.TCP_KEEPIDLE,
                            max(1, int(delay_ms / 1000)))
        return sock.getsockname()[1]

    async def serve(self, client_connected_cb, host, port):
        return await asyncio.start_server(
            client_connected_cb, host, port)

    async def dns_udp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        qid = struct.unpack('>H', payload[:2])[0]
        stream, _ = await loop.create_datagram_endpoint(
            lambda: _UdpQuery(fut, qid), remote_addr=(resolver, port))
        try:
            stream.sendto(payload)
            return await asyncio.wait_for(fut, timeout_s)
        finally:
            stream.close()

    async def dns_tcp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(resolver, port), timeout_s)
        try:
            writer.write(struct.pack('>H', len(payload)) + payload)
            await writer.drain()
            ln = struct.unpack('>H', await asyncio.wait_for(
                reader.readexactly(2), timeout_s))[0]
            return await asyncio.wait_for(
                reader.readexactly(ln), timeout_s)
        finally:
            writer.close()


class FabricTransport(Transport):
    """netsim's virtual data plane as a transport. ``fabric`` is a
    ``cueball_tpu.netsim.Fabric`` (duck-typed — this module never
    imports netsim); ``wire`` is an optional ``SimWire``-shaped DNS
    byte mover. No real socket exists anywhere: connections are
    SimConnections on virtual timers, so the same pool workload runs
    byte-identically from a seed."""

    name = 'fabric'

    def __init__(self, fabric, wire=None, ident: str = 'netsim'):
        self.fabric = fabric
        self.wire = wire
        self._ident = ident

    def connector(self, backend: dict):
        return self.fabric.constructor(backend)

    async def dns_udp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        if self.wire is None:
            raise NotImplementedError(
                'FabricTransport has no SimWire attached')
        return await self.wire.udp(resolver, port, payload, timeout_s)

    async def dns_tcp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        if self.wire is None:
            raise NotImplementedError(
                'FabricTransport has no SimWire attached')
        return await self.wire.tcp(resolver, port, payload, timeout_s)

    def host_ident(self) -> str:
        return self._ident


class NativeTransport(Transport):
    """The plug-in surface for the C data path (native/transport, next
    PR): a registered-but-stubbed backend so the dispatch plumbing,
    the registry name and the docs contract all exist before the
    first native byte moves. Every seam raises until the native module
    fills it in via :func:`register_transport`."""

    name = 'native'


# -- registry ---------------------------------------------------------------

_REGISTRY: dict = {'asyncio': AsyncioTransport, 'native': NativeTransport}
_default: Transport | None = None


def register_transport(name: str, factory) -> None:
    """Register a transport factory (a zero-arg callable returning a
    Transport) under ``name`` for ``get_transport(name)`` / the pool's
    ``options['transport']`` string form."""
    _REGISTRY[name] = factory


def get_transport(spec=None) -> Transport:
    """Resolve a transport: None -> the process-default
    AsyncioTransport singleton, a string -> the registry, a Transport
    instance -> itself."""
    global _default
    if spec is None:
        if _default is None:
            _default = AsyncioTransport()
        return _default
    if isinstance(spec, str):
        factory = _REGISTRY.get(spec)
        if factory is None:
            raise ValueError('unknown transport %r (registered: %s)' % (
                spec, ', '.join(sorted(_REGISTRY))))
        return factory()
    if isinstance(spec, Transport):
        return spec
    raise TypeError('transport must be None, a name or a Transport, '
                    'got %r' % (spec,))


def host_ident() -> str:
    """The default transport's host identity (what monitor.py stamps
    on kang snapshots instead of touching the socket module)."""
    return get_transport().host_ident()


__all__ = ['Transport', 'AsyncioTransport', 'FabricTransport',
           'NativeTransport', 'TcpStreamConnection',
           'WatchedStreamProtocol', 'register_transport',
           'get_transport', 'host_ident']

"""Support algorithms and validation helpers.

Rebuild of reference `lib/utils.js`:
- recovery-spec validation (lib/utils.js:116-186)
- randomized retry delay spread (lib/utils.js:446-461)
- monotonic millisecond clock (lib/utils.js:198-204)
- Fisher-Yates shuffle (lib/utils.js:207-217)
- the pure `planRebalance` pool planner (lib/utils.js:239-393)
- claim/release stack-trace gating (lib/utils.js:48-115)
- error-event metric helpers (lib/utils.js:29-46,395-444)
"""

from __future__ import annotations

import logging
import math
import random
import time
import traceback

from . import metrics as mod_metrics


# ---------------------------------------------------------------------------
# Contextual child loggers (the bunyan log.child analogue)
#
# The reference binds component/backend/localPort context into every log
# record via bunyan child loggers (reference lib/pool.js:152-157,
# lib/connection-fsm.js:149-155,913-918). The stdlib analogue is a
# LoggerAdapter: context rides on the record (record.cueball, for
# structured handlers) and is prefixed into the message (for plain
# formatters). Children of children merge their context.

class ContextLogger(logging.LoggerAdapter):
    def process(self, msg, kwargs):
        extra = kwargs.get('extra')
        if extra is None:
            kwargs['extra'] = extra = {}
        extra.setdefault('cueball', self.extra)
        if self.extra:
            ctx = ' '.join(
                '%s=%s' % (k, v) for k, v in self.extra.items())
            msg = '[%s] %s' % (ctx, msg)
        return msg, kwargs


def make_child_logger(log, **context):
    """Return a logger carrying `log`'s context plus `context`
    (reference bunyan log.child). Accepts a plain Logger, a
    ContextLogger, or None (falls back to the 'cueball' logger)."""
    if log is None:
        log = logging.getLogger('cueball')
    if isinstance(log, logging.LoggerAdapter):
        merged = dict(log.extra or {})
        merged.update(context)
        return ContextLogger(log.logger, merged)
    return ContextLogger(log, dict(context))

# ---------------------------------------------------------------------------
# assert-plus style validation

def _chk(cond: bool, msg: str) -> None:
    if not cond:
        raise AssertionError(msg)


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


# ---------------------------------------------------------------------------
# Error-event metrics (reference lib/utils.js:29-46,395-444)

METRIC_CUEBALL_EVENT_COUNTER = 'cueball_events'

# Whitelist of error events tracked by the shared counter; events outside
# this list are silently ignored (reference lib/utils.js:37-46).
METRIC_ERROR_EVENTS = frozenset([
    'timeout-during-connect',
    'error-during-connect',
    'close-during-connect',
    'error-while-connected',
    'retries-exhausted',
    'claim-timeout',
    'error-while-claimed',
    'failed-state',
])


def create_error_metrics(options: dict) -> 'mod_metrics.Collector':
    """Adopt options['collector'] or create one; idempotently declare the
    cueball_events counter (reference lib/utils.js:395-419)."""
    collector = options.get('collector')
    if collector is None:
        collector = mod_metrics.create_collector(
            labels={'component': 'cueball'})
    collector.counter(
        name=METRIC_CUEBALL_EVENT_COUNTER,
        help='Total number of cueball error events')
    return collector


def update_error_metrics(collector: 'mod_metrics.Collector', uuid: str,
                         err_str: str) -> None:
    """Count a whitelisted error event (reference lib/utils.js:421-444)."""
    if err_str not in METRIC_ERROR_EVENTS:
        return
    # Hostname for a metric label, not byte movement.
    import socket as mod_socket  # cblint: ignore=C110
    counter = collector.get_collector(METRIC_CUEBALL_EVENT_COUNTER)
    counter.increment({
        'hostname': mod_socket.gethostname(),
        'uuid': uuid,
        'type': 'error',
        'evt': err_str,
    })


# ---------------------------------------------------------------------------
# Stack-trace gating (reference lib/utils.js:48-115)
#
# Claim/release stack capture is off by default for performance; turn it on
# with enable_stack_traces() (the dtrace capture-stack probe analogue is a
# process-wide flag plus the FSM transition tracer hooks in fsm.py).

_STACK_TRACES_ENABLED = False


def enable_stack_traces() -> None:
    global _STACK_TRACES_ENABLED
    _STACK_TRACES_ENABLED = True


def disable_stack_traces() -> None:
    global _STACK_TRACES_ENABLED
    _STACK_TRACES_ENABLED = False


def stack_traces_enabled() -> bool:
    return _STACK_TRACES_ENABLED


_FAKE_STACK = ('Error\n at unknown (stack traces disabled)\n'
               ' at unknown (stack traces disabled)\n')


def maybe_capture_stack_trace() -> dict:
    """Return {'stack': str}; a real formatted stack when enabled, else a
    fixed two-frame placeholder (reference lib/utils.js:100-114)."""
    if _STACK_TRACES_ENABLED:
        return {'stack': ''.join(traceback.format_stack(limit=16))}
    return {'stack': _FAKE_STACK}


# ---------------------------------------------------------------------------
# Recovery-spec validation (reference lib/utils.js:116-186)

_RECOVERY_KEYS = frozenset([
    'retries', 'timeout', 'maxTimeout', 'delay', 'maxDelay', 'delaySpread'])

_DAY_MS = 1000 * 3600 * 24


def assert_recovery(obj, name: str | None = None) -> None:
    if name is None:
        name = 'recovery'
    _chk(isinstance(obj, dict), '%s must be a dict' % name)
    unknown = set(obj.keys()) - _RECOVERY_KEYS
    _chk(not unknown, '%s has unknown keys: %r' % (name, sorted(unknown)))

    _chk(_is_num(obj.get('retries')), '%s.retries must be a number' % name)
    _chk(math.isfinite(obj['retries']), '%s.retries must be finite' % name)
    _chk(obj['retries'] >= 0, '%s.retries must be >= 0' % name)

    _chk(_is_num(obj.get('timeout')), '%s.timeout must be a number' % name)
    _chk(math.isfinite(obj['timeout']), '%s.timeout must be finite' % name)
    _chk(obj['timeout'] > 0, '%s.timeout must be > 0' % name)

    max_timeout = obj.get('maxTimeout')
    if max_timeout is not None:
        _chk(_is_num(max_timeout), '%s.maxTimeout must be a number' % name)
        _chk(obj['timeout'] <= max_timeout,
             '%s.maxTimeout must be >= timeout' % name)

    _chk(_is_num(obj.get('delay')), '%s.delay must be a number' % name)
    _chk(math.isfinite(obj['delay']), '%s.delay must be finite' % name)
    _chk(obj['delay'] >= 0, '%s.delay must be >= 0' % name)

    max_delay = obj.get('maxDelay')
    if max_delay is not None:
        _chk(_is_num(max_delay), '%s.maxDelay must be a number' % name)
        _chk(obj['delay'] <= max_delay,
             '%s.maxDelay must be >= delay' % name)

    spread = obj.get('delaySpread')
    if spread is not None:
        _chk(_is_num(spread), '%s.delaySpread must be a number' % name)
        _chk(0.0 <= spread <= 1.0,
             '%s.delaySpread must be between 0.0 and 1.0' % name)

    # Exponential growth caps: with no explicit max, retries must be small
    # enough that delay * 2^retries stays under one day
    # (reference lib/utils.js:162-186).
    if max_delay is None:
        _chk(obj['retries'] < 32,
             '%s.maxDelay is required when retries >= 32' % name)
        _chk(obj['delay'] * (1 << int(obj['retries'])) < _DAY_MS,
             '%s.maxDelay is required with given values of retries and '
             'delay (effective unspecified maxDelay is > 1 day)' % name)
    if max_timeout is None:
        _chk(obj['retries'] < 32,
             '%s.maxTimeout is required when retries >= 32' % name)
        _chk(obj['timeout'] * (1 << int(obj['retries'])) < _DAY_MS,
             '%s.maxTimeout is required with given values of retries and '
             'timeout (effective unspecified maxTimeout is > 1 day)' % name)


def assert_recovery_set(obj) -> None:
    """Validate a map of operation-name -> recovery spec
    (reference lib/utils.js:116-122). Operation names are free-form; the
    framework looks up 'default', 'connect', 'initial', 'dns', 'dns_srv'."""
    _chk(isinstance(obj, dict), 'recovery must be a dict')
    for k, v in obj.items():
        assert_recovery(v, 'recovery.' + k)


def assert_claim_delay(delay) -> None:
    """Validate targetClaimDelay (reference lib/utils.js:188-196)."""
    if delay is None:
        return
    _chk(_is_num(delay), 'options.targetClaimDelay must be a number')
    _chk(math.isfinite(delay), 'options.targetClaimDelay must be finite')
    _chk(delay > 0, 'options.targetClaimDelay > 0')
    _chk(delay == int(delay), 'options.targetClaimDelay must be integral')


# ---------------------------------------------------------------------------
# Clock / randomness seams
#
# Every time read and every random draw the framework makes goes through
# these two process-wide injection points. The defaults are exactly the
# historical behaviour (time.monotonic/time.time and the global `random`
# module, so `random.seed()` still pins the stream the way
# tests/test_runq_conformance.py relies on). The netsim virtual-time
# fabric (cueball_tpu/netsim/) swaps in a VirtualClock plus a seeded
# random.Random so a scenario seed fully determines a run; see
# docs/netsim.md.

class SystemClock:
    """Default clock: real monotonic + wall time."""

    def monotonic(self) -> float:
        """Seconds, monotonic (time origin unspecified)."""
        return time.monotonic()

    def wall(self) -> float:
        """Seconds since the epoch (time.time)."""
        return time.time()


_clock = SystemClock()
_rng = random  # module default: the global `random` stream


# Consumers that cache clock-derived state (the native trace recorder
# reads CLOCK_MONOTONIC directly unless a virtual clock is installed)
# register here to be told when the clock seam changes.
_clock_hooks: list = []


def add_clock_hook(fn) -> None:
    """Call ``fn(clock)`` after every set_clock(); idempotent."""
    if fn not in _clock_hooks:
        _clock_hooks.append(fn)


def remove_clock_hook(fn) -> None:
    try:
        _clock_hooks.remove(fn)
    except ValueError:
        pass


def set_clock(clock) -> object:
    """Install a process-wide clock (an object with .monotonic() and
    .wall(), both in seconds); returns the previous clock so callers
    can restore it in a finally block."""
    global _clock
    old = _clock
    _clock = clock
    for fn in list(_clock_hooks):
        fn(clock)
    return old


def get_clock():
    return _clock


def set_rng(rng) -> object:
    """Install the process-wide RNG (random.Random-compatible: random /
    randrange / getrandbits / shuffle); returns the previous one. All
    framework randomness — backoff jitter, pool preference inserts,
    DNS resolver shuffle and qid draws, trace ids — flows through
    this seam."""
    global _rng
    old = _rng
    _rng = rng
    return old


def get_rng():
    return _rng


def make_uuid() -> str:
    """A random version-4 UUID string drawn from the RNG seam, so
    pool/set/resolver identities are reproducible under netsim's
    seeded runs (uuid.uuid4() would read os.urandom and make
    otherwise-deterministic trace exports differ run to run)."""
    bits = _rng.getrandbits(128)
    bits = (bits & ~(0xf << 76)) | (0x4 << 76)       # version 4
    bits = (bits & ~(0x3 << 62)) | (0x2 << 62)       # RFC 4122 variant
    h = '%032x' % bits
    return '%s-%s-%s-%s-%s' % (h[:8], h[8:12], h[12:16], h[16:20],
                               h[20:])


def current_millis() -> float:
    """Monotonic time in milliseconds (reference lib/utils.js:198-204),
    read through the pluggable clock seam."""
    return _clock.monotonic() * 1000.0


def wall_time() -> float:
    """Epoch seconds through the pluggable clock seam (the `time.time()`
    every scheduling deadline in the framework uses)."""
    return _clock.wall()


def shuffle(array: list) -> list:
    """In-place Fisher-Yates shuffle (reference lib/utils.js:207-217)."""
    i = len(array)
    while i > 0:
        j = _rng.randrange(i)
        i -= 1
        array[i], array[j] = array[j], array[i]
    return array


def gen_delay(recov_or_delay, spread: float | None = None) -> int:
    """Randomized retry delay: base * (1 - spread/2 + U(0,1)*spread), i.e.
    uniformly within +/- spread/2 of base; default spread 0.2. Decorrelates
    retry herds across clients (reference lib/utils.js:446-461)."""
    base = recov_or_delay
    if isinstance(recov_or_delay, dict) and spread is None:
        base = recov_or_delay['delay']
        spread = recov_or_delay.get('delaySpread')
    _chk(_is_num(base), 'base delay must be a number')
    if spread is None:
        spread = 0.2
    return round(base * (1 - spread / 2.0 + _rng.random() * spread))


delay = gen_delay


# ---------------------------------------------------------------------------
# planRebalance (reference lib/utils.js:219-393)

def plan_rebalance(connections: dict, dead: dict, target: int, max_: int,
                   singleton: bool = False) -> dict:
    """Pure pool-balance planner.

    Given the current {backend_key: [connection, ...]} map, the dead-backend
    map, the target connection count and the max cap, compute a plan:
    {'add': [backend_key, ...], 'remove': [connection, ...]}.

    Semantics (reference lib/utils.js:239-366, behaviour pinned by the
    test table in reference test/utils.test.js):
    - Want `target` connections spread round-robin over backends in
      preference order (the order of `connections` keys).
    - A dead backend encountered during allocation gets exactly one probe
      connection, and a replacement allocation is queued for each slot it
      would have filled.
    - Replacements round-robin too; a replacement landing on another dead
      backend can itself be replaced, but only while under `max_`, and the
      planner guarantees every backend is tried at least once before
      double-allocating (starvation guard).
    - `singleton` mode (ConnectionSet): at most one connection per backend.
    """
    _chk(isinstance(connections, dict), 'connections must be a dict')
    _chk(_is_num(target), 'target must be a number')
    _chk(_is_num(max_), 'max must be a number')
    _chk(target >= 0, 'target must be >= 0')
    _chk(max_ >= target, 'max must be >= target')

    keys = list(connections.keys())
    wanted: dict[str, int] = {}
    plan = {'add': [], 'remove': []}

    # Pass 1: allocate `target` slots round-robin; dead backends get one
    # probe each and accrue replacement credits.
    done = 0
    replacements = 0
    for _ in range(int(target)):
        if not keys:
            break
        k = keys.pop(0)
        keys.append(k)
        if k not in wanted:
            wanted[k] = 0
        if dead.get(k) is not True:
            if singleton:
                if wanted[k] == 0:
                    wanted[k] = 1
                    done += 1
            else:
                wanted[k] += 1
                done += 1
            continue
        if wanted[k] == 0:
            wanted[k] = 1
            done += 1
        replacements += 1

    # Apply the max cap to replacement credits.
    if done + replacements > max_:
        replacements = int(max_) - done

    # Pass 2: allocate replacements round-robin with the cap-aware
    # starvation guard (reference lib/utils.js:296-366).
    i = 0
    while i < replacements:
        if not keys:
            break
        k = keys.pop(0)
        keys.append(k)
        if k not in wanted:
            wanted[k] = 0
        if dead.get(k) is not True:
            if singleton:
                if wanted[k] == 0:
                    wanted[k] = 1
                    done += 1
                    i += 1
                    continue
            else:
                wanted[k] += 1
                done += 1
                i += 1
                continue

        count = done + replacements - i
        if singleton:
            empties = [kk for kk in keys
                       if dead.get(kk) is not True and kk not in wanted]
        else:
            empties = [kk for kk in keys
                       if dead.get(kk) is not True or kk not in wanted]

        if count + 1 <= max_:
            # Room for both this probe and a further replacement.
            if wanted[k] == 0:
                wanted[k] = 1
                done += 1
            if empties:
                replacements += 1
        elif count <= max_ and empties:
            # Room for only one, but a possibly-live candidate exists:
            # spend the slot there instead.
            replacements += 1
        elif count <= max_:
            # Room for one and everything looks dead: probe this one.
            if wanted[k] == 0:
                wanted[k] = 1
                done += 1
        else:
            break
        i += 1

    # Diff wanted vs. actual. Removals walk backends in reverse preference
    # order and shed the oldest connections first; additions walk in
    # preference order (reference lib/utils.js:368-391).
    rev = list(connections.keys())[::-1]
    for key in rev:
        have = len(connections.get(key) or [])
        want = wanted.get(key, 0)
        lst = list(connections[key])
        while have > want:
            plan['remove'].append(lst.pop(0))
            have -= 1
    for key in connections.keys():
        have = len(connections.get(key) or [])
        want = wanted.get(key, 0)
        while have < want:
            plan['add'].append(key)
            have += 1

    return plan


planRebalance = plan_rebalance

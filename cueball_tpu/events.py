"""Synchronous event emitter.

The reference is built on Node's EventEmitter contract: synchronous
delivery in registration order, `once` auto-removal, listener
introspection for the claim-handle leak detector
(reference lib/connection-fsm.js:786-808 counts listeners by function
identity). This is a minimal faithful equivalent for asyncio programs;
emission is synchronous, scheduling is the caller's concern.
"""

from __future__ import annotations

import os
import typing

try:
    if os.environ.get('CUEBALL_NO_NATIVE'):
        _native = None
    else:
        from . import _cueball_native as _native
except ImportError:
    _native = None


class PyEventEmitter:
    """Node-style event emitter with synchronous delivery (pure-Python
    reference implementation; the C core in native/emitter.c mirrors
    these semantics exactly and replaces it when built)."""

    def __init__(self) -> None:
        self._ee_listeners: dict[str, list] = {}
        # External-listener mutation epoch: bumped on every add/remove
        # of a non-framework listener (FSM gates are marked
        # `_cueball_internal`). The claim-handle leak detector skips
        # its per-event count sweep while the epoch is unchanged; the
        # C core keeps the same counter (emitter.c ee_mutations).
        self._ee_mut = 0

    # -- registration ----------------------------------------------------

    def on(self, event: str, listener: typing.Callable) -> typing.Callable:
        """Register listener; returns it so callers can hold a removal ref."""
        self._ee_listeners.setdefault(event, []).append(listener)
        if not getattr(listener, '_cueball_internal', False):
            self._ee_mut += 1
        return listener

    add_listener = on

    def once(self, event: str, listener: typing.Callable) -> typing.Callable:
        def wrapper(*args, **kwargs):
            self.remove_listener(event, wrapper)
            return listener(*args, **kwargs)
        wrapper.__wrapped_listener__ = listener
        self.on(event, wrapper)
        return wrapper

    def remove_listener(self, event: str, listener: typing.Callable) -> None:
        lst = self._ee_listeners.get(event)
        if not lst:
            return
        # Identity scan first (the overwhelmingly common case on the
        # claim hot path); fall back to the once()-wrapper scan.
        for i, entry in enumerate(lst):
            if entry is listener:
                if not getattr(entry, '_cueball_internal', False):
                    self._ee_mut += 1
                del lst[i]
                break
        else:
            for i, entry in enumerate(lst):
                if getattr(entry, '__wrapped_listener__', None) is listener:
                    if not getattr(entry, '_cueball_internal', False):
                        self._ee_mut += 1
                    del lst[i]
                    break
        if not lst:
            self._ee_listeners.pop(event, None)

    def remove_all_listeners(self, event: str | None = None) -> None:
        # Conservative bump (may not have removed anything external):
        # a spurious bump only costs the leak detector one extra sweep.
        self._ee_mut += 1
        if event is None:
            self._ee_listeners.clear()
        else:
            self._ee_listeners.pop(event, None)

    # -- introspection ---------------------------------------------------

    def listeners(self, event: str) -> list:
        return list(self._ee_listeners.get(event, ()))

    def listener_count(self, event: str) -> int:
        return len(self._ee_listeners.get(event, ()))

    def event_names(self) -> list[str]:
        return [k for k, v in self._ee_listeners.items() if v]

    def mutation_count(self) -> int:
        """External-listener mutation epoch (see __init__)."""
        return self._ee_mut

    # -- emission --------------------------------------------------------

    def emit(self, event: str, *args) -> bool:
        """Deliver synchronously to a snapshot of current listeners.

        Returns True if anyone was listening (Node contract; the Set's
        assert_emit crash-if-unhandled check relies on this,
        reference lib/set.js:471-479).
        """
        lst = self._ee_listeners.get(event)
        if not lst:
            return False
        if len(lst) == 1:
            # Fast path: a lone listener that unsubscribes mid-call has
            # already run, so no snapshot copy is needed.
            lst[0](*args)
        else:
            for listener in tuple(lst):
                listener(*args)
        return True


EventEmitter = PyEventEmitter if _native is None else _native.EventEmitter

"""Error hierarchy with VError-style cause chaining.

Rebuild of reference `lib/errors.js:9-112`. Every class carries the
contextual objects (pool, backend) and a cause chain; messages embed the
pool uuid/domain or backend host:port the way the reference does so that
operator logs stay greppable. Cause chaining uses Python's native
``__cause__`` plus a ``cause()`` accessor mirroring VError.
"""

from __future__ import annotations


class CueBallError(Exception):
    """Base for all framework errors; supports cause chaining."""

    def __init__(self, message: str, cause: 'BaseException | None' = None):
        super().__init__(message)
        # Only assign when a cause exists: setting __cause__ (even to
        # None) flips __suppress_context__ and would hide the implicit
        # exception context from tracebacks.
        if cause is not None:
            self.__cause__ = cause

    def cause(self) -> 'BaseException | None':
        return self.__cause__

    def full_message(self) -> str:
        """Message with the cause chain appended, VError-style."""
        msg = str(self)
        c = self.__cause__
        while c is not None:
            msg += ': ' + str(c)
            c = getattr(c, '__cause__', None)
        return msg


class ClaimHandleMisusedError(CueBallError):
    """User treated a claim handle as if it were the connection
    (reference lib/errors.js:26-35)."""

    def __init__(self):
        super().__init__(
            'CueBall claim handle used as if it was a socket (check the '
            'order and number of arguments in your claim callbacks)')


class ClaimTimeoutError(CueBallError):
    """Claim sat in the wait queue past its timeout
    (reference lib/errors.js:37-47)."""

    def __init__(self, pool):
        self.pool = pool
        super().__init__(
            'Timed out while waiting for connection in pool %s (%s)' % (
                pool.p_uuid, pool.p_domain))


class NoBackendsError(CueBallError):
    """Claim made while the resolver has produced no backends
    (reference lib/errors.js:49-58)."""

    def __init__(self, pool, cause: 'BaseException | None' = None):
        self.pool = pool
        super().__init__(
            'No backends available in pool %s (%s)' % (
                pool.p_uuid, pool.p_domain), cause)


class PoolFailedError(CueBallError):
    """Pool is in the failed state: all backends declared dead
    (reference lib/errors.js:60-75)."""

    def __init__(self, pool, cause: 'BaseException | None' = None):
        self.pool = pool
        dead = len(pool.p_dead)
        avail = len(pool.p_keys)
        super().__init__(
            'Connections to backends of pool %s (%s) are persistently '
            'failing; request aborted (%d of %d declared dead, in state '
            '"failed")' % (pool.p_uuid.split('-')[0], pool.p_domain,
                           dead, avail), cause)


class PoolStoppingError(CueBallError):
    """Claim made on a stopping/stopped pool
    (reference lib/errors.js:77-87)."""

    def __init__(self, pool):
        self.pool = pool
        super().__init__(
            'Pool %s (%s) is stopping and cannot take new requests' % (
                pool.p_uuid.split('-')[0], pool.p_domain))


class ConnectionError(CueBallError):
    """Connection emitted 'error' (reference lib/errors.js:89-101).

    Named for parity with the reference API; unrelated to (and does not
    catch) Python's builtin OSError-based ConnectionError.
    """

    def __init__(self, backend: dict, event: str, state: str,
                 cause: 'BaseException | None' = None):
        self.backend = backend
        super().__init__(
            'Connection to backend %s (%s:%s) emitted "%s" during %s' % (
                backend.get('name') or backend.get('key'),
                backend.get('address'), backend.get('port'),
                event, state), cause)


class ConnectionTimeoutError(CueBallError):
    """Connect attempt exceeded its timeout
    (reference lib/errors.js:103-112)."""

    def __init__(self, backend: dict):
        self.backend = backend
        super().__init__(
            'Connection timed out to backend %s (%s:%s)' % (
                backend.get('name') or backend.get('key'),
                backend.get('address'), backend.get('port')))


class ConnectionClosedError(CueBallError):
    """Connection closed unexpectedly (reference lib/errors.js:114-123)."""

    def __init__(self, backend: dict):
        self.backend = backend
        super().__init__(
            'Connection closed unexpectedly to backend %s (%s:%s)' % (
                backend.get('name') or backend.get('key'),
                backend.get('address'), backend.get('port')))


class TransportNotAvailableError(CueBallError):
    """A transport backend is registered but its data path is not
    built in this process (the ``native`` stub until native/transport
    lands). Carries the seam that was asked for — ``'resolve'`` when
    ``get_transport`` refused the backend at resolution time, else one
    of the five seam method names — so callers and logs can tell a
    missing build from a miswired call site."""

    def __init__(self, seam: str, transport: str = 'native',
                 cause: 'BaseException | None' = None):
        self.seam = seam
        self.transport = transport
        super().__init__(
            "transport %r is not available (seam %r): the data path "
            "is not built in this process; register a real factory "
            "via register_transport(%r, ...)" % (transport, seam,
                                                 transport), cause)


class ShardDeadError(CueBallError):
    """A FleetRouter call was routed to a shard whose event loop is no
    longer running (loop stopped, thread exited, or child process
    died). Claims and submits against pools owned by that shard fail
    fast with this error instead of deadlocking on a loop that will
    never pump; the router re-homes the pools when the shard is
    restarted."""

    def __init__(self, shard_id: int, detail: str = '',
                 cause: 'BaseException | None' = None):
        self.shard_id = shard_id
        msg = 'Shard %r event loop is not running' % (shard_id,)
        if detail:
            msg += ' (%s)' % detail
        super().__init__(msg, cause)

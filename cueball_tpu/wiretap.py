"""Transport wire ledger: what happens *below* the Transport seam.

The PR-11 phase ledger stops at an opaque ``socket_wait`` phase; this
module is the instrument that looks under it, three ways:

- The **TransportLedger** — per-(transport, seam) fixed-slot counters
  every backend feeds: connect/read/write syscall-equivalent counts,
  byte totals in both directions, readiness→callback dispatch latency
  and write-buffer highwater. The five seams are the Transport plug-in
  contract (``SEAMS`` mirrors ``transport.SEAM_METHODS``; ``make
  check`` pins the two together via cbflow A006), so the asyncio and
  fabric backends emit comparable counters and a future native
  backend has a conformance target (``trace.WIRE_EVENT_CODES`` are its
  reserved ring slots).

- The **loop-lag sampler** — a self-rescheduling timer per event loop
  measuring scheduled-vs-actual callback delta (the "is the loop
  saturated" signal), armed alongside the SIGPROF sampler on the debug
  signal and refusing to run under a non-system clock exactly like
  profile.start_sampler (netsim scenarios stay deterministic).

- The **socket_wait decomposition** — transports stamp wire marks
  (kernel readiness time, loop dispatch time) on each connection; the
  ledger keys them by the exact ``(start, end)`` floats of the connect
  span so profile.claim_ledger can split ``socket_wait`` into
  ``kernel_wait`` / ``loop_dispatch`` / ``proto_parse`` sub-phases
  without touching the trace ring's byte format.

Everything is off until :func:`enable_wiretap` installs the ledger;
disabled, every hook costs one module-global load and a None check
(the ``_prof`` seam discipline — the bench A/B gate holds the enabled
claim-path overhead under 1%). Surfaces: ``GET /kang/transport``,
``cueball_transport_{bytes,events,dispatch_lag_ms,loop_lag_ms}`` on
/metrics (histograms fold under ``merge_expositions``), a section in
the SIGUSR2 dump, netsim failure dumps, and
:meth:`FleetRouter.wiretap_fleet` merging per-shard records via
:func:`reduce_wiretap`. See docs/transport.md §Wire ledger.
"""

from __future__ import annotations

import asyncio

from . import utils as mod_utils

__all__ = [
    'SEAMS',
    'SUB_PHASES',
    'PARITY_FIELDS',
    'SeamStats',
    'TransportLedger',
    'enable_wiretap',
    'disable_wiretap',
    'wiretap_enabled',
    'seam_stats',
    'watch',
    'instrument_writer',
    'record_connect',
    'wire_wait',
    'connect_breakdown',
    'snapshot',
    'wire_totals',
    'register_wire_source',
    'unregister_wire_source',
    'start_loop_lag_sampler',
    'stop_loop_lag_sampler',
    'loop_lag_stats',
    'loop_lag_p99_us',
    'wiretap_record',
    'reduce_wiretap',
    'dump_wiretap',
]

#: The five Transport seams, in display order. Membership is a
#: cross-module contract: transport.SEAM_METHODS must match exactly
#: (cbflow rule A006 pins both against the Transport class), and the
#: /kang/transport ``?seam=`` filter validates against this tuple.
SEAMS = ('connector', 'create_stream', 'serve', 'dns_udp', 'dns_tcp')

#: The socket_wait sub-phases, in display order. profile.claim_ledger
#: emits them under ``led['wire']`` holding
#: ``sum(SUB_PHASES) == phases['socket_wait']`` exactly per claim.
SUB_PHASES = ('kernel_wait', 'loop_dispatch', 'proto_parse')

#: SeamStats fields the asyncio-vs-fabric parity gate compares. The
#: latency/highwater fields are excluded (wall-clock vs virtual time),
#: and ``closes`` is excluded because the real-socket path suppresses
#: the 'close' emit on owner-initiated destroy while netsim emits it
#: (see docs/transport.md §Wire ledger).
PARITY_FIELDS = ('events', 'connects', 'errors', 'reads', 'writes',
                 'bytes_in', 'bytes_out')

# Connect-breakdown retention: (start, end) -> (kernel, dispatch,
# parse) entries kept for claim_ledger replay. Sized to comfortably
# cover the trace ring (claims outlive their connects rarely; 4096
# matches the trace assembler's pending cap).
_BREAKDOWN_CAP = 4096

DEFAULT_LAG_INTERVAL_MS = 20.0
DEFAULT_LAG_RING = 512


class SeamStats:
    """Fixed-slot counters for one (transport, seam) pair. All fields
    are plain ints/floats mutated in place from the hot path — no
    dict lookups, no allocation after construction."""

    __slots__ = ('events', 'connects', 'errors', 'closes', 'reads',
                 'writes', 'bytes_in', 'bytes_out', 'dispatch_count',
                 'dispatch_ms_total', 'dispatch_ms_max',
                 'buf_highwater')

    def __init__(self):
        self.events = 0            # seam invocations
        self.connects = 0          # successful connects / accepts
        self.errors = 0
        self.closes = 0
        self.reads = 0             # syscall-equivalent reads
        self.writes = 0            # syscall-equivalent writes
        self.bytes_in = 0
        self.bytes_out = 0
        self.dispatch_count = 0    # readiness->callback latencies seen
        self.dispatch_ms_total = 0.0
        self.dispatch_ms_max = 0.0
        self.buf_highwater = 0     # max write-buffer depth observed

    def as_dict(self) -> dict:
        return {name: getattr(self, name) for name in self.__slots__}


class TransportLedger:
    """The process-wide wire ledger. One instance lives at module
    scope while wiretap is enabled; transports fetch SeamStats through
    :func:`seam_stats` (None when disabled) so the disabled cost stays
    at a global load + None check."""

    def __init__(self, collector=None):
        self.collector = collector
        self._stats: dict = {}        # (transport, seam) -> SeamStats
        self._wire: dict = {}         # transport -> [kernel, disp, parse]
        self._breakdown: dict = {}    # (start, end) -> (k, d, p)
        self._breakdown_order: list = []
        # The ONE bound-method object registered as the collect hook:
        # remove_collect_hook compares by identity, and every
        # ``self._publish`` attribute access builds a fresh bound
        # method, so enable/disable must hand the collector the same
        # object.
        self._publish_hook = self._publish

    # -- counters --------------------------------------------------------

    def seam(self, transport: str, seam: str) -> SeamStats:
        st = self._stats.get((transport, seam))
        if st is None:
            if seam not in SEAMS:
                raise ValueError('unknown seam %r (one of %s)'
                                 % (seam, ', '.join(SEAMS)))
            st = self._stats[(transport, seam)] = SeamStats()
        return st

    # -- connect decomposition -------------------------------------------

    def record_connect(self, transport: str, start: float, end: float,
                       marks) -> None:
        """Fold one finished connect into the wire totals and retain
        its breakdown keyed by the exact (start, end) floats — the
        same values connection_fsm hands the tracer as the connect
        span, which is what lets claim_ledger find it again.

        ``marks`` is ``(ready, dispatched)`` — when the kernel
        reported the socket writable and when the awaiting coroutine
        actually resumed — or None (no protocol-level marks: the whole
        span counts as kernel_wait)."""
        if end < start:
            end = start
        if marks is None:
            kernel, dispatch, parse = end - start, 0.0, 0.0
        else:
            ready, dispatched = marks
            ready = min(max(ready, start), end)
            dispatched = min(max(dispatched, ready), end)
            kernel = ready - start
            dispatch = dispatched - ready
            parse = end - dispatched
        tot = self._wire.get(transport)
        if tot is None:
            tot = self._wire[transport] = [0.0, 0.0, 0.0]
        tot[0] += kernel
        tot[1] += dispatch
        tot[2] += parse
        key = (start, end)
        if key not in self._breakdown:
            if len(self._breakdown_order) >= _BREAKDOWN_CAP:
                old = self._breakdown_order.pop(0)
                self._breakdown.pop(old, None)
            self._breakdown_order.append(key)
        self._breakdown[key] = (kernel, dispatch, parse)
        if self.collector is not None and dispatch >= 0.0:
            self.collector.histogram(
                'cueball_transport_dispatch_lag_ms',
                'Kernel readiness to callback dispatch latency per '
                'transport connect (ms)').observe(
                    dispatch, {'transport': transport})

    def wire_wait(self, transport: str, kernel_ms: float) -> None:
        """Attribute a bare in-kernel wait (no dispatch marks — e.g.
        the claim-readiness probe dribbling segments) to a
        transport's kernel_wait total."""
        if kernel_ms <= 0.0:
            return
        tot = self._wire.get(transport)
        if tot is None:
            tot = self._wire[transport] = [0.0, 0.0, 0.0]
        tot[0] += kernel_ms

    def connect_breakdown(self, start: float, end: float):
        return self._breakdown.get((start, end))

    # -- export ----------------------------------------------------------

    def snapshot(self) -> dict:
        """``{transport: {seam: {field: value}}}`` for every seam that
        has recorded at least one event."""
        out: dict = {}
        for (transport, seam), st in sorted(self._stats.items()):
            out.setdefault(transport, {})[seam] = st.as_dict()
        return out

    def wire_totals(self) -> dict:
        return {t: dict(zip(SUB_PHASES, tot))
                for t, tot in sorted(self._wire.items())}

    def _publish(self) -> None:
        """Collect hook: fold current counters into gauges at scrape
        time (histograms are observed live; see record_connect and the
        lag sampler)."""
        collector = self.collector
        for (transport, seam), st in self._stats.items():
            labels = {'transport': transport, 'seam': seam}
            collector.gauge(
                'cueball_transport_events',
                'Seam invocations recorded by the transport wire '
                'ledger').set(st.events, labels)
            for direction, val in (('in', st.bytes_in),
                                   ('out', st.bytes_out)):
                collector.gauge(
                    'cueball_transport_bytes',
                    'Bytes moved per transport seam and direction'
                ).set(val, dict(labels, direction=direction))


# The module-global hot-path guard: None while disabled.
_LEDGER: TransportLedger | None = None


def enable_wiretap(collector=None) -> TransportLedger:
    """Install the process-wide TransportLedger (idempotent). With a
    metrics ``collector``, registers a collect hook publishing
    ``cueball_transport_{events,bytes}`` and observes the
    dispatch/loop-lag histograms as they happen."""
    global _LEDGER
    if _LEDGER is not None:
        return _LEDGER
    led = TransportLedger(collector=collector)
    if collector is not None:
        collector.add_collect_hook(led._publish_hook)
    _LEDGER = led
    return led


def disable_wiretap() -> bool:
    """Drop the ledger (counters are discarded). Returns whether one
    was installed."""
    global _LEDGER
    led = _LEDGER
    _LEDGER = None
    if led is None:
        return False
    if led.collector is not None:
        led.collector.remove_collect_hook(led._publish_hook)
    return True


def wiretap_enabled() -> bool:
    return _LEDGER is not None


def seam_stats(transport: str, seam: str):
    """The hot-path accessor: SeamStats for (transport, seam), or None
    while wiretap is disabled. Transports call this once per seam
    invocation and skip all accounting on None."""
    led = _LEDGER
    if led is None:
        return None
    return led.seam(transport, seam)


def watch(st: SeamStats, conn) -> None:
    """Attach outcome-counting listeners to a connection-contract
    object ('connect'/'error'/'close'). Listeners are marked
    framework-internal so the claim-handle leak detector and the
    listener mutation epoch ignore them."""

    def on_connect():
        st.connects += 1

    def on_error(err=None):
        st.errors += 1

    def on_close():
        st.closes += 1

    on_connect._cueball_internal = True
    on_error._cueball_internal = True
    on_close._cueball_internal = True
    conn.on('connect', on_connect)
    conn.on('error', on_error)
    conn.on('close', on_close)


def instrument_writer(st: SeamStats, writer) -> None:
    """Shadow ``writer.write`` with a counting wrapper (writes,
    bytes_out, write-buffer highwater). Instance-attribute shadowing,
    not subclassing: the StreamWriter is already built by the time the
    connect path knows wiretap is on."""
    inner = writer.write
    transport = writer.transport

    def write(data):
        st.writes += 1
        st.bytes_out += len(data)
        inner(data)
        try:
            depth = transport.get_write_buffer_size()
        except Exception:
            return
        if depth > st.buf_highwater:
            st.buf_highwater = depth

    writer.write = write


def record_connect(transport: str, start: float, end: float,
                   marks) -> None:
    """Module-level forwarder used by connection_fsm (one global load
    + None check when disabled)."""
    led = _LEDGER
    if led is not None:
        led.record_connect(transport, start, end, marks)


def wire_wait(transport: str, kernel_ms: float) -> None:
    led = _LEDGER
    if led is not None:
        led.wire_wait(transport, kernel_ms)


def connect_breakdown(start: float, end: float):
    """(kernel, dispatch, parse) ms for the connect span keyed by the
    exact (start, end) floats, or None (wiretap off, span evicted, or
    connect predates enable)."""
    led = _LEDGER
    if led is None:
        return None
    return led.connect_breakdown(start, end)


#: Pull hooks for backends that count wire traffic out-of-band (the
#: native C data plane folds its atomic counters into the ledger on
#: demand). Called before every module-level snapshot/wire_totals
#: read so readers never see stale native rows.
_WIRE_SOURCES: list = []


def register_wire_source(pull) -> None:
    """Register a zero-arg callable that folds externally-counted
    wire traffic into the live ledger; invoked before snapshot() and
    wire_totals(). Idempotent per callable."""
    if pull not in _WIRE_SOURCES:
        _WIRE_SOURCES.append(pull)


def unregister_wire_source(pull) -> bool:
    try:
        _WIRE_SOURCES.remove(pull)
        return True
    except ValueError:
        return False


def _pull_wire_sources() -> None:
    for pull in list(_WIRE_SOURCES):
        pull()


def snapshot() -> dict:
    led = _LEDGER
    if led is None:
        return {}
    _pull_wire_sources()
    return led.snapshot()


def wire_totals() -> dict:
    led = _LEDGER
    if led is None:
        return {}
    _pull_wire_sources()
    return led.wire_totals()


# -- loop-lag sampler --------------------------------------------------------

class _LoopLagSampler:
    __slots__ = ('loop', 'interval_s', 'ring', 'samples', 'count',
                 'max_us', 'handle', 'stopped')

    def __init__(self, loop, interval_s: float, ring: int):
        self.loop = loop
        self.interval_s = interval_s
        self.ring = ring
        self.samples: list = []     # lag in us, overwrite-oldest
        self.count = 0
        self.max_us = 0.0
        self.handle = None
        self.stopped = False

    def _arm(self) -> None:
        expected = self.loop.time() + self.interval_s
        self.handle = self.loop.call_later(
            self.interval_s, self._fire, expected)

    def _fire(self, expected: float) -> None:
        if self.stopped:
            return
        lag_us = max(0.0, (self.loop.time() - expected) * 1e6)
        if len(self.samples) >= self.ring:
            del self.samples[0]
        self.samples.append(lag_us)
        self.count += 1
        if lag_us > self.max_us:
            self.max_us = lag_us
        led = _LEDGER
        if led is not None and led.collector is not None:
            led.collector.histogram(
                'cueball_transport_loop_lag_ms',
                'Scheduled-vs-actual event loop callback delta '
                '(ms)').observe(lag_us / 1000.0)
        self._arm()

    def stop(self) -> None:
        self.stopped = True
        if self.handle is not None:
            self.handle.cancel()
            self.handle = None

    def stats(self) -> dict:
        ordered = sorted(self.samples)
        n = len(ordered)

        def pct(q):
            if n == 0:
                return 0.0
            return ordered[min(n - 1, int(q * n))]

        return {
            'running': not self.stopped,
            'samples': self.count,
            'ring': self.ring,
            'p50_us': pct(0.50),
            'p99_us': pct(0.99),
            'max_us': self.max_us,
        }


_lag_samplers: dict = {}          # id(loop) -> _LoopLagSampler
_lag_disabled_reason: str | None = None


def start_loop_lag_sampler(interval_ms: float = DEFAULT_LAG_INTERVAL_MS,
                           ring: int = DEFAULT_LAG_RING) -> bool:
    """Arm the loop-lag sampler on the current running loop
    (idempotent per loop). Refuses — recording why in
    loop_lag_stats()['disabled_reason'] — under a non-system clock
    (netsim must stay deterministic; a timer firing "late" in virtual
    time is meaningless) or when no loop is running here."""
    global _lag_disabled_reason
    if not isinstance(mod_utils.get_clock(), mod_utils.SystemClock):
        _lag_disabled_reason = 'non-system clock installed (netsim?)'
        return False
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        _lag_disabled_reason = 'no running event loop'
        return False
    key = id(loop)
    sampler = _lag_samplers.get(key)
    if sampler is not None and not sampler.stopped:
        return True
    sampler = _LoopLagSampler(loop, max(0.001, interval_ms / 1000.0),
                              int(ring))
    _lag_samplers[key] = sampler
    sampler._arm()
    _lag_disabled_reason = None
    return True


def stop_loop_lag_sampler() -> bool:
    """Disarm every armed loop sampler (collected stats survive until
    the next start on the same loop). Returns whether any was
    running."""
    any_running = False
    for sampler in _lag_samplers.values():
        if not sampler.stopped:
            any_running = True
            sampler.stop()
    return any_running


def _current_sampler():
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        loop = None
    if loop is not None:
        sampler = _lag_samplers.get(id(loop))
        if sampler is not None:
            return sampler
    if len(_lag_samplers) == 1:
        return next(iter(_lag_samplers.values()))
    return None


def loop_lag_stats() -> dict:
    """Lag stats for the current loop's sampler when there is one,
    else the worst-case merge across all sampled loops."""
    sampler = _current_sampler()
    if sampler is not None:
        out = sampler.stats()
    elif _lag_samplers:
        merged = [s.stats() for s in _lag_samplers.values()]
        out = {
            'running': any(m['running'] for m in merged),
            'samples': sum(m['samples'] for m in merged),
            'ring': max(m['ring'] for m in merged),
            'p50_us': max(m['p50_us'] for m in merged),
            'p99_us': max(m['p99_us'] for m in merged),
            'max_us': max(m['max_us'] for m in merged),
        }
    else:
        out = {'running': False, 'samples': 0, 'ring': 0,
               'p50_us': 0.0, 'p99_us': 0.0, 'max_us': 0.0}
    out['disabled_reason'] = _lag_disabled_reason
    return out


def loop_lag_p99_us() -> float:
    """The FleetSampler telemetry column: current-loop lag p99 in us
    (0.0 when no sampler is armed here) — one dict lookup plus a
    sort of at most `ring` floats, called once per O(dirty) patch
    pass, not per row."""
    sampler = _current_sampler()
    if sampler is None:
        return 0.0
    ordered = sorted(sampler.samples)
    n = len(ordered)
    if n == 0:
        return 0.0
    return ordered[min(n - 1, int(0.99 * n))]


# -- fleet merge (FleetRouter.wiretap_fleet) ---------------------------------

def wiretap_record(shard: int | None = None) -> dict:
    """One shard's mergeable wiretap record. The TransportLedger is
    process-global (thread-backend shards share it), so the per-shard
    payload is the loop-local part: that shard loop's lag stats."""
    return {
        'shard': shard,
        'enabled': _LEDGER is not None,
        'loop_lag': loop_lag_stats(),
    }


def reduce_wiretap(records) -> dict:
    """Merge per-shard wiretap records shard -> host, the reduction
    shape of reduce_profile: lag folds worst-case (a single saturated
    loop is the signal), the shared transport counters ride along
    once, and the per-shard records are retained."""
    records = [r for r in records if r]
    return {
        'n_shards': len(records),
        'enabled': _LEDGER is not None,
        'loop_lag_p99_us': max(
            (r.get('loop_lag', {}).get('p99_us', 0.0)
             for r in records), default=0.0),
        'loop_lag_samples': sum(
            r.get('loop_lag', {}).get('samples', 0) for r in records),
        'transports': snapshot(),
        'wire_ms': wire_totals(),
        'shards': records,
    }


# -- SIGUSR2 dump section ----------------------------------------------------

def dump_wiretap() -> str:
    """Wire-ledger section for the SIGUSR2 dump; '' when wiretap was
    never enabled and no lag sampler ever armed, so the dump stays
    absent-but-well-formed."""
    led = _LEDGER
    lag = loop_lag_stats()
    if led is None and not _lag_samplers and not _lag_disabled_reason:
        return ''
    out = ['-- transport wire ledger --']
    out.append('  wiretap: %s' %
               ('enabled' if led is not None else 'disabled'))
    if lag['disabled_reason']:
        out.append('  loop lag: disabled (%s)' % lag['disabled_reason'])
    else:
        out.append('  loop lag: samples=%d p50=%.0fus p99=%.0fus '
                   'max=%.0fus%s' % (lag['samples'], lag['p50_us'],
                                     lag['p99_us'], lag['max_us'],
                                     '' if lag['running']
                                     else ' (stopped)'))
    if led is not None:
        for transport, seams in led.snapshot().items():
            for seam, st in seams.items():
                out.append('  %s/%s: events=%d connects=%d errors=%d '
                           'reads=%d writes=%d in=%dB out=%dB '
                           'highwater=%d' % (
                               transport, seam, st['events'],
                               st['connects'], st['errors'],
                               st['reads'], st['writes'],
                               st['bytes_in'], st['bytes_out'],
                               st['buf_highwater']))
        for transport, tot in led.wire_totals().items():
            out.append('  wire %s: kernel_wait=%.1fms '
                       'loop_dispatch=%.1fms proto_parse=%.1fms' % (
                           transport, tot['kernel_wait'],
                           tot['loop_dispatch'], tot['proto_parse']))
    return '\n'.join(out) + '\n'

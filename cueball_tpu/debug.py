"""Runtime observability attach: flip diagnostics on a LIVE process.

The reference enables claim stack-trace capture on a running process
with zero code change by attaching a dtrace probe (reference
lib/utils.js:59-99: the `capture-stack` USDT probe flips
`stackTracesEnabled` from outside). Python has no USDT, so the
equivalent external attach points are:

- **a signal** — :func:`install_debug_handler` binds SIGUSR2 (by
  default); each delivery toggles process-wide stack capture
  (utils.enable_stack_traces) and dumps the FSM state + history ring of
  every pool, set, resolver and connection slot registered with the
  process-global pool monitor to the ``cueball.debug`` logger, so an
  operator can `kill -USR2 <pid>` a wedged process and read what every
  FSM did last.
- **environment variables** — read once at `import cueball_tpu`:
  ``CUEBALL_STACK_TRACES=1`` starts with capture enabled, and
  ``CUEBALL_DEBUG_SIGNAL=1`` (or a signal name like ``SIGUSR1``)
  installs the handler without any application code.

The dump itself is also callable in-process (:func:`dump_fsm_histories`)
and is what the kang surface uses for ad-hoc archaeology.
"""

from __future__ import annotations

import io
import logging
import os
import signal
import time

from . import trace as mod_trace
from . import utils as mod_utils

_LOG = logging.getLogger('cueball.debug')


def _fsm_line(tag: str, fsm) -> str:
    try:
        state = fsm.get_state()
    except Exception:
        state = '?'
    hist = []
    timed = getattr(fsm, 'get_history_timed', None)
    get_history = getattr(fsm, 'get_history', None)
    if timed is not None:
        try:
            # Dwell annotations (reference changelog #119): how long
            # each recorded state actually lasted.
            entries = timed()
            for i, (name, at) in enumerate(entries):
                if i + 1 < len(entries):
                    name += '(%dms)' % round(entries[i + 1][1] - at)
                hist.append(name)
        except Exception:
            pass
    elif get_history is not None:
        try:
            hist = get_history()
        except Exception:
            pass
    return '  %-14s state=%-12s history=%s\n' % (tag, state,
                                                 '->'.join(hist))


def _health_section() -> str:
    """'-- fleet health --' dump lines for every active HealthMonitor;
    '' when the health engine was never imported or has no monitors."""
    import sys
    mod = sys.modules.get('cueball_tpu.parallel.health')
    if mod is None:
        return ''
    monitors = mod.active_monitors()
    if not monitors:
        return ''
    out = ['-- fleet health (%d monitor(s)) --' % len(monitors)]
    for mon in monitors:
        last = mon.hm_last
        if last is None:
            out.append('  (no tick yet)')
            continue
        f = last['fleet']
        out.append(
            '  epoch=%d backends=%d gray=%s burn_fast=%.2f '
            'burn_slow=%.2f p99=%.1fms err_rate=%.4f%s%s' % (
                last['epoch'], int(f['n_backends']),
                ','.join(last['gray']) or '-',
                float(f['burn_fast']), float(f['burn_slow']),
                float(f['claim_p99_ms']), float(f['err_rate']),
                ' PAGE' if f['alert_page'] else '',
                ' TICKET' if f['alert_ticket'] else ''))
        for key, b in sorted(last['backends'].items()):
            if not b['gray']:
                continue
            out.append('   gray %-24s ewma=%.1fms z=%.1f score=%d' % (
                key, b['ewma_ms'], b['z'], b['score']))
    return '\n'.join(out) + '\n'


def dump_fsm_histories(stream=None) -> str:
    """Dump state + history of every FSM registered with the pool
    monitor (pools, sets, DNS resolvers, and their connection slots and
    socket managers). Returns the report; also writes it to `stream`
    when given."""
    from .monitor import pool_monitor

    buf = io.StringIO()
    buf.write('cueball FSM dump pid=%d t=%.3f stack_traces=%s\n' % (
        os.getpid(), time.time(), mod_utils.stack_traces_enabled()))
    run_meta = mod_trace.get_run_metadata()
    if run_meta:
        # Inside a netsim scenario: name the replayable run this dump
        # belongs to (seed + scenario identity).
        buf.write('netsim run: %s\n' % ' '.join(
            '%s=%s' % (k, run_meta[k]) for k in sorted(run_meta)
            if k != 'schedule'))

    for uuid, pool in list(pool_monitor.pm_pools.items()):
        shard = getattr(pool, 'p_shard', None)
        buf.write('pool %s domain=%s%s\n' % (
            uuid, pool.p_domain,
            '' if shard is None else ' shard=%d' % shard))
        buf.write(_fsm_line('(pool)', pool))
        for key, slots in list(pool.p_connections.items()):
            for slot in slots:
                buf.write(_fsm_line('slot %s' % key[:12], slot))
                smgr = getattr(slot, 'csf_smgr', None)
                if smgr is not None:
                    buf.write(_fsm_line(' smgr', smgr))
        if pool.p_dead:
            buf.write('  dead=%s\n' % sorted(pool.p_dead.keys()))

    for uuid, cset in list(pool_monitor.pm_sets.items()):
        buf.write('set %s domain=%s\n' % (uuid, cset.cs_domain))
        buf.write(_fsm_line('(set)', cset))
        for key, slot in list(cset.cs_fsm.items()):
            buf.write(_fsm_line('slot %s' % key[:12], slot))
            smgr = getattr(slot, 'csf_smgr', None)
            if smgr is not None:
                buf.write(_fsm_line(' smgr', smgr))

    for uuid, res in list(pool_monitor.pm_dns_res.items()):
        buf.write('dns_res %s domain=%s\n' % (uuid, res.r_domain))
        buf.write(_fsm_line('(resolver)', res))

    # Started FleetRouters (if the shard package is in play): shard FSM
    # states and the pool -> shard ownership map, so one SIGUSR2 answers
    # "which shard owns the wedged pool" too.
    for router in mod_trace._active_fleet_routers():
        buf.write('fleet_router backend=%s shards=%d\n' % (
            router.fr_backend, router.fr_nshards))
        for sid, fsm in sorted(router.fr_fsms.items()):
            buf.write(_fsm_line('shard %d' % sid, fsm))
        for name, rec in sorted(router.fr_pools.items()):
            buf.write('  pool %-24s -> shard %d\n' % (name, rec.shard_id))

    # Active health monitors: the verdicts next to the FSM states, so
    # one SIGUSR2 also answers "which backend is gray" and "is the SLO
    # burning". Late-bound like the router section — the parallel
    # package (and jax) is only consulted if something imported it.
    buf.write(_health_section())

    # When claim tracing is on, the slowest recent claims land next to
    # the FSM states: a wedged process's dump answers both "what state
    # is everything in" and "where did claim latency go".
    traces = mod_trace.dump_traces()
    if traces:
        buf.write(traces)

    # Claim-path profiler: sampler state, fleet cost attribution, and
    # the slowest claims' phase ledgers. '' (section absent, dump still
    # well-formed) when nothing was ever profiled.
    from . import profile as mod_profile
    prof = mod_profile.dump_profile()
    if prof:
        buf.write(prof)

    report = buf.getvalue()
    if stream is not None:
        stream.write(report)
    return report


def _emit_dump(signum: int) -> None:
    _LOG.warning('debug signal %d: stack traces now %s\n%s',
                 signum,
                 'ENABLED' if mod_utils.stack_traces_enabled()
                 else 'disabled',
                 dump_fsm_histories())


def _on_debug_signal(signum, frame) -> None:
    """SIGUSR2 handler: toggle stack capture, dump all FSM histories.

    The toggle itself is plain Python state (safe at any interrupt
    point); the dump + logging are NOT reentrancy-safe (a buffered
    stream write interrupted mid-write raises RuntimeError), so when an
    asyncio loop is running they are deferred to it via
    call_soon_threadsafe (the only call_soon variant documented safe
    from signal handlers) and only run inline as a last resort."""
    if mod_utils.stack_traces_enabled():
        mod_utils.disable_stack_traces()
    else:
        mod_utils.enable_stack_traces()
    # The toggle doubles as the profiler attach point (tools/cbprofile
    # `make profile`): first USR2 arms the SIGPROF phase sampler,
    # second disarms it — the dump that follows each delivery shows
    # the sampler state and whatever it collected. start/stop are
    # no-ops-with-reasons (netsim clock, non-main thread), never
    # raises out of a signal handler.
    try:
        from . import profile as mod_profile
        if mod_utils.stack_traces_enabled():
            mod_profile.start_sampler()
        else:
            mod_profile.stop_sampler()
    except Exception:
        pass
    import asyncio
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        loop = None
    if loop is not None:
        loop.call_soon_threadsafe(_emit_dump, signum)
    else:
        _emit_dump(signum)


def install_debug_handler(signum: int = signal.SIGUSR2):
    """Install the live-attach diagnostic handler (dtrace-probe
    analogue). Returns the previous handler."""
    return signal.signal(signum, _on_debug_signal)


def uninstall_debug_handler(prev, signum: int = signal.SIGUSR2) -> None:
    signal.signal(signum, prev)


def init_from_env(env=os.environ) -> None:
    """Apply CUEBALL_STACK_TRACES / CUEBALL_DEBUG_SIGNAL. Called once at
    package import so both work with zero application code. Bad values
    (unknown signal name, import off the main thread) must not make the
    package unimportable: they log and continue."""
    if env.get('CUEBALL_STACK_TRACES', '') not in ('', '0'):
        mod_utils.enable_stack_traces()
    sig = env.get('CUEBALL_DEBUG_SIGNAL', '')
    if sig and sig != '0':
        try:
            name = sig.upper()
            if not name.startswith('SIG'):
                name = 'SIG' + name
            signum = signal.SIGUSR2 if sig == '1' \
                else getattr(signal, name)
            install_debug_handler(signum)
        except (AttributeError, ValueError, OSError) as e:
            _LOG.warning(
                'CUEBALL_DEBUG_SIGNAL=%s not installed: %s', sig, e)

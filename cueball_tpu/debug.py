"""Runtime observability attach: flip diagnostics on a LIVE process.

The reference enables claim stack-trace capture on a running process
with zero code change by attaching a dtrace probe (reference
lib/utils.js:59-99: the `capture-stack` USDT probe flips
`stackTracesEnabled` from outside). Python has no USDT, so the
equivalent external attach points are:

- **a signal** — :func:`install_debug_handler` binds SIGUSR2 (by
  default); each delivery toggles process-wide stack capture
  (utils.enable_stack_traces) and dumps the FSM state + history ring of
  every pool, set, resolver and connection slot registered with the
  process-global pool monitor to the ``cueball.debug`` logger, so an
  operator can `kill -USR2 <pid>` a wedged process and read what every
  FSM did last.
- **environment variables** — read once at `import cueball_tpu`:
  ``CUEBALL_STACK_TRACES=1`` starts with capture enabled, and
  ``CUEBALL_DEBUG_SIGNAL=1`` (or a signal name like ``SIGUSR1``)
  installs the handler without any application code.

The dump itself is also callable in-process (:func:`dump_fsm_histories`)
and is what the kang surface uses for ad-hoc archaeology.
"""

from __future__ import annotations

import io
import logging
import os
import signal
import sys
import threading

from . import trace as mod_trace
from . import utils as mod_utils

_LOG = logging.getLogger('cueball.debug')

# The licensed cross-thread marshal sites, as package-relative paths.
# This tuple is the SINGLE source of truth for loop-affinity rule
# A001: tools/cbflow.py parses it statically (any
# call_soon_threadsafe / run_coroutine_threadsafe outside these
# modules is a finding), and LoopAffinityChecker licenses the same
# set at runtime (and records which sites were actually exercised, so
# the conformance test can prove the registry is live, not
# aspirational). Everything here is a deliberate cross-loop boundary:
# the shard marshal layer (worker/proc/router), the signal-handler
# dump deferral below, and the sync-client bridge.
A001_MARSHAL_MODULES = (
    'debug.py',
    'integrations/httpx.py',
    # Native-plane teardown crosses threads (shard router joining a
    # worker loop): the completion-pump reader must be removed on the
    # owning loop, so close_plane_threadsafe marshals the close with
    # call_soon_threadsafe.
    'native_transport.py',
    'shard/proc.py',
    'shard/router.py',
    'shard/worker.py',
)


def _fsm_line(tag: str, fsm) -> str:
    try:
        state = fsm.get_state()
    except Exception:
        state = '?'
    hist = []
    timed = getattr(fsm, 'get_history_timed', None)
    get_history = getattr(fsm, 'get_history', None)
    if timed is not None:
        try:
            # Dwell annotations (reference changelog #119): how long
            # each recorded state actually lasted.
            entries = timed()
            for i, (name, at) in enumerate(entries):
                if i + 1 < len(entries):
                    name += '(%dms)' % round(entries[i + 1][1] - at)
                hist.append(name)
        except Exception:
            pass
    elif get_history is not None:
        try:
            hist = get_history()
        except Exception:
            pass
    return '  %-14s state=%-12s history=%s\n' % (tag, state,
                                                 '->'.join(hist))


def _health_section() -> str:
    """'-- fleet health --' dump lines for every active HealthMonitor;
    '' when the health engine was never imported or has no monitors."""
    import sys
    mod = sys.modules.get('cueball_tpu.parallel.health')
    if mod is None:
        return ''
    monitors = mod.active_monitors()
    if not monitors:
        return ''
    out = ['-- fleet health (%d monitor(s)) --' % len(monitors)]
    for mon in monitors:
        last = mon.hm_last
        if last is None:
            out.append('  (no tick yet)')
            continue
        f = last['fleet']
        out.append(
            '  epoch=%d backends=%d gray=%s burn_fast=%.2f '
            'burn_slow=%.2f p99=%.1fms err_rate=%.4f%s%s' % (
                last['epoch'], int(f['n_backends']),
                ','.join(last['gray']) or '-',
                float(f['burn_fast']), float(f['burn_slow']),
                float(f['claim_p99_ms']), float(f['err_rate']),
                ' PAGE' if f['alert_page'] else '',
                ' TICKET' if f['alert_ticket'] else ''))
        for key, b in sorted(last['backends'].items()):
            if not b['gray']:
                continue
            out.append('   gray %-24s ewma=%.1fms z=%.1f score=%d' % (
                key, b['ewma_ms'], b['z'], b['score']))
    return '\n'.join(out) + '\n'


def dump_fsm_histories(stream=None) -> str:
    """Dump state + history of every FSM registered with the pool
    monitor (pools, sets, DNS resolvers, and their connection slots and
    socket managers). Returns the report; also writes it to `stream`
    when given."""
    from .monitor import pool_monitor

    buf = io.StringIO()
    buf.write('cueball FSM dump pid=%d t=%.3f stack_traces=%s\n' % (
        os.getpid(), mod_utils.wall_time(),
        mod_utils.stack_traces_enabled()))
    run_meta = mod_trace.get_run_metadata()
    if run_meta:
        # Inside a netsim scenario: name the replayable run this dump
        # belongs to (seed + scenario identity).
        buf.write('netsim run: %s\n' % ' '.join(
            '%s=%s' % (k, run_meta[k]) for k in sorted(run_meta)
            if k != 'schedule'))

    for uuid, pool in list(pool_monitor.pm_pools.items()):
        shard = getattr(pool, 'p_shard', None)
        buf.write('pool %s domain=%s%s\n' % (
            uuid, pool.p_domain,
            '' if shard is None else ' shard=%d' % shard))
        buf.write(_fsm_line('(pool)', pool))
        for key, slots in list(pool.p_connections.items()):
            for slot in slots:
                buf.write(_fsm_line('slot %s' % key[:12], slot))
                smgr = getattr(slot, 'csf_smgr', None)
                if smgr is not None:
                    buf.write(_fsm_line(' smgr', smgr))
        if pool.p_dead:
            buf.write('  dead=%s\n' % sorted(pool.p_dead.keys()))

    for uuid, cset in list(pool_monitor.pm_sets.items()):
        buf.write('set %s domain=%s\n' % (uuid, cset.cs_domain))
        buf.write(_fsm_line('(set)', cset))
        for key, slot in list(cset.cs_fsm.items()):
            buf.write(_fsm_line('slot %s' % key[:12], slot))
            smgr = getattr(slot, 'csf_smgr', None)
            if smgr is not None:
                buf.write(_fsm_line(' smgr', smgr))

    for uuid, res in list(pool_monitor.pm_dns_res.items()):
        buf.write('dns_res %s domain=%s\n' % (uuid, res.r_domain))
        buf.write(_fsm_line('(resolver)', res))

    # Started FleetRouters (if the shard package is in play): shard FSM
    # states and the pool -> shard ownership map, so one SIGUSR2 answers
    # "which shard owns the wedged pool" too.
    for router in mod_trace._active_fleet_routers():
        buf.write('fleet_router backend=%s shards=%d\n' % (
            router.fr_backend, router.fr_nshards))
        for sid, fsm in sorted(router.fr_fsms.items()):
            buf.write(_fsm_line('shard %d' % sid, fsm))
        for name, rec in sorted(router.fr_pools.items()):
            buf.write('  pool %-24s -> shard %d\n' % (name, rec.shard_id))

    # Active health monitors: the verdicts next to the FSM states, so
    # one SIGUSR2 also answers "which backend is gray" and "is the SLO
    # burning". Late-bound like the router section — the parallel
    # package (and jax) is only consulted if something imported it.
    buf.write(_health_section())

    # When claim tracing is on, the slowest recent claims land next to
    # the FSM states: a wedged process's dump answers both "what state
    # is everything in" and "where did claim latency go".
    traces = mod_trace.dump_traces()
    if traces:
        buf.write(traces)

    # Claim-path profiler: sampler state, fleet cost attribution, and
    # the slowest claims' phase ledgers. '' (section absent, dump still
    # well-formed) when nothing was ever profiled.
    from . import profile as mod_profile
    prof = mod_profile.dump_profile()
    if prof:
        buf.write(prof)

    # Transport wire ledger: per-seam counters, socket_wait wire
    # totals and loop-lag stats. Same absent-but-well-formed contract.
    from . import wiretap as mod_wiretap
    wire = mod_wiretap.dump_wiretap()
    if wire:
        buf.write(wire)

    report = buf.getvalue()
    if stream is not None:
        stream.write(report)
    return report


def _emit_dump(signum: int) -> None:
    _LOG.warning('debug signal %d: stack traces now %s\n%s',
                 signum,
                 'ENABLED' if mod_utils.stack_traces_enabled()
                 else 'disabled',
                 dump_fsm_histories())


def _on_debug_signal(signum, frame) -> None:
    """SIGUSR2 handler: toggle stack capture, dump all FSM histories.

    The toggle itself is plain Python state (safe at any interrupt
    point); the dump + logging are NOT reentrancy-safe (a buffered
    stream write interrupted mid-write raises RuntimeError), so when an
    asyncio loop is running they are deferred to it via
    call_soon_threadsafe (the only call_soon variant documented safe
    from signal handlers) and only run inline as a last resort."""
    if mod_utils.stack_traces_enabled():
        mod_utils.disable_stack_traces()
    else:
        mod_utils.enable_stack_traces()
    # The toggle doubles as the profiler attach point (tools/cbprofile
    # `make profile`): first USR2 arms the SIGPROF phase sampler,
    # second disarms it — the dump that follows each delivery shows
    # the sampler state and whatever it collected. start/stop are
    # no-ops-with-reasons (netsim clock, non-main thread), never
    # raises out of a signal handler.
    try:
        from . import profile as mod_profile
        from . import wiretap as mod_wiretap
        if mod_utils.stack_traces_enabled():
            mod_profile.start_sampler()
            mod_wiretap.start_loop_lag_sampler()
        else:
            mod_profile.stop_sampler()
            mod_wiretap.stop_loop_lag_sampler()
    except Exception:
        pass
    import asyncio
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        loop = None
    if loop is not None:
        loop.call_soon_threadsafe(_emit_dump, signum)
    else:
        _emit_dump(signum)


def install_debug_handler(signum: int = signal.SIGUSR2):
    """Install the live-attach diagnostic handler (dtrace-probe
    analogue). Returns the previous handler."""
    return signal.signal(signum, _on_debug_signal)


def uninstall_debug_handler(prev, signum: int = signal.SIGUSR2) -> None:
    signal.signal(signum, prev)


def _package_rel(filename: str) -> str | None:
    """Path relative to the innermost cueball_tpu package directory,
    or None for frames outside the package (same scoping rule as
    tools/cbflow.py's static pass)."""
    parts = filename.replace('\\', '/').split('/')
    if 'cueball_tpu' not in parts:
        return None
    idx = len(parts) - 1 - parts[::-1].index('cueball_tpu')
    rel = parts[idx + 1:]
    return '/'.join(rel) or None


# Default entry points watched by LoopAffinityChecker.watch() when no
# explicit method list is given. Deliberately NOT "every public
# method": wrapping listener-registration methods would change bound-
# method identity and break EventEmitter.remove_listener.
_DEFAULT_WATCH = ('claim', 'claim_cb', 'claim_many', 'stop',
                  'defer', 'wheel_arm', 'wheel_cancel')


class LoopAffinityChecker:
    """Opt-in runtime twin of cbflow rule A001.

    While installed (``with LoopAffinityChecker() as lc:`` or
    ``lc.install()`` / ``lc.uninstall()``):

    - raw ``loop.call_soon``/``call_later``/``call_at`` from a thread
      that is not the loop's running thread is recorded as an
      ``off_thread_schedule`` violation (the bug class
      call_soon_threadsafe exists to prevent);
    - every ``call_soon_threadsafe`` / ``run_coroutine_threadsafe``
      is attributed to the nearest cueball_tpu frame: licensed
      modules (:data:`A001_MARSHAL_MODULES`) land in
      :attr:`marshals_exercised`, any other package frame is an
      ``unlicensed_marshal`` violation;
    - every FSM transition in the process (via
      ``fsm.add_transition_tracer``) must stay on the thread that
      performed that FSM's first transition
      (``off_thread_transition``);
    - :meth:`watch` wraps declared entry points of pool / cset /
      runq / shard-router objects so a direct off-thread call is
      caught even when it never reaches the loop
      (``off_thread_call``).

    Violations accumulate as dicts in :attr:`violations`;
    ``raise_on_violation=True`` turns the first one into an
    AssertionError at the offending call site. The static/dynamic
    conformance test (tests/test_cbflow_conformance.py) runs the
    pool+cset+sharded soaks under this checker and asserts zero
    violations with every licensed marshal module exercised.
    """

    def __init__(self, raise_on_violation: bool = False):
        self.raise_on_violation = raise_on_violation
        self.violations: list[dict] = []
        self.marshals_exercised: set[str] = set()
        self._installed = False
        self._saved: dict = {}
        self._watched: list = []
        self._class_watch: dict = {}
        self._instances: dict = {}
        self._fsm_threads: dict = {}
        self._tls = threading.local()

    # -- recording --------------------------------------------------------

    def _record(self, kind: str, **info) -> None:
        info['kind'] = kind
        self.violations.append(info)
        if self.raise_on_violation:
            raise AssertionError('loop-affinity violation: %r' % info)

    def _site(self):
        """Nearest cueball_tpu frame of the current call, skipping
        this module's own wrappers: (relpath, lineno) or None."""
        f = sys._getframe(2)
        here = _package_rel(__file__)
        while f is not None:
            rel = _package_rel(f.f_code.co_filename)
            if rel is not None and not (rel == here and
                                        f.f_code.co_name.startswith(
                                            '_lc_')):
                return rel, f.f_lineno
            f = f.f_back
        return None

    # -- loop patching ----------------------------------------------------

    def install(self):
        import asyncio

        if self._installed:
            return self
        base = asyncio.base_events.BaseEventLoop
        self._saved = {
            'call_soon': base.call_soon,
            'call_later': base.call_later,
            'call_at': base.call_at,
            'call_soon_threadsafe': base.call_soon_threadsafe,
        }
        checker = self

        def _guarded(name, check):
            orig = checker._saved[name]

            def _lc_wrapper(loop, *args, **kwargs):
                if not getattr(checker._tls, 'busy', False):
                    checker._tls.busy = True
                    try:
                        check(loop)
                    finally:
                        checker._tls.busy = False
                return orig(loop, *args, **kwargs)
            _lc_wrapper.__name__ = '_lc_' + name
            return _lc_wrapper

        def _check_same_thread(loop):
            owner = getattr(loop, '_thread_id', None)
            if owner is not None and owner != threading.get_ident():
                site = checker._site()
                checker._record(
                    'off_thread_schedule',
                    site=site, loop=repr(loop),
                    thread=threading.get_ident(), owner=owner)

        def _check_marshal(loop):
            site = checker._site()
            if site is None:
                return       # non-package caller: not ours to police
            rel = site[0]
            if rel in A001_MARSHAL_MODULES:
                checker.marshals_exercised.add(rel)
            else:
                checker._record('unlicensed_marshal', site=site,
                                thread=threading.get_ident())

        base.call_soon = _guarded('call_soon', _check_same_thread)
        base.call_later = _guarded('call_later', _check_same_thread)
        base.call_at = _guarded('call_at', _check_same_thread)
        base.call_soon_threadsafe = _guarded('call_soon_threadsafe',
                                             _check_marshal)

        from . import fsm as mod_fsm
        self._tracer = self._on_transition
        mod_fsm.add_transition_tracer(self._tracer)
        self._installed = True
        return self

    def uninstall(self) -> None:
        import asyncio

        if not self._installed:
            return
        base = asyncio.base_events.BaseEventLoop
        for name, orig in self._saved.items():
            setattr(base, name, orig)
        self._saved = {}
        from . import fsm as mod_fsm
        mod_fsm.remove_transition_tracer(self._tracer)
        while self._watched:
            obj, name, orig, had = self._watched.pop()
            if had:
                setattr(obj, name, orig)
            else:
                try:
                    delattr(obj, name)
                except AttributeError:
                    pass
        for (cls, name), orig in self._class_watch.items():
            setattr(cls, name, orig)
        self._class_watch.clear()
        self._instances.clear()
        # _fsm_threads is deliberately NOT cleared: it is the record
        # of what the checker observed (the conformance test asserts
        # on it after uninstall); its strong refs die with the
        # checker object itself.
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- FSM transition affinity ------------------------------------------

    def _on_transition(self, fsm, old_state, new_state) -> None:
        # Keyed by id() with a strong ref alongside, so ids cannot be
        # recycled while the checker is installed.
        key = id(fsm)
        tid = threading.get_ident()
        rec = self._fsm_threads.get(key)
        if rec is None:
            self._fsm_threads[key] = (fsm, tid)
        elif rec[1] != tid:
            self._record('off_thread_transition',
                         fsm=type(fsm).__name__,
                         transition=(old_state, new_state),
                         thread=tid, owner=rec[1])

    # -- explicit object watching -----------------------------------------

    def watch(self, obj, methods=None, tag: str | None = None):
        """Wrap `obj`'s entry-point methods (default: the subset of
        ``_DEFAULT_WATCH`` it actually has) so every call is checked
        against the thread that made the FIRST call. Works on
        modules (runq) and plain instances via instance attributes;
        fully slotted instances (the FSM family: pool, cset, router
        — no ``__dict__``) get a class-level wrapper that dispatches
        on a per-instance registry, so unwatched siblings pay one
        dict miss and nothing else."""
        names = methods if methods is not None else [
            n for n in _DEFAULT_WATCH
            if callable(getattr(obj, n, None))]
        owner: dict = {'thread': None}
        label = tag or type(obj).__name__

        def _check(name):
            tid = threading.get_ident()
            if owner['thread'] is None:
                owner['thread'] = tid
            elif owner['thread'] != tid:
                self._record('off_thread_call', obj=label,
                             method=name, thread=tid,
                             owner=owner['thread'])

        if getattr(obj, '__dict__', None) is not None:
            def _make(name, orig):
                def _lc_watched(*args, **kwargs):
                    _check(name)
                    return orig(*args, **kwargs)
                _lc_watched.__name__ = '_lc_' + name
                return _lc_watched

            for name in names:
                orig = getattr(obj, name)
                had = name in vars(obj)
                setattr(obj, name, _make(name, orig))
                self._watched.append((obj, name, orig, had))
            return obj

        # Slotted instance: per-class wrapper, per-instance dispatch.
        cls = type(obj)
        self._instances[id(obj)] = (obj, set(names), _check)
        for name in names:
            key = (cls, name)
            if key in self._class_watch:
                continue
            orig = getattr(cls, name)
            self._class_watch[key] = orig

            def _make_cls(name, orig):
                def _lc_watched(inst, *args, **kwargs):
                    rec = self._instances.get(id(inst))
                    if rec is not None and name in rec[1]:
                        rec[2](name)
                    return orig(inst, *args, **kwargs)
                _lc_watched.__name__ = '_lc_' + name
                return _lc_watched

            setattr(cls, name, _make_cls(name, orig))
        return obj


def init_from_env(env=os.environ) -> None:
    """Apply CUEBALL_STACK_TRACES / CUEBALL_DEBUG_SIGNAL. Called once at
    package import so both work with zero application code. Bad values
    (unknown signal name, import off the main thread) must not make the
    package unimportable: they log and continue."""
    if env.get('CUEBALL_STACK_TRACES', '') not in ('', '0'):
        mod_utils.enable_stack_traces()
    sig = env.get('CUEBALL_DEBUG_SIGNAL', '')
    if sig and sig != '0':
        try:
            name = sig.upper()
            if not name.startswith('SIG'):
                name = 'SIG' + name
            signum = signal.SIGUSR2 if sig == '1' \
                else getattr(signal, name)
            install_debug_handler(signum)
        except (AttributeError, ValueError, OSError) as e:
            _LOG.warning(
                'CUEBALL_DEBUG_SIGNAL=%s not installed: %s', sig, e)

"""Kang-style debug HTTP server.

The reference exposes pool-monitor snapshots over Joyent's kang
protocol, with the HTTP server supplied by the consumer (kang is a
devDependency; reference lib/pool-monitor.js:60-216,
test/monitor.test.js). Here the framework ships its own asyncio HTTP
endpoint: persistent HTTP/1.1 connections (Connection: close and
HTTP/1.0 honored), strict request-line/header parsing (400 on
malformed, 405 on non-GET), and the kang service-ident handshake —
/kang/snapshot leads with the `service` block (name, component, ident,
version, pid) that kang aggregators use to identify an agent, built
from PoolMonitor.to_kang_options().

    GET /kang/snapshot          - service ident + all registered objects
    GET /kang/types             - ['pool', 'set', 'dns_res']
    GET /kang/objects/<type>    - ids of registered objects of a type
    GET /kang/obj/<type>/<id>   - one object's snapshot
    GET /kang/fleet             - attached FleetSampler's batched decisions
    GET /kang/shards            - started FleetRouters' shard snapshots
    GET /kang/traces            - claim/DNS trace ring as NDJSON spans;
                                  ?limit=N keeps the newest N traces,
                                  ?backend=<key> keeps only traces with
                                  a span attributed to that backend
    GET /kang/health            - health monitors' verdicts: per-backend
                                  gray flags and SLO burn rates
    GET /kang/profile           - claim-path profile as collapsed-stack
                                  flamegraph text (ledger phases +
                                  sampler hits; empty when idle)
    GET /kang/transport         - transport wire ledger: per-seam
                                  byte/syscall counters, socket_wait
                                  wire totals and loop-lag stats;
                                  ?transport=<name> / ?seam=<name>
                                  narrow the counter table
    GET /metrics                - prometheus text metrics (collector)
"""

from __future__ import annotations

import asyncio
import json
import os
import urllib.parse

from . import trace as mod_trace
from .monitor import pool_monitor

_MAX_HEADERS = 64
_MAX_LINE = 8192

_REASONS = {200: b'OK', 400: b'Bad Request', 404: b'Not Found',
            405: b'Method Not Allowed'}


def _json_default(o):
    return repr(o)


def _kang_snapshot() -> dict:
    """The kang agent handshake: service ident first, then stats and
    the per-type object listings (kang snapshot shape; reference
    lib/pool-monitor.js:60-79 toKangOptions feeds the same fields to
    the kang server)."""
    opts = pool_monitor.to_kang_options()
    snap = {
        'service': {
            'name': opts['service_name'],
            'component': 'cueball_tpu',
            'ident': opts['ident'],
            'version': opts['version'],
            'pid': os.getpid(),
        },
        'stats': opts['stats'](),
    }
    snap.update(pool_monitor.snapshot())
    return snap


async def _read_request(reader):
    """Parse one request. Returns (method, path, keep_alive) or a
    status int on protocol error, or None on clean EOF."""
    try:
        line = await reader.readline()
    except ValueError:       # line exceeded the stream's 64 KiB limit
        return 400
    if not line:
        return None
    if len(line) > _MAX_LINE:
        return 400
    parts = line.decode('latin-1').rstrip('\r\n').split(' ')
    if len(parts) != 3 or not parts[1].startswith('/'):
        return 400
    method, path, version = parts
    if version not in ('HTTP/1.1', 'HTTP/1.0'):
        return 400

    headers = {}
    # One extra iteration beyond the cap belongs to the blank
    # terminator line, so exactly _MAX_HEADERS headers are accepted.
    for _ in range(_MAX_HEADERS + 1):
        try:
            h = await reader.readline()
        except ValueError:
            return 400
        if h in (b'\r\n', b'\n'):
            break
        if h == b'' or len(h) > _MAX_LINE:
            return 400
        name, sep, value = h.decode('latin-1').partition(':')
        if not sep:
            return 400
        headers[name.strip().lower()] = value.strip()
    else:
        return 400

    conn = headers.get('connection', '').lower()
    if version == 'HTTP/1.0':
        keep_alive = conn == 'keep-alive'
    else:
        keep_alive = conn != 'close'

    # Drain any request body so keep-alive never parses body bytes as
    # the next request line; chunked is not worth parsing on a debug
    # port, so such connections simply close after the response.
    if 'transfer-encoding' in headers:
        keep_alive = False
    else:
        clen = headers.get('content-length')
        if clen is not None:
            try:
                n = int(clen)
            except ValueError:
                return 400
            if n < 0 or n > (1 << 20):
                return 400
            try:
                await reader.readexactly(n)
            except asyncio.IncompleteReadError:
                return None
    return method, path, keep_alive


def _health_payload() -> dict:
    """Active HealthMonitors' verdicts, without importing the parallel
    package (and jax) until something could actually have started one
    (the same late-binding trick as trace._active_fleet_routers)."""
    import sys
    mod = sys.modules.get('cueball_tpu.parallel.health')
    if mod is None:
        return {'n_monitors': 0, 'monitors': [], 'fleet': {}}
    return mod.health_snapshot()


def _route(method: str, path: str, collector):
    """Dispatch one request; returns (status, ctype, body)."""
    if method != 'GET':
        return 405, 'application/json', b'{"error": "GET only"}'
    path, _, query = path.partition('?')
    ctype = 'application/json'
    try:
        if path == '/kang/snapshot':
            body = json.dumps(_kang_snapshot(),
                              default=_json_default).encode()
        elif path == '/kang/types':
            body = json.dumps(pool_monitor.list_types()).encode()
        elif path.startswith('/kang/objects/'):
            t = path.split('/')[3]
            body = json.dumps(pool_monitor.list_objects(t)).encode()
        elif path.startswith('/kang/obj/'):
            _, _, _, t, id_ = path.split('/', 4)
            body = json.dumps(pool_monitor.get(t, id_),
                              default=_json_default).encode()
        elif path == '/kang/fleet':
            body = json.dumps(pool_monitor.fleet_snapshot(),
                              default=_json_default).encode()
        elif path == '/kang/shards':
            body = json.dumps(
                {'routers': [r.snapshot()
                             for r in mod_trace._active_fleet_routers()]},
                default=_json_default).encode()
        elif path == '/kang/traces':
            # Completed claim/DNS traces, one OTLP-field-named span per
            # line (see trace.py). Empty body when tracing is off.
            # ?limit=N / ?backend=<key> narrow to whole traces (the
            # slow claims attributed to a flagged backend).
            params = urllib.parse.parse_qs(query,
                                           keep_blank_values=True)
            limit = backend = None
            if 'limit' in params:
                try:
                    limit = int(params['limit'][-1])
                except ValueError:
                    return (400, ctype, json.dumps(
                        {'error': 'limit must be an integer, got %r'
                                  % params['limit'][-1]}).encode())
                if limit < 0:
                    return (400, ctype, json.dumps(
                        {'error': 'limit must be >= 0, got %d'
                                  % limit}).encode())
            if 'backend' in params:
                backend = params['backend'][-1]
                if not mod_trace.backend_known(backend):
                    return (400, ctype, json.dumps(
                        {'error': 'unknown backend %r' % backend}
                    ).encode())
            body = mod_trace.filter_ndjson(
                mod_trace.export_ndjson(), limit, backend).encode()
            ctype = 'application/x-ndjson'
        elif path == '/kang/health':
            # ?limit=N keeps the newest N monitor rows (the fleet
            # merge always covers all of them). Malformed params are
            # 400s with JSON bodies, same convention as /kang/traces.
            params = urllib.parse.parse_qs(query,
                                           keep_blank_values=True)
            unknown = sorted(set(params) - {'limit'})
            if unknown:
                return (400, ctype, json.dumps(
                    {'error': 'unknown parameter(s) %s; supported: '
                              'limit' % ', '.join(unknown)}).encode())
            payload = _health_payload()
            if 'limit' in params:
                try:
                    limit = int(params['limit'][-1])
                except ValueError:
                    return (400, ctype, json.dumps(
                        {'error': 'limit must be an integer, got %r'
                                  % params['limit'][-1]}).encode())
                if limit < 0:
                    return (400, ctype, json.dumps(
                        {'error': 'limit must be >= 0, got %d'
                                  % limit}).encode())
                payload = dict(payload,
                               monitors=payload['monitors'][-limit:]
                               if limit else [])
            body = json.dumps(payload,
                              default=_json_default).encode()
        elif path == '/kang/profile':
            # Collapsed-stack flamegraph text: one "frame;frame N"
            # line per ledger phase and sampler bucket; feed to any
            # flamegraph renderer. Empty when nothing was profiled.
            # ?phase=<name> keeps only that ledger phase's stacks;
            # malformed params are 400 JSON, per the /kang/traces
            # convention.
            from . import profile as mod_profile
            params = urllib.parse.parse_qs(query,
                                           keep_blank_values=True)
            unknown = sorted(set(params) - {'phase'})
            if unknown:
                return (400, ctype, json.dumps(
                    {'error': 'unknown parameter(s) %s; supported: '
                              'phase' % ', '.join(unknown)}).encode())
            phase = None
            if 'phase' in params:
                phase = params['phase'][-1]
                if phase not in mod_profile.PHASES:
                    return (400, ctype, json.dumps(
                        {'error': 'unknown phase %r; one of %s' % (
                            phase, ', '.join(mod_profile.PHASES))}
                    ).encode())
            text = mod_profile.flamegraph()
            if phase is not None:
                kept = [ln for ln in text.splitlines()
                        if ln.split(' ')[0].split(';')[1] == phase]
                text = '\n'.join(kept) + '\n' if kept else ''
            body = text.encode()
            ctype = 'text/plain; charset=utf-8'
        elif path == '/kang/transport':
            # The wiretap ledger: per-(transport, seam) counters, the
            # socket_wait wire totals and loop-lag sampler stats.
            # ?transport=<name> / ?seam=<name> narrow the counter
            # table; malformed params are 400 JSON, per the
            # /kang/traces convention.
            from . import wiretap as mod_wiretap
            params = urllib.parse.parse_qs(query,
                                           keep_blank_values=True)
            unknown = sorted(set(params) - {'transport', 'seam'})
            if unknown:
                return (400, ctype, json.dumps(
                    {'error': 'unknown parameter(s) %s; supported: '
                              'transport, seam'
                              % ', '.join(unknown)}).encode())
            seam = None
            if 'seam' in params:
                seam = params['seam'][-1]
                if seam not in mod_wiretap.SEAMS:
                    return (400, ctype, json.dumps(
                        {'error': 'unknown seam %r; one of %s' % (
                            seam, ', '.join(mod_wiretap.SEAMS))}
                    ).encode())
            transports = mod_wiretap.snapshot()
            if 'transport' in params:
                tname = params['transport'][-1]
                if tname not in transports:
                    return (400, ctype, json.dumps(
                        {'error': 'unknown transport %r; active: %s'
                                  % (tname,
                                     ', '.join(sorted(transports))
                                     or '(none)')}).encode())
                transports = {tname: transports[tname]}
            if seam is not None:
                transports = {
                    t: {seam: seams[seam]}
                    for t, seams in transports.items()
                    if seam in seams}
            body = json.dumps({
                'enabled': mod_wiretap.wiretap_enabled(),
                'transports': transports,
                'wire_ms': mod_wiretap.wire_totals(),
                'loop_lag': mod_wiretap.loop_lag_stats(),
            }, default=_json_default).encode()
        elif path == '/metrics' and collector is not None:
            body = collector.collect().encode()
            ctype = 'text/plain; version=0.0.4'
        else:
            return 404, ctype, b'{"error": "not found"}'
    except (KeyError, ValueError, IndexError) as e:
        return 404, ctype, json.dumps({'error': str(e)}).encode()
    return 200, ctype, body


async def _serve_client(reader, writer, collector=None):
    try:
        while True:
            req = await _read_request(reader)
            if req is None:
                return
            if isinstance(req, int):        # protocol error
                status, ctype, body = (req, 'application/json',
                                       b'{"error": "bad request"}')
                keep_alive = False
            else:
                method, path, keep_alive = req
                status, ctype, body = _route(method, path, collector)
            writer.write(
                b'HTTP/1.1 %d %s\r\nContent-Type: %s\r\n'
                b'Content-Length: %d\r\nConnection: %s\r\n\r\n' % (
                    status, _REASONS.get(status, b'Error'),
                    ctype.encode(), len(body),
                    b'keep-alive' if keep_alive else b'close') + body)
            await writer.drain()
            if not keep_alive:
                return
    except ConnectionError:
        pass
    finally:
        writer.close()


async def serve_monitor(port: int = 0, host: str = '127.0.0.1',
                        collector=None, transport=None):
    """Start the kang endpoint; returns the asyncio server (its bound
    port via server.sockets[0].getsockname()[1]). The listening socket
    comes from the Transport seam (default AsyncioTransport)."""
    from . import transport as mod_transport
    return await mod_transport.get_transport(transport).serve(
        lambda r, w: _serve_client(r, w, collector=collector),
        host, port)

"""Kang-style debug HTTP server.

The reference exposes pool-monitor snapshots over Joyent's kang protocol,
with the HTTP server supplied by the consumer (kang is a devDependency;
reference lib/pool-monitor.js:60-216, test/monitor.test.js). Here the
framework ships its own minimal asyncio HTTP endpoint:

    GET /kang/snapshot          - full snapshot of all registered objects
    GET /kang/types             - ['pool', 'set', 'dns_res']
    GET /kang/objects/<type>    - ids of registered objects of a type
    GET /kang/obj/<type>/<id>   - one object's snapshot
    GET /kang/fleet             - attached FleetSampler's batched decisions
    GET /metrics                - prometheus text metrics (collector)
"""

from __future__ import annotations

import asyncio
import json

from .monitor import pool_monitor


def _json_default(o):
    return repr(o)


async def _serve_client(reader, writer, collector=None):
    try:
        line = await reader.readline()
        if not line:
            return
        parts = line.decode('latin-1').split(' ')
        if len(parts) < 2:
            return
        method, path = parts[0], parts[1]
        while True:
            h = await reader.readline()
            if h in (b'\r\n', b'\n', b''):
                break

        status = 200
        ctype = 'application/json'
        try:
            if path == '/kang/snapshot':
                body = json.dumps(pool_monitor.snapshot(),
                                  default=_json_default).encode()
            elif path == '/kang/types':
                body = json.dumps(pool_monitor.list_types()).encode()
            elif path.startswith('/kang/objects/'):
                t = path.split('/')[3]
                body = json.dumps(pool_monitor.list_objects(t)).encode()
            elif path.startswith('/kang/obj/'):
                _, _, _, t, id_ = path.split('/', 4)
                body = json.dumps(pool_monitor.get(t, id_),
                                  default=_json_default).encode()
            elif path == '/kang/fleet':
                body = json.dumps(pool_monitor.fleet_snapshot(),
                                  default=_json_default).encode()
            elif path == '/metrics' and collector is not None:
                body = collector.collect().encode()
                ctype = 'text/plain; version=0.0.4'
            else:
                status, body = 404, b'{"error": "not found"}'
        except (KeyError, ValueError, IndexError) as e:
            status, body = 404, json.dumps(
                {'error': str(e)}).encode()

        writer.write(
            b'HTTP/1.1 %d %s\r\nContent-Type: %s\r\n'
            b'Content-Length: %d\r\nConnection: close\r\n\r\n' % (
                status, b'OK' if status == 200 else b'Not Found',
                ctype.encode(), len(body)) + body)
        await writer.drain()
    finally:
        writer.close()


async def serve_monitor(port: int = 0, host: str = '127.0.0.1',
                        collector=None):
    """Start the kang endpoint; returns the asyncio server (its bound
    port via server.sockets[0].getsockname()[1])."""
    return await asyncio.start_server(
        lambda r, w: _serve_client(r, w, collector=collector),
        host, port)

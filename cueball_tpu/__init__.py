"""cueball_tpu -- connection pooling and service discovery for TPU-host fleets.

A from-scratch, asyncio-native rebuild of the capability set of
TritonDataCenter/node-cueball (reference: /root/reference/lib/index.js:17-38).
Where the reference manages pools of TCP/TLS connections from Node.js
services to DNS-discovered backends, this framework manages pools of
asyncio connections from TPU-host processes (controllers, data loaders,
inference routers) to DCN-side service fleets.

Public API parity map (reference lib/index.js:17-38):

  ConnectionPool        -> cueball_tpu.ConnectionPool      (pool.py)
  ConnectionSet         -> cueball_tpu.ConnectionSet       (cset.py)
  Resolver              -> cueball_tpu.Resolver            (resolver.py)
  DNSResolver           -> cueball_tpu.DNSResolver         (resolver.py)
  StaticIpResolver      -> cueball_tpu.StaticIpResolver    (resolver.py)
  resolverForIpOrDomain -> cueball_tpu.resolver_for_ip_or_domain
  HttpAgent/HttpsAgent  -> cueball_tpu.HttpAgent/HttpsAgent (agent.py)
  poolMonitor           -> cueball_tpu.pool_monitor        (monitor.py)
  enableStackTraces     -> cueball_tpu.enable_stack_traces (utils.py)
  error classes         -> cueball_tpu.errors              (errors.py)

The numeric control algorithms (low-pass shrink damping, CoDel, backoff
schedules) additionally have batched JAX implementations under
cueball_tpu.ops / cueball_tpu.parallel for fleet-scale telemetry on TPU.
"""

from .errors import (
    ClaimHandleMisusedError,
    ClaimTimeoutError,
    NoBackendsError,
    PoolFailedError,
    PoolStoppingError,
    ConnectionError,
    ConnectionTimeoutError,
    ConnectionClosedError,
    TransportNotAvailableError,
)
from .events import EventEmitter
from .fsm import FSM
from .cqueue import Queue
from .utils import (
    enable_stack_traces,
    stack_traces_enabled,
    current_millis,
    plan_rebalance,
)
from .codel import ControlledDelay

from .resolver import (
    Resolver,
    DNSResolver,
    StaticIpResolver,
    ResolverFSM,
    resolver_for_ip_or_domain,
    config_for_ip_or_domain,
)
from .pool import ConnectionPool
from .monitor import pool_monitor
from .cset import ConnectionSet
from .agent import HttpAgent, HttpsAgent
from .trace import (
    enable_tracing,
    disable_tracing,
    tracing_enabled,
    trace_ring,
)
from .debug import (
    dump_fsm_histories,
    install_debug_handler,
    LoopAffinityChecker,
    init_from_env as _debug_init_from_env,
)
from .transport import (
    Transport,
    AsyncioTransport,
    FabricTransport,
    NativeTransport,
    get_transport,
    register_transport,
)

__version__ = '1.0.0'

# Live-attach diagnostics (reference lib/utils.js:59-99 dtrace probe
# analogue): CUEBALL_STACK_TRACES=1 enables claim stack capture at
# startup; CUEBALL_DEBUG_SIGNAL=1 (or a signal name) installs a handler
# that toggles capture and dumps all FSM histories on each delivery.
_debug_init_from_env()

# camelCase aliases matching the reference's exact export names
# (reference lib/index.js:17-38), for drop-in familiarity.
resolverForIpOrDomain = resolver_for_ip_or_domain
configForIpOrDomain = config_for_ip_or_domain
poolMonitor = pool_monitor
enableStackTraces = enable_stack_traces

__all__ = [
    'ConnectionPool', 'ConnectionSet',
    'Resolver', 'DNSResolver', 'StaticIpResolver', 'ResolverFSM',
    'resolver_for_ip_or_domain', 'config_for_ip_or_domain',
    'resolverForIpOrDomain', 'configForIpOrDomain',
    'HttpAgent', 'HttpsAgent',
    'pool_monitor', 'poolMonitor', 'enableStackTraces',
    'dump_fsm_histories', 'install_debug_handler',
    'LoopAffinityChecker',
    'enable_tracing', 'disable_tracing', 'tracing_enabled',
    'trace_ring',
    'Transport', 'AsyncioTransport', 'FabricTransport',
    'NativeTransport', 'get_transport', 'register_transport',
    'EventEmitter', 'FSM', 'Queue', 'ControlledDelay',
    'enable_stack_traces', 'stack_traces_enabled', 'current_millis',
    'plan_rebalance',
    'ClaimHandleMisusedError', 'ClaimTimeoutError', 'NoBackendsError',
    'PoolFailedError', 'PoolStoppingError', 'ConnectionError',
    'ConnectionTimeoutError', 'ConnectionClosedError',
    'TransportNotAvailableError',
]

"""Intrusive doubly-linked queue.

Rebuild of the reference's `lib/queue.js:13-75`: a sentinel-node circular
doubly-linked list giving O(1) push/shift and — the important part — O(1)
removal from the middle via the node handle, which the pool uses to pull
cancelled waiters and stale idle slots out of its queues without scanning
(reference lib/pool.js:191-193 idleq/initq/waiters usage).
"""

from __future__ import annotations

import typing


class QueueNode:
    __slots__ = ('value', 'prev', 'next', '_queue')

    def __init__(self, value, queue: 'Queue | None'):
        self.value = value
        self.prev: 'QueueNode | None' = None
        self.next: 'QueueNode | None' = None
        self._queue = queue

    def remove(self) -> None:
        """Unlink this node from its queue; idempotent."""
        if self._queue is None:
            return
        q = self._queue
        assert self.prev is not None and self.next is not None
        self.prev.next = self.next
        self.next.prev = self.prev
        self.prev = None
        self.next = None
        self._queue = None
        q._length -= 1

    def is_queued(self) -> bool:
        return self._queue is not None


class Queue:
    """FIFO with O(1) arbitrary removal. Iteration yields values."""

    def __init__(self) -> None:
        # Sentinel head: head.next is front, head.prev is back.
        self._head = QueueNode(None, None)
        self._head.prev = self._head
        self._head.next = self._head
        self._length = 0

    def __len__(self) -> int:
        return self._length

    @property
    def length(self) -> int:
        return self._length

    def is_empty(self) -> bool:
        return self._length == 0

    def push(self, value) -> QueueNode:
        """Append to the back; returns the node handle."""
        node = QueueNode(value, self)
        back = self._head.prev
        assert back is not None
        node.prev = back
        node.next = self._head
        back.next = node
        self._head.prev = node
        self._length += 1
        return node

    def peek(self):
        if self._length == 0:
            return None
        assert self._head.next is not None
        return self._head.next.value

    def shift(self):
        """Pop from the front; returns the value (None if empty)."""
        if self._length == 0:
            return None
        node = self._head.next
        assert node is not None
        node.remove()
        return node.value

    def __iter__(self) -> typing.Iterator:
        """Iterate over a snapshot of the nodes present at iteration start,
        skipping any removed mid-iteration. (Hardening over the reference's
        forEach, lib/queue.js:66-73, which breaks if a callback removes the
        next node.)"""
        nodes = []
        node = self._head.next
        while node is not self._head:
            assert node is not None
            nodes.append(node)
            node = node.next
        for n in nodes:
            if n.is_queued():
                yield n.value

    def for_each(self, fn: typing.Callable) -> None:
        for v in self:
            fn(v)

"""DNS service-discovery resolver.

Rebuild of reference `lib/resolver.js:152-1377`: the 23-state
SRV -> AAAA -> A -> process -> sleep workflow with TTL-driven refresh.

Workflow (reference lib/resolver.js:153-178): query SRV records for
`service.domain`; for each resulting (name, port) fill in addresses via
AAAA then A lookups (exploiting the SRV response's Additional section
when present); diff the resulting backend set against the previous one,
emitting 'removed' then 'added'; then sleep until the earliest TTL
expiry and resume at the stage whose data expired.

Policy matrix preserved (SURVEY.md §7.4 calls it compatibility-critical):
- SRV NXDOMAIN/NODATA/NOTIMP: fall through to plain AAAA/A on the base
  domain; re-check SRV in 60min, or the NODATA SOA TTL when present.
- SRV REFUSED: non-retryable; other errors: exponential backoff retries.
- Anti-flap: after retries exhaust, only fall back to A/AAAA if SRV has
  never succeeded before (node-moray depends on this accidental API:
  reference lib/resolver.js:687-723).
- AAAA NODATA/NOTIMP: skip name quietly (cached NIC_CACHE_TTL);
  A NODATA with v6 present: skip; NXDOMAIN/REFUSED: non-retryable.
- Multi-resolver failures vote on the most common rcode
  (reference lib/resolver.js:1227-1259).
- IPv6 lookups are skipped entirely when no global v6 NIC exists
  (60s-cached probe, reference lib/resolver.js:738-772).
- Nameserver bootstrap ("Dynamic Resolver mode"): when `resolvers` is a
  single DNS name, a shared refcounted bootstrap resolver looks it up
  via _dns._udp and feeds this resolver's nameserver list
  (reference lib/resolver.js:475-540, docs/api.adoc:752-801).
"""

from __future__ import annotations

import logging
import math
import os
# Interface enumeration (not byte movement): getifaddrs-style
# probing has no Transport verb, so the raw import stays licensed.
import socket  # cblint: ignore=C110

from . import dns_client as mod_nsc
from . import trace as mod_trace
from . import utils as mod_utils
from .events import EventEmitter
from .fsm import FSM
from .utils import delay as gen_delay

NIC_CACHE_TTL_S = 60.0

_nic_cache: dict = {'updated': None, 'have_v6': False}


def _probe_global_v6() -> bool:
    """True if this host has a global (non-loopback) IPv6 address. Uses
    a connected UDP socket, which sends no packets
    (the os.networkInterfaces() analogue, reference
    lib/resolver.js:741-755)."""
    try:
        s = socket.socket(socket.AF_INET6, socket.SOCK_DGRAM)
        try:
            s.connect(('2001:4860:4860::8888', 53))
            addr = s.getsockname()[0]
            return addr not in ('::1', '::')
        finally:
            s.close()
    except OSError:
        return False


def have_global_v6() -> bool:
    now = mod_utils.get_clock().monotonic()
    if _nic_cache['updated'] is None or \
            now - _nic_cache['updated'] > NIC_CACHE_TTL_S:
        _nic_cache['have_v6'] = _probe_global_v6()
        _nic_cache['updated'] = now
    return _nic_cache['have_v6']


def _read_resolv_conf(path='/etc/resolv.conf') -> list[str]:
    """Parse nameserver lines; [8.8.8.8, 8.8.4.4] fallback
    (reference lib/resolver.js:492-510)."""
    import re
    try:
        with open(path) as f:
            content = f.read()
    except OSError:
        return ['8.8.8.8', '8.8.4.4']
    out = []
    for line in content.split('\n'):
        m = re.match(r'^\s*nameserver\s+([^\s]+)\s*$', line)
        if m:
            from .resolver import _is_ip
            if _is_ip(m.group(1)):
                out.append(m.group(1))
    return out or ['8.8.8.8', '8.8.4.4']


class DNSResolverFSM(FSM):
    """Inner DNS resolver machine; the public DNSResolver() factory wraps
    it in the 5-state ResolverFSM contract
    (reference lib/resolver.js:408 returns the wrapper)."""

    # Shared bootstrap registry + per-concurrency client cache
    # (reference lib/resolver.js:411-413,385-392).
    bootstrap_resolvers: dict = {}
    global_ns_clients: dict = {}

    def __init__(self, options: dict):
        if not isinstance(options, dict):
            raise AssertionError('options must be a dict')
        resolvers = options.get('resolvers')
        if resolvers is not None and not (
                isinstance(resolvers, list) and
                all(isinstance(r, str) for r in resolvers)):
            raise AssertionError(
                'options.resolvers must be a list of strings')
        domain = options.get('domain')
        if not isinstance(domain, str):
            raise AssertionError('options.domain must be a string')

        self.r_uuid = mod_utils.make_uuid()
        self.r_resolvers = list(resolvers or [])
        self.r_domain = domain
        self.r_service = options.get('service') or '_http._tcp'
        self.r_maxres = options.get('maxDNSConcurrency') or 3
        self.r_defport = options.get('defaultPort') or 80
        self.r_is_bootstrap = bool(options.get('_isBootstrap'))

        if self.r_is_bootstrap:
            # Bootstrap resolvers look up the DNS service itself and try
            # all possible resolvers (reference lib/resolver.js:265-281).
            self.r_service = '_dns._udp'
            self.r_defport = 53
            self.r_maxres = 10
            self.r_ref_count = 0

        self.r_log = mod_utils.make_child_logger(
            options.get('log') or logging.getLogger('cueball.dns'),
            component='CueBallDNSResolver', domain=self.r_domain)

        recovery = options.get('recovery')
        if not isinstance(recovery, dict):
            raise AssertionError('options.recovery is required')
        self.r_recovery = recovery

        from .utils import assert_recovery
        dns_srv_recov = recovery.get('default')
        dns_recov = recovery.get('default')
        if recovery.get('dns'):
            dns_srv_recov = recovery['dns']
            dns_recov = recovery['dns']
        if recovery.get('dns_srv'):
            dns_srv_recov = recovery['dns_srv']
        assert_recovery(dns_srv_recov, 'recovery.dns_srv')
        assert_recovery(dns_recov, 'recovery.dns')

        def mkretry(r):
            return {
                'max': r['retries'], 'count': r['retries'],
                'timeout': r['timeout'], 'minDelay': r['delay'],
                'delay': r['delay'],
                'delaySpread': r.get('delaySpread') or 0.2,
                'maxDelay': r.get('maxDelay') or math.inf,
            }
        self.r_srv_retry = mkretry(dns_srv_recov)
        self.r_retry = mkretry(dns_recov)

        # Next-refresh deadlines (epoch seconds); normally TTL expiries,
        # error-retry times otherwise (reference lib/resolver.js:330-343).
        now = mod_utils.wall_time()
        self.r_next_service: float | None = now
        self.r_next_v6: float | None = now
        self.r_next_v4: float | None = now

        self.r_last_srv_ttl = 60
        self.r_last_ttl = 60
        self.r_last_error = None

        self.r_srvs: list[dict] = []
        self.r_srv_rem: list[dict] = []
        self.r_srv: dict | None = None
        self.r_backends: dict[str, dict] = {}

        self.r_bootstrap = None
        self.r_bootstrap_res: dict = {}

        # Injectable for tests (the reference stubs mname-client).
        self.r_nsclient = options.get('dnsClient')
        if self.r_nsclient is None:
            cache = DNSResolverFSM.global_ns_clients
            self.r_nsclient = cache.get(self.r_maxres)
            if self.r_nsclient is None:
                self.r_nsclient = mod_nsc.DnsClient(
                    concurrency=self.r_maxres)
                cache[self.r_maxres] = self.r_nsclient

        self.r_stopping = False
        self.r_have_seen_srv = False
        self.r_have_seen_addr = False
        self.r_counters: dict[str, int] = {}
        self.r_last_processed = None

        super().__init__('init')

    # -- helpers -----------------------------------------------------------

    def _incr_counter(self, counter: str) -> None:
        self.r_counters[counter] = self.r_counters.get(counter, 0) + 1

    def _hwm_counter(self, counter: str, val) -> None:
        if self.r_counters.get(counter, -math.inf) < val:
            self.r_counters[counter] = val

    def start(self) -> None:
        self.emit('startAsserted')

    def stop(self) -> None:
        self.r_stopping = True
        self.emit('stopAsserted')

    def count(self) -> int:
        return len(self.r_backends)

    def list(self) -> dict:
        return dict(self.r_backends)

    def get_last_error(self):
        return self.r_last_error

    getLastError = get_last_error

    # -- states ------------------------------------------------------------

    def state_init(self, S):
        S.validTransitions(['check_ns'])
        from .monitor import pool_monitor
        self.r_stopping = False
        pool_monitor.register_dns_resolver(self)
        if self.r_bootstrap is not None:
            self.r_bootstrap.r_ref_count -= 1
            if self.r_bootstrap.r_ref_count <= 0:
                self.r_bootstrap.stop()
            self.r_bootstrap = None
        S.goto_state_on(self, 'startAsserted', 'check_ns')

    def state_check_ns(self, S):
        """Figure out which nameservers to use: explicit IPs, a bootstrap
        name, or /etc/resolv.conf (reference lib/resolver.js:465-510)."""
        S.validTransitions(['srv', 'bootstrap_ns'])
        from .resolver import _is_ip
        if self.r_resolvers:
            # 'host@port' is accepted for non-53 nameservers (test rigs);
            # strip the port before deciding IP vs. bootstrap name.
            not_ip = [r for r in self.r_resolvers
                      if _is_ip(r.partition('@')[0]) == 0]
            if not not_ip:
                S.gotoState('srv')
                return
            assert len(not_ip) == 1, \
                'only one bootstrap resolver name is supported'
            self.r_resolvers = []
            boot = DNSResolverFSM.bootstrap_resolvers.get(not_ip[0])
            if boot is None:
                res = DNSResolver({
                    'domain': not_ip[0],
                    'log': self.r_log,
                    'recovery': self.r_recovery,
                    'dnsClient': self.r_nsclient,
                    '_isBootstrap': True,
                })
                boot = res.r_fsm
                DNSResolverFSM.bootstrap_resolvers[not_ip[0]] = boot
            self.r_bootstrap = boot
            boot.r_ref_count += 1
            S.gotoState('bootstrap_ns')
        else:
            self.r_resolvers = _read_resolv_conf()
            S.gotoState('srv')

    def state_bootstrap_ns(self, S):
        S.validTransitions(['srv'])
        boot = self.r_bootstrap

        def on_added(k, srv):
            self.r_bootstrap_res[k] = srv
            self.r_resolvers.append(srv['address'])

        def on_removed(k):
            srv = self.r_bootstrap_res.pop(k)
            assert srv['address'] in self.r_resolvers
            self.r_resolvers.remove(srv['address'])

        # Persistent listeners: survive this state BY DESIGN (the
        # bootstrap keeps feeding r_resolvers for the resolver's whole
        # life, reference lib/resolver.js:513-526) — exempt from the
        # state-scoped registration discipline.
        boot.on('added', on_added)        # cbfsm: ignore=F006
        boot.on('removed', on_removed)    # cbfsm: ignore=F006

        if boot.count() > 0:
            srvs = boot.list()
            self.r_bootstrap_res = srvs
            for k, srv in srvs.items():
                self.r_resolvers.append(srv['address'])
            S.gotoState('srv')
        else:
            S.on(boot, 'added', lambda k, srv: S.gotoState('srv'))
            boot.start()

    # -- SRV section -------------------------------------------------------

    def state_srv(self, S):
        S.validTransitions(['srv_try'])
        r = self.r_srv_retry
        r['delay'] = r['minDelay']
        r['count'] = r['max']
        S.gotoState('srv_try')

    def state_srv_try(self, S):
        S.validTransitions(['aaaa', 'srv_error'])
        name = '%s.%s' % (self.r_service, self.r_domain)
        req = self.resolve(name, 'SRV', self.r_srv_retry['timeout'])

        def on_answers(ans, ttl):
            self.r_next_service = mod_utils.wall_time() + ttl
            self.r_last_srv_ttl = ttl
            self.r_last_ttl = ttl
            self.r_have_seen_srv = True

            # Merge cached v4/v6 expiries from the previous round
            # (reference lib/resolver.js:554-580).
            old_lookup: dict = {}
            for srv in self.r_srvs:
                old_lookup.setdefault(srv['name'], {})[srv['port']] = srv
            for srv in ans:
                old = old_lookup.get(srv['name'], {}).get(srv['port'])
                if old is None:
                    continue
                for fld in ('expiry_v4', 'addresses_v4', 'expiry_v6',
                            'addresses_v6'):
                    if old.get(fld) is not None:
                        srv[fld] = old[fld]

            self.r_srvs = ans
            S.gotoState('aaaa')
        S.on(req, 'answers', on_answers)

        def on_error(err):
            from .resolver import NoNameError, NoRecordsError
            self.r_last_error = RuntimeError(
                'SRV lookup for "%s" failed: %s' % (name, err))
            self.r_last_error.__cause__ = err
            self._incr_counter('srv-failure')

            code = getattr(err, 'code', None)
            if isinstance(err, (NoRecordsError, NoNameError)) or \
                    code == 'NOTIMP':
                # No SRV records: fall through to plain AAAA/A on the
                # base domain; re-check in 60min or the SOA TTL
                # (reference lib/resolver.js:589-644).
                self.r_srvs = [{'name': self.r_domain,
                                'port': self.r_defport}]
                ttl = 60 * 60
                if code == 'NOTIMP':
                    self.r_log.info(
                        'SRV got NOTIMP for %s; retry in %d seconds',
                        self.r_service, ttl)
                else:
                    if getattr(err, 'ttl', None):
                        ttl = err.ttl
                    self.r_log.info(
                        'no SRV records for %s; retry in %d seconds',
                        self.r_service, ttl)
                self.r_next_service = mod_utils.wall_time() + ttl
                self._incr_counter('srv-skipped')
                S.gotoState('aaaa')
            elif code == 'REFUSED':
                # Authoritative server refusing recursion: retrying is
                # pointless (reference lib/resolver.js:646-655).
                self.r_srv_retry['count'] = 0
                S.gotoState('srv_error')
            else:
                S.gotoState('srv_error')
        S.on(req, 'error', on_error)
        req.send()

    def state_srv_error(self, S):
        S.validTransitions(['srv_try', 'aaaa', 'sleep'])
        r = self.r_srv_retry
        r['count'] -= 1
        if r['count'] > 0:
            d = gen_delay(r['delay'], r['delaySpread'])
            S.timeout(d, lambda: S.gotoState('srv_try'))
            r['delay'] *= 2
            if r['delay'] > r['maxDelay']:
                r['delay'] = r['maxDelay']
            return

        self.r_srvs = [{'name': self.r_domain, 'port': self.r_defport}]
        d = mod_utils.wall_time() + self.r_last_srv_ttl
        self.r_next_service = d

        # Anti-flap rules (reference lib/resolver.js:687-723): only fall
        # back to plain-name A/AAAA if SRV has never succeeded.
        if not self.r_have_seen_srv and not self.r_have_seen_addr:
            self.r_log.debug(
                'no SRV records found for service %s, trying as a '
                'plain name', self.r_service)
            S.gotoState('aaaa')
            return
        elif not self.r_have_seen_srv:
            self.r_log.info(
                'no SRV records found for service %s, falling back '
                'to A/AAAA for 15min', self.r_service)
            self.r_next_service = mod_utils.wall_time() + 60 * 15
            S.gotoState('aaaa')
            return

        # Wake up for SRV, not A/AAAA.
        if self.r_next_v6 is not None and self.r_next_v6 < d:
            self.r_next_v6 = d
        if self.r_next_v4 is not None and self.r_next_v4 < d:
            self.r_next_v4 = d
        S.gotoState('sleep')

    # -- AAAA section ------------------------------------------------------

    def state_aaaa(self, S):
        S.validTransitions(['aaaa_next', 'a'])
        if have_global_v6():
            self.r_next_v6 = None
            self.r_srv_rem = list(self.r_srvs)
            S.gotoState('aaaa_next')
        else:
            # Re-check after the NIC cache has definitely expired.
            self.r_next_v6 = mod_utils.wall_time() + NIC_CACHE_TTL_S + 0.001
            S.gotoState('a')

    def state_aaaa_next(self, S):
        S.validTransitions(['aaaa_try', 'a'])
        r = self.r_retry
        r['delay'] = r['minDelay']
        r['count'] = r['max']
        if self.r_srv_rem:
            self.r_srv = self.r_srv_rem.pop(0)
            S.gotoState('aaaa_try')
        else:
            S.gotoState('a')

    def state_aaaa_try(self, S):
        S.validTransitions(['aaaa_next', 'aaaa_error'])
        srv = self.r_srv
        from .resolver import _is_ip

        if srv.get('additionals'):
            self.r_log.debug('skipping v6 lookup for %s, using '
                             'additionals from SRV', srv['name'])
            srv['addresses_v6'] = [a for a in srv['additionals']
                                   if _is_ip(a) == 6]
            S.gotoState('aaaa_next')
            return

        now = mod_utils.wall_time()
        if srv.get('expiry_v6') is not None and srv['expiry_v6'] > now:
            if self.r_next_v6 is None or \
                    srv['expiry_v6'] <= self.r_next_v6:
                self.r_next_v6 = srv['expiry_v6']
            S.gotoState('aaaa_next')
            return

        req = self.resolve(srv['name'], 'AAAA', self.r_retry['timeout'])

        def on_answers(ans, ttl):
            d = mod_utils.wall_time() + ttl
            if self.r_next_v6 is None or d <= self.r_next_v6:
                self.r_next_v6 = d
            self.r_last_ttl = ttl
            self.r_have_seen_addr = True
            srv['expiry_v6'] = d
            srv['addresses_v6'] = [v['address'] for v in ans]
            S.gotoState('aaaa_next')
        S.on(req, 'answers', on_answers)

        def on_error(err):
            from .resolver import NoRecordsError
            code = getattr(err, 'code', None)
            if isinstance(err, NoRecordsError) or code == 'NOTIMP':
                # Name likely has only A records; skip quietly, cached
                # like the NIC data (reference lib/resolver.js:832-851).
                srv['expiry_v6'] = mod_utils.wall_time() + NIC_CACHE_TTL_S
                S.gotoState('aaaa_next')
                return
            elif code == 'REFUSED':
                self.r_retry['count'] = 0
            self.r_last_error = RuntimeError(
                'IPv6 (AAAA) lookup failed for "%s": %s' % (
                    srv['name'], err))
            self.r_last_error.__cause__ = err
            S.gotoState('aaaa_error')
        S.on(req, 'error', on_error)
        req.send()

    def state_aaaa_error(self, S):
        S.validTransitions(['aaaa_try', 'aaaa_next'])
        r = self.r_retry
        r['count'] -= 1
        if r['count'] > 0:
            d = gen_delay(r['delay'], r['delaySpread'])
            S.timeout(d, lambda: S.gotoState('aaaa_try'))
            r['delay'] *= 2
            if r['delay'] > r['maxDelay']:
                r['delay'] = r['maxDelay']
        else:
            d = mod_utils.wall_time() + 60 * 60
            if self.r_next_v6 is None or d <= self.r_next_v6:
                self.r_next_v6 = d
            S.gotoState('aaaa_next')

    # -- A section ---------------------------------------------------------

    def state_a(self, S):
        S.validTransitions(['a_next'])
        self.r_next_v4 = None
        self.r_srv_rem = list(self.r_srvs)
        S.gotoState('a_next')

    def state_a_next(self, S):
        S.validTransitions(['a_try', 'process'])
        r = self.r_retry
        r['delay'] = r['minDelay']
        r['count'] = r['max']
        if self.r_srv_rem:
            self.r_srv = self.r_srv_rem.pop(0)
            S.gotoState('a_try')
        else:
            S.gotoState('process')

    def state_a_try(self, S):
        S.validTransitions(['a_next', 'a_error'])
        srv = self.r_srv
        from .resolver import _is_ip

        if srv.get('additionals'):
            self.r_log.debug('skipping v4 lookup for %s, using '
                             'additionals from SRV', srv['name'])
            srv['addresses_v4'] = [a for a in srv['additionals']
                                   if _is_ip(a) == 4]
            S.gotoState('a_next')
            return

        now = mod_utils.wall_time()
        if srv.get('expiry_v4') is not None and srv['expiry_v4'] > now:
            if self.r_next_v4 is None or \
                    srv['expiry_v4'] <= self.r_next_v4:
                self.r_next_v4 = srv['expiry_v4']
            S.gotoState('a_next')
            return

        req = self.resolve(srv['name'], 'A', self.r_retry['timeout'])

        def on_answers(ans, ttl):
            d = mod_utils.wall_time() + ttl
            if self.r_next_v4 is None or d <= self.r_next_v4:
                self.r_next_v4 = d
            self.r_last_ttl = ttl
            self.r_have_seen_addr = True
            srv['expiry_v4'] = d
            srv['addresses_v4'] = [v['address'] for v in ans]
            S.gotoState('a_next')
        S.on(req, 'answers', on_answers)

        def on_error(err):
            from .resolver import NoNameError, NoRecordsError
            code = getattr(err, 'code', None)
            if isinstance(err, NoRecordsError):
                # NODATA for A: fine if we already have v6 addresses
                # (reference lib/resolver.js:958-973).
                if srv.get('addresses_v6'):
                    S.gotoState('a_next')
                    return
                self.r_retry['count'] = 0
            elif isinstance(err, NoNameError):
                self.r_retry['count'] = 0
            elif code == 'REFUSED':
                self.r_retry['count'] = 0
            self.r_last_error = RuntimeError(
                'IPv4 (A) lookup for "%s" failed: %s' % (
                    srv['name'], err))
            self.r_last_error.__cause__ = err
            S.gotoState('a_error')
        S.on(req, 'error', on_error)
        req.send()

    def state_a_error(self, S):
        S.validTransitions(['a_try', 'a_next'])
        r = self.r_retry
        r['count'] -= 1
        if r['count'] > 0:
            d = gen_delay(r['delay'], r['delaySpread'])
            S.timeout(d, lambda: S.gotoState('a_try'))
            r['delay'] *= 2
            if r['delay'] > r['maxDelay']:
                r['delay'] = r['maxDelay']
        else:
            d = mod_utils.wall_time() + self.r_last_ttl
            if self.r_next_v4 is None or d <= self.r_next_v4:
                self.r_next_v4 = d
            S.gotoState('a_next')

    # -- process + sleep ---------------------------------------------------

    def state_process(self, S):
        """Diff new backends vs. old; emit 'removed' then 'added' then
        'updated' (reference lib/resolver.js:1024-1108)."""
        S.validTransitions(['sleep'])
        from .resolver import srv_key

        old_backends = self.r_backends
        new_backends: dict[str, dict] = {}
        all_addrs: list[str] = []
        for srv in self.r_srvs:
            srv['addresses'] = list(srv.get('addresses_v6') or []) + \
                list(srv.get('addresses_v4') or [])
            for addr in srv['addresses']:
                final = {'name': srv['name'], 'port': srv['port'],
                         'address': addr}
                all_addrs.append(addr)
                new_backends[srv_key(final)] = final

        if not new_backends:
            err = RuntimeError(
                'failed to find any DNS records for (%s.)%s' % (
                    self.r_service, self.r_domain))
            err.__cause__ = self.r_last_error
            self._incr_counter('empty-set')
            self.r_log.warning('finished processing: %s', err)
            self.emit('updated', err)
            S.gotoState('sleep')
            return

        removed = [k for k in old_backends if k not in new_backends]
        added = [k for k in new_backends if k not in old_backends]

        self.r_backends = new_backends

        if old_backends and (removed or added):
            self.r_log.info('records changed in DNS: added=%r '
                            'removed=%r', added, removed)

        for k in removed:
            self.emit('removed', k)
            self._incr_counter('backend-removed')
        for k in added:
            self.emit('added', k, new_backends[k])
            self._incr_counter('backend-added')

        if self.r_is_bootstrap:
            gone = [r for r in self.r_resolvers if r not in all_addrs]
            self.r_resolvers = all_addrs
            if gone:
                self.r_log.info(
                    'removed %d resolvers from bootstrap', len(gone))

        self.emit('updated')
        self.r_last_processed = {'added': added, 'removed': removed}
        S.gotoState('sleep')

    def state_sleep(self, S):
        S.validTransitions(['init', 'srv', 'aaaa', 'a'])
        if self.r_stopping:
            S.gotoState('init')
            return

        now = mod_utils.wall_time()
        min_delay = (self.r_next_service or now) - now
        state = 'srv'
        if self.r_next_v6 is not None and \
                self.r_next_v6 - now < min_delay:
            min_delay = self.r_next_v6 - now
            state = 'aaaa'
        if self.r_next_v4 is not None and \
                self.r_next_v4 - now < min_delay:
            min_delay = self.r_next_v4 - now
            state = 'a'

        self._hwm_counter('max-sleep', round(min_delay * 1000))

        if min_delay < 0:
            S.gotoState(state)
        else:
            # Forward-only TTL spread (1.0 to 1.0+spread): re-querying a
            # cache early just returns the same answer
            # (reference lib/resolver.js:1129-1143).
            d = min_delay * (
                1 + mod_utils.get_rng().random() *
                self.r_retry['delaySpread'])
            self.r_log.debug('sleeping %.2fs until next %s expiry',
                             d, state)
            S.timeout(d * 1000, lambda: S.gotoState(state))
            S.goto_state_on(self, 'stopAsserted', 'init')

    # -- lookup helper -----------------------------------------------------

    def resolve(self, domain: str, rtype: str, timeout: float):
        """One lookup as an EventEmitter with .send(); emits
        'answers'(list, minTTL) or 'error'(err)
        (reference lib/resolver.js:1210-1377)."""
        from .resolver import NoNameError, NoRecordsError

        opts = {'domain': domain, 'type': rtype, 'timeout': timeout,
                'resolvers': self.r_resolvers}
        if self.r_is_bootstrap:
            opts['errorThreshold'] = min(
                self.r_maxres, len(self.r_resolvers))

        em = EventEmitter()

        def send():
            # Each send() is one wire lookup: give it its own DnsTrace
            # (dns_client adds a dns_query child span per resolver).
            tracer = mod_trace._runtime
            if tracer is not None:
                opts['trace'] = tracer.dns_begin(domain, rtype)
            self.r_nsclient.lookup(opts, on_lookup)
        em.send = send

        def on_lookup(err, msg):
            dns_trace = opts.get('trace')
            if dns_trace is not None:
                # Wire round-trip is over (post-processing below is
                # local); rcode voting may still rewrite err for the
                # caller, but the wire outcome is what we time.
                dns_trace.done('error' if err is not None else 'ok',
                               err)
                opts['trace'] = None
            # Multi-error: the responding resolvers vote for the most
            # common rcode (reference lib/resolver.js:1227-1259).
            if err is not None and \
                    getattr(err, 'name', None) == 'MultiError':
                codes: dict[str, int] = {}
                for e in err.errors():
                    if getattr(e, 'name', None) == 'TimeoutError':
                        self._incr_counter('timeout')
                        continue
                    code = getattr(e, 'code', None)
                    if code is None:
                        continue
                    codes[code] = codes.get(code, 0) + 1
                    self._incr_counter('rcode-' + code.lower())
                if codes:
                    err.code = sorted(codes, key=lambda c: -codes[c])[0]
            if err is not None and \
                    getattr(err, 'code', None) == 'NXDOMAIN':
                err = NoNameError(domain, err)

            # Newer binder returns an SOA TTL for NODATA
            # (reference lib/resolver.js:1266-1279).
            if err is None and msg is not None and \
                    not msg.get_answers():
                ttl = None
                for v in msg.get_authority():
                    if v['type'] == 'SOA' and v['ttl'] > 0:
                        ttl = v['ttl']
                err = NoRecordsError(domain, rtype, ttl)

            if err is not None:
                code = getattr(err, 'code', None)
                if code:
                    self._incr_counter('rcode-' + str(code).lower())
                em.emit('error', err)
                return

            answers = msg.get_answers()
            min_ttl = None
            ans: list[dict] = []
            self._incr_counter('rcode-ok')

            if rtype in ('A', 'AAAA'):
                for a in answers:
                    if a['type'] != rtype:
                        if a['type'] in ('CNAME', 'DNAME'):
                            self._incr_counter('cname')
                            continue
                        self._incr_counter('unknown-rrtype')
                        self.r_log.warning(
                            'got unsupported answer rrtype: %s',
                            a['type'])
                        continue
                    if min_ttl is None or a['ttl'] < min_ttl:
                        min_ttl = a['ttl']
                    ans.append({'name': a['name'],
                                'address': a['target']})
            elif rtype == 'SRV':
                # Exploit the Additional section to skip A/AAAA round
                # trips (reference lib/resolver.js:1318-1343).
                cache: dict[str, list] = {}
                for rr in msg.get_additionals():
                    if rr['type'] not in ('A', 'AAAA'):
                        if rr['type'] in ('CNAME', 'DNAME', 'OPT'):
                            continue
                        self._incr_counter('unknown-rrtype')
                        self.r_log.warning(
                            'got unsupported additional rrtype: %s',
                            rr['type'])
                        continue
                    if rr.get('target'):
                        if min_ttl is None or rr['ttl'] < min_ttl:
                            min_ttl = rr['ttl']
                        cache.setdefault(rr['name'], []).append(
                            rr['target'])
                for a in answers:
                    if a['type'] != 'SRV':
                        if a['type'] in ('CNAME', 'DNAME'):
                            self._incr_counter('cname')
                            continue
                        self._incr_counter('unknown-rrtype')
                        self.r_log.warning(
                            'got unsupported answer rrtype: %s',
                            a['type'])
                        continue
                    if min_ttl is None or a['ttl'] < min_ttl:
                        min_ttl = a['ttl']
                    obj = {'name': a['target'], 'port': a['port']}
                    if a['target'] in cache:
                        self._incr_counter('additionals-used')
                        obj['additionals'] = cache[a['target']]
                    ans.append(obj)
            else:
                raise ValueError('Invalid record type ' + rtype)

            if not ans:
                em.emit('error', NoRecordsError(domain, rtype))
                return
            em.emit('answers', ans, min_ttl)

        return em


def DNSResolver(options: dict):
    """Build a DNS resolver wrapped in the public 5-state ResolverFSM
    contract (constructor-returns-wrapper, reference
    lib/resolver.js:408)."""
    from .resolver import ResolverFSM
    inner = DNSResolverFSM(options)
    return ResolverFSM(inner, options)

"""DNS service-discovery resolver (reference lib/resolver.js:152-1377).

Full SRV -> AAAA -> A -> process -> sleep workflow with TTL-driven
refresh. Placeholder during the staged build; completed in the DNS stage
(SURVEY.md §7.2 stage 7).
"""

from __future__ import annotations


class DNSResolver:  # pragma: no cover - staged build placeholder
    def __init__(self, options: dict | None = None):
        raise NotImplementedError(
            'DNSResolver lands in build stage 7 (SURVEY.md §7.2)')

"""Process-global pool monitor with kang-style snapshots.

Rebuild of reference `lib/pool-monitor.js`: a singleton registry of every
live pool/set/DNS-resolver in the process, exposing structural snapshots
(per-backend FSM state counts, dead lists, counters, next DNS wakeups)
for operator debugging. The reference serves these over Joyent's "kang"
debug protocol; here :meth:`PoolMonitor.to_kang_options` returns the same
shape, and :func:`serve_monitor` (in http_server.py) serves it as JSON
over HTTP (GET /kang/snapshot).
"""

from __future__ import annotations

from .transport import host_ident as _host_ident


class PoolMonitor:
    def __init__(self):
        self.pm_pools: dict[str, object] = {}
        self.pm_sets: dict[str, object] = {}
        self.pm_dns_res: dict[str, object] = {}
        self.pm_fleet = None  # attached FleetSampler, if any
        # Bumped on every pool (un)registration so the FleetSampler can
        # skip its row-reconcile walk on ticks where the fleet roster is
        # unchanged (the overwhelmingly common case).
        self.pm_generation = 0

    # -- fleet telemetry bridge ------------------------------------------

    def attach_fleet_sampler(self, sampler) -> None:
        """Publish a FleetSampler's batched decisions through the kang
        surface (snapshot()['fleet'] and GET /kang/fleet)."""
        self.pm_fleet = sampler

    attachFleetSampler = attach_fleet_sampler

    def detach_fleet_sampler(self) -> None:
        self.pm_fleet = None

    detachFleetSampler = detach_fleet_sampler

    def fleet_snapshot(self) -> dict:
        if self.pm_fleet is None:
            return {'attached': False}
        snap = self.pm_fleet.snapshot()
        snap['attached'] = True
        return snap

    # -- registration (reference lib/pool-monitor.js:27-58) --------------

    def register_pool(self, pool) -> None:
        self.pm_pools[pool.p_uuid] = pool
        self.pm_generation += 1

    registerPool = register_pool

    def unregister_pool(self, pool) -> None:
        assert pool.p_uuid in self.pm_pools
        del self.pm_pools[pool.p_uuid]
        self.pm_generation += 1

    unregisterPool = unregister_pool

    def register_set(self, cset) -> None:
        self.pm_sets[cset.cs_uuid] = cset

    registerSet = register_set

    def unregister_set(self, cset) -> None:
        assert cset.cs_uuid in self.pm_sets
        del self.pm_sets[cset.cs_uuid]

    unregisterSet = unregister_set

    def register_dns_resolver(self, res) -> None:
        self.pm_dns_res[res.r_uuid] = res

    registerDnsResolver = register_dns_resolver

    def unregister_dns_resolver(self, res) -> None:
        assert res.r_uuid in self.pm_dns_res
        del self.pm_dns_res[res.r_uuid]

    unregisterDnsResolver = unregister_dns_resolver

    # -- snapshots (reference lib/pool-monitor.js:60-216) -----------------

    def list_types(self) -> list[str]:
        return ['pool', 'set', 'dns_res']

    def list_objects(self, type_: str) -> list[str]:
        if type_ == 'pool':
            return list(self.pm_pools.keys())
        if type_ == 'set':
            return list(self.pm_sets.keys())
        if type_ == 'dns_res':
            return list(self.pm_dns_res.keys())
        raise ValueError('Invalid type "%s"' % type_)

    def get(self, type_: str, id_: str) -> dict:
        if type_ == 'pool':
            return self.get_pool(id_)
        if type_ == 'set':
            return self.get_set(id_)
        if type_ == 'dns_res':
            return self.get_dns_resolver(id_)
        raise ValueError('Invalid type "%s"' % type_)

    def get_pool(self, id_: str) -> dict:
        pool = self.pm_pools[id_]
        obj: dict = {}
        obj['backends'] = pool.p_backends
        obj['connections'] = {}
        ks = list(pool.p_keys)
        for k in pool.p_connections.keys():
            if k not in ks:
                ks.append(k)
        for k in ks:
            conns = pool.p_connections.get(k) or []
            counts: dict[str, int] = {}
            for fsm in conns:
                s = fsm.get_state()
                counts[s] = counts.get(s, 0) + 1
            obj['connections'][k] = counts
        obj['dead_backends'] = list(pool.p_dead.keys())
        if pool.p_last_rebalance is not None:
            obj['last_rebalance'] = round(pool.p_last_rebalance)
        obj['resolvers'] = getattr(pool.p_resolver, 'r_resolvers', None)
        obj['state'] = pool.get_state()
        shard = getattr(pool, 'p_shard', None)
        if shard is not None:
            # Stamped by a FleetRouter at pool construction; plain
            # (unsharded) pools keep their historical snapshot shape.
            obj['shard'] = shard
        obj['counters'] = pool.p_counters
        inner = getattr(pool.p_resolver, 'r_fsm', pool.p_resolver)
        obj['options'] = {
            'domain': getattr(inner, 'r_domain', None) or pool.p_domain,
            'service': getattr(inner, 'r_service', None),
            'defaultPort': getattr(inner, 'r_defport', None),
            'spares': pool.p_spares,
            'maximum': pool.p_max,
        }
        return obj

    getPool = get_pool

    def get_set(self, id_: str) -> dict:
        cset = self.pm_sets[id_]
        obj: dict = {}
        obj['backends'] = cset.cs_backends
        obj['fsms'] = {}
        obj['connections'] = list(cset.cs_connections.keys())
        ks = list(cset.cs_keys)
        for k in cset.cs_fsm.keys():
            if k not in ks:
                ks.append(k)
        for k in ks:
            fsm = cset.cs_fsm.get(k)
            if fsm is None:
                continue
            s = fsm.get_state()
            obj['fsms'][k] = {s: 1}
        obj['dead_backends'] = list(cset.cs_dead.keys())
        if cset.cs_last_rebalance is not None:
            obj['last_rebalance'] = round(cset.cs_last_rebalance)
        obj['resolvers'] = getattr(cset.cs_resolver, 'r_resolvers', None)
        obj['state'] = cset.get_state()
        obj['counters'] = cset.cs_counters
        obj['target'] = cset.cs_target
        obj['maximum'] = cset.cs_max
        inner = getattr(cset.cs_resolver, 'r_fsm', cset.cs_resolver)
        obj['options'] = {
            'domain': getattr(inner, 'r_domain', None) or cset.cs_domain,
            'service': getattr(inner, 'r_service', None),
            'defaultPort': getattr(inner, 'r_defport', None),
        }
        return obj

    getSet = get_set

    def get_dns_resolver(self, id_: str) -> dict:
        res = self.pm_dns_res[id_]
        obj: dict = {
            'domain': res.r_domain,
            'service': res.r_service,
            'resolvers': res.r_resolvers,
            'defaultPort': res.r_defport,
            'state': res.get_state(),
            'next': {},
            'backends': res.r_backends,
            'counters': res.r_counters,
        }
        if getattr(res, 'r_next_service', None):
            obj['next']['srv'] = _iso(res.r_next_service)
        if getattr(res, 'r_next_v6', None):
            obj['next']['v6'] = _iso(res.r_next_v6)
        if getattr(res, 'r_next_v4', None):
            obj['next']['v4'] = _iso(res.r_next_v4)
        return obj

    getDnsResolver = get_dns_resolver

    def to_kang_options(self) -> dict:
        return {
            'uri_base': '/kang',
            'service_name': 'cueball',
            'version': '1.0.0',
            'ident': _host_ident(),
            'list_types': self.list_types,
            'list_objects': self.list_objects,
            'get': self.get,
            'stats': lambda: {},
        }

    toKangOptions = to_kang_options

    def snapshot(self) -> dict:
        """Full JSON-able snapshot of every registered object (what the
        kang HTTP endpoint serves)."""
        out: dict = {'service_name': 'cueball',
                     'ident': _host_ident(),
                     'types': {}}
        for t in self.list_types():
            out['types'][t] = {
                id_: self.get(t, id_) for id_ in self.list_objects(t)}
        if self.pm_fleet is not None:
            out['fleet'] = self.fleet_snapshot()
        from . import trace as mod_trace
        routers = mod_trace._active_fleet_routers()
        if routers:
            # Started FleetRouters: backend, per-shard FSM states and
            # the pool -> shard ownership map, merged into the one
            # fleet-wide snapshot.
            out['shards'] = [r.snapshot() for r in routers]
        if mod_trace.tracing_enabled():
            # Ring occupancy + sampling counters (the spans themselves
            # are served raw by GET /kang/traces).
            out['traces'] = mod_trace.summary()
        run_meta = mod_trace.get_run_metadata()
        if run_meta:
            out['netsim_run'] = run_meta
        return out


def _iso(ts: float) -> str:
    import datetime
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc).isoformat()


# Process-global singleton (reference lib/pool-monitor.js:9).
pool_monitor = PoolMonitor()
monitor = pool_monitor

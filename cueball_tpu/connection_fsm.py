"""The slot stack: SocketMgrFSM, CueBallClaimHandle, ConnectionSlotFSM.

Rebuild of reference `lib/connection-fsm.js`. Three interlocking Moore
machines manage each pool/set "slot":

- :class:`SocketMgrFSM` wraps one live "connection" at a time (constructed
  via the user-supplied ``constructor(backend)``), deduplicates
  connect/error/close/timeout events, and implements exponential backoff
  with randomized spread and a "monitor" mode (infinite retries pinned at
  max backoff) used to probe dead backends
  (reference lib/connection-fsm.js:68-425).
- :class:`CueBallClaimHandle` is the FSM handed to users on claim():
  waiting→claiming→claimed→released/closed (+cancelled/failed), running
  the double-handshake with the slot (try→claim→accept/reject) that
  closes the claim-vs-disconnect race, claim timeouts, and leaked-
  event-handler detection on release
  (reference lib/connection-fsm.js:427-808, docs/internals.adoc:454-477).
- :class:`ConnectionSlotFSM` drives the SocketMgr (when to retry vs.
  reconnect vs. stop), honors the pool's ``wanted`` flag, accepts claims,
  converts a monitor slot into a normal slot on success, and schedules
  idle-time health checks (reference lib/connection-fsm.js:810-1242).

Connection interface expected from ``constructor(backend)`` (reference
docs/api.adoc:580-645): an EventEmitter emitting ``connect``, ``error``,
``close`` (and optionally ``connectError``, ``timeout``,
``connectTimeout``) with a ``destroy()`` method; optionally
``ref()/unref()``, ``setUnwanted()``, and a ``localPort`` attribute.
"""

from __future__ import annotations

import logging
import math

from . import errors as mod_errors
from . import runq as mod_runq
from . import trace as mod_trace
from . import utils as mod_utils
from . import wiretap as mod_wiretap
from .events import _native
from .fsm import FSM
from .runq import defer

# Bound to cueball_tpu.profile while its sampler runs, so SIGPROF
# samples landing inside connection-open plumbing attribute to the
# socket_wait phase.
_prof = None

# Terminal claim handles are recycled through a C freelist when the
# native engine is loaded (see obtain_claim_handle): allocating the
# handle + its dict + FSM innards is a measurable slice of the queued
# claim path (docs/claim-path-profile.md round 6).
_HANDLE_FREELIST = _native is not None and \
    hasattr(_native, 'handle_free_pop')

# FSM state-handle gates are framework-internal listeners; the native
# Gate type carries no attributes, so recognize it by type.
_GATE_TYPE = _native.Gate if _native is not None else None


def _assert_obj(v, name):
    if not isinstance(v, dict) and v is None:
        raise AssertionError('%s is required' % name)


def count_listeners(emitter, event: str) -> int:
    """Count user-attached listeners, ignoring the framework's own
    (reference lib/connection-fsm.js:786-808 filters by function name; we
    mark internal handlers with a `_cueball_internal` attribute)."""
    try:
        # Native emitters filter in C over a snapshot of the list.
        return emitter.count_external(event)
    except AttributeError:
        pass
    try:
        ls = emitter._ee_listeners.get(event, ())
    except AttributeError:
        ls = emitter.listeners(event)
    n = 0
    for h in ls:
        if not callable(h) or getattr(h, '_cueball_internal', False):
            continue
        if _GATE_TYPE is not None and type(h) is _GATE_TYPE:
            continue
        w = getattr(h, '__wrapped_listener__', None)
        if w is not None:
            if getattr(w, '_cueball_internal', False):
                continue
            if _GATE_TYPE is not None and type(w) is _GATE_TYPE:
                continue
        n += 1
    return n


def _internal(fn):
    fn._cueball_internal = True
    return fn


# Events swept by the release leak check (reference
# lib/connection-fsm.js:786-808 sweeps the same four).
_LEAK_EVENTS = ('close', 'error', 'readable', 'data')


def _listener_epoch(emitter):
    """External-listener mutation epoch of `emitter`, or None when the
    emitter doesn't expose one (foreign emitter: always sweep).

    Both engine emitters bump a counter on every *external* listener
    add/remove (framework gates don't count), so an unchanged epoch
    proves the leak-check counts cannot have moved and the per-event
    ``count_listeners`` sweep can be skipped on the claim hot path."""
    mc = getattr(emitter, 'mutation_count', None)
    if mc is None:
        return None
    try:
        return mc()
    except TypeError:
        return None


_STACK_PARSE_CACHE: dict[int, tuple[str, list]] = {}


def _parse_stack(stack: str) -> list:
    """Parse a formatted stack into stripped frame lines. Stack capture
    is off by default (reference lib/utils.js:52-58), so every claim
    passes the same placeholder string — cache its parse by identity."""
    cached = _STACK_PARSE_CACHE.get(id(stack))
    if cached is not None and cached[0] is stack:
        return list(cached[1])
    parsed = [l.strip().removeprefix('at ')
              for l in stack.split('\n')[1:]]
    if len(_STACK_PARSE_CACHE) < 8:
        _STACK_PARSE_CACHE[id(stack)] = (stack, parsed)
    return list(parsed)


# ---------------------------------------------------------------------------
# SocketMgrFSM

class SocketMgrFSM(FSM):
    """Owns one connection at a time; states init/connecting/connected/
    error/backoff/closed/failed (reference lib/connection-fsm.js:85-425).

    Driven by its ConnectionSlotFSM through the signal functions
    ``connect()``, ``retry()``, ``close()``.
    """

    def __init__(self, options: dict):
        constructor = options['constructor']
        if not callable(constructor):
            raise AssertionError('options.constructor must be callable')
        self.sm_pool = options['pool']
        self.sm_backend = options['backend']
        # Small-int backend identity for the attribution surfaces: the
        # native trace recorder stamps it into slot flags so drained
        # claims land in the right per-backend health column even when
        # the Python-side span payload is gone (trace.backend_index).
        self.sm_backend_index = mod_trace.backend_index(
            self.sm_backend.get('key'))
        self.sm_constructor = constructor
        self.sm_slot = options['slot']

        recovery = options['recovery']
        connect_recov = recovery.get('default')
        initial_recov = recovery.get('default')
        if recovery.get('connect'):
            initial_recov = recovery['connect']
            connect_recov = recovery['connect']
        if recovery.get('initial'):
            initial_recov = recovery['initial']
        mod_utils.assert_recovery(connect_recov, 'recovery.connect')
        mod_utils.assert_recovery(initial_recov, 'recovery.initial')
        self.sm_initial_recov = initial_recov
        self.sm_connect_recov = connect_recov

        # Backend identity rides on every record
        # (reference lib/connection-fsm.js:149-155); a localPort child
        # is layered on at connect time (state_connected).
        self.sm_log = mod_utils.make_child_logger(
            options.get('log') or logging.getLogger('cueball.socketmgr'),
            component='CueBallSocketMgrFSM',
            backend=self.sm_backend.get('key'),
            address=self.sm_backend.get('address'),
            port=self.sm_backend.get('port'))

        self.sm_last_error = None
        self.sm_socket = None
        self.sm_monitor: bool | None = None
        # Last completed connect as (start_ms, end_ms): claim traces
        # attach it as their 'connect' child span, whether the connect
        # happened during the claim or predates it (trace.py).
        self.sm_connect_started = None
        self.sm_last_connect = None

        super().__init__('init')
        self.set_monitor(bool(options['monitor']))

    # -- knobs -----------------------------------------------------------

    def set_monitor(self, value: bool) -> None:
        """Toggle monitor mode: infinite retries, no exponential growth —
        timeout/delay pinned at their max values
        (reference lib/connection-fsm.js:171-184)."""
        assert self.is_in_state('init') or self.is_in_state('connected')
        if value == self.sm_monitor:
            return
        self.sm_monitor = value
        self.reset_backoff()

    setMonitor = set_monitor

    def reset_backoff(self) -> None:
        r = self.sm_initial_recov
        self.sm_retries = r['retries']
        self.sm_retries_left = r['retries']
        self.sm_min_delay = r['delay']
        self.sm_delay = r['delay']
        self.sm_max_delay = r.get('maxDelay') or math.inf
        self.sm_timeout = r['timeout']
        self.sm_max_timeout = r.get('maxTimeout') or math.inf
        self.sm_delay_spread = r.get('delaySpread') or 0.2

        if self.sm_monitor is True:
            mult = 1 << int(self.sm_retries)
            self.sm_delay = self.sm_max_delay
            if not math.isfinite(self.sm_delay):
                self.sm_delay = r['delay'] * mult
            self.sm_timeout = self.sm_max_timeout
            if not math.isfinite(self.sm_timeout):
                self.sm_timeout = r['timeout'] * mult
            # Keep retrying a failed backend forever.
            self.sm_retries = math.inf
            self.sm_retries_left = math.inf

    resetBackoff = reset_backoff

    def _sm_telemetry_dirty(self) -> None:
        """Flag the owning pool's fleet-telemetry row stale. Called on
        entry to and exit from 'backoff' — the only transitions that
        move the retry-ladder signals the FleetSampler columns carry.
        Guarded getattr: ConnectionSet slots hand a cset as 'pool'."""
        dirty = getattr(self.sm_pool, '_telemetry_dirty', None)
        if dirty is not None:
            dirty()

    def set_unwanted(self) -> None:
        """Forward to the current socket if it supports it
        (reference lib/connection-fsm.js:211-222)."""
        sock = self.sm_socket
        if sock is not None and \
                callable(getattr(sock, 'set_unwanted', None)):
            sock.set_unwanted()
        elif sock is not None and \
                callable(getattr(sock, 'setUnwanted', None)):
            sock.setUnwanted()

    setUnwanted = set_unwanted

    # -- signal functions ------------------------------------------------

    def connect(self) -> None:
        assert self.is_in_state('init') or self.is_in_state('closed'), (
            'SocketMgrFSM.connect may only be called in state "init" or '
            '"closed" (is in "%s")' % self.get_state())
        self.emit('connectAsserted')

    def retry(self) -> None:
        assert self.is_in_state('closed') or self.is_in_state('error'), (
            'SocketMgrFSM.retry may only be called in state "closed" or '
            '"error" (is in "%s")' % self.get_state())
        self.emit('retryAsserted')

    def close(self) -> None:
        assert self.is_in_state('connected') or \
            self.is_in_state('backoff'), (
            'SocketMgrFSM.close may only be called in state "connected" '
            'or "backoff" (is in "%s")' % self.get_state())
        self.emit('closeAsserted')

    def get_last_error(self):
        return self.sm_last_error

    getLastError = get_last_error

    def get_socket(self):
        assert self.is_in_state('connected'), (
            'sockets may only be retrieved from SocketMgrFSMs in '
            '"connected" state (is in "%s")' % self.get_state())
        return self.sm_socket

    getSocket = get_socket

    # -- states ----------------------------------------------------------

    def state_init(self, S):
        S.validTransitions(['connecting'])
        S.goto_state_on(self, 'connectAsserted', 'connecting')

    def state_connecting(self, S):
        S.validTransitions(['connected', 'error'])
        self._sm_telemetry_dirty()   # may be leaving 'backoff'
        self.sm_connect_started = mod_utils.current_millis()

        def on_timeout():
            self.sm_last_error = mod_errors.ConnectionTimeoutError(
                self.sm_backend)
            S.gotoState('error')
            self.sm_pool._incr_counter('timeout-during-connect')
        S.timeout(self.sm_timeout, on_timeout)

        self.sm_log.debug('calling constructor to open new connection')
        prof = _prof
        if prof is None:
            self.sm_socket = self.sm_constructor(self.sm_backend)
        else:
            tok = prof.push_phase('socket_wait')
            try:
                self.sm_socket = self.sm_constructor(self.sm_backend)
            finally:
                prof.pop_phase(tok)
        if self.sm_socket is None:
            raise AssertionError('constructor returned no connection')
        self.sm_socket.sm_fsm = self

        S.on(self.sm_socket, 'connect', lambda *a:
             S.gotoState('connected'))

        @_internal
        def on_error(err=None):
            self.sm_last_error = mod_errors.ConnectionError(
                self.sm_backend, 'error', 'connect', err)
            S.gotoState('error')
            self.sm_log.debug('emitted error while connecting: %r', err)
            self.sm_pool._incr_counter('error-during-connect')
        S.on(self.sm_socket, 'error', on_error)

        def on_connect_error(err=None):
            self.sm_last_error = mod_errors.ConnectionError(
                self.sm_backend, 'connectError', 'connect', err)
            S.gotoState('error')
            self.sm_pool._incr_counter('error-during-connect')
        S.on(self.sm_socket, 'connectError', on_connect_error)

        def on_close(*a):
            self.sm_last_error = mod_errors.ConnectionClosedError(
                self.sm_backend)
            S.gotoState('error')
            self.sm_log.debug('closed while connecting')
            self.sm_pool._incr_counter('close-during-connect')
        S.on(self.sm_socket, 'close', on_close)

        def on_conn_timeout(*a):
            self.sm_last_error = mod_errors.ConnectionTimeoutError(
                self.sm_backend)
            S.gotoState('error')
            self.sm_log.debug('timed out while connecting')
            self.sm_pool._incr_counter('timeout-during-connect')
        S.on(self.sm_socket, 'timeout', on_conn_timeout)
        S.on(self.sm_socket, 'connectTimeout', on_conn_timeout)

    def state_connected(self, S):
        S.validTransitions(['error', 'closed'])

        self.sm_log.debug('connected')
        if self.sm_connect_started is not None:
            now = mod_utils.current_millis()
            self.sm_last_connect = (self.sm_connect_started, now)
            self.sm_connect_started = None
            tracer = mod_trace._runtime
            if tracer is not None:
                tracer.connect_done(self.sm_backend.get('key'),
                                    *self.sm_last_connect)
            if mod_wiretap._LEDGER is not None:
                # Key the wire breakdown by the exact floats the
                # tracer just recorded as the connect span, so the
                # phase ledger's socket_wait decomposition can find
                # it again at replay time.
                sock = self.sm_socket
                mod_wiretap._LEDGER.record_connect(
                    getattr(sock, 'wt_transport', 'unknown'),
                    *self.sm_last_connect,
                    getattr(sock, 'wt_marks', None))
        self.reset_backoff()

        @_internal
        def on_error(err=None):
            self.sm_last_error = mod_errors.ConnectionError(
                self.sm_backend, 'error', 'operation', err)
            S.gotoState('error')
            self.sm_pool._incr_counter('error-while-connected')
            self.sm_log.debug('emitted error while connected: %r', err)
        S.on(self.sm_socket, 'error', on_error)
        S.goto_state_on(self.sm_socket, 'close', 'closed')
        S.goto_state_on(self, 'closeAsserted', 'closed')

    def state_error(self, S):
        S.validTransitions(['backoff'])
        if self.sm_socket is not None:
            self.sm_socket.destroy()
        self.sm_socket = None
        S.goto_state_on(self, 'retryAsserted', 'backoff')

    def state_backoff(self, S):
        S.validTransitions(['failed', 'connecting', 'closed'])
        self._sm_telemetry_dirty()   # ladder position becomes visible

        # "retries" means "attempts" in the cueball API; compare to 1
        # (reference lib/connection-fsm.js:365-371).
        if self.sm_retries_left != math.inf and self.sm_retries_left <= 1:
            S.gotoState('failed')
            return

        delay = mod_utils.delay(self.sm_delay, self.sm_delay_spread)

        if self.sm_retries != math.inf:
            self.sm_retries_left -= 1
            self.sm_delay *= 2
            self.sm_timeout *= 2
            if self.sm_timeout > self.sm_max_timeout:
                self.sm_timeout = self.sm_max_timeout
            if self.sm_delay > self.sm_max_delay:
                self.sm_delay = self.sm_max_delay

        S.timeout(delay, lambda: S.gotoState('connecting'))
        S.goto_state_on(self, 'closeAsserted', 'closed')

    def state_closed(self, S):
        S.validTransitions(['backoff', 'connecting'])
        self._sm_telemetry_dirty()   # may be leaving 'backoff'
        if self.sm_socket is not None:
            self.sm_socket.destroy()
        self.sm_socket = None
        self.sm_log.debug('connection closed')
        S.goto_state_on(self, 'retryAsserted', 'backoff')
        S.goto_state_on(self, 'connectAsserted', 'connecting')

    def state_failed(self, S):
        S.validTransitions([])
        self._sm_telemetry_dirty()   # leaving 'backoff'
        self.sm_log.warning(
            'failed to connect to backend, retries exhausted: %r',
            self.sm_last_error)
        self.sm_pool._incr_counter('retries-exhausted')


# ---------------------------------------------------------------------------
# CueBallClaimHandle

class CueBallClaimHandle(FSM):
    """FSM handed out to pool users on claim()
    (reference lib/connection-fsm.js:427-784)."""

    # The on/once overrides below only reject *user* 'readable'/'close'
    # subscriptions; framework-internal state registrations never use
    # those events, so the native core may append them straight to the
    # C listener table (emitter.c emitter_internal_on_fast).
    _cueball_safe_internal_on = True

    def __init__(self, options: dict):
        claim_timeout = options['claimTimeout']
        self.ch_claim_timeout = claim_timeout
        self.ch_pool = options['pool']
        throw_error = options.get('throwError')
        self.ch_throw_error = True if throw_error is None else throw_error

        claim_stack = options['claimStack']
        if not isinstance(claim_stack, str):
            raise AssertionError('options.claimStack must be a string')
        self.ch_claim_stack = _parse_stack(claim_stack)

        callback = options['callback']
        if not callable(callback):
            raise AssertionError('options.callback must be callable')
        self.ch_callback = callback

        # Child logger built lazily: handles log only on unusual paths
        # (leak check, double release), and building a LoggerAdapter on
        # every claim costs ~5% of the claim/release hot path.
        self._ch_log_parent = options.get('log')
        self._ch_log = None

        self.ch_slot = None
        self.ch_waiter_node = None  # pool claim-queue node (O(1) unlink)
        self.ch_requeue = None      # pool try_next; set AFTER init so
        #                             only re-entries to waiting fire it
        self.ch_release_stack: list[str] | None = None
        self.ch_connection = None
        self.ch_pre_listeners: dict[str, int] = {}
        self.ch_pre_epoch = None    # listener epoch at claim snapshot
        self.ch_cancelled = False
        self.ch_last_error = None
        self._ch_arm_timer = None
        self.ch_do_release_leak_check = True
        self.ch_pinger = False
        self.ch_started = mod_utils.current_millis()
        self.ch_trace = None  # ClaimTrace, attached by the pool/set
        #                       when tracing is enabled (trace.py)

        super().__init__('waiting')

    @property
    def ch_log(self):
        if self._ch_log is None:
            self._ch_log = mod_utils.make_child_logger(
                self._ch_log_parent or logging.getLogger(
                    'cueball.claimhandle'),
                component='CueBallClaimHandle')
        return self._ch_log

    # -- misuse traps ----------------------------------------------------
    # Users sometimes mix up the (handle, connection) callback argument
    # order; make treating the handle as a socket fail loudly
    # (reference lib/connection-fsm.js:529-557).

    @property
    def writable(self):
        raise mod_errors.ClaimHandleMisusedError()

    @property
    def readable(self):
        raise mod_errors.ClaimHandleMisusedError()

    def write(self, *a, **kw):
        raise mod_errors.ClaimHandleMisusedError()

    def read(self, *a, **kw):
        raise mod_errors.ClaimHandleMisusedError()

    def on(self, event, listener=None):
        if event in ('readable', 'close'):
            raise mod_errors.ClaimHandleMisusedError()
        return super().on(event, listener)

    def once(self, event, listener=None):
        if event in ('readable', 'close'):
            raise mod_errors.ClaimHandleMisusedError()
        return super().once(event, listener)

    def disable_release_leak_check(self) -> None:
        self.ch_do_release_leak_check = False

    disableReleaseLeakCheck = disable_release_leak_check

    def arm_claim_timer(self) -> None:
        """Called by the pool when this handle parks in the claim
        queue: arm the claim timeout now (see state_waiting — claims
        served without parking never pay for a timer). After arming,
        _ch_arm_timer holds the wheel token instead of the closure."""
        arm = self._ch_arm_timer
        if callable(arm):
            arm()

    def _ch_wheel_fire(self, token) -> None:
        """Deadline bucket fired (runq timer wheel). The wheel rounds
        deadlines UP to the next quantum, so firing is never early;
        stale tokens (this handle re-parked or resolved since) are
        recognized by identity and ignored."""
        if self._ch_arm_timer is not token:
            return
        self._ch_arm_timer = None
        if self.is_in_state('waiting'):
            self.timeout()

    def _ch_unpark(self) -> None:
        """O(1)-unlink this handle's claim-queue node, if parked. Runs
        at entry to every state that leaves 'waiting', so a resolved
        handle never stays pinned in the pool's wait queue until a
        dequeue that may not come (the pool used to do this from a
        per-claim stateChanged listener; owning it here saves that
        subscription on the claim hot path). Also drops the un-fired
        arm closure (it captures the waiting state's handle, and a
        fast-path claim would otherwise pin that for the whole lease)
        or cancels the armed wheel token."""
        tok = self._ch_arm_timer
        self._ch_arm_timer = None
        if type(tok) is tuple:
            mod_runq.wheel_cancel(tok)
        node = self.ch_waiter_node
        if node is not None:
            node.remove()
            self.ch_waiter_node = None
            # The claim queue's head (and so the head sojourn the
            # fleet sampler publishes) may have moved; flag the row.
            # Guarded: ConnectionSet claims hand a cset as 'pool'.
            dirty = getattr(self.ch_pool, '_telemetry_dirty', None)
            if dirty is not None:
                dirty()

    # -- signal functions ------------------------------------------------

    def try_(self, slot: 'ConnectionSlotFSM') -> None:
        assert self.is_in_state('waiting'), (
            'ClaimHandle.try_ may only be called in state "waiting" '
            '(is in "%s")' % self.get_state())
        assert slot.is_in_state('idle'), (
            'ClaimHandle.try_ may only be called on a slot in state '
            '"idle" (is in "%s")' % slot.get_state())
        self.ch_slot = slot
        self.emit('tryAsserted')

    def accept(self, connection) -> None:
        assert self.is_in_state('claiming')
        self.ch_connection = connection
        self.emit('accepted')

    def reject(self) -> None:
        assert self.is_in_state('claiming')
        self.emit('rejected')

    def cancel(self) -> None:
        if self.is_in_state('claimed'):
            self.release()
        else:
            self.ch_cancelled = True
            self.emit('cancelled')

    def timeout(self) -> None:
        assert self.is_in_state('waiting')
        self.emit('timeout')

    def fail(self, err) -> None:
        self.emit('error', err)

    def _relinquish(self, event: str) -> None:
        if not self.is_in_state('claimed'):
            if self.is_in_state('released') or self.is_in_state('closed'):
                # Name the first release's call site. Python stacks are
                # oldest-first (unlike the reference's node stacks), so
                # walk from the END, skipping this package's own capture
                # frames (matched by the package directory, not a bare
                # substring — a repo cloned AS 'cueball_tpu/' must not
                # have its own frames skipped), to reach the releaser.
                import os
                pkg_dir = os.path.dirname(os.path.abspath(__file__)) \
                    + os.sep
                who = 'unknown'
                for line in reversed(self.ch_release_stack or []):
                    s = line.strip()
                    if s.startswith('File "') and \
                            pkg_dir not in s.split(',')[0]:
                        who = s
                        break
                raise RuntimeError(
                    'Connection not claimed by this handle, released '
                    'by %s' % who)
            raise RuntimeError(
                'ClaimHandle.release() called while in state "%s"' %
                self.get_state())
        e = mod_utils.maybe_capture_stack_trace()
        self.ch_release_stack = _parse_stack(e['stack'])
        self.emit(event)

    def release(self) -> None:
        self._relinquish('releaseAsserted')

    def close(self) -> None:
        self._relinquish('closeAsserted')

    def get_last_error(self):
        return self.ch_last_error

    # -- states ----------------------------------------------------------

    def state_waiting(self, S):
        S.validTransitions(['claiming', 'cancelled', 'failed'])

        self.ch_slot = None
        if self.ch_trace is not None:
            # No-op on the first entry; after a rejected handshake it
            # closes the handshake span and opens a new queue_wait.
            self.ch_trace.requeued()
        if self.ch_requeue is not None:
            # Re-entry after a rejected claim: ask the pool to try
            # again next tick (the initial entry runs during __init__,
            # before the pool has installed ch_requeue — the pool
            # schedules that first try itself).  Deliberately NOT
            # S.immediate: the requeue must survive leaving 'waiting'
            # (a claim can be handed out before the tick fires).
            defer(self.ch_requeue)

        S.goto_state_on(self, 'tryAsserted', 'claiming')

        def on_timeout():
            self.ch_last_error = mod_errors.ClaimTimeoutError(self.ch_pool)
            self.ch_pool._incr_counter('claim-timeout')
            S.gotoState('failed')

        # The timeout timer is armed LAZILY, by the pool, only when
        # the handle actually parks in the wait queue
        # (arm_claim_timer): a claim served from the idle queue never
        # waits, and skipping the arm+cancel saves timer churn on
        # every fast-path claim. Armed deadlines go to the runq timer
        # wheel — one shared loop.call_later per 5ms bucket instead of
        # a TimerHandle + timer-heap entry per parked claim; the
        # deadline stays measured from ch_started, so the deferred
        # arm never extends it, and the wheel's fire calls
        # _ch_wheel_fire -> timeout(), handled by on_timeout above.
        t = self.ch_claim_timeout
        if isinstance(t, (int, float)) and math.isfinite(t):
            def _arm():
                self._ch_arm_timer = mod_runq.wheel_arm(
                    self.ch_started + t, self)
            self._ch_arm_timer = _arm
        else:
            # No finite deadline: nothing to arm, so don't make the
            # pool's arm_claim_timer pay for a closure per park.
            self._ch_arm_timer = None

        S.on(self, 'timeout', on_timeout)

        def on_error(err):
            self.ch_last_error = err
            S.gotoState('failed')
        S.on(self, 'error', on_error)

        S.goto_state_on(self, 'cancelled', 'cancelled')

    def state_claiming(self, S):
        S.validTransitions(['claimed', 'waiting', 'cancelled'])

        self._ch_unpark()
        if self.ch_trace is not None:
            self.ch_trace.claiming(self.ch_slot)
        S.goto_state_on(self, 'accepted', 'claimed')

        def on_rejected():
            if self.ch_cancelled:
                S.gotoState('cancelled')
            else:
                S.gotoState('waiting')
        S.on(self, 'rejected', on_rejected)

        self.ch_slot.claim(self)

    def state_claimed(self, S):
        S.validTransitions(['released', 'closed'])

        S.goto_state_on(self, 'releaseAsserted', 'released')
        S.goto_state_on(self, 'closeAsserted', 'closed')

        if self.ch_trace is not None:
            self.ch_trace.claimed()

        if self.ch_cancelled:
            S.gotoState('released')
            return

        conn = self.ch_connection
        epoch = _listener_epoch(conn)
        cached = getattr(conn, '_cueball_listener_counts', None)
        if epoch is not None and cached is not None and \
                cached[0] == epoch:
            # Nobody added/removed an external listener since the last
            # snapshot: reuse it instead of re-walking four listener
            # lists per claim (~7% of a claim/release cycle,
            # docs/claim-path-profile.md round 5).
            self.ch_pre_listeners = cached[1]
        else:
            self.ch_pre_listeners = {
                evt: count_listeners(conn, evt) for evt in _LEAK_EVENTS}
            if epoch is not None:
                try:
                    conn._cueball_listener_counts = (
                        epoch, self.ch_pre_listeners)
                except (AttributeError, TypeError):
                    pass
        self.ch_pre_epoch = epoch

        @_internal
        def on_error(err=None):
            count = count_listeners(self.ch_connection, 'error')
            if count == 0 and self.ch_throw_error:
                # End-user attached no 'error' listener: act like nothing
                # is listening and raise
                # (reference lib/connection-fsm.js:697-709).
                raise err if isinstance(err, BaseException) else \
                    mod_errors.CueBallError(repr(err))
            self.ch_log.warning(
                'connection emitted error while claimed: %r', err)
            self.ch_pool._incr_counter('error-while-claimed')
        S.on(self.ch_connection, 'error', on_error)

        self.ch_callback(None, self, self.ch_connection)

    def state_released(self, S):
        S.validTransitions([])
        if self.ch_trace is not None:
            self.ch_trace.released('release')
        if _HANDLE_FREELIST:
            # After this tick's pump batch (deferred stateChanged
            # emissions included) the handle is inert; recycle it.
            defer(self._ch_recycle)
        if not self.ch_do_release_leak_check:
            return
        conn = self.ch_connection
        epoch = _listener_epoch(conn)
        if epoch is not None and epoch == self.ch_pre_epoch:
            # Zero external listener mutations while claimed: the
            # counts provably match the claim-time snapshot; skip the
            # sweep (a leaker necessarily bumps the epoch).
            return
        new_counts = {}
        for evt in _LEAK_EVENTS:
            new_count = count_listeners(conn, evt)
            new_counts[evt] = new_count
            old_count = self.ch_pre_listeners.get(evt)
            if old_count is not None and new_count > old_count:
                self.ch_log.warning(
                    'connection claimer looks like it leaked event '
                    'handlers: event=%s before=%d after=%d',
                    evt, old_count, new_count)
        if epoch is not None:
            # Refresh the snapshot so the next claim of this
            # connection can skip its pre-count walk too.
            try:
                conn._cueball_listener_counts = (epoch, new_counts)
            except (AttributeError, TypeError):
                pass

    def state_closed(self, S):
        S.validTransitions([])
        # No leak check: the connection is being closed anyway.
        if self.ch_trace is not None:
            self.ch_trace.released('close')
        if _HANDLE_FREELIST:
            defer(self._ch_recycle)

    def state_cancelled(self, S):
        S.validTransitions([])
        self._ch_unpark()
        if self.ch_trace is not None:
            self.ch_trace.cancelled()
        # Public API contract: the callback is never called after
        # cancel() (reference lib/connection-fsm.js:770-777).

    def state_failed(self, S):
        S.validTransitions([])
        self._ch_unpark()
        if self.ch_trace is not None:
            self.ch_trace.failed(self.ch_last_error)
        S.immediate(lambda: self.ch_callback(self.ch_last_error))

    def _ch_recycle(self) -> None:
        """Deferred from the terminal released/closed entries: clear
        every internal reference that could pin pool state (crucially
        ch_requeue — its try_next closure cycles back through the
        pool) and offer the handle to the C freelist. NOT run from
        failed/cancelled: state_failed's deferred callback still needs
        ch_callback, and neither state is worth optimizing."""
        if type(self) is not CueBallClaimHandle:
            return  # subclasses must not resurface as plain handles
        if not (self.is_in_state('released') or
                self.is_in_state('closed')):
            return
        self.ch_requeue = None
        self.ch_callback = None
        self.ch_slot = None
        self.ch_connection = None
        self.ch_waiter_node = None
        self.ch_trace = None
        self.ch_pre_listeners = {}
        _native.handle_free_push(self)


def obtain_claim_handle(options: dict) -> CueBallClaimHandle:
    """Claim-handle factory for the pool hot path: recycle a terminal
    handle from the C freelist when the native engine is loaded
    (re-running __init__ re-enters 'waiting' exactly like a fresh
    construction), else construct one."""
    if _HANDLE_FREELIST:
        h = _native.handle_free_pop()
        if h is not None:
            h.remove_all_listeners()
            h.__init__(options)
            return h
    return CueBallClaimHandle(options)


def arm_claim_timers(handles) -> None:
    """Batched arm_claim_timer for claim_many's park path. A batch
    shares one claimTimeout and its handles were minted in the same
    loop tick, so their deadlines land in (at most one quantum of)
    the same wheel bucket: resolve the bucket once via wheel_arm_many
    instead of per handle. The shared deadline is the LATEST in the
    batch — the wheel may fire a claim a few ms late (inside its
    normal quantum slop), never early."""
    arm = [h for h in handles if callable(h._ch_arm_timer)]
    if not arm:
        return
    deadline = max(h.ch_started for h in arm) + arm[0].ch_claim_timeout
    for h, tok in zip(arm, mod_runq.wheel_arm_many(deadline, arm)):
        h._ch_arm_timer = tok


# ---------------------------------------------------------------------------
# ConnectionSlotFSM

class ConnectionSlotFSM(FSM):
    """One pool/set slot; drives a SocketMgrFSM and reports the
    transitions its Pool or Set cares about
    (reference lib/connection-fsm.js:810-1242)."""

    def __init__(self, options: dict):
        self.csf_pool = options['pool']
        self.csf_backend = options['backend']
        self.csf_wanted = True
        self.csf_handle = None
        self.csf_prev_handle = None
        self.csf_monitor = bool(options['monitor'])

        self.csf_checker = options.get('checker')
        self.csf_check_timeout = options.get('checkTimeout')

        self.csf_log = mod_utils.make_child_logger(
            options.get('log') or logging.getLogger('cueball.slot'),
            component='CueBallConnectionSlotFSM',
            backend=self.csf_backend.get('key'),
            address=self.csf_backend.get('address'),
            port=self.csf_backend.get('port'))

        self.csf_smgr = SocketMgrFSM({
            'pool': options['pool'],
            'constructor': options['constructor'],
            'backend': options['backend'],
            'log': options.get('log'),
            'recovery': options['recovery'],
            'monitor': bool(options['monitor']),
            'slot': self,
        })

        super().__init__('init')

    # -- public interface ------------------------------------------------

    def set_unwanted(self) -> None:
        if self.csf_wanted is False:
            return
        self.csf_wanted = False
        self.csf_smgr.set_unwanted()
        self.emit('unwanted')

    setUnwanted = set_unwanted

    def start(self) -> None:
        assert self.is_in_state('init')
        self.emit('startAsserted')

    def claim(self, handle: CueBallClaimHandle) -> None:
        assert self.is_in_state('idle')
        assert self.csf_handle is None
        self.csf_handle = handle
        self.emit('claimAsserted')

    def make_child_logger(self, *a, **kw):
        return self.csf_log

    makeChildLogger = make_child_logger

    def get_socket_mgr(self) -> SocketMgrFSM:
        return self.csf_smgr

    getSocketMgr = get_socket_mgr

    def get_backend(self) -> dict:
        return self.csf_backend

    getBackend = get_backend

    def is_running_ping(self) -> bool:
        return bool(self.is_in_state('busy') and self.csf_handle and
                    self.csf_handle.ch_pinger)

    isRunningPing = is_running_ping

    # -- states ----------------------------------------------------------

    def state_init(self, S):
        S.validTransitions(['connecting'])
        S.goto_state_on(self, 'startAsserted', 'connecting')

    def state_connecting(self, S):
        S.validTransitions(['failed', 'retrying', 'idle'])
        smgr = self.csf_smgr

        def on_changed(st):
            if st in ('init', 'connecting'):
                pass
            elif st == 'failed':
                S.gotoState('failed')
            elif st == 'error':
                S.gotoState('retrying')
            elif st == 'connected':
                S.gotoState('idle')
            else:
                raise RuntimeError(
                    'Unhandled smgr state transition: .connect() => '
                    '"%s"' % st)
        S.on(smgr, 'stateChanged', on_changed)
        prof = _prof
        if prof is None:
            smgr.connect()
        else:
            tok = prof.push_phase('socket_wait')
            try:
                smgr.connect()
            finally:
                prof.pop_phase(tok)

    def state_failed(self, S):
        S.validTransitions([])
        assert self.csf_smgr.is_in_state('failed'), 'smgr must be failed'

    def state_retrying(self, S):
        S.validTransitions(['idle', 'failed', 'retrying', 'stopped',
                            'stopping'])
        smgr = self.csf_smgr

        def on_changed(st):
            if st in ('backoff', 'connecting'):
                pass
            elif st == 'failed':
                S.gotoState('failed')
            elif st == 'error':
                if self.csf_monitor and not self.csf_wanted:
                    S.gotoState('stopped')
                else:
                    S.gotoState('retrying')
            elif st == 'connected':
                S.gotoState('idle')
            else:
                raise RuntimeError(
                    'Unhandled smgr state transition: .retry() => '
                    '"%s"' % st)
        S.on(smgr, 'stateChanged', on_changed)

        def on_unwanted():
            if self.csf_monitor and smgr.is_in_state('backoff'):
                S.gotoState('stopping')
        S.on(self, 'unwanted', on_unwanted)

        smgr.retry()

    def state_idle(self, S):
        S.validTransitions(['retrying', 'connecting', 'stopping',
                            'stopped', 'busy'])
        smgr = self.csf_smgr

        if self.csf_handle is not None:
            self.csf_prev_handle = self.csf_handle
        self.csf_handle = None

        # Monitor successfully connected: convert to a normal slot
        # (reference lib/connection-fsm.js:1053-1057).
        if self.csf_monitor is True:
            self.csf_monitor = False
            smgr.set_monitor(False)

        def on_unwanted():
            if smgr.is_in_state('connected'):
                S.gotoState('stopping')
            elif smgr.is_in_state('error') or smgr.is_in_state('closed'):
                # The disconnect landed in this same loop turn and its
                # stateChanged is still queued. The reference's guard
                # only handles a connected smgr
                # (lib/connection-fsm.js:1065-1069); with the entry
                # short-circuit below that strands an unwanted slot in
                # 'idle' with no registrations at all — nothing would
                # ever move it again and pool.stop() hangs in
                # 'stopping.backends' (found by tests/test_soak.py).
                # The slot is unwanted and the socket is gone: finish.
                S.gotoState('stopped')

        if not self.csf_wanted:
            on_unwanted()
            return
        S.on(self, 'unwanted', on_unwanted)

        def on_changed(st):
            if st == 'error':
                S.gotoState('retrying')
            elif st == 'closed':
                if not self.csf_wanted:
                    S.gotoState('stopped')
                else:
                    S.gotoState('connecting')
            else:
                raise RuntimeError(
                    'Unhandled smgr state transition: connected => '
                    '"%s"' % st)
        S.on(smgr, 'stateChanged', on_changed)

        S.goto_state_on(self, 'claimAsserted', 'busy')

        if self.csf_check_timeout is not None and \
                self.csf_checker is not None:
            S.timeout(self.csf_check_timeout,
                      lambda: do_ping_check(self, self.csf_checker))

    def state_busy(self, S):
        S.validTransitions(['idle', 'stopping', 'stopped', 'retrying',
                            'killing', 'connecting'])
        smgr = self.csf_smgr
        hdl = self.csf_handle
        # Track the smgr state via events: a disconnect may have happened
        # in this same loop turn and its stateChanged not yet delivered
        # (reference lib/connection-fsm.js:881-889,1130-1139).
        state = {'smgr': 'connected'}

        def on_smgr_changed(st):
            state['smgr'] = st
        S.on(smgr, 'stateChanged', on_smgr_changed)

        def on_release():
            if state['smgr'] == 'connected':
                if self.csf_wanted:
                    S.gotoState('idle')
                else:
                    S.gotoState('stopping')
            elif state['smgr'] == 'closed':
                if self.csf_wanted:
                    S.gotoState('connecting')
                else:
                    S.gotoState('stopped')
            elif state['smgr'] == 'error':
                S.gotoState('retrying')
            else:
                raise RuntimeError(
                    'Handle released while smgr was in unhandled state '
                    '"%s"' % smgr.get_state())

        def on_close():
            if state['smgr'] == 'connected':
                S.gotoState('killing')
            else:
                S.gotoState('retrying')

        def on_hdl_changed(st):
            if st == 'released':
                on_release()
            elif st == 'closed':
                on_close()
        S.on(hdl, 'stateChanged', on_hdl_changed)

        # The smgr may have already left 'connected' by the time we get
        # here; if we lost the race, treat it like a release
        # (reference lib/connection-fsm.js:1183-1196).
        if smgr.is_in_state('connected'):
            sock = smgr.get_socket()
            probe = getattr(sock, 'cb_claim_ready', None)
            if probe is None:
                hdl.accept(sock)
            else:
                # Transport-level claim-readiness probe: a transport
                # that must move bytes before the connection is usable
                # for THIS claim (e.g. netsim trickling TCP segments
                # mid-handshake) exposes cb_claim_ready(done); accept
                # is deferred until done(ok). The handle sits in
                # 'claiming' throughout, so probe time lands in the
                # ledger's handshake phase, not queue_wait. A probe
                # that completes synchronously is byte-identical to
                # the plain accept path. Transports MUST eventually
                # call done — a probed claim cannot time out.
                def on_ready(ok):
                    if self.csf_handle is not hdl or \
                            not hdl.is_in_state('claiming') or \
                            not self.is_in_state('busy'):
                        return
                    if ok and state['smgr'] == 'connected':
                        hdl.accept(sock)
                    else:
                        hdl.reject()
                        self.csf_handle = None
                        on_release()
                probe(on_ready)
        else:
            hdl.reject()
            self.csf_handle = None
            on_release()

    def state_killing(self, S):
        S.validTransitions(['retrying'])
        smgr = self.csf_smgr

        def on_changed(st):
            if st in ('closed', 'error'):
                S.gotoState('retrying')
        S.on(smgr, 'stateChanged', on_changed)

        # The socket may have closed already with the stateChanged event
        # still pending; don't double-close
        # (reference lib/connection-fsm.js:1209-1216).
        if not smgr.is_in_state('closed') and \
                not smgr.is_in_state('error'):
            smgr.close()

    def state_stopping(self, S):
        S.validTransitions(['stopped'])
        smgr = self.csf_smgr

        def on_changed(st):
            if st in ('closed', 'error'):
                S.gotoState('stopped')
        S.on(smgr, 'stateChanged', on_changed)

        if not smgr.is_in_state('closed') and \
                not smgr.is_in_state('error'):
            smgr.close()

    def state_stopped(self, S):
        S.validTransitions([])
        smgr = self.csf_smgr
        assert smgr.is_in_state('closed') or smgr.is_in_state('error') or \
            smgr.is_in_state('failed'), 'smgr must be stopped'


def do_ping_check(fsm: ConnectionSlotFSM, checker) -> None:
    """Run the user health 'checker' over an idle slot by claiming it
    through a private handle (reference lib/connection-fsm.js:1101-1127)."""

    def ping_check_adapter(err, hdl=None, conn=None):
        # Infinite timeout and no .fail(): err is always None here.
        assert err is None
        checker(hdl, conn)

    handle = CueBallClaimHandle({
        'pool': fsm.csf_pool,
        'claimStack': ('Error\n'
                       'at claim\n'
                       'at cueball.do_ping_check\n'
                       'at cueball.do_ping_check\n'),
        'callback': ping_check_adapter,
        'log': fsm.csf_log,
        'claimTimeout': math.inf,
    })
    handle.ch_pinger = True
    # If we lose the race back to 'waiting', just drop the handle
    # (reference lib/connection-fsm.js:1121-1126).
    handle.try_(fsm)

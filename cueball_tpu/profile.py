"""Continuous claim-path profiler: phase ledger + sampling flamegraphs.

Two instruments over one substrate, answering "where do a claim's
microseconds go" with zero new hot-path instrumentation:

- The **phase ledger** replays the trace ring's completed claim spans
  (trace.py already records them for /kang/traces) into per-claim time
  accounting across the named claim phases — queue wait, CoDel pacing,
  runq pump, FSM transitions, socket wait, handshake, lease — holding
  a ``phase_sum ≈ wall`` invariant per claim. Ledger numbers are pure
  replay arithmetic: deterministic under netsim, byte-identical between
  the native and pure recorders, and free when nobody asks.

- The **sampling profiler** attributes CPU time *within* those phases:
  a SIGPROF-driven C handler (native/emitter.c) appends (phase, site,
  t) samples to a preallocated overwrite-oldest ring, reading a phase
  tag the engine already updates at sites the hot path visits anyway
  (trace events, the pump drain, FSM transitions). A pure-Python
  fallback (signal.setitimer + frame inspection) covers
  CUEBALL_NO_NATIVE. The sampler auto-disables under a substituted
  clock (netsim VirtualClock) so simulated scenarios stay
  deterministic.

Surfaces: collapsed-stack flamegraph text at ``GET /kang/profile``,
``cueball_claim_phase_ms{phase=...}`` histograms on /metrics, a
profiler section in the SIGUSR2 dump (:func:`dump_profile`),
``.netsim-failures/`` dumps embedding the ledger of the slowest
claims, and :meth:`FleetRouter.profile_fleet` merging per-shard
records (:func:`reduce_profile`) like ``reduce_health``.
"""

from __future__ import annotations

import signal
import sys

from . import trace as mod_trace
from . import utils as mod_utils
from . import wiretap as mod_wiretap
from .events import _native

__all__ = [
    'PHASES',
    'SUB_PHASES',
    'claim_ledger',
    'phase_ledger',
    'ledger_summary',
    'profile_record',
    'reduce_profile',
    'flamegraph',
    'start_sampler',
    'stop_sampler',
    'sampler_running',
    'sampler_stats',
    'dump_profile',
]

#: The named claim phases, in ledger/flamegraph display order. Order
#: and membership are a cross-surface contract: the C sampler's
#: PROF_PHASE_* numbering (native/emitter.c) maps into this tuple via
#: _PHASE_BY_ID, and the bench cost-attribution table and the
#: cueball_claim_phase_ms histogram label values are drawn from it.
PHASES = ('queue_wait', 'codel', 'runq_pump', 'fsm',
          'socket_wait', 'handshake', 'lease', 'other')

#: socket_wait sub-phases (re-exported from wiretap, the module that
#: defines them): where the opaque socket_wait phase actually went —
#: in-kernel readiness wait, event-loop dispatch lag, or Python
#: protocol/constructor work. They live in ``led['wire']``, NOT in
#: ``led['phases']``: PHASES membership is the C sampler / histogram
#: label contract and the ``sum(phases) == wall`` identity stays on
#: the eight named phases, while ``sum(led['wire'].values()) ==
#: phases['socket_wait']`` holds exactly per claim.
SUB_PHASES = mod_wiretap.SUB_PHASES

# C PROF_PHASE_* numbering -> phase name (index = C constant).
_PHASE_BY_ID = ('other', 'queue_wait', 'codel', 'runq_pump', 'fsm',
                'socket_wait', 'handshake', 'lease')
_PHASE_IDS = {name: i for i, name in enumerate(_PHASE_BY_ID)}

# Native sampler sites are the TREV_* event code last seen before the
# sample (a coarse frame id: which claim-path edge the engine crossed
# most recently).
_SITE_NAMES = {
    0: 'engine', 1: 'claim_begin', 2: 'codel', 3: 'slot_select',
    4: 'claiming', 5: 'claimed', 6: 'requeued', 7: 'released',
    8: 'failed', 9: 'cancelled', 10: 'dns_begin', 11: 'dns_query',
    12: 'dns_query_end', 13: 'dns_done',
}

_NATIVE_PROF_OK = _native is not None and hasattr(_native, 'prof_start')

DEFAULT_INTERVAL_MS = 5.0
DEFAULT_SAMPLER_RING = 8192

# Flamegraph weights are integer microseconds (collapsed-stack format
# wants integer counts); sampler stacks are weighted by sample count.
_US_PER_MS = 1000


# -- phase ledger -----------------------------------------------------------

def claim_ledger(trace) -> dict | None:
    """Per-claim time accounting across :data:`PHASES` (all ms).

    Derived entirely from the claim's recorded spans, which are
    contiguous by construction (queue_wait ends where the handshake
    begins, the handshake ends where the lease begins, the lease ends
    at release; Trace.finish closes stragglers at the root end), so
    ``sum(phases) == wall`` up to float addition and ``coverage`` —
    the named share of wall time — sits at ~1.0 on both the fast and
    queued paths. ``socket_wait`` is the during-claim part of the
    connect span and is carved OUT of queue_wait (the claim queues
    while its socket connects) so the phases stay disjoint. The
    ``codel``/``runq_pump``/``fsm`` columns are sampler-attributed
    phases: the ledger carries them (non-null, 0.0) so every surface
    shows the full phase set, and the flamegraph's sampler stacks say
    where their CPU went. Returns None for a trace still open or not
    a claim."""
    root = trace.root
    if root.end is None or root.attrs.get('kind') != 'claim':
        return None
    wall = root.end - root.start
    queue_wait = handshake = lease = socket_wait = 0.0
    connect_parts = []
    for span in trace.spans[1:]:
        d = span.duration()
        if d is None:
            continue
        if span.name == 'queue_wait':
            queue_wait += d
        elif span.name == 'handshake':
            handshake += d
        elif span.name == 'lease':
            lease += d
        elif span.name == 'connect' and span.attrs.get('during_claim'):
            # Only the part inside the claim window counts against it.
            part = max(
                0.0, min(span.end, root.end) - max(span.start,
                                                   root.start))
            socket_wait += part
            if part > 0.0:
                connect_parts.append((span.start, span.end, part))
    socket_wait = min(socket_wait, queue_wait)
    queue_wait -= socket_wait
    phases = {
        'queue_wait': queue_wait,
        'codel': 0.0,
        'runq_pump': 0.0,
        'fsm': 0.0,
        'socket_wait': socket_wait,
        'handshake': handshake,
        'lease': lease,
    }
    named = sum(phases.values())
    phases['other'] = max(wall - named, 0.0)
    wire, decomposed = _decompose_socket_wait(socket_wait,
                                              connect_parts)
    return {
        'trace_id': trace.trace_id,
        'wire': wire,
        'wire_decomposed': decomposed,
        'pool': root.attrs.get('pool', ''),
        'domain': root.attrs.get('domain', ''),
        'shard': root.attrs.get('shard'),
        'backend': getattr(trace, 'ct_backend', '') or '',
        'outcome': root.attrs.get('outcome', '?'),
        'wall_ms': wall,
        'phases': phases,
        'coverage': (named / wall) if wall > 0.0 else 1.0,
    }


def _decompose_socket_wait(socket_wait: float, connect_parts) -> tuple:
    """Split one claim's socket_wait across :data:`SUB_PHASES` using
    the wiretap ledger's per-connect breakdowns (keyed by the exact
    connect-span floats). Returns ``(wire_dict, decomposed)``;
    without wiretap data the whole phase is attributed to kernel_wait
    (``decomposed`` False). The returned values are nudged so
    ``kernel_wait + loop_dispatch + proto_parse == socket_wait`` holds
    under plain float addition — the per-claim identity the parity
    and scenario gates assert with ``==``."""
    if socket_wait > 0.0 and connect_parts and \
            mod_wiretap._LEDGER is not None:
        kernel = dispatch = parse = 0.0
        found = False
        for start, end, part in connect_parts:
            bk = mod_wiretap._LEDGER.connect_breakdown(start, end)
            if bk is None:
                continue
            span_len = end - start
            f = (part / span_len) if span_len > 0.0 else 0.0
            kernel += bk[0] * f
            dispatch += bk[1] * f
            parse += bk[2] * f
            found = True
        total = kernel + dispatch + parse
        if found and total > 0.0:
            scale = socket_wait / total
            kernel *= scale
            dispatch *= scale
            parse = socket_wait - kernel - dispatch
            if parse < 0.0:
                kernel += parse
                parse = 0.0
            if kernel + dispatch + parse != socket_wait:
                kernel = socket_wait - dispatch - parse
            if kernel < 0.0 or \
                    kernel + dispatch + parse != socket_wait:
                kernel, dispatch, parse = socket_wait, 0.0, 0.0
            return ({'kernel_wait': kernel,
                     'loop_dispatch': dispatch,
                     'proto_parse': parse}, True)
    return ({'kernel_wait': socket_wait, 'loop_dispatch': 0.0,
             'proto_parse': 0.0}, False)


def phase_ledger(traces=None) -> list:
    """Ledgers for every completed claim in `traces` (default: the
    live trace ring), oldest first."""
    if traces is None:
        traces = mod_trace.trace_ring()
    out = []
    for trace in traces:
        led = claim_ledger(trace)
        if led is not None:
            out.append(led)
    return out


def ledger_summary(ledgers) -> dict:
    """Fold per-claim ledgers into one cost-attribution record:
    total wall, per-phase totals, the wall-weighted coverage, and the
    socket_wait wire sub-phase totals (``wire_ms``/``wire_claims``
    fold only claims the wiretap ledger actually decomposed, so the
    undecomposed remainder stays visibly in the opaque parent
    phase)."""
    phase_ms = {p: 0.0 for p in PHASES}
    wire_ms = {p: 0.0 for p in SUB_PHASES}
    wire_claims = 0
    wall = 0.0
    named = 0.0
    n = 0
    for led in ledgers:
        n += 1
        wall += led['wall_ms']
        for p, ms in led['phases'].items():
            phase_ms[p] = phase_ms.get(p, 0.0) + ms
        if led.get('wire_decomposed'):
            wire_claims += 1
            for p, ms in led['wire'].items():
                wire_ms[p] = wire_ms.get(p, 0.0) + ms
        named += led['wall_ms'] * led['coverage']
    return {
        'claims': n,
        'wall_ms': wall,
        'phase_ms': phase_ms,
        'wire_ms': wire_ms,
        'wire_claims': wire_claims,
        'coverage': (named / wall) if wall > 0.0 else 1.0,
    }


# -- fleet merge (FleetRouter.profile_fleet) --------------------------------

def profile_record(shard: int | None = None) -> dict:
    """One shard's (or the whole process's) mergeable profile record.

    With `shard` set, only claims stamped with that shard id count —
    thread-backend shards share one process-wide trace ring, so the
    filter is what keeps per-shard records disjoint. Spawn-backend
    children call this in their own process (their ring IS the
    shard's) and still pass their id so the record is labelled."""
    ledgers = phase_ledger()
    if shard is not None:
        ledgers = [led for led in ledgers
                   if led['shard'] is None or led['shard'] == shard]
    rec = ledger_summary(ledgers)
    rec['shard'] = shard
    rec['sampler'] = sampler_stats()
    return rec


def reduce_profile(records) -> dict:
    """Merge per-shard profile records shard -> host, the same
    reduction shape as health.reduce_health: totals sum, coverage is
    re-derived wall-weighted, and the per-shard records ride along."""
    records = [r for r in records if r]
    phase_ms = {p: 0.0 for p in PHASES}
    wire_ms = {p: 0.0 for p in SUB_PHASES}
    wire_claims = 0
    wall = 0.0
    named = 0.0
    claims = 0
    for rec in records:
        claims += rec.get('claims', 0)
        wall += rec.get('wall_ms', 0.0)
        for p, ms in (rec.get('phase_ms') or {}).items():
            phase_ms[p] = phase_ms.get(p, 0.0) + ms
        for p, ms in (rec.get('wire_ms') or {}).items():
            wire_ms[p] = wire_ms.get(p, 0.0) + ms
        wire_claims += rec.get('wire_claims', 0)
        named += rec.get('wall_ms', 0.0) * rec.get('coverage', 0.0)
    return {
        'n_shards': len(records),
        'claims': claims,
        'wall_ms': wall,
        'phase_ms': phase_ms,
        'wire_ms': wire_ms,
        'wire_claims': wire_claims,
        'coverage': (named / wall) if wall > 0.0 else 1.0,
        'shards': records,
    }


# -- sampling profiler ------------------------------------------------------

# Accumulated samples: (phase, site) -> count. Fed by _collect_samples
# from whichever engine is running; survives sampler stop so the
# flamegraph covers the whole profiled window.
_samples: dict = {}
_sample_total = 0
_sampler_engine: str | None = None   # 'native' | 'pure' | None
_disabled_reason: str | None = None
_pure_ring: list = []
_pure_cap = DEFAULT_SAMPLER_RING
_pure_dropped = 0
_pure_prev_handler = None

# Phase hint for the PURE sampler, and the seam the engine modules use
# for the phases whose code is Python under both engines (pool.py's
# CoDel pacer, connection_fsm's connect initiation): while the sampler
# runs, those modules' `_prof` global points at this module and they
# bracket their work with push_phase/pop_phase; stopped, they pay one
# global load + None check.
_pure_hint = _PHASE_IDS['other']

# Modules that carry a `_prof` seam; bound lazily at sampler start so
# importing profile never drags the whole engine in.
_SEAM_MODULES = ('cueball_tpu.pool', 'cueball_tpu.connection_fsm',
                 'cueball_tpu.runq', 'cueball_tpu.fsm',
                 'cueball_tpu.native_transport')


def push_phase(name: str) -> int:
    """Tag the engine phase for subsequent samples; returns the
    previous tag for pop_phase. Callable under either engine."""
    global _pure_hint
    phase = _PHASE_IDS[name]
    if _sampler_engine == 'native':
        return _native.prof_set_phase(phase)
    prev = _pure_hint
    _pure_hint = phase
    return prev


def pop_phase(token: int) -> None:
    global _pure_hint
    if _sampler_engine == 'native':
        _native.prof_set_phase(token)
    else:
        _pure_hint = token


def _pure_sigprof(signum, frame):
    """The CUEBALL_NO_NATIVE fallback handler: attribute the sample to
    the explicit phase hint when one is pushed, else by the
    interrupted frame's module (runq -> runq_pump, fsm engines -> fsm,
    the selector poll -> socket_wait)."""
    global _pure_dropped
    phase = _pure_hint
    site = 'engine'
    if frame is not None:
        fname = frame.f_code.co_filename
        site = frame.f_code.co_name
        if phase == _PHASE_IDS['other']:
            if fname.endswith('runq.py'):
                phase = _PHASE_IDS['runq_pump']
            elif fname.endswith(('fsm.py', 'connection_fsm.py')):
                phase = _PHASE_IDS['fsm']
            elif 'selectors' in fname or site == 'select':
                phase = _PHASE_IDS['socket_wait']
    if len(_pure_ring) >= _pure_cap:
        del _pure_ring[0]
        _pure_dropped += 1
    _pure_ring.append((phase, site, mod_utils.current_millis()))


def _bind_seams(value) -> None:
    for name in _SEAM_MODULES:
        mod = sys.modules.get(name)
        if mod is not None and hasattr(mod, '_prof'):
            mod._prof = value


def start_sampler(interval_ms: float = DEFAULT_INTERVAL_MS,
                  ring: int = DEFAULT_SAMPLER_RING) -> bool:
    """Arm the SIGPROF sampler. Returns False (and records why in
    sampler_stats()['disabled_reason']) instead of arming when a
    non-system clock is installed — netsim scenarios must stay
    deterministic, and profiling virtual time is meaningless — or when
    the platform can't deliver the signal here (non-main thread)."""
    global _sampler_engine, _disabled_reason, _pure_cap, \
        _pure_prev_handler
    if _sampler_engine is not None:
        return True
    if not isinstance(mod_utils.get_clock(), mod_utils.SystemClock):
        _disabled_reason = 'non-system clock installed (netsim?)'
        return False
    if _NATIVE_PROF_OK:
        _native.prof_configure(int(ring))
        _native.prof_start(max(1, int(interval_ms * 1000)))
        _sampler_engine = 'native'
    else:
        try:
            _pure_prev_handler = signal.signal(signal.SIGPROF,
                                               _pure_sigprof)
            signal.setitimer(signal.ITIMER_PROF, interval_ms / 1000.0,
                             interval_ms / 1000.0)
        except (ValueError, OSError) as e:
            _disabled_reason = 'cannot arm SIGPROF here (%s)' % e
            return False
        _pure_cap = int(ring)
        _sampler_engine = 'pure'
    _disabled_reason = None
    _bind_seams(sys.modules[__name__])
    return True


def stop_sampler() -> bool:
    """Disarm the sampler, folding pending samples into the
    accumulated flamegraph counts. Returns whether it was running."""
    global _sampler_engine, _pure_prev_handler
    engine = _sampler_engine
    if engine is None:
        return False
    _bind_seams(None)
    if engine == 'native':
        _collect_samples()
        _native.prof_stop()
        _collect_samples()
        _native.prof_configure(0)
    else:
        try:
            signal.setitimer(signal.ITIMER_PROF, 0.0)
            if _pure_prev_handler is not None:
                signal.signal(signal.SIGPROF, _pure_prev_handler)
        except (ValueError, OSError):
            pass
        _pure_prev_handler = None
        _collect_samples()
    _sampler_engine = None
    return True


def sampler_running() -> bool:
    return _sampler_engine is not None


def _collect_samples() -> None:
    """Drain the active ring into the accumulated (phase, site)
    counts."""
    global _sample_total
    if _sampler_engine == 'native':
        raw = _native.prof_drain()
        for phase_id, site, _t in raw:
            key = (_PHASE_BY_ID[phase_id]
                   if phase_id < len(_PHASE_BY_ID) else 'other',
                   _SITE_NAMES.get(site, 'site_%d' % site))
            _samples[key] = _samples.get(key, 0) + 1
            _sample_total += 1
    elif _sampler_engine == 'pure' or _pure_ring:
        raw, _pure_ring[:] = list(_pure_ring), []
        for phase_id, site, _t in raw:
            key = (_PHASE_BY_ID[phase_id]
                   if phase_id < len(_PHASE_BY_ID) else 'other',
                   str(site))
            _samples[key] = _samples.get(key, 0) + 1
            _sample_total += 1


def sampler_stats() -> dict:
    out = {
        'running': _sampler_engine is not None,
        'engine': _sampler_engine,
        'samples': _sample_total,
        'disabled_reason': _disabled_reason,
    }
    if _sampler_engine == 'native':
        out['ring'] = dict(_native.prof_stats())
    elif _sampler_engine == 'pure':
        out['ring'] = {'capacity': _pure_cap,
                       'pending': len(_pure_ring),
                       'dropped': _pure_dropped,
                       'running': True}
    return out


def reset_samples() -> None:
    """Drop accumulated sample counts (bench arms start clean)."""
    global _sample_total, _pure_dropped
    _collect_samples()
    _samples.clear()
    _sample_total = 0
    _pure_dropped = 0


# -- flamegraph -------------------------------------------------------------

def flamegraph(traces=None) -> str:
    """Collapsed-stack flamegraph text (the /kang/profile payload).

    Ledger stacks first — ``claim;<phase> <microseconds>`` in fixed
    PHASES order, zero phases skipped — then, only when the sampler
    has actually collected samples, ``sampler;<phase>;<site> <count>``
    stacks sorted by phase order then site. The ledger half is pure
    span replay, so on a seeded netsim run (where the sampler is
    auto-disabled) the output is byte-identical between the native
    and pure recorders."""
    total = ledger_summary(phase_ledger(traces))
    out = []
    for phase in PHASES:
        ms = total['phase_ms'].get(phase, 0.0)
        if phase == 'socket_wait' and total.get('wire_claims', 0) > 0:
            # Wiretap decomposed at least one claim: nest the wire
            # sub-phases under the parent frame, keeping only the
            # undecomposed remainder on the parent line. With wiretap
            # off this branch never runs and the output stays
            # byte-identical to the un-decomposed format.
            wire = total['wire_ms']
            residual = ms - sum(wire.values())
            us = int(round(max(residual, 0.0) * _US_PER_MS))
            if us > 0:
                out.append('claim;%s %d' % (phase, us))
            for sub in SUB_PHASES:
                sub_us = int(round(wire.get(sub, 0.0) * _US_PER_MS))
                if sub_us > 0:
                    out.append('claim;%s;%s %d' % (phase, sub, sub_us))
            continue
        us = int(round(ms * _US_PER_MS))
        if us > 0:
            out.append('claim;%s %d' % (phase, us))
    _collect_samples()
    if _samples:
        order = {p: i for i, p in enumerate(PHASES)}
        for (phase, site), count in sorted(
                _samples.items(),
                key=lambda kv: (order.get(kv[0][0], 99), kv[0][1])):
            out.append('sampler;%s;%s %d' % (phase, site, count))
    return '\n'.join(out) + '\n' if out else ''


# -- SIGUSR2 dump section ---------------------------------------------------

def dump_profile(limit: int = 5) -> str:
    """Profiler section for the SIGUSR2 dump: sampler state, the
    fleet-wide cost attribution, and the slowest claims' ledgers.
    '' when there is nothing to report (sampler never armed and no
    completed claims) so the dump stays absent-but-well-formed."""
    ledgers = phase_ledger()
    if not ledgers and _sampler_engine is None and not _samples:
        return ''
    out = ['-- claim-path profiler --']
    stats = sampler_stats()
    if stats['running']:
        ring = stats.get('ring') or {}
        out.append('  sampler: running engine=%s samples=%d '
                   'ring_pending=%s dropped=%s' %
                   (stats['engine'], stats['samples'],
                    ring.get('pending', '?'), ring.get('dropped', '?')))
    elif stats['disabled_reason']:
        out.append('  sampler: disabled (%s)' %
                   stats['disabled_reason'])
    else:
        out.append('  sampler: stopped samples=%d' % stats['samples'])
    if ledgers:
        total = ledger_summary(ledgers)
        parts = ['%s=%.1f' % (p, total['phase_ms'][p])
                 for p in PHASES if total['phase_ms'][p] > 0.0]
        out.append('  ledger: %d claims wall=%.1fms coverage=%.3f %s'
                   % (total['claims'], total['wall_ms'],
                      total['coverage'], ' '.join(parts)))
        if total.get('wire_claims', 0) > 0:
            out.append('  socket_wait wire: %s (%d claims decomposed)'
                       % (' '.join('%s=%.1f' % (p, total['wire_ms'][p])
                                   for p in SUB_PHASES),
                          total['wire_claims']))
        slow = sorted(ledgers, key=lambda led: led['wall_ms'],
                      reverse=True)[:limit]
        for led in slow:
            parts = ['%s=%.1f' % (p, led['phases'][p])
                     for p in PHASES if led['phases'][p] > 0.0]
            out.append('    %s %8.1fms %-9s %s' % (
                led['trace_id'][:8], led['wall_ms'], led['outcome'],
                ' '.join(parts)))
    if _samples:
        top = sorted(_samples.items(), key=lambda kv: -kv[1])[:limit]
        out.append('  top sample sites: ' + ' '.join(
            '%s;%s=%d' % (p, s, c) for (p, s), c in top))
    return '\n'.join(out) + '\n'

"""ConnectionPool: claim/release leases over DNS-discovered backends.

Rebuild of reference `lib/pool.js`. A pool maintains busy/init/idle
connection slots per backend, fed by a Resolver's added/removed events:

- spares policy + claim-driven growth to `maximum`
  (reference lib/pool.js:102-124)
- low-pass (128-tap EMA FIR @5Hz) damping of pool shrink under recently
  high load (reference lib/pool.js:37-100,251-262,579-585)
- per-backend churn rate limiting (reference lib/pool.js:599-662)
- decoherence shuffle >=60s (reference lib/pool.js:234-245,501-519;
  rationale docs/internals.adoc:275-386)
- dead-backend declaration + monitor probe slots + failed-state
  short-circuit (reference lib/pool.js:771-794,378-426)
- CoDel claim-queue shedding when targetClaimDelay is set
  (reference lib/pool.js:195-200,735-753,874-885)

Pool FSM: starting -> running <-> failed -> stopping -> stopping.backends
-> stopped (reference lib/pool.js:315-487, docs/api.adoc:180-219).

The claim path is callback-based for parity (`claim_cb`), with an
asyncio-native `claim()` coroutine wrapper returning (handle, connection).
"""

from __future__ import annotations

import asyncio
import logging
import math

from . import codel as mod_codel
from . import errors as mod_errors
from . import trace as mod_trace
from . import utils as mod_utils
from .connection_fsm import (ConnectionSlotFSM, arm_claim_timers,
                             obtain_claim_handle)
from .cqueue import Queue
from .events import EventEmitter
from .fsm import FSM, get_loop
from .runq import defer

# Low-pass filter parameters (reference lib/pool.js:43-48): 5 Hz sampling,
# 128-tap EMA with time constant -0.2 -> pass band ~0.25 Hz, -10 dB at
# 0.5 Hz, -20 dB at 2.5 Hz.
LP_RATE = 5
LP_INT = round(1000 / LP_RATE)

# CoDel pacer cadence lives with the rest of the control-law constants
# (re-exported here for back-compat; see codel.py for the rationale).
CODEL_PACE = mod_codel.CODEL_PACE

# Bound to cueball_tpu.profile while its sampler runs, so SIGPROF
# samples landing inside the CoDel pacer attribute to the codel phase.
_prof = None

# Fleet-actuation advisory freshness bound (ms): ~5 sampler ticks at
# the default 200 ms cadence. Older advisories are ignored and the
# pool's own filter governs again.
FLEET_ADVISORY_TTL = 1000

# How long (ms) after the last accepted control decision a LOWER epoch
# is still treated as stale. A restarted sampler's epoch counter
# restarts from 1; once this window has passed with no decisions, the
# pool trusts the new counter instead of rejecting it forever.
CONTROL_EPOCH_TTL = 5000


def gen_taps(count: int, tc: float) -> list[float]:
    """Generate normalized EMA filter taps (reference lib/pool.js:50-76).
    `tc` is the decay time constant: negative, fractional; closer to 0.0
    means lower cutoff frequency and sharper roll-off."""
    taps = [math.exp(tc * i) for i in range(count)]
    s = sum(taps)
    return [t / s for t in taps]


LP_TAPS = gen_taps(128, -0.2)


class FIRFilter:
    """FIR filter over a circular buffer (reference lib/pool.js:78-100).

    The pure-Python form is the pool's hot-path implementation;
    `cueball_tpu.ops.fir` holds the batched JAX/TPU form used for
    fleet-wide telemetry. Samples arrive at LP_RATE (5 Hz) but the
    output is read on every rebalance pass — potentially thousands of
    times per sample under queued load — so the dot product is
    evaluated lazily once per put() and cached between samples."""

    def __init__(self, taps: list[float]):
        self.f_taps = taps
        self.f_buf = [0.0] * len(taps)
        self.f_ptr = 0
        self.f_out = 0.0
        self.f_dirty = False

    def put(self, v: float) -> None:
        self.f_buf[self.f_ptr] = v
        self.f_ptr += 1
        if self.f_ptr == len(self.f_taps):
            self.f_ptr = 0
        self.f_dirty = True

    def get(self) -> float:
        if not self.f_dirty:
            return self.f_out
        i = self.f_ptr - 1
        if i < 0:
            i += len(self.f_taps)
        acc = 0.0
        for tap in self.f_taps:
            acc += self.f_buf[i] * tap
            i -= 1
            if i < 0:
                i += len(self.f_taps)
        self.f_out = acc
        self.f_dirty = False
        return acc


class _Interval:
    """Recurring timer emitting 'timeout' on an EventEmitter (the node
    setInterval-feeding-an-emitter pattern of reference
    lib/pool.js:228-262). asyncio timers don't hold the loop open, so no
    unref() is needed."""

    def __init__(self, ms: float, emitter: EventEmitter):
        self._ms = ms
        self._emitter = emitter
        self._cancelled = False
        self._handle = None
        self._schedule()

    def _schedule(self):
        loop = get_loop()
        self._handle = loop.call_later(self._ms / 1000.0, self._fire)

    def _fire(self):
        if self._cancelled:
            return
        self._emitter.emit('timeout')
        if not self._cancelled:
            self._schedule()

    def cancel(self):
        self._cancelled = True
        if self._handle is not None:
            self._handle.cancel()


class ConnectionPool(FSM):
    """Reference CueBallConnectionPool (lib/pool.js:125-266 ctor)."""

    def __init__(self, options: dict):
        if not isinstance(options, dict):
            raise AssertionError('options must be a dict')
        constructor = options.get('constructor')
        # The transport seam: options['transport'] (a Transport
        # instance, a registry name, or None) supplies the connection
        # constructor when the caller doesn't pass one explicitly; an
        # explicit constructor always wins (it IS a transport
        # decision the caller already made).
        self.p_transport = None
        if options.get('transport') is not None:
            from . import transport as mod_transport
            self.p_transport = mod_transport.get_transport(
                options['transport'])
            if constructor is None:
                constructor = self.p_transport.connector
        if not callable(constructor):
            raise AssertionError('options.constructor must be callable')

        self.p_uuid = mod_utils.make_uuid()
        self.p_constructor = constructor

        domain = options.get('domain')
        if not isinstance(domain, str):
            raise AssertionError('options.domain must be a string')
        self.p_domain = domain
        mod_utils.assert_claim_delay(options.get('targetClaimDelay'))

        recovery = options.get('recovery')
        mod_utils.assert_recovery_set(recovery or {})
        if not recovery or 'default' not in recovery:
            raise AssertionError('options.recovery.default is required')
        self.p_recovery = recovery

        # Child logger carrying pool identity into every record
        # (reference lib/pool.js:152-157).
        self.p_log = mod_utils.make_child_logger(
            options.get('log') or logging.getLogger('cueball.pool'),
            component='CueBallConnectionPool', domain=domain,
            service=options.get('service'), pool=self.p_uuid)

        self.p_collector = mod_utils.create_error_metrics(options)

        spares = options.get('spares')
        maximum = options.get('maximum')
        if not isinstance(spares, int) or not isinstance(maximum, int):
            raise AssertionError(
                'options.spares and options.maximum must be numbers')
        self.p_spares = spares
        self.p_max = maximum

        self.p_checker = options.get('checker')
        self.p_check_timeout = options.get('checkTimeout')

        self.p_keys: list[str] = []
        self.p_backends: dict[str, dict] = {}
        self.p_connections: dict[str, list[ConnectionSlotFSM]] = {}
        self.p_dead: dict[str, bool] = {}
        self.p_lastrate: dict[str, dict] = {}

        max_churn = options.get('maxChurnRate')
        self.p_maxrate = max_churn if max_churn is not None else math.inf

        self.p_last_rebalance = None
        self.p_in_rebalance = False
        self.p_rebal_scheduled = False
        self.p_started_resolver = False
        self.p_lpf = FIRFilter(LP_TAPS)

        self.p_idleq = Queue()
        self.p_initq = Queue()
        self.p_waiters = Queue()

        self.p_codel = None
        tcd = options.get('targetClaimDelay')
        if isinstance(tcd, (int, float)) and math.isfinite(tcd):
            self.p_codel = mod_codel.ControlledDelay(tcd)
        # Continuous-evaluation pacer state (see codel.CODEL_PACE): armed
        # while a standing queue exists; drops only while a dequeue has
        # happened within the last control interval, so a fully stalled
        # pool keeps the reference's shed-at-dequeue/getMaxIdle-bound
        # behaviour (reference lib/codel.js:96-118).
        self.p_codel_pacer = None
        self.p_last_dequeue = -math.inf
        self.p_pace_shaving = False
        self.p_pace_above_since = 0.0
        self.p_pace_below_since = 0.0
        # Mean-tracking accumulator for the current overload episode
        # (see _pace_comp): sum of (sojourn - target) over every
        # resolved waiter.
        self.p_pace_sum_err = 0.0

        self.p_last_error = None
        self.p_counters: dict[str, int] = {}

        if options.get('resolver') is not None:
            self.p_resolver = options['resolver']
            self.p_resolver_custom = True
        else:
            from .resolver import Resolver
            self.p_resolver = Resolver({
                'resolvers': options.get('resolvers'),
                'domain': domain,
                'service': options.get('service'),
                'maxDNSConcurrency': options.get('maxDNSConcurrency'),
                'defaultPort': options.get('defaultPort'),
                'log': self.p_log,
                'recovery': recovery,
            })
            self.p_resolver_custom = False

        # Periodic rebalance sweep: busy->idle returns are handled lazily
        # (reference lib/pool.js:224-232).
        self.p_rebal_timer = EventEmitter()
        self.p_rebal_timer_inst = _Interval(10000, self.p_rebal_timer)

        # Decoherence shuffle, clamped to >= 60s
        # (reference lib/pool.js:234-245).
        shuffle_intvl = options.get('decoherenceInterval')
        if shuffle_intvl is None or shuffle_intvl < 60:
            shuffle_intvl = 60
        self.p_shuffle_timer = EventEmitter()
        self.p_shuffle_timer_inst = _Interval(
            shuffle_intvl * 1000, self.p_shuffle_timer)

        self.p_last_rebal_clamped = False
        self.p_rate_delay_timer = None

        # Fleet actuation (opt-in, default OFF): when enabled AND a
        # fresh advisory has arrived from a FleetSampler({'actuate':
        # True}), the rebalance shrink clamp consults the batched
        # TPU-computed FIR value instead of the local p_lpf. The laws
        # are identical (tests/test_sampler.py parity), so behavior
        # matches; the flag exists so the default path never depends
        # on a sampler being alive.
        self.p_fleet_actuation = bool(options.get('fleetActuation'))
        self.p_fleet_advisory: tuple[float, float] | None = None

        # Control-plane actuation (opt-in, default OFF): when enabled,
        # a FleetSampler running the fused control step
        # (parallel.control) may push whole decisions — adapted CoDel
        # target + spares plan — through apply_control_decision. Both
        # ends opt in, same contract as fleetActuation: the sampler
        # offers decisions to every row, the pool accepts only under
        # this flag. p_ctrl_epoch/p_ctrl_at implement the stale-epoch
        # guard (see apply_control_decision).
        self.p_control_actuation = bool(options.get('controlActuation'))
        self.p_ctrl_epoch = 0
        self.p_ctrl_at = -math.inf
        # Audit trail of the last accepted decision's health citation
        # (the fleet health verdict the control plane saw when it
        # decided): None until a decision carrying one is accepted.
        self.p_ctrl_health: dict | None = None

        # Fleet-telemetry push handles (see FleetSampler): a tuple so
        # the per-event dirty mark is a plain iteration — empty for the
        # (default) unsampled pool, one entry per attached sampler.
        self.p_telemetry: tuple = ()

        # Low-pass filter sampling at 5 Hz
        # (reference lib/pool.js:249-262).
        self.p_lp_emitter = EventEmitter()
        self.p_lp_emitter.on('timeout', self._lp_sample)
        self.p_lp_timer = _Interval(LP_INT, self.p_lp_emitter)

        super().__init__('starting')

    # -- internals -------------------------------------------------------

    def lp_load_sample(self) -> float:
        """The load figure the 5 Hz LP filter tracks: busy connections
        plus the spares setting (reference lib/pool.js:251-262). Shared
        with the fleet telemetry sampler so the batched law sees exactly
        what the per-pool law sees."""
        conns = sum(len(v) for v in self.p_connections.values())
        spares = len(self.p_idleq) + len(self.p_initq)
        busy = conns - spares
        return busy + self.p_spares

    def _lp_sample(self) -> None:
        self.p_lpf.put(self.lp_load_sample())
        if self.p_last_rebal_clamped:
            self.rebalance()

    def receive_fleet_advisory(self, filtered: float,
                               at_ms: float | None = None) -> None:
        """Store the fleet sampler's batched FIR output for this pool.
        Called every sampler tick when actuation is on; consulted by
        _rebalance only if this pool opted in via fleetActuation."""
        self.p_fleet_advisory = (
            float(filtered),
            at_ms if at_ms is not None else mod_utils.current_millis())

    # -- fleet telemetry push protocol -----------------------------------

    def telemetry_attach(self, handle) -> None:
        """Accept a FleetSampler row handle. From here on the pool
        (and its slots/claims) call handle.mark_dirty() at every event
        that can move a gathered signal, so the sampler re-reads this
        pool only on ticks where something actually changed."""
        self.p_telemetry = self.p_telemetry + (handle,)

    def telemetry_detach(self, handle) -> None:
        self.p_telemetry = tuple(
            h for h in self.p_telemetry if h is not handle)

    def _telemetry_dirty(self) -> None:
        """O(1) per attached sampler: flag this pool's telemetry row
        stale. Cheap enough for the claim hot path (a no-op tuple walk
        when no sampler is attached)."""
        for h in self.p_telemetry:
            h.mark_dirty()

    def set_spares(self, spares: int) -> None:
        """Reconfigure the spares target at runtime (and tell any
        attached fleet sampler the row moved)."""
        if not isinstance(spares, int):
            raise AssertionError('spares must be a number')
        self.p_spares = spares
        self._telemetry_dirty()
        self.rebalance()

    setSpares = set_spares

    def set_maximum(self, maximum: int) -> None:
        """Reconfigure the connection cap at runtime (and tell any
        attached fleet sampler the row moved)."""
        if not isinstance(maximum, int):
            raise AssertionError('maximum must be a number')
        self.p_max = maximum
        self._telemetry_dirty()
        self.rebalance()

    setMaximum = set_maximum

    def apply_control_decision(self, epoch: int, codel_target=None,
                               spares=None, at_ms=None,
                               health=None) -> bool:
        """Guarded control-plane actuation: accept one decision row
        from the fused control step (parallel.control).

        The whole decision is validated BEFORE anything mutates —
        rejection (returns False) leaves the pool, its CoDel state and
        its FSM untouched:

        - the pool must have opted in (``controlActuation`` option);
        - ``epoch`` must be a fresh int: strictly greater than the
          last applied epoch, unless the last apply is older than
          CONTROL_EPOCH_TTL (a restarted sampler's counter restarts;
          after the TTL its decisions are trusted again);
        - ``codel_target`` (when given) needs a live ControlledDelay
          and must sit within [CODEL_TARGET_MIN, CODEL_TARGET_MAX];
        - ``spares`` (when given) must be an int in [0, maximum].

        On accept, only the values that actually moved are applied:
        the CoDel target via the guarded ``set_target`` and the spares
        setting via the same dirty-mark + rebalance path as
        ``set_spares``. ``health`` (when given with an accepted
        decision) is kept verbatim as ``p_ctrl_health`` — the fleet
        health verdict the control plane cited, so a SIGUSR2 dump or
        kang snapshot can answer "what did the controller believe when
        it moved this pool". Cost when the control plane is idle:
        zero — nothing on the claim path reads any of this."""
        if not self.p_control_actuation:
            return False
        now = at_ms if at_ms is not None else mod_utils.current_millis()
        if not isinstance(epoch, int) or isinstance(epoch, bool):
            return False
        stale_ok = now - self.p_ctrl_at > CONTROL_EPOCH_TTL
        if epoch <= self.p_ctrl_epoch and not stale_ok:
            return False
        if codel_target is not None:
            if self.p_codel is None:
                return False
            if not isinstance(codel_target, (int, float)) or \
                    isinstance(codel_target, bool) or \
                    not math.isfinite(codel_target) or \
                    not (mod_codel.CODEL_TARGET_MIN <= codel_target
                         <= mod_codel.CODEL_TARGET_MAX):
                return False
        if spares is not None:
            if not isinstance(spares, int) or isinstance(spares, bool) \
                    or spares < 0 or spares > self.p_max:
                return False
        # Validation complete; apply.
        self.p_ctrl_epoch = epoch
        self.p_ctrl_at = now
        if health is not None:
            self.p_ctrl_health = health
        if codel_target is not None and \
                codel_target != self.p_codel.cd_targdelay:
            self.p_codel.set_target(codel_target)
            self._telemetry_dirty()
        if spares is not None and spares != self.p_spares:
            self.p_spares = spares
            self._telemetry_dirty()
            self.rebalance()
        return True

    applyControlDecision = apply_control_decision

    def _shrink_floor(self) -> float:
        """The low-pass load figure the shrink clamp uses: the fleet
        advisory when actuation is on and the advisory is fresh
        (within FLEET_ADVISORY_TTL), else the local filter. Falling
        back — never blocking — on a stale advisory means a stopped
        or wedged sampler degrades to exactly the stock behavior."""
        if self.p_fleet_actuation and self.p_fleet_advisory is not None:
            val, at = self.p_fleet_advisory
            if mod_utils.current_millis() - at <= FLEET_ADVISORY_TTL:
                return val
        return self.p_lpf.get()

    def _incr_counter(self, counter: str, n: int = 1) -> None:
        mod_utils.update_error_metrics(
            self.p_collector, self.p_uuid, counter)
        self.p_counters[counter] = self.p_counters.get(counter, 0) + n

    _incrCounter = _incr_counter

    def _hwm_counter(self, counter: str, val: int) -> None:
        if self.p_counters.get(counter, -math.inf) < val:
            self.p_counters[counter] = val

    # -- CoDel pacer -----------------------------------------------------
    #
    # Entry mirrors CoDel: shave mode engages only after the queue head
    # has sat above targetClaimDelay continuously for a full control
    # interval (burst tolerance preserved). While engaged, every tick
    # sheds the waiters whose sojourn exceeds the target, pinning head
    # sojourn at ~target instead of letting it ride the release cadence.
    # Exit is hysteretic: mode disengages only after no waiter has
    # crossed the target for a full interval (under sustained overload
    # fresh waiters cross constantly, so it stays engaged). The
    # reference's ControlledDelay at the dequeue sites is untouched; in
    # shave mode it simply stops seeing above-target sojourns and serves
    # instead of dropping.

    def _arm_codel_pacer(self) -> None:
        if self.p_codel is None or self.p_codel_pacer is not None:
            return
        self.p_codel_pacer = get_loop().call_later(
            CODEL_PACE / 1000.0, self._codel_pace)

    def _pace_clocks_reset(self) -> None:
        """Forget the shave-mode clocks so the next overload burst
        gets full CoDel burst tolerance (the analogue of
        ControlledDelay.empty() resetting cd_first_above_time). The
        mean-tracking accumulators survive: transient service stalls
        and hysteresis exits happen mid-episode, and wiping the
        deficit there would re-introduce the ramp-up undershoot."""
        self.p_pace_shaving = False
        self.p_pace_above_since = 0.0
        self.p_pace_below_since = 0.0

    def _pace_reset(self) -> None:
        """Episode over (claim queue fully drained): clocks AND the
        mean-tracking accumulator start fresh."""
        self._pace_clocks_reset()
        self.p_pace_sum_err = 0.0

    def _pace_account(self, sojourn_err: float) -> None:
        """One resolved waiter's (sojourn - target) enters the
        episode's running deficit.

        Clamped to +/- target * (queue_len + 1): the deficit exists to
        repay the CURRENT standing queue's worth of compensation, and
        a genuine overload ramp never banks more than that (arrivals
        outpace service, so the queue grows faster than the deficit).
        Without the clamp, a long healthy-but-never-quite-empty
        stretch (sojourns far below target, queue never draining to
        zero) would bank an unbounded deficit and pin the shed
        threshold at 2x target for minutes into the next real
        overload."""
        self.p_pace_sum_err += sojourn_err
        limit = self.p_codel.cd_targdelay * (len(self.p_waiters) + 1.0)
        if self.p_pace_sum_err < -limit:
            self.p_pace_sum_err = -limit
        elif self.p_pace_sum_err > limit:
            self.p_pace_sum_err = limit

    def _pace_comp(self) -> float:
        """Mean-tracking compensation (ms) added to the shed
        threshold. An overload episode's ramp-up claims structurally
        resolve BELOW target (they can't have waited longer than the
        episode is old), so shedding at exactly `target` leaves the
        episode's average sojourn under the target — ~-240 ms at a
        5000 ms target under the reference's own load protocol
        (test/codel.test.js:245-297). Shedding at
        `target + deficit/queue_len` makes each shed repay an equal
        share of the accumulated deficit; the deficit-per-queued-claim
        ratio is invariant as the queue drains, so the episode's mean
        lands on the target. Capped at `target` (no shed waits past
        2x target; the getMaxIdle bound still applies far above)."""
        if self.p_pace_sum_err >= 0.0 or len(self.p_waiters) == 0:
            return 0.0
        return min(-self.p_pace_sum_err / len(self.p_waiters),
                   self.p_codel.cd_targdelay)

    def _codel_pace(self) -> None:
        prof = _prof
        if prof is None:
            return self._codel_pace_body()
        tok = prof.push_phase('codel')
        try:
            return self._codel_pace_body()
        finally:
            prof.pop_phase(tok)

    def _codel_pace_body(self) -> None:
        self.p_codel_pacer = None
        if self.p_codel is None or \
                self.is_in_state('stopping') or self.is_in_state('stopped'):
            return
        # Resolved handles unlink themselves from p_waiters at their
        # own state entries (CueBallClaimHandle._ch_unpark), so the
        # queue only holds live waiters here (modulo same-tick races
        # handled below).
        if len(self.p_waiters) == 0:
            self._pace_reset()
            return
        now = mod_utils.current_millis()
        if now - self.p_last_dequeue > mod_codel.CODEL_INTERVAL:
            # Service stalled: stop pacing entirely (the reference
            # behaviour — shed at dequeue or at the getMaxIdle bound —
            # takes over). The next dequeue or queued claim re-arms.
            # Clocks only: the episode (standing queue) continues.
            self._pace_clocks_reset()
            return
        target = self.p_codel.cd_targdelay
        interval = mod_codel.CODEL_INTERVAL
        comp = self._pace_comp()
        tracer = mod_trace._runtime
        head_over = False
        while len(self.p_waiters) > 0:
            hdl = self.p_waiters.peek()
            if not hdl.is_in_state('waiting'):
                self.p_waiters.shift()
                continue
            soj = now - hdl.ch_started
            if soj <= target:
                break
            head_over = True
            if self.p_pace_above_since == 0:
                self.p_pace_above_since = now
            if not self.p_pace_shaving and \
                    now - self.p_pace_above_since < interval:
                break
            self.p_pace_shaving = True
            if soj <= target + comp:
                break
            self.p_waiters.shift()
            self._incr_counter('codel-paced-drop')
            if tracer is not None:
                tracer.codel_shed(hdl, 'paced', soj, target)
            self._pace_account(soj - target)
            hdl.timeout()
        if head_over:
            self.p_pace_below_since = 0
        elif self.p_pace_shaving:
            if self.p_pace_below_since == 0:
                self.p_pace_below_since = now
            elif now - self.p_pace_below_since >= interval:
                self._pace_clocks_reset()
        else:
            self.p_pace_above_since = 0
        if len(self.p_waiters) == 0:
            self.p_codel.empty()
            self._pace_reset()
            return
        self._arm_codel_pacer()

    def on_resolver_added(self, k: str, backend: dict) -> None:
        """Insert at a random position in the preference list
        (reference lib/pool.js:285-291; randomized per-client so load
        spreads across the fleet, docs/internals.adoc:275-386)."""
        backend['key'] = k
        idx = mod_utils.get_rng().randrange(len(self.p_keys) + 1)
        self.p_keys.insert(idx, k)
        self.p_backends[k] = backend
        self.rebalance()

    def on_resolver_removed(self, k: str) -> None:
        assert k in self.p_keys, 'resolver key %s not found' % k
        self.p_keys.remove(k)
        self.p_backends.pop(k, None)
        self.p_dead.pop(k, None)
        # Slot cleanup happens in the slot stateChanged handler once the
        # FSMs come to rest (reference lib/pool.js:293-313).
        for fsm in list(self.p_connections.get(k) or []):
            fsm.set_unwanted()

    # -- states ----------------------------------------------------------

    def state_starting(self, S):
        S.validTransitions(['failed', 'running', 'stopping'])
        from .monitor import pool_monitor
        pool_monitor.register_pool(self)

        S.on(self.p_resolver, 'added', self.on_resolver_added)
        S.on(self.p_resolver, 'removed', self.on_resolver_removed)

        if self.p_resolver.is_in_state('failed'):
            self.p_log.warning(
                'pre-provided resolver has already failed, pool will '
                'start up in "failed" state')
            self.p_last_error = mod_errors.CueBallError(
                'Pool resolver entered state "failed"',
                self.p_resolver.get_last_error())
            S.gotoState('failed')
            return

        def on_res_changed(state):
            if state == 'failed':
                self.p_log.warning('underlying resolver failed, moving '
                                   'pool to "failed" state')
                self.p_last_error = mod_errors.CueBallError(
                    'Pool resolver entered state "failed"',
                    self.p_resolver.get_last_error())
                S.gotoState('failed')
        S.on(self.p_resolver, 'stateChanged', on_res_changed)

        if self.p_resolver.is_in_state('running'):
            for k, backend in self.p_resolver.list().items():
                self.on_resolver_added(k, backend)
        elif self.p_resolver.is_in_state('stopped') and \
                not self.p_resolver_custom:
            self.p_resolver.start()
            self.p_started_resolver = True

        S.goto_state_on(self, 'connectedToBackend', 'running')

        def on_closed_backend(*a):
            dead = len(self.p_dead)
            self._hwm_counter('max-dead-backends', dead)
            if dead >= len(self.p_keys):
                self.p_log.warning(
                    'pool has exhausted all retries, now moving to '
                    '"failed" state (%d dead)', dead)
                S.gotoState('failed')
        S.on(self, 'closedBackend', on_closed_backend)

        S.goto_state_on(self, 'stopAsserted', 'stopping')

    def state_failed(self, S):
        S.validTransitions(['running', 'stopping'])
        S.on(self.p_resolver, 'added', self.on_resolver_added)
        S.on(self.p_resolver, 'removed', self.on_resolver_removed)
        S.on(self.p_shuffle_timer, 'timeout', self.reshuffle)

        def on_connected(*a):
            assert not self.p_resolver.is_in_state('failed')
            self.p_log.info('successfully connected to a backend, '
                            'moving back to running state')
            S.gotoState('running')
        S.on(self, 'connectedToBackend', on_connected)

        S.goto_state_on(self, 'stopAsserted', 'stopping')

        self._incr_counter('failed-state')

        # Pending-event re-check: a sibling slot may have connected in
        # this very loop turn — its 'connectedToBackend' fired while
        # 'running' (which has no listener for it) just before the
        # last-dead-backend event pushed us here. The reference only
        # listens for FUTURE connects and can wedge in 'failed' on this
        # interleaving; re-checking current slot state on entry designs
        # the race out (same pattern as the slot busy-state check,
        # reference lib/connection-fsm.js:881-889).
        for conns in self.p_connections.values():
            for fsm in conns:
                if fsm.is_in_state('idle') or fsm.is_in_state('busy'):
                    self.p_log.info(
                        'entered failed with a live connection already '
                        'up; returning to running')
                    S.gotoState('running')
                    return

        # Fail all outstanding waiting claims
        # (reference lib/pool.js:398-406).
        while not self.p_waiters.is_empty():
            hdl = self.p_waiters.shift()
            if hdl.is_in_state('waiting'):
                hdl.fail(mod_errors.PoolFailedError(
                    self, self.p_last_error))

    def state_running(self, S):
        S.validTransitions(['failed', 'stopping'])
        S.on(self.p_resolver, 'added', self.on_resolver_added)
        S.on(self.p_resolver, 'removed', self.on_resolver_removed)
        S.on(self.p_rebal_timer, 'timeout', self.rebalance)
        S.on(self.p_shuffle_timer, 'timeout', self.reshuffle)

        def on_closed_backend(*a):
            dead = len(self.p_dead)
            self._hwm_counter('max-dead-backends', dead)
            if dead >= len(self.p_keys):
                self.p_log.warning(
                    'pool has exhausted all retries, now moving to '
                    '"failed" state (%d dead)', dead)
                S.gotoState('failed')
        S.on(self, 'closedBackend', on_closed_backend)

        S.goto_state_on(self, 'stopAsserted', 'stopping')

    def state_stopping(self, S):
        S.validTransitions(['stopping.backends'])
        if self.p_started_resolver:
            def on_res_changed(s):
                if s == 'stopped':
                    S.gotoState('stopping.backends')
            S.on(self.p_resolver, 'stateChanged', on_res_changed)
            self.p_resolver.stop()
            if self.p_resolver.is_in_state('stopped'):
                S.gotoState('stopping.backends')
        else:
            S.gotoState('stopping.backends')

    def state_stopping_backends(self, S):
        S.validTransitions(['stopped'])
        fsms = [fsm for conns in self.p_connections.values()
                for fsm in conns]
        remaining = {'n': len(fsms)}

        def done_one():
            remaining['n'] -= 1
            if remaining['n'] == 0:
                S.gotoState('stopped')

        if not fsms:
            S.immediate(lambda: S.gotoState('stopped'))
            return

        for fsm in fsms:
            fsm.set_unwanted()
            if fsm.is_in_state('stopped') or fsm.is_in_state('failed'):
                done_one()
            else:
                def on_changed(st, _fsm=fsm):
                    if st in ('stopped', 'failed'):
                        done_one()
                S.on(fsm, 'stateChanged', on_changed)

    def state_stopped(self, S):
        S.validTransitions([])
        from .monitor import pool_monitor
        pool_monitor.unregister_pool(self)
        self.p_keys = []
        self.p_connections = {}
        self.p_backends = {}
        self.p_rebal_timer_inst.cancel()
        self.p_shuffle_timer_inst.cancel()
        self.p_lp_timer.cancel()
        if self.p_rate_delay_timer is not None:
            self.p_rate_delay_timer.cancel()
        if self.p_codel_pacer is not None:
            self.p_codel_pacer.cancel()
            self.p_codel_pacer = None

    # -- public helpers --------------------------------------------------

    def should_retry_backend(self, backend: str) -> bool:
        return backend in self.p_backends

    def is_declared_dead(self, backend: str) -> bool:
        return self.p_dead.get(backend) is True

    isDeclaredDead = is_declared_dead

    def get_last_error(self):
        return self.p_last_error

    getLastError = get_last_error

    def reshuffle(self) -> None:
        """Decoherence shuffle: move a random preference entry so
        per-client orderings decorrelate over time
        (reference lib/pool.js:501-519)."""
        if len(self.p_keys) <= 1:
            return
        taken = self.p_keys.pop()
        idx = mod_utils.get_rng().randrange(len(self.p_keys) + 1)
        conns = sum(len(v) for v in self.p_connections.values())
        if len(self.p_keys) > conns and idx < conns:
            self.p_log.info('random shuffle puts backend "%s" at idx %d',
                            taken, idx)
        self.p_keys.insert(idx, taken)
        self.rebalance()

    def stop(self) -> None:
        self.emit('stopAsserted')

    # -- rebalancing -----------------------------------------------------

    def rebalance(self, *_a) -> None:
        if len(self.p_keys) < 1:
            return
        if self.is_in_state('stopping') or self.is_in_state('stopped'):
            return
        if self.p_rebal_scheduled is not False:
            return
        self.p_rebal_scheduled = True
        defer(self._rebalance)

    def _rebalance(self) -> None:
        """Compute and apply a plan toward even distribution
        (reference lib/pool.js:544-666)."""
        if self.p_in_rebalance is not False:
            return
        self.p_in_rebalance = True
        self.p_rebal_scheduled = False

        total = 0
        conns: dict[str, list] = {}
        for k in self.p_keys:
            conns[k] = list(self.p_connections.get(k) or [])
            total += len(conns[k])
        spares = len(self.p_idleq) + len(self.p_initq) - \
            len(self.p_waiters)
        if spares < 0:
            spares = 0
        busy = total - spares
        if busy < 0:
            busy = 0
        extras = len(self.p_waiters) - len(self.p_initq)
        if extras < 0:
            extras = 0

        target = busy + extras + self.p_spares

        # Clamp shrinking against the low-pass-filtered recent load
        # (reference lib/pool.js:577-592); the figure comes from the
        # fleet advisory when actuation is enabled (_shrink_floor).
        min_ = math.ceil(self._shrink_floor())
        if target < min_ * 1.05:
            target = min_
            self.p_last_rebal_clamped = True
        else:
            self.p_last_rebal_clamped = False

        if target > self.p_max:
            target = self.p_max

        plan = mod_utils.plan_rebalance(
            conns, self.p_dead, target, self.p_max)

        if plan['remove'] or plan['add']:
            self.p_log.debug(
                'rebalancing pool, remove %d, add %d (busy = %d, '
                'spares = %d, target = %d)', len(plan['remove']),
                len(plan['add']), busy, spares, target)

        now = mod_utils.wall_time()
        rate_delay = None

        for fsm in plan['remove']:
            k = fsm.get_backend()['key']
            lastrate = self.p_lastrate.get(k)
            n = len(self.p_connections.get(k) or []) - 1
            if lastrate:
                tdelta = now - lastrate['time']
                ndelta = n - lastrate['count']
                rate = abs(ndelta / tdelta) if tdelta else math.inf
                if rate > self.p_maxrate:
                    tnext = lastrate['time'] + \
                        abs(ndelta) / self.p_maxrate
                    delay = tnext - now
                    if rate_delay is None or delay < rate_delay:
                        rate_delay = delay
                    continue
            self.p_lastrate[k] = {'time': now, 'count': n}

            fsm.set_unwanted()
            # If it stopped synchronously, don't count it against the cap
            # (reference lib/pool.js:646-653).
            if fsm.is_in_state('stopped') or fsm.is_in_state('failed'):
                total -= 1

        for k in plan['add']:
            lastrate = self.p_lastrate.get(k)
            n = len(self.p_connections.get(k) or []) + 1
            if lastrate:
                tdelta = now - lastrate['time']
                ndelta = n - lastrate['count']
                rate = abs(ndelta / tdelta) if tdelta else math.inf
                if rate > self.p_maxrate:
                    tnext = lastrate['time'] + \
                        abs(ndelta) / self.p_maxrate
                    delay = tnext - now
                    if rate_delay is None or delay < rate_delay:
                        rate_delay = delay
                    continue
            self.p_lastrate[k] = {'time': now, 'count': n}
            total += 1
            if total > self.p_max:
                # Never exceed the socket cap.
                continue
            self.add_connection(k)

        if rate_delay is not None:
            if self.p_rate_delay_timer is not None:
                self.p_rate_delay_timer.cancel()
            self.p_rate_delay_timer = get_loop().call_later(
                (rate_delay * 1000 + 10) / 1000.0, self.rebalance)

        self.p_in_rebalance = False
        self.p_last_rebalance = mod_utils.wall_time()

    def add_connection(self, key: str) -> None:
        """Create a slot for `key` and wire the pool's slot stateChanged
        orchestration (reference lib/pool.js:668-810)."""
        if self.is_in_state('stopping') or self.is_in_state('stopped'):
            return

        backend = self.p_backends[key]
        backend['key'] = key

        fsm = ConnectionSlotFSM({
            'constructor': self.p_constructor,
            'backend': backend,
            'log': self.p_log,
            'pool': self,
            'checker': self.p_checker,
            'checkTimeout': self.p_check_timeout,
            'recovery': self.p_recovery,
            'monitor': self.p_dead.get(key) is True,
        })
        self.p_connections.setdefault(key, []).append(fsm)

        fsm.p_initq_node = self.p_initq.push(fsm)
        fsm.p_idleq_node = None

        def on_changed(new_state):
            # Every slot transition can move the busy count (and so
            # the gathered load sample); one dirty mark covers all the
            # branches below.
            self._telemetry_dirty()
            if fsm.p_initq_node:
                # Still starting up during these transitions.
                if new_state in ('init', 'connecting', 'retrying'):
                    return
                fsm.p_initq_node.remove()
                fsm.p_initq_node = None

            if new_state == 'idle':
                self.emit('connectedToBackend', key, fsm)
                if key in self.p_dead:
                    del self.p_dead[key]
                    self.rebalance()

            if new_state == 'idle' and fsm.is_in_state('idle'):
                # Slot became available: hand to a waiter or queue idle.
                if key not in self.p_backends:
                    fsm.set_unwanted()
                    return

                self.p_last_dequeue = mod_utils.current_millis()
                # Both shed sites share the pacer's mean-tracking
                # threshold: the start is shifted forward by the
                # compensation so the scalar CoDel only sees a claim
                # as over-target once its TRUE sojourn exceeds
                # target + comp (see _pace_comp).
                comp = self._pace_comp() if self.p_codel is not None \
                    else 0.0
                while len(self.p_waiters) > 0:
                    hdl = self.p_waiters.shift()
                    drop = self.p_codel is not None and \
                        self.p_codel.overloaded(hdl.ch_started + comp)
                    if not hdl.is_in_state('waiting'):
                        continue
                    if self.p_codel is not None:
                        # Every resolved waiter (served or dropped)
                        # feeds the pacer's mean-tracking deficit.
                        self._pace_account(
                            self.p_last_dequeue - hdl.ch_started -
                            self.p_codel.cd_targdelay)
                    if drop:
                        tracer = mod_trace._runtime
                        if tracer is not None:
                            tracer.codel_shed(
                                hdl, 'dequeue',
                                self.p_last_dequeue - hdl.ch_started,
                                self.p_codel.cd_targdelay)
                        hdl.timeout()
                        continue
                    if self.p_codel is not None:
                        # Service is live again; waiters may remain
                        # queued behind this one, so resume pacing.
                        self._arm_codel_pacer()
                    if hdl.ch_trace is not None:
                        if self.p_codel is not None:
                            hdl.ch_trace.codel_decision(
                                'served',
                                self.p_last_dequeue - hdl.ch_started,
                                self.p_codel.cd_targdelay)
                        hdl.ch_trace.slot_selected('drain')
                    hdl.try_(fsm)
                    return

                if self.p_codel is not None:
                    self.p_codel.empty()
                    self._pace_reset()

                fsm.p_idleq_node = self.p_idleq.push(fsm)
                return

            # Health-check claims sit on the initq so they don't count
            # as busy (reference lib/pool.js:762-768).
            if new_state == 'busy' and fsm.is_running_ping() and \
                    not fsm.p_initq_node:
                fsm.p_initq_node = self.p_initq.push(fsm)

            if new_state == 'failed':
                # No dead mark if the backend has been removed
                # (regression #144, reference lib/pool.js:771-777), or
                # if a sibling slot is connected to it right now — the
                # backend demonstrably works, and whether its 'idle'
                # lands before or after our 'failed' must not decide
                # the pool's fate (the reference relies on the
                # idle-clears-dead ordering here).
                sibling_up = any(
                    s is not fsm and (s.is_in_state('idle') or
                                      s.is_in_state('busy'))
                    for s in self.p_connections.get(key, ()))
                if key in self.p_backends and not sibling_up:
                    self.p_dead[key] = True
                err = fsm.get_socket_mgr().get_last_error()
                if err is not None:
                    self.p_last_error = err

            if new_state in ('stopped', 'failed'):
                lst = self.p_connections.get(key)
                if lst:
                    assert fsm in lst
                    lst.remove(fsm)
                    if not lst:
                        del self.p_connections[key]
                self.emit('closedBackend', key, fsm)
                self.rebalance()

            if fsm.p_idleq_node:
                # Was idle, now isn't: off the idle queue.
                fsm.p_idleq_node.remove()
                fsm.p_idleq_node = None
                self.rebalance()

        fsm.on('stateChanged', on_changed)
        fsm.start()
        # The initq push above changed the load sample immediately;
        # the slot's first stateChanged only lands next loop turn.
        self._telemetry_dirty()

    addConnection = add_connection

    def print_connections(self) -> dict:
        """Debug dump of per-backend slot states
        (reference lib/pool.js:812-832); returns the structure it
        prints."""
        obj: dict = {'connections': {}, 'dead': dict(self.p_dead)}
        ks = list(self.p_keys)
        for k in self.p_connections.keys():
            if k not in ks:
                ks.append(k)
        for k in ks:
            counts: dict[str, int] = {}
            for fsm in self.p_connections.get(k) or []:
                s = fsm.get_state()
                counts[s] = counts.get(s, 0) + 1
            obj['connections'][k] = counts
        print('live:', obj['connections'])
        print('dead:', obj['dead'])
        return obj

    printConnections = print_connections

    # -- stats -----------------------------------------------------------

    def get_stats(self) -> dict:
        """Counter snapshot + queue gauges (reference lib/pool.js:834-857,
        added for #132)."""
        tconns = sum(len(v) for v in self.p_connections.values())
        return {
            'counters': dict(self.p_counters),
            'totalConnections': tconns,
            'idleConnections': len(self.p_idleq),
            'pendingConnections': len(self.p_initq),
            'waiterCount': len(self.p_waiters),
        }

    getStats = get_stats

    def codel_enabled(self) -> bool:
        """Whether this pool derives claim deadlines from CoDel
        (targetClaimDelay). Such pools reject an explicit claim
        timeout (reference lib/pool.js:874-885); integration layers
        use this to decide whether to forward one."""
        return self.p_codel is not None

    # -- claim -----------------------------------------------------------

    def claim_cb(self, options=None, cb=None):
        """Callback-style claim (reference lib/pool.js:859-969). Returns
        the ClaimHandle (or a cancel-shim for early failures). ``cb`` is
        called with (err) or (None, handle, connection)."""
        if callable(options) and cb is None:
            cb = options
            options = {}
        options = options or {}
        if not callable(cb):
            raise AssertionError('cb must be callable')
        err_on_empty = options.get('errorOnEmpty')

        if self.p_codel is not None:
            if isinstance(options.get('timeout'), (int, float)):
                raise RuntimeError('options.timeout not allowed when '
                                   'targetClaimDelay has been set')
            timeout = self.p_codel.get_max_idle()
        elif isinstance(options.get('timeout'), (int, float)):
            timeout = options['timeout']
        else:
            timeout = math.inf

        self._incr_counter('claim')

        state = {'done': False}
        if self.is_in_state('stopping') or self.is_in_state('stopped'):
            def fail_stopping():
                if not state['done']:
                    cb(mod_errors.PoolStoppingError(self))
                state['done'] = True
            defer(fail_stopping)
            return _CancelShim(state)
        if self.is_in_state('failed'):
            def fail_failed():
                if not state['done']:
                    cb(mod_errors.PoolFailedError(
                        self, self.p_last_error))
                state['done'] = True
            defer(fail_failed)
            return _CancelShim(state)

        e = mod_utils.maybe_capture_stack_trace()

        handle = obtain_claim_handle({
            'pool': self,
            'claimStack': e['stack'],
            'callback': cb,
            'log': self.p_log,
            'claimTimeout': timeout,
        })

        # Tracing off: one module-global load + None check per claim.
        tracer = mod_trace._runtime
        if tracer is not None:
            tracer.claim_begin(handle, self)

        def try_next():
            if not handle.is_in_state('waiting'):
                return

            # Take an idle connection if one is truly idle. Entries may
            # be stale (stateChanged is emitted async); rip them off and
            # move on (reference lib/pool.js:929-951).
            while len(self.p_idleq) > 0:
                fsm = self.p_idleq.shift()
                fsm.p_idleq_node = None
                if not fsm.is_in_state('idle'):
                    continue
                # The idleq shift moved the busy count NOW; the slot's
                # 'busy' stateChanged only lands next loop turn.
                self._telemetry_dirty()
                if handle.ch_trace is not None:
                    handle.ch_trace.slot_selected('idleq')
                handle.try_(fsm)
                return

            if err_on_empty and self.p_resolver.count() < 1:
                handle.fail(mod_errors.NoBackendsError(
                    self, self.p_resolver.get_last_error()))
                return

            handle.ch_waiter_node = self.p_waiters.push(handle)
            self._telemetry_dirty()   # a head sojourn may now exist
            handle.arm_claim_timer()
            self._hwm_counter('max-claim-queue', len(self.p_waiters))
            self._incr_counter('queued-claim')
            self._arm_codel_pacer()
            self.rebalance()

        # First try runs next tick (the reference's deferred
        # stateChanged('waiting') ordering); re-entries to 'waiting'
        # (claim rejected) re-schedule via ch_requeue, and queue-node
        # unlink on resolution lives in the handle's own state entries
        # (_ch_unpark) — no per-claim stateChanged subscription.
        handle.ch_requeue = try_next
        defer(try_next)

        return handle

    async def claim(self, options: dict | None = None):
        """Asyncio-native claim: returns (handle, connection); raises the
        claim error otherwise. Cancelling the awaiting task cancels the
        claim (so the callback contract of the reference's
        waiter.cancel() maps onto task cancellation)."""
        loop = get_loop()
        fut: asyncio.Future = loop.create_future()

        def cb(err, hdl=None, conn=None):
            if fut.cancelled():
                if hdl is not None:
                    hdl.release()
                return
            if err is not None:
                fut.set_exception(err)
            else:
                fut.set_result((hdl, conn))

        waiter = self.claim_cb(options, cb)
        try:
            return await fut
        except asyncio.CancelledError:
            waiter.cancel()
            raise

    # -- batched claim ---------------------------------------------------

    def _claim_retry(self, handle, err_on_empty) -> None:
        """Single-handle requeue for claim_many handles (the exact
        try_next body of claim_cb): runs when a rejected handshake
        re-enters 'waiting'. Re-entries are rare, so the park
        bookkeeping here is per-handle, not batched."""
        if not handle.is_in_state('waiting'):
            return
        while len(self.p_idleq) > 0:
            fsm = self.p_idleq.shift()
            fsm.p_idleq_node = None
            if not fsm.is_in_state('idle'):
                continue
            self._telemetry_dirty()
            if handle.ch_trace is not None:
                handle.ch_trace.slot_selected('idleq')
            handle.try_(fsm)
            return
        if err_on_empty and self.p_resolver.count() < 1:
            handle.fail(mod_errors.NoBackendsError(
                self, self.p_resolver.get_last_error()))
            return
        handle.ch_waiter_node = self.p_waiters.push(handle)
        self._telemetry_dirty()
        handle.arm_claim_timer()
        self._hwm_counter('max-claim-queue', len(self.p_waiters))
        self._incr_counter('queued-claim')
        self._arm_codel_pacer()
        self.rebalance()

    def claim_many_cb(self, n: int, options=None, cb=None):
        """Batched callback claim: mint ``n`` claims with the
        per-claim bookkeeping paid once per batch — one option/timeout
        parse, one pool-state check, one stack capture, one deferred
        dispatch hop, and (for the parked remainder) one telemetry
        flag, one queued-claim counter bump, one pacer nudge, one
        rebalance and one timer-wheel bucket resolution. ``cb`` fires
        once per claim with the single-claim (err) / (None, handle,
        connection) signature; claims that find no idle slot park in
        the wait queue exactly like single claims (FIFO order
        preserved within the batch). Returns the list of ClaimHandles
        (or cancel-shims when the pool is stopping/failed)."""
        if callable(options) and cb is None:
            cb = options
            options = {}
        options = options or {}
        if not callable(cb):
            raise AssertionError('cb must be callable')
        if not isinstance(n, int) or n < 0:
            raise AssertionError('n must be a non-negative integer')
        err_on_empty = options.get('errorOnEmpty')

        if self.p_codel is not None:
            if isinstance(options.get('timeout'), (int, float)):
                raise RuntimeError('options.timeout not allowed when '
                                   'targetClaimDelay has been set')
            timeout = self.p_codel.get_max_idle()
        elif isinstance(options.get('timeout'), (int, float)):
            timeout = options['timeout']
        else:
            timeout = math.inf

        self._incr_counter('claim', n)

        if self.is_in_state('stopping') or self.is_in_state('stopped') \
                or self.is_in_state('failed'):
            failed = self.is_in_state('failed')
            states = [{'done': False} for _ in range(n)]

            def fail_all():
                for st in states:
                    if not st['done']:
                        cb(mod_errors.PoolFailedError(
                            self, self.p_last_error) if failed
                           else mod_errors.PoolStoppingError(self))
                    st['done'] = True
            defer(fail_all)
            return [_CancelShim(st) for st in states]

        e = mod_utils.maybe_capture_stack_trace()
        tracer = mod_trace._runtime
        handles = []
        for _ in range(n):
            handle = obtain_claim_handle({
                'pool': self,
                'claimStack': e['stack'],
                'callback': cb,
                'log': self.p_log,
                'claimTimeout': timeout,
            })
            if tracer is not None:
                tracer.claim_begin(handle, self)
            # Rejection re-entries keep single-claim semantics via the
            # per-handle retry; only the initial dispatch is batched.
            handle.ch_requeue = \
                lambda h=handle: self._claim_retry(h, err_on_empty)
            handles.append(handle)

        def dispatch():
            parked = []
            touched_idle = False
            for handle in handles:
                if not handle.is_in_state('waiting'):
                    continue
                slot = None
                # Stale idleq entries: same rip-and-move-on as
                # claim_cb's try_next (reference lib/pool.js:929-951).
                while len(self.p_idleq) > 0:
                    fsm = self.p_idleq.shift()
                    fsm.p_idleq_node = None
                    if fsm.is_in_state('idle'):
                        slot = fsm
                        break
                if slot is not None:
                    touched_idle = True
                    if handle.ch_trace is not None:
                        handle.ch_trace.slot_selected('idleq')
                    handle.try_(slot)
                    continue
                if err_on_empty and self.p_resolver.count() < 1:
                    handle.fail(mod_errors.NoBackendsError(
                        self, self.p_resolver.get_last_error()))
                    continue
                parked.append(handle)
            if touched_idle:
                # Idleq shifts moved the busy count NOW; one flag
                # covers the whole batch.
                self._telemetry_dirty()
            if parked:
                for handle in parked:
                    handle.ch_waiter_node = self.p_waiters.push(handle)
                arm_claim_timers(parked)
                self._telemetry_dirty()
                self._hwm_counter('max-claim-queue',
                                  len(self.p_waiters))
                self._incr_counter('queued-claim', len(parked))
                self._arm_codel_pacer()
                self.rebalance()

        defer(dispatch)
        return handles

    async def claim_many(self, n: int, options: dict | None = None):
        """Asyncio-native batched claim: returns a list of ``n``
        (handle, connection) pairs once every claim in the batch has
        resolved. If any claim fails, the batch's successful claims
        are released and the first error raised (all-or-nothing, so a
        partial batch can't leak leases). Cancelling the awaiting
        task cancels unresolved claims and releases resolved ones."""
        if n == 0:
            return []
        loop = get_loop()
        fut: asyncio.Future = loop.create_future()
        results: list = []
        state = {'pending': n, 'err': None}

        def cb(err, hdl=None, conn=None):
            if fut.cancelled():
                if hdl is not None:
                    hdl.release()
                return
            if err is not None:
                if state['err'] is None:
                    state['err'] = err
            else:
                results.append((hdl, conn))
            state['pending'] -= 1
            if state['pending'] == 0:
                if state['err'] is not None:
                    for pair in results:
                        pair[0].release()
                    fut.set_exception(state['err'])
                else:
                    fut.set_result(results)

        waiters = self.claim_many_cb(n, options, cb)
        try:
            return await fut
        except asyncio.CancelledError:
            for w in waiters:
                w.cancel()
            raise

    def release_many(self, handles) -> None:
        """Release a batch of claimed handles. Each release's slot
        events defer through the runq pump, so the whole batch drains
        in one pump tick instead of one loop turn apiece."""
        for h in handles:
            h.release()


class _CancelShim:
    """Stands in for a handle when claim() fails fast
    (reference lib/pool.js:889-910)."""

    def __init__(self, state):
        self._state = state

    def cancel(self):
        self._state['done'] = True

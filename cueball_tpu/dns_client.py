"""Minimal DNS wire-protocol client (mname-client replacement).

The reference depends on Joyent's `mname-client` for DNS packet
encode/decode and resolver fan-out (reference lib/resolver.js:24,
385-392, 1210-1377). This is a from-scratch asyncio implementation of
the parts cueball uses:

- query encoding for SRV/AAAA/A lookups
- response parsing with name decompression, answers/authority/additionals
  sections, and the record types the resolver consumes
  (A, AAAA, SRV, SOA, CNAME/DNAME recognition, OPT skipping)
- EDNS(0): queries advertise a 1400 B UDP payload via an OPT
  pseudo-RR (RFC 6891), so fleet-sized SRV answer sets arrive in one
  datagram instead of eating a TC->TCP retry per refresh
- UDP transport with TCP fallback when the TC (truncation) bit is set
- multi-resolver fan-out with per-resolver error collection; when all
  resolvers fail the caller receives a MultiError whose parts carry the
  rcode, enabling the resolver's rcode-voting policy
  (reference lib/resolver.js:1227-1259).

Record objects are plain dicts with keys name/type/ttl/target/port,
matching what the resolver's answer-processing expects.
"""

from __future__ import annotations

import asyncio
import struct

from . import utils as mod_utils

# RR type codes
TYPE_A = 1
TYPE_CNAME = 5
TYPE_SOA = 6
TYPE_AAAA = 28
TYPE_SRV = 33
TYPE_OPT = 41
TYPE_DNAME = 39

TYPE_NAMES = {TYPE_A: 'A', TYPE_CNAME: 'CNAME', TYPE_SOA: 'SOA',
              TYPE_AAAA: 'AAAA', TYPE_SRV: 'SRV', TYPE_OPT: 'OPT',
              TYPE_DNAME: 'DNAME'}
TYPE_CODES = {v: k for k, v in TYPE_NAMES.items()}

RCODES = {0: 'NOERROR', 1: 'FORMERR', 2: 'SERVFAIL', 3: 'NXDOMAIN',
          4: 'NOTIMP', 5: 'REFUSED'}

CLASS_IN = 1


class DnsError(Exception):
    """Non-zero rcode from a nameserver; .code carries the rcode name."""

    def __init__(self, code: str, domain: str, resolver: str | None = None):
        self.code = code
        self.domain = domain
        self.resolver = resolver
        super().__init__('DNS error %s for %s%s' % (
            code, domain, ' from %s' % resolver if resolver else ''))


class DnsTimeoutError(Exception):
    """One resolver timed out. name attr mirrors mname-client's
    TimeoutError identification (reference lib/resolver.js:1235)."""

    name = 'TimeoutError'

    def __init__(self, domain: str, resolver: str | None = None):
        self.domain = domain
        self.resolver = resolver
        super().__init__('DNS timeout for %s%s' % (
            domain, ' from %s' % resolver if resolver else ''))


class MultiError(Exception):
    """All resolvers failed; parts available via errors()
    (verror MultiError analogue)."""

    name = 'MultiError'

    def __init__(self, errs: list):
        self._errs = errs
        super().__init__('all resolvers failed: %s' %
                         '; '.join(str(e) for e in errs))

    def errors(self) -> list:
        return list(self._errs)


# ---------------------------------------------------------------------------
# Wire encoding / decoding

def encode_name(name: str) -> bytes:
    out = b''
    for label in name.rstrip('.').split('.'):
        raw = label.encode('idna') if not label.isascii() else \
            label.encode()
        if len(raw) > 63:
            raise ValueError('DNS label too long: %r' % label)
        out += bytes([len(raw)]) + raw
    return out + b'\x00'


# EDNS(0) advertised UDP payload size (RFC 6891). The plain-DNS 512 B
# ceiling truncates the SRV answer set of any real fleet (~18 records)
# and costs a TCP retry per refresh; 1400 keeps the datagram under
# common path MTUs while fitting ~60 SRV records.
EDNS_UDP_SIZE = 1400


def build_query(qid: int, domain: str, qtype: str,
                edns_size: int | None = EDNS_UDP_SIZE) -> bytes:
    flags = 0x0100  # RD
    arcount = 0 if edns_size is None else 1
    header = struct.pack('>HHHHHH', qid, flags, 1, 0, 0, arcount)
    question = encode_name(domain) + struct.pack(
        '>HH', TYPE_CODES[qtype], CLASS_IN)
    if edns_size is None:
        return header + question
    # OPT pseudo-RR (RFC 6891 6.1.2): root name, TYPE=OPT, CLASS
    # carries the advertised UDP payload size, TTL carries extended
    # rcode/version/flags (all zero: EDNS version 0, no DO), no rdata.
    opt = b'\x00' + struct.pack('>HHIH', TYPE_OPT, edns_size, 0, 0)
    return header + question + opt


def _decode_name(data: bytes, off: int) -> tuple[str, int]:
    labels = []
    jumped = False
    end = off
    seen = set()
    while True:
        if off >= len(data):
            raise ValueError('truncated name')
        ln = data[off]
        if ln & 0xC0 == 0xC0:
            ptr = struct.unpack('>H', data[off:off + 2])[0] & 0x3FFF
            if not jumped:
                end = off + 2
                jumped = True
            if ptr in seen:
                raise ValueError('name compression loop')
            seen.add(ptr)
            off = ptr
            continue
        off += 1
        if ln == 0:
            break
        labels.append(data[off:off + ln].decode('ascii', 'replace'))
        off += ln
    if not jumped:
        end = off
    return '.'.join(labels), end


def _parse_rr(data: bytes, off: int) -> tuple[dict, int]:
    name, off = _decode_name(data, off)
    rtype, rclass, ttl, rdlen = struct.unpack(
        '>HHIH', data[off:off + 10])
    off += 10
    rdata = data[off:off + rdlen]
    rdstart = off
    off += rdlen

    rr = {'name': name, 'type': TYPE_NAMES.get(rtype, rtype),
          'ttl': ttl, 'target': None, 'port': None}
    if rtype == TYPE_A and rdlen == 4:
        rr['target'] = '.'.join(str(b) for b in rdata)
    elif rtype == TYPE_AAAA and rdlen == 16:
        import ipaddress
        rr['target'] = str(ipaddress.IPv6Address(rdata))
    elif rtype == TYPE_SRV:
        prio, weight, port = struct.unpack('>HHH', rdata[:6])
        tgt, _ = _decode_name(data, rdstart + 6)
        rr.update({'priority': prio, 'weight': weight, 'port': port,
                   'target': tgt})
    elif rtype in (TYPE_CNAME, TYPE_DNAME):
        tgt, _ = _decode_name(data, rdstart)
        rr['target'] = tgt
    elif rtype == TYPE_SOA:
        mname, noff = _decode_name(data, rdstart)
        rname, noff = _decode_name(data, noff)
        serial, refresh, retry, expire, minimum = struct.unpack(
            '>IIIII', data[noff:noff + 20])
        rr.update({'mname': mname, 'minimum': minimum})
    return rr, off


class DnsMessage:
    """Parsed response; mirrors the mname-client message interface the
    resolver consumes (getAnswers/getAuthority/getAdditionals)."""

    def __init__(self, qid: int, rcode: str, tc: bool,
                 answers: list, authority: list, additionals: list):
        self.qid = qid
        self.rcode = rcode
        self.tc = tc
        self._answers = answers
        self._authority = authority
        self._additionals = additionals

    def get_answers(self) -> list:
        return self._answers

    getAnswers = get_answers

    def get_authority(self) -> list:
        return self._authority

    getAuthority = get_authority

    def get_additionals(self) -> list:
        return self._additionals

    getAdditionals = get_additionals


def parse_response(data: bytes) -> DnsMessage:
    qid, flags, qd, an, ns, ar = struct.unpack('>HHHHHH', data[:12])
    rcode = RCODES.get(flags & 0xF, 'RCODE%d' % (flags & 0xF))
    tc = bool(flags & 0x0200)
    off = 12
    for _ in range(qd):
        _, off = _decode_name(data, off)
        off += 4
    sections = []
    for count in (an, ns, ar):
        rrs = []
        for _ in range(count):
            rr, off = _parse_rr(data, off)
            rrs.append(rr)
        sections.append(rrs)
    return DnsMessage(qid, rcode, tc, *sections)


# ---------------------------------------------------------------------------
# Sans-io query core

class DnsQueryCore:
    """The pure per-resolver query state machine, no loop and no
    sockets: callers move the bytes, the core decides what they mean.

    Protocol::

        core = DnsQueryCore(domain, qtype)
        verb, payload = core.begin()          # ('udp', query bytes)
        while verb != 'done':
            data = <exchange payload via verb>
            verb, payload = core.on_response(data)
        msg = payload                         # parsed DnsMessage

    Decisions encoded (formerly inlined in ``_query_wire``):

    - FORMERR/NOTIMP on the FIRST (EDNS) response only -> retry once
      as a plain RFC 1035 query with a fresh qid (RFC 6891 6.2.2). A
      genuine FORMERR on the plain retry propagates as DnsError.
    - TC bit on either UDP response -> replay the current payload over
      TCP.
    - Any other non-NOERROR rcode -> DnsError.
    - Malformed bytes -> struct.error/ValueError propagate from
      ``parse_response``; timeout policy belongs to the driver.
    """

    def __init__(self, domain: str, qtype: str, rng=None,
                 resolver: str | None = None):
        self.domain = domain
        self.qtype = qtype
        self.resolver = resolver
        self._rng = rng if rng is not None else mod_utils.get_rng()
        # States: 'udp-edns' (first try, OPT attached) -> 'udp-plain'
        # (EDNS fallback) -> 'tcp' (truncation replay). The fallback
        # edge only exists from 'udp-edns'.
        self._state = 'udp-edns'
        self._payload = build_query(
            self._rng.randrange(65536), domain, qtype)

    def begin(self) -> tuple:
        return ('udp', self._payload)

    def on_response(self, data: bytes) -> tuple:
        msg = parse_response(data)
        if self._state == 'udp-edns' and \
                msg.rcode in ('FORMERR', 'NOTIMP'):
            self._state = 'udp-plain'
            self._payload = build_query(
                self._rng.randrange(65536), self.domain, self.qtype,
                edns_size=None)
            return ('udp', self._payload)
        if self._state != 'tcp' and msg.tc:
            self._state = 'tcp'
            return ('tcp', self._payload)
        if msg.rcode != 'NOERROR':
            raise DnsError(msg.rcode, self.domain, self.resolver)
        return ('done', msg)


# ---------------------------------------------------------------------------
# Transport

async def query_udp(resolver: str, port: int, payload: bytes,
                    timeout_s: float) -> bytes:
    from . import transport as mod_transport
    return await mod_transport.get_transport().dns_udp(
        resolver, port, payload, timeout_s)


async def query_tcp(resolver: str, port: int, payload: bytes,
                    timeout_s: float) -> bytes:
    from . import transport as mod_transport
    return await mod_transport.get_transport().dns_tcp(
        resolver, port, payload, timeout_s)


class DnsTransport:
    """Wire-transport seam: how raw query bytes reach a resolver and
    how raw response bytes come back. The default sends real datagrams
    and TCP streams on the running asyncio loop; netsim's SimWire
    (cueball_tpu/netsim/dns.py) substitutes a scripted middlebox so the
    full _query_wire state machine — EDNS fallback, TC->TCP retry,
    truncation errors, deadline sharing — runs against hostile answers
    without a socket (ROADMAP item 5's first consumer)."""

    async def udp(self, resolver: str, port: int, payload: bytes,
                  timeout_s: float) -> bytes:
        return await query_udp(resolver, port, payload, timeout_s)

    async def tcp(self, resolver: str, port: int, payload: bytes,
                  timeout_s: float) -> bytes:
        return await query_tcp(resolver, port, payload, timeout_s)


class DnsClient:
    """Resolver fan-out client (mname-client DnsClient equivalent).

    lookup(opts, cb): opts = {domain, type, timeout (ms), resolvers,
    errorThreshold?}; cb(err, msg). Tries resolvers in a randomized
    order, UDP first with TCP fallback on truncation; stops at the first
    clean answer. errorThreshold caps how many resolvers are tried
    (used by bootstrap resolvers, reference lib/resolver.js:1216-1219).
    """

    def __init__(self, concurrency: int = 3,
                 transport: DnsTransport | None = None):
        self.concurrency = max(1, concurrency)
        self.transport = transport or DnsTransport()

    def lookup(self, opts: dict, cb) -> None:
        # Fire-and-forget by design: _lookup is the reference's
        # callback-style contract (mname-client lookup(opts, cb)) —
        # every outcome, including exceptions, is delivered through
        # cb(err, result), so no task reference is kept.
        asyncio.ensure_future(self._lookup(opts, cb))  # cbflow: ignore=A004

    async def _query_one(self, resolver: str, domain: str, qtype: str,
                         timeout_s: float, trace=None) -> DnsMessage:
        """One resolver's attempt; when a DnsTrace rides along in
        opts['trace'], the whole attempt (UDP, EDNS fallback, TC->TCP)
        becomes one 'dns_query' span with its outcome."""
        if trace is None:
            return await self._query_wire(resolver, domain, qtype,
                                          timeout_s)
        span = trace.query_begin(resolver)
        try:
            msg = await self._query_wire(resolver, domain, qtype,
                                         timeout_s)
        except BaseException as err:
            trace.query_end(span, type(err).__name__)
            raise
        trace.query_end(span, 'ok')
        return msg

    async def _query_wire(self, resolver: str, domain: str, qtype: str,
                          timeout_s: float) -> DnsMessage:
        host, _, portstr = resolver.partition('@')
        port = int(portstr) if portstr else 53
        core = DnsQueryCore(domain, qtype, resolver=resolver)
        # One DEADLINE for this resolver's whole attempt: the EDNS
        # fallback and the TC->TCP retry each consume what remains,
        # never a fresh slice — otherwise one resolver could stretch
        # to 3x its budget and stall failover to the next wave. Read
        # through the clock seam so netsim's virtual clock (which also
        # backs the loop's own time()) drives the budget.
        clk = mod_utils.get_clock()
        deadline = clk.monotonic() + timeout_s

        def left() -> float:
            return max(deadline - clk.monotonic(), 0.001)
        verb, payload = core.begin()
        try:
            while verb != 'done':
                if verb == 'udp':
                    data = await self.transport.udp(host, port,
                                                    payload, left())
                else:
                    data = await self.transport.tcp(host, port,
                                                    payload, left())
                verb, payload = core.on_response(data)
        except (asyncio.TimeoutError, TimeoutError):
            raise DnsTimeoutError(domain, resolver)
        except struct.error as e:
            # Malformed packet; surface as a parse error rather than
            # letting it kill the lookup task.
            raise ValueError('malformed DNS response from %s: %s' % (
                resolver, e))
        return payload

    async def _lookup(self, opts: dict, cb) -> None:
        domain = opts['domain']
        qtype = opts['type']
        timeout_ms = opts.get('timeout') or 5000
        resolvers = list(opts.get('resolvers') or [])
        if not resolvers:
            cb(MultiError([DnsError('SERVFAIL', domain)]), None)
            return
        threshold = opts.get('errorThreshold') or len(resolvers)
        trace = opts.get('trace')

        mod_utils.get_rng().shuffle(resolvers)
        resolvers = resolvers[:threshold]
        errs: list[Exception] = []

        # Bounded parallel fan-out: up to `concurrency` resolvers are
        # queried at once; the first clean answer wins and the rest are
        # cancelled (mname-client's concurrency semantics).
        waves = [resolvers[i:i + self.concurrency]
                 for i in range(0, len(resolvers), self.concurrency)]
        per_wave_s = (timeout_ms / 1000.0) / len(waves)

        try:
            for wave in waves:
                tasks = [
                    asyncio.ensure_future(self._query_one(
                        r, domain, qtype, per_wave_s, trace=trace))
                    for r in wave]
                try:
                    pending = set(tasks)
                    while pending:
                        done, pending = await asyncio.wait(
                            pending,
                            return_when=asyncio.FIRST_COMPLETED)
                        for task in done:
                            try:
                                msg = task.result()
                            except asyncio.CancelledError:
                                continue
                            except Exception as e:
                                errs.append(e)
                                continue
                            cb(None, msg)
                            return
                finally:
                    for task in tasks:
                        if not task.done():
                            task.cancel()

            if len(errs) == 1:
                cb(errs[0], None)
            else:
                cb(MultiError(errs), None)
        except Exception as e:  # defense: the callback must always fire
            cb(e, None)

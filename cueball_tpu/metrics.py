"""Prometheus-style metric collector (artedi equivalent).

The reference depends on Joyent's `artedi` for its error-event counter
(reference lib/utils.js:24,395-444; README.adoc:113,137 documents sharing a
collector across pools/agents). This is a minimal compatible rebuild:
label-keyed counters/gauges/histograms with a text-format serializer
(exposition format v0.0.4: label values escaped, no braces on empty
label sets, histograms as cumulative `_bucket{le=...}` + `_sum`/`_count`).
"""

from __future__ import annotations

import threading


def _label_key(labels: dict | None) -> tuple:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def _escape_label_value(value) -> str:
    """Escape a label value per the text-format spec: backslash, double
    quote and newline must be backslash-escaped or they corrupt the whole
    payload (a raw '"' ends the value early; a raw newline ends the
    sample line)."""
    return (str(value)
            .replace('\\', '\\\\')
            .replace('"', '\\"')
            .replace('\n', '\\n'))


def _unescape_label_value(value: str) -> str:
    """Invert :func:`_escape_label_value` (a left-to-right scan — naive
    chained .replace() corrupts a trailing backslash followed by 'n')."""
    out = []
    i, n = 0, len(value)
    while i < n:
        c = value[i]
        if c == '\\' and i + 1 < n:
            nxt = value[i + 1]
            if nxt == 'n':
                out.append('\n')
                i += 2
                continue
            if nxt in ('\\', '"'):
                out.append(nxt)
                i += 2
                continue
        out.append(c)
        i += 1
    return ''.join(out)


def _format_sample(name: str, key: tuple, value: float) -> str:
    """One exposition line. Empty label sets render with no braces at
    all ('name value', not 'name{} value')."""
    if not key:
        return '%s %g' % (name, value)
    lbl = ','.join('%s="%s"' % (k, _escape_label_value(val))
                   for k, val in key)
    return '%s{%s} %g' % (name, lbl, value)


class Counter:
    metric_type = 'counter'

    def __init__(self, name: str, help: str = '',
                 static_labels: dict | None = None):
        self.name = name
        self.help = help
        self._static = dict(static_labels or {})
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def _merged_key(self, labels: dict | None) -> tuple:
        merged = dict(self._static)
        merged.update(labels or {})
        return _label_key(merged)

    def increment(self, labels: dict | None = None, value: float = 1) -> None:
        key = self._merged_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + value

    add = increment

    def value(self, labels: dict | None = None) -> float:
        return self._values.get(self._merged_key(labels), 0)

    def total(self) -> float:
        return sum(self._values.values())

    def remove(self, labels: dict | None = None) -> None:
        """Drop one labeled sample row (e.g. a gauge for a pool that has
        been stopped); a no-op when the row never existed."""
        with self._lock:
            self._values.pop(self._merged_key(labels), None)

    def serialize(self) -> str:
        out = ['# HELP %s %s' % (self.name, self.help),
               '# TYPE %s %s' % (self.name, self.metric_type)]
        for key, v in sorted(self._values.items()):
            out.append(_format_sample(self.name, key, v))
        return '\n'.join(out) + '\n'


class Gauge(Counter):
    metric_type = 'gauge'

    def set(self, value: float, labels: dict | None = None) -> None:
        key = self._merged_key(labels)
        with self._lock:
            self._values[key] = value


# Milliseconds-oriented default buckets: the claim path operates between
# sub-millisecond (hot cycle) and tens of seconds (connect timeouts).
DEFAULT_BUCKETS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                   1000, 2500, 5000, 10000)


class Histogram:
    """Cumulative histogram (fixed buckets, upper-bound inclusive).

    Serialized per the text format as `name_bucket{le="..."}` rows (the
    `le="+Inf"` bucket always equals `name_count`), plus `name_sum` and
    `name_count`."""

    metric_type = 'histogram'

    def __init__(self, name: str, help: str = '',
                 static_labels: dict | None = None,
                 buckets: tuple | None = None):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._static = dict(static_labels or {})
        # label key -> [counts per bucket + inf, sum, count]
        self._series: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def _merged_key(self, labels: dict | None) -> tuple:
        merged = dict(self._static)
        merged.update(labels or {})
        return _label_key(merged)

    def observe(self, value: float, labels: dict | None = None) -> None:
        key = self._merged_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = series
            counts, _, _ = series
            for i, le in enumerate(self.buckets):
                if value <= le:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            series[1] += value
            series[2] += 1

    def count(self, labels: dict | None = None) -> int:
        series = self._series.get(self._merged_key(labels))
        return series[2] if series is not None else 0

    def sum(self, labels: dict | None = None) -> float:
        series = self._series.get(self._merged_key(labels))
        return series[1] if series is not None else 0.0

    def remove(self, labels: dict | None = None) -> None:
        with self._lock:
            self._series.pop(self._merged_key(labels), None)

    def serialize(self) -> str:
        out = ['# HELP %s %s' % (self.name, self.help),
               '# TYPE %s %s' % (self.name, self.metric_type)]
        for key, series in sorted(self._series.items()):
            counts, total, n = series
            cum = 0
            for i, le in enumerate(self.buckets):
                cum += counts[i]
                bkey = key + (('le', '%g' % le),)
                out.append(_format_sample(self.name + '_bucket', bkey, cum))
            bkey = key + (('le', '+Inf'),)
            out.append(_format_sample(self.name + '_bucket', bkey, n))
            out.append(_format_sample(self.name + '_sum', key, total))
            out.append(_format_sample(self.name + '_count', key, n))
        return '\n'.join(out) + '\n'


class Collector:
    """Registry of named metrics; declarations are idempotent (the
    reference relies on this when an agent-created collector is passed
    down into pools, lib/utils.js:405-416) but re-declaring a name as a
    different metric type raises TypeError."""

    def __init__(self, labels: dict | None = None):
        self._labels = dict(labels or {})
        self._metrics: dict[str, Counter | Histogram] = {}
        self._hooks: tuple = ()
        self._lock = threading.Lock()

    def _declare(self, name: str, help: str, metric_type: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif m.metric_type != metric_type:
                raise TypeError(
                    'metric %r already registered as a %s, not a %s' %
                    (name, m.metric_type, metric_type))
            return m

    def counter(self, name: str, help: str = '') -> Counter:
        return self._declare(
            name, help, 'counter',
            lambda: Counter(name, help, self._labels))

    def gauge(self, name: str, help: str = '') -> Gauge:
        return self._declare(
            name, help, 'gauge',
            lambda: Gauge(name, help, self._labels))

    def histogram(self, name: str, help: str = '',
                  buckets: tuple | None = None) -> Histogram:
        return self._declare(
            name, help, 'histogram',
            lambda: Histogram(name, help, self._labels, buckets))

    def get_collector(self, name: str) -> Counter | Histogram:
        return self._metrics[name]

    getCollector = get_collector

    def add_collect_hook(self, fn) -> None:
        """Register fn() to run at the top of collect(): lets gauges be
        refreshed lazily at scrape time instead of on every pool event."""
        self._hooks = self._hooks + (fn,)

    def remove_collect_hook(self, fn) -> None:
        self._hooks = tuple(h for h in self._hooks if h is not fn)

    def collect(self) -> str:
        """Serialize all metrics in Prometheus text format."""
        for fn in self._hooks:
            fn()
        return ''.join(m.serialize() for m in self._metrics.values())


def create_collector(labels: dict | None = None) -> Collector:
    return Collector(labels)


def merge_expositions(texts) -> str:
    """Merge several exposition-format payloads into one.

    The spawn shard backend gives every child process its own
    collector; a fleet-wide /metrics scrape gathers each child's
    ``collect()`` text and merges here. Sample lines concatenate
    grouped under one ``# HELP``/``# TYPE`` header pair per metric
    family (repeating a family header mid-payload is a spec
    violation); the first payload to declare a family wins its header.
    Gauge/counter sample rows are kept verbatim and in arrival order —
    children are expected to disambiguate with a ``shard`` label,
    exactly like the thread backend's shard-labelled gauges on a shared
    collector. Histogram families instead FOLD: identical series
    (same name + label set, including ``le``) sum across payloads, so
    the merged cumulative buckets, ``_sum`` and ``_count`` describe the
    fleet-wide distribution — children's per-phase claim histograms
    carry no shard label on purpose, and verbatim concatenation would
    emit duplicate series (a spec violation Prometheus resolves by
    keeping only one child's data).
    """
    families: dict[str, dict] = {}
    order: list[str] = []
    for text in texts:
        if not text:
            continue
        current = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith('# HELP ') or line.startswith('# TYPE '):
                _, kind, name_rest = line.split(' ', 2)
                name, _, rest = name_rest.partition(' ')
                fam = families.get(name)
                if fam is None:
                    fam = {'help': None, 'type': None, 'samples': []}
                    families[name] = fam
                    order.append(name)
                if kind == 'HELP' and fam['help'] is None:
                    fam['help'] = rest
                elif kind == 'TYPE' and fam['type'] is None:
                    fam['type'] = rest
                current = name
                continue
            if line.lstrip().startswith('#'):
                # Any other comment (including a bare '# HELP'): not a
                # family header, not a sample — never let it masquerade
                # as a metric family named '#'.
                continue
            # A sample line; histogram rows (name_bucket/_sum/_count)
            # belong to the family whose headers precede them.
            if current is None:
                name = line.split('{', 1)[0].split(' ', 1)[0]
                fam = families.setdefault(
                    name, {'help': None, 'type': None, 'samples': []})
                if name not in order:
                    order.append(name)
                fam['samples'].append(line)
            else:
                families[current]['samples'].append(line)
    out = []
    for name in order:
        fam = families[name]
        if fam['help'] is not None:
            # rstrip keeps an empty help string from leaving a
            # trailing space on the header line.
            out.append(('# HELP %s %s' % (name, fam['help'])).rstrip())
        if fam['type'] is not None:
            out.append('# TYPE %s %s' % (name, fam['type']))
        if fam['type'] == 'histogram':
            out.extend(_fold_histogram_samples(fam['samples']))
        else:
            out.extend(fam['samples'])
    return '\n'.join(out) + '\n' if out else ''


def _fold_histogram_samples(lines) -> list:
    """Sum same-series histogram rows (identical name + label string,
    so cumulative ``_bucket`` rows fold per ``le`` and ``_sum`` /
    ``_count`` fold per label set). Our serializer emits labels in
    sorted key order, so the label string is a stable series key.
    First-seen series order is preserved and values re-format with the
    serializer's %g, which keeps the merge idempotent. Rows whose
    value doesn't parse pass through verbatim at the end."""
    totals: dict[str, float] = {}
    order: list[str] = []
    passthrough: list[str] = []
    for line in lines:
        series, _, value = line.rpartition(' ')
        try:
            val = float(value)
        except ValueError:
            passthrough.append(line)
            continue
        if series not in totals:
            totals[series] = 0.0
            order.append(series)
        totals[series] += val
    out = ['%s %g' % (series, totals[series]) for series in order]
    out.extend(passthrough)
    return out

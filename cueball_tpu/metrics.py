"""Prometheus-style metric collector (artedi equivalent).

The reference depends on Joyent's `artedi` for its error-event counter
(reference lib/utils.js:24,395-444; README.adoc:113,137 documents sharing a
collector across pools/agents). This is a minimal compatible rebuild:
label-keyed counters/gauges/histograms with a text-format serializer.
"""

from __future__ import annotations

import threading


def _label_key(labels: dict | None) -> tuple:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


class Counter:
    metric_type = 'counter'

    def __init__(self, name: str, help: str = '',
                 static_labels: dict | None = None):
        self.name = name
        self.help = help
        self._static = dict(static_labels or {})
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def increment(self, labels: dict | None = None, value: float = 1) -> None:
        merged = dict(self._static)
        merged.update(labels or {})
        key = _label_key(merged)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + value

    add = increment

    def value(self, labels: dict | None = None) -> float:
        merged = dict(self._static)
        merged.update(labels or {})
        return self._values.get(_label_key(merged), 0)

    def total(self) -> float:
        return sum(self._values.values())

    def serialize(self) -> str:
        out = ['# HELP %s %s' % (self.name, self.help),
               '# TYPE %s %s' % (self.name, self.metric_type)]
        for key, v in sorted(self._values.items()):
            lbl = ','.join('%s="%s"' % (k, val) for k, val in key)
            out.append('%s{%s} %g' % (self.name, lbl, v))
        return '\n'.join(out) + '\n'


class Gauge(Counter):
    metric_type = 'gauge'

    def set(self, value: float, labels: dict | None = None) -> None:
        merged = dict(self._static)
        merged.update(labels or {})
        with self._lock:
            self._values[_label_key(merged)] = value


class Collector:
    """Registry of named metrics; counter() declarations are idempotent
    (the reference relies on this when an agent-created collector is passed
    down into pools, lib/utils.js:405-416)."""

    def __init__(self, labels: dict | None = None):
        self._labels = dict(labels or {})
        self._metrics: dict[str, Counter] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = '') -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help, self._labels)
                self._metrics[name] = m
            return m

    def gauge(self, name: str, help: str = '') -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help, self._labels)
                self._metrics[name] = m
            assert isinstance(m, Gauge)
            return m

    def get_collector(self, name: str) -> Counter:
        return self._metrics[name]

    getCollector = get_collector

    def collect(self) -> str:
        """Serialize all metrics in Prometheus text format."""
        return ''.join(m.serialize() for m in self._metrics.values())


def create_collector(labels: dict | None = None) -> Collector:
    return Collector(labels)

"""Single-pump engine run queue (setImmediate-phase analogue).

Every deferral the FSM engine issues — gated ``S.immediate``
callbacks, deferred ``stateChanged`` emissions, the claim path's
``try_next``/requeue hops, the cset stopping drain — used to be its
own ``loop.call_soon``, and each one paid a full asyncio ``Handle`` +
contextvars ``Context.run`` round trip (~13% of a claim/release cycle,
docs/claim-path-profile.md round 5). ``defer()`` instead pushes one
entry onto a per-loop FIFO and schedules at most ONE pump callback per
loop tick to drain it: N deferrals per tick cost one Handle/Context,
the way node batches the whole ``setImmediate`` phase for the
reference.

Ordering contract (the iteration-boundary semantics of node's
setImmediate phase):

- entries drain in push order — engine deferrals stay FIFO among
  themselves, and against plain user ``call_soon`` callbacks the burst
  occupies the loop slot of its FIRST deferral (a user callback
  scheduled before the burst runs before it, one scheduled after the
  burst runs after it; a callback scheduled mid-burst observes the
  batch as one unit, exactly node's setImmediate-phase behaviour);
- the drain only delivers the entries present when it starts: pushes
  made DURING a drain open a fresh batch drained by a new pump on the
  NEXT loop iteration, never the same drain — same-tick execution
  would collapse the reference's two-loop-tick claim cycle
  (lib/pool.js:859-969 semantics);
- a raising entry is routed to ``loop.call_exception_handler`` and the
  rest of the batch still drains, matching how an exception in an
  individual ``call_soon`` callback behaves.

``set_pump_enabled(False)`` (or ``CUEBALL_NO_PUMP=1`` at import)
drops back to the reference's literal scheduling — one ``call_soon``
per deferral, including each deferred ``stateChanged`` emission —
which is what the interleaved off/on/off bench A/B (bench.py
``bench_pump_ab``) measures against. Engine-deferral ordering is
identical in both modes (the conformance suite pins a byte-identical
pool transition trace across them); only the scheduling cost
changes.

The native engine implements the same queue in C
(native/emitter.c pump machinery) and pushes its deferred
``stateChanged`` emissions into it, so both engines share one pump
and one FIFO.
"""

import asyncio
import os

from .events import _native

__all__ = ['defer', 'pump_enabled', 'set_pump_enabled']


if _native is not None:
    defer = _native.pump_defer
    _set_pump_enabled = _native.pump_set_enabled
    _pump_enabled = _native.pump_enabled

    def set_pump_enabled(flag):
        """Enable/disable pump coalescing; returns the previous
        setting (for try/finally restoration in benches and tests)."""
        return _set_pump_enabled(bool(flag))

    def pump_enabled():
        return _pump_enabled()
else:
    _pending = {}  # loop -> list of (cb, *args) entry tuples
    _enabled = True

    def _pump(loop):
        entries = _pending.pop(loop, None)
        if entries is None:
            return
        for entry in entries:
            try:
                entry[0](*entry[1:])
            except (SystemExit, KeyboardInterrupt):
                raise
            except BaseException as exc:
                loop.call_exception_handler({
                    'message': 'cueball runq deferral',
                    'exception': exc,
                })

    def defer(cb, *args):
        """Schedule ``cb(*args)`` for the next loop iteration on the
        shared engine pump (plain ``call_soon`` when the pump is
        disabled). Requires a running event loop, like call_soon."""
        loop = asyncio.get_running_loop()
        if not _enabled:
            loop.call_soon(cb, *args)
            return
        batch = _pending.get(loop)
        if batch is not None:
            # Pump already scheduled for this loop's current tick.
            batch.append((cb,) + args)
            return
        if _pending:
            # Batches stranded on loops that closed before draining
            # died with their loop (like undelivered call_soon
            # handles); prune so they can't accumulate across
            # asyncio.run() calls.
            for stale in [ln for ln in _pending if ln.is_closed()]:
                del _pending[stale]
        _pending[loop] = [(cb,) + args]
        loop.call_soon(_pump, loop)

    def set_pump_enabled(flag):
        """Enable/disable pump coalescing; returns the previous
        setting (for try/finally restoration in benches and tests)."""
        global _enabled
        old = _enabled
        _enabled = bool(flag)
        return old

    def pump_enabled():
        return _enabled


if os.environ.get('CUEBALL_NO_PUMP'):
    set_pump_enabled(False)

"""Single-pump engine run queue (setImmediate-phase analogue).

Every deferral the FSM engine issues — gated ``S.immediate``
callbacks, deferred ``stateChanged`` emissions, the claim path's
``try_next``/requeue hops, the cset stopping drain — used to be its
own ``loop.call_soon``, and each one paid a full asyncio ``Handle`` +
contextvars ``Context.run`` round trip (~13% of a claim/release cycle,
docs/claim-path-profile.md round 5). ``defer()`` instead pushes one
entry onto a per-loop FIFO and schedules at most ONE pump callback per
loop tick to drain it: N deferrals per tick cost one Handle/Context,
the way node batches the whole ``setImmediate`` phase for the
reference.

Ordering contract (the iteration-boundary semantics of node's
setImmediate phase):

- entries drain in push order — engine deferrals stay FIFO among
  themselves, and against plain user ``call_soon`` callbacks the burst
  occupies the loop slot of its FIRST deferral (a user callback
  scheduled before the burst runs before it, one scheduled after the
  burst runs after it; a callback scheduled mid-burst observes the
  batch as one unit, exactly node's setImmediate-phase behaviour);
- the drain only delivers the entries present when it starts: pushes
  made DURING a drain open a fresh batch drained by a new pump on the
  NEXT loop iteration, never the same drain — same-tick execution
  would collapse the reference's two-loop-tick claim cycle
  (lib/pool.js:859-969 semantics);
- a raising entry is routed to ``loop.call_exception_handler`` and the
  rest of the batch still drains, matching how an exception in an
  individual ``call_soon`` callback behaves.

``set_pump_enabled(False)`` (or ``CUEBALL_NO_PUMP=1`` at import)
drops back to the reference's literal scheduling — one ``call_soon``
per deferral, including each deferred ``stateChanged`` emission —
which is what the interleaved off/on/off bench A/B (bench.py
``bench_pump_ab``) measures against. Engine-deferral ordering is
identical in both modes (the conformance suite pins a byte-identical
pool transition trace across them); only the scheduling cost
changes.

The native engine implements the same queue in C
(native/emitter.c pump machinery) and pushes its deferred
``stateChanged`` emissions into it, so both engines share one pump
and one FIFO.
"""

import asyncio
import os

from . import utils as mod_utils
from .events import _native

__all__ = ['defer', 'pump_enabled', 'set_pump_enabled', 'pump_depth',
           'wheel_arm', 'wheel_arm_many', 'wheel_cancel', 'wheel_depth',
           'WHEEL_QUANTUM_MS']

# Bound to cueball_tpu.profile while its sampler runs, so SIGPROF
# samples landing mid-pump attribute to the runq_pump phase (the
# native engine's pump marks the phase in C; this seam covers the
# pure fallback).
_prof = None


if _native is not None:
    defer = _native.pump_defer
    _set_pump_enabled = _native.pump_set_enabled
    _pump_enabled = _native.pump_enabled

    def set_pump_enabled(flag):
        """Enable/disable pump coalescing; returns the previous
        setting (for try/finally restoration in benches and tests)."""
        return _set_pump_enabled(bool(flag))

    def pump_enabled():
        return _pump_enabled()

    pump_depth = _native.pump_depth
else:
    _pending = {}  # loop -> list of (cb, *args) entry tuples
    _enabled = True

    def _pump(loop):
        entries = _pending.pop(loop, None)
        if entries is None:
            return
        prof = _prof
        tok = prof.push_phase('runq_pump') if prof is not None else None
        try:
            for entry in entries:
                try:
                    entry[0](*entry[1:])
                except (SystemExit, KeyboardInterrupt):
                    raise
                except BaseException as exc:
                    loop.call_exception_handler({
                        'message': 'cueball runq deferral',
                        'exception': exc,
                    })
        finally:
            if prof is not None:
                prof.pop_phase(tok)

    def defer(cb, *args):
        """Schedule ``cb(*args)`` for the next loop iteration on the
        shared engine pump (plain ``call_soon`` when the pump is
        disabled). Requires a running event loop, like call_soon."""
        loop = asyncio.get_running_loop()
        if not _enabled:
            loop.call_soon(cb, *args)
            return
        batch = _pending.get(loop)
        if batch is not None:
            # Pump already scheduled for this loop's current tick.
            batch.append((cb,) + args)
            return
        if _pending:
            # Batches stranded on loops that closed before draining
            # died with their loop (like undelivered call_soon
            # handles); prune so they can't accumulate across
            # asyncio.run() calls.
            for stale in [ln for ln in _pending if ln.is_closed()]:
                del _pending[stale]
        _pending[loop] = [(cb,) + args]
        loop.call_soon(_pump, loop)

    def set_pump_enabled(flag):
        """Enable/disable pump coalescing; returns the previous
        setting (for try/finally restoration in benches and tests)."""
        global _enabled
        old = _enabled
        _enabled = bool(flag)
        return old

    def pump_enabled():
        return _enabled

    def pump_depth():
        """Entries waiting in undrained pump batches (all loops) —
        exported as the cueball_pump_queue_depth gauge."""
        return sum(len(batch) for batch in _pending.values())


if os.environ.get('CUEBALL_NO_PUMP'):
    set_pump_enabled(False)


# -- batched claim-deadline timer wheel ----------------------------------
#
# Arming a per-claim asyncio timer costs a heapq push + Handle +
# TimerHandle and, far worse, a heap pollution of cancelled entries for
# every claim that completes in time (nearly all of them — round-6
# profile, docs/claim-path-profile.md). The wheel coalesces claim
# deadlines into WHEEL_QUANTUM_MS buckets with ONE loop.call_later per
# bucket: arming and cancelling are plain dict ops, and a bucket's
# single timer fires every handle that is still parked in it. Claim
# timeouts are second-resolution liveness bounds, so up to one quantum
# of firing slop is well inside spec (the FSM re-checks the real
# deadline against current_millis() when it fires).

WHEEL_QUANTUM_MS = 5.0

_wheel: dict = {}  # loop -> {bucket: {token: handle}}
_wheel_tok = 0

#: Optional native bucket-timer hook: ``fn(loop, delay_ms, fire) ->
#: bool`` arms the bucket deadline on the C transport plane's deadline
#: heap (one TIMER completion in the batched drain instead of an
#: asyncio TimerHandle per bucket). A False return — no plane bound to
#: this loop, or it is shutting down — falls back to loop.call_later,
#: so netsim/virtual-clock loops and plain asyncio pools are
#: untouched. Installed by cueball_tpu.native_transport on import.
_native_timer = None


def set_native_timer(fn) -> None:
    """Install (or clear, with None) the native bucket-timer hook."""
    global _native_timer
    _native_timer = fn


def _arm_bucket(loop, bucket) -> None:
    """Arm the single shared timer for a fresh wheel bucket, on the
    native plane's deadline heap when one is bound to this loop, else
    via loop.call_later."""
    delay_ms = max(
        bucket * WHEEL_QUANTUM_MS - mod_utils.current_millis(), 0.0)
    hook = _native_timer
    if hook is not None:
        def fire(loop=loop, bucket=bucket):
            _wheel_fire(loop, bucket)
        if hook(loop, delay_ms, fire):
            return
    loop.call_later(delay_ms / 1000.0, _wheel_fire, loop, bucket)


def wheel_arm(deadline_ms, handle):
    """Park `handle` until monotonic-ms `deadline_ms` rounds up to its
    wheel bucket; returns an opaque token for wheel_cancel(). When the
    bucket fires, `handle._ch_wheel_fire(token)` decides whether the
    deadline still applies. Requires a running loop, like call_soon."""
    global _wheel_tok
    loop = asyncio.get_running_loop()
    bucket = int(deadline_ms // WHEEL_QUANTUM_MS) + 1
    buckets = _wheel.get(loop)
    if buckets is None:
        if _wheel:
            # Prune buckets stranded on closed loops (their timers
            # died with the loop), mirroring the pump's pruning.
            for stale in [ln for ln in _wheel if ln.is_closed()]:
                del _wheel[stale]
        buckets = _wheel[loop] = {}
    _wheel_tok += 1
    token = (loop, bucket, _wheel_tok)
    slot = buckets.get(bucket)
    if slot is None:
        slot = buckets[bucket] = {}
        _arm_bucket(loop, bucket)
    slot[token] = handle
    return token


def wheel_arm_many(deadline_ms, handles):
    """Batched wheel_arm for handles sharing one deadline (the
    claim_many park path): the loop lookup, bucket computation and
    timer-exists check are paid once for the whole batch, then each
    handle is one dict insert. Returns one token per handle, in
    order."""
    global _wheel_tok
    loop = asyncio.get_running_loop()
    bucket = int(deadline_ms // WHEEL_QUANTUM_MS) + 1
    buckets = _wheel.get(loop)
    if buckets is None:
        if _wheel:
            for stale in [ln for ln in _wheel if ln.is_closed()]:
                del _wheel[stale]
        buckets = _wheel[loop] = {}
    slot = buckets.get(bucket)
    if slot is None:
        slot = buckets[bucket] = {}
        _arm_bucket(loop, bucket)
    tokens = []
    for handle in handles:
        _wheel_tok += 1
        token = (loop, bucket, _wheel_tok)
        slot[token] = handle
        tokens.append(token)
    return tokens


def _wheel_fire(loop, bucket):
    buckets = _wheel.get(loop)
    if buckets is None:
        return
    slot = buckets.pop(bucket, None)
    if not buckets:
        _wheel.pop(loop, None)
    if not slot:
        return
    for token, handle in slot.items():
        try:
            handle._ch_wheel_fire(token)
        except (SystemExit, KeyboardInterrupt):
            raise
        except BaseException as exc:
            loop.call_exception_handler({
                'message': 'cueball timer wheel deadline',
                'exception': exc,
            })


def wheel_cancel(token):
    """Unpark a handle; cancelling is two dict lookups and a pop — the
    bucket's shared timer is left to fire and find nobody home."""
    buckets = _wheel.get(token[0])
    if buckets is None:
        return
    slot = buckets.get(token[1])
    if slot is not None:
        slot.pop(token, None)


def wheel_depth():
    """Handles currently parked in the wheel (all loops/buckets)."""
    return sum(len(slot)
               for buckets in _wheel.values()
               for slot in buckets.values())

"""Mesh-sharded fleet telemetry.

SURVEY.md §7.1 is explicit: the reference has no tensor programs, so
there is no training step to shard. What a TPU host running this
framework *does* have at scale is control-plane telemetry: thousands of
pools' load samples, claim-queue sojourns, and retry-backoff ladders.
parallel.telemetry batches the framework's control laws (FIR shrink
damping, rebalance targeting, CoDel, backoff) into one jitted step,
sharded over a `jax.sharding.Mesh` 'pools' axis, with the fleet-wide
aggregates (mean load, overload fraction, retry pressure) becoming XLA
all-reduces over ICI. parallel.sampler bridges the live runtime into
that step: it samples every pool registered in the process-global
monitor each LP tick and publishes the batched decisions.
parallel.health judges the sampled fleet: per-backend claim
attribution folds into robust on-mesh anomaly detection (gray
flags with hysteresis) and SLO burn-rate tracking.
"""

from .control import (ControlInputs, ControlState, apply_decisions,
                      control_init, control_inputs, control_step,
                      make_control_step, make_shardmap_control_step,
                      reduce_control)
from .health import (DEFAULT_OBJECTIVES, BackendTable, HealthInputs,
                     HealthMonitor, HealthState, SLOObjectives,
                     health_init, health_inputs, health_snapshot,
                     health_step, make_health_step,
                     make_shardmap_health_step, reduce_health)
from .sampler import FleetSampler
from .telemetry import (FleetInputs, FleetState, fleet_init,
                        fleet_inputs, fleet_scan, fleet_step,
                        fold_backend_slots, make_live_step,
                        make_sharded_scan, make_sharded_step,
                        make_shardmap_step, shard_inputs, shard_state,
                        shard_window)

__all__ = ['BackendTable', 'ControlInputs', 'ControlState',
           'DEFAULT_OBJECTIVES', 'FleetInputs', 'FleetSampler',
           'FleetState', 'HealthInputs', 'HealthMonitor',
           'HealthState', 'SLOObjectives', 'apply_decisions',
           'control_init', 'control_inputs', 'control_step',
           'fleet_init', 'fleet_inputs', 'fleet_scan', 'fleet_step',
           'fold_backend_slots', 'health_init', 'health_inputs',
           'health_snapshot', 'health_step', 'make_control_step',
           'make_health_step', 'make_live_step', 'make_sharded_scan',
           'make_sharded_step', 'make_shardmap_control_step',
           'make_shardmap_health_step', 'make_shardmap_step',
           'reduce_control', 'reduce_health', 'shard_inputs',
           'shard_state', 'shard_window']

"""Mesh-sharded fleet telemetry.

SURVEY.md §7.1 is explicit: the reference has no tensor programs, so
there is no training step to shard. What a TPU host running this
framework *does* have at scale is control-plane telemetry: thousands of
pools' load samples and claim-queue sojourns. parallel.telemetry batches
the framework's control laws (FIR shrink damping, rebalance targeting,
CoDel) into one jitted step, sharded over a `jax.sharding.Mesh` 'pools'
axis, with the fleet-wide aggregates (mean load, overload fraction)
becoming XLA all-reduces over ICI.
"""

from .telemetry import (FleetState, fleet_init, fleet_step,
                        make_sharded_step)

__all__ = ['FleetState', 'fleet_init', 'fleet_step', 'make_sharded_step']

"""Mesh-sharded fleet telemetry.

SURVEY.md §7.1 is explicit: the reference has no tensor programs, so
there is no training step to shard. What a TPU host running this
framework *does* have at scale is control-plane telemetry: thousands of
pools' load samples, claim-queue sojourns, and retry-backoff ladders.
parallel.telemetry batches the framework's control laws (FIR shrink
damping, rebalance targeting, CoDel, backoff) into one jitted step,
sharded over a `jax.sharding.Mesh` 'pools' axis, with the fleet-wide
aggregates (mean load, overload fraction, retry pressure) becoming XLA
all-reduces over ICI. parallel.sampler bridges the live runtime into
that step: it samples every pool registered in the process-global
monitor each LP tick and publishes the batched decisions.
"""

from .control import (ControlInputs, ControlState, apply_decisions,
                      control_init, control_inputs, control_step,
                      make_control_step, make_shardmap_control_step,
                      reduce_control)
from .sampler import FleetSampler
from .telemetry import (FleetInputs, FleetState, fleet_init,
                        fleet_inputs, fleet_scan, fleet_step,
                        make_live_step, make_sharded_scan,
                        make_sharded_step, make_shardmap_step,
                        shard_inputs, shard_state, shard_window)

__all__ = ['ControlInputs', 'ControlState', 'FleetInputs',
           'FleetSampler', 'FleetState', 'apply_decisions',
           'control_init', 'control_inputs', 'control_step',
           'fleet_init', 'fleet_inputs', 'fleet_scan', 'fleet_step',
           'make_control_step', 'make_live_step', 'make_sharded_scan',
           'make_sharded_step', 'make_shardmap_control_step',
           'make_shardmap_step', 'reduce_control', 'shard_inputs',
           'shard_state', 'shard_window']

"""Live bridge: registered pools -> batched TPU telemetry step.

The reference runs each pool's control laws per-pool, in-process, on a
5 Hz timer (reference lib/pool.js:251-262). The FleetSampler batches
that loop: every tick it feeds, for every ConnectionPool registered
in the process-global :data:`cueball_tpu.monitor.pool_monitor`, exactly
the signals the pool's own Python laws consume —

- the LP load sample ``busy + spares`` (same formula as
  ``ConnectionPool._lp_sample``),
- the head-of-claim-queue sojourn and CoDel target,
- the deepest slot backoff position (``sm_min_delay``/``sm_delay``
  ladder of SocketMgrFSM),

— runs the jitted :func:`~cueball_tpu.parallel.telemetry.fleet_step`
over the whole fleet at once, and publishes the per-pool decisions and
fleet aggregates through the kang snapshot (``/kang/fleet``) and the
prometheus collector (``cueball_fleet_*`` gauges).

The batched laws are the *same* laws the pools run per-claim in Python;
``tests/test_sampler.py`` asserts element-for-element agreement between
the two on live pools under load.

Rows: pools get stable rows in fixed-capacity arrays (capacity doubles
as the fleet grows, which is the only recompile); departed pools free
their row and the `reset` mask clears carried filter/CoDel state when
a row is reassigned.

Incremental gather: the per-pool signals live in preallocated numpy
columns owned by the sampler, maintained *event-driven* rather than
re-walked every tick. On row assignment the pool receives a
:class:`TelemetryRowHandle`; the pool (and its slots and claim
handles) call ``mark_dirty()`` at exactly the moments a gathered
signal can move — slot state changes, claim enqueue/dequeue, backoff
ladder entry/exit, spares/max reconfiguration — and ``sample_once``
re-reads (via :meth:`FleetSampler.gather_pool_signals`, the same
formulas as the oracle :meth:`FleetSampler.gather_pool`) only the
dirty rows. The only per-tick vectorized work over the full capacity
is the head-sojourn column (``now - head_ts``) and the column copies
handed to the transfer cache, so the host tick is O(dirty rows), not
O(fleet). Pools lacking the push protocol (duck-typed on
``telemetry_attach``) fall back to being re-gathered every tick.
See docs/fleet-telemetry.md for the full column/dirty contract.
"""

from __future__ import annotations

import heapq
import math
import typing
from collections.abc import Mapping

from .. import trace as mod_trace
from .. import utils as mod_utils
from ..events import EventEmitter
from ..monitor import pool_monitor as default_monitor

if typing.TYPE_CHECKING:
    from ..metrics import Collector

SAMPLER_INT = 200  # ms, the pools' own LP cadence (lib/pool.js:251)

# Rebase the epoch-relative clock before float32 resolution decays:
# at 2^20 ms (~17 min) the f32 ulp is 0.0625 ms, ample for the 100 ms
# CoDel control interval. MARGIN keeps post-rebase `now` large enough
# that clamped-stale timestamps keep their "very old" semantics.
EPOCH_LIMIT = float(2 ** 20)
EPOCH_MARGIN = 1000.0

_CONTROL_GAUGES = {
    'pressure': 'fleet overload fraction seen by the control step',
    'mean_load': 'mean busy+spares load seen by the control step',
    'applied': 'control decisions accepted by pools last step',
    'rejected': 'control decisions rejected by pools last step',
    'epoch': 'decision epoch of the last control step',
    'step_ms': 'host-side duration of the last control step (ms)',
}

_FLEET_GAUGES = {
    'n_pools': 'pools currently sampled into the fleet step',
    'mean_load': 'mean busy+spares load across the fleet',
    'mean_filtered': 'mean FIR-filtered load across the fleet',
    'overload_frac': 'fraction of pools with a CoDel drop this tick',
    'max_sojourn': 'worst head-of-queue claim sojourn (ms)',
    'retry_frac': 'fraction of pools with slots in retry backoff',
    'mean_retry_backoff': 'mean reproduced backoff delay (ms)',
    'loop_lag_p99_us': 'worst observed event-loop callback lag p99 '
                       '(us, wiretap loop-lag sampler; 0 when unarmed)',
}

# Per-row defaults for the event-maintained signal columns: the values
# an unoccupied (or just-released) row carries. target_delay=inf means
# "CoDel off" in the batched law.
_COL_DEFAULTS = {
    'samples': 0.0,
    'target_delay': math.inf,
    'spares': 0.0,
    'maximum': 0.0,
    'retry_delay': 0.0,
    'retry_max_delay': 0.0,
    'retry_attempt': 0.0,
    'n_retrying': 0.0,
}


class TelemetryRowHandle:
    """A pool's write capability into its sampler's dirty set.

    Handed to the pool at row assignment (``pool.telemetry_attach``).
    ``mark_dirty()`` is the whole push protocol: one O(1) set-add, no
    payload — the sampler re-reads the pool's signals itself on the
    next tick. Events may fire many times between ticks; the set
    dedupes them into a single re-gather. ``detach()`` (called by the
    sampler when the row is freed) turns the handle inert, so a pool
    that outlives its row — or that a second sampler still tracks —
    can keep calling mark_dirty() safely."""

    __slots__ = ('th_row', 'th_dirty')

    def __init__(self, row: int, dirty: set):
        self.th_row = row
        self.th_dirty = dirty

    def mark_dirty(self) -> None:
        d = self.th_dirty
        if d is not None:
            d.add(self.th_row)

    def detach(self) -> None:
        self.th_dirty = None


class _TickPools(Mapping):
    """Lazy per-pool decision mapping for one tick's record.

    Building 10k+ eagerly-materialized per-pool dicts every 200 ms
    would put the publish path right back at O(fleet); this view
    renders a pool's entry only when someone actually reads it (tests,
    the kang snapshot — which materializes, or an operator poking
    fs_latest). The backing arrays are the tick's committed copies, so
    the view stays frozen even as the live columns keep moving."""

    __slots__ = ('tp_rows', 'tp_cols', 'tp_out')

    def __init__(self, rows: dict, cols: dict, out: dict):
        self.tp_rows = rows
        self.tp_cols = cols
        self.tp_out = out

    def __len__(self):
        return len(self.tp_rows)

    def __iter__(self):
        return iter(self.tp_rows)

    def __contains__(self, uuid):
        return uuid in self.tp_rows

    def __getitem__(self, uuid):
        row = self.tp_rows[uuid]
        cols = self.tp_cols
        out = self.tp_out
        # target_delay=inf means "CoDel off" in the arrays; publish
        # None instead (Infinity is not valid JSON and the kang
        # surface is read by strict external parsers).
        td = float(cols['target_delay'][row])
        return {
            'row': row,
            'inputs': {
                'sample': float(cols['samples'][row]),
                'sojourn': float(cols['sojourns'][row]),
                'target_delay': td if math.isfinite(td) else None,
                'spares': float(cols['spares'][row]),
                'maximum': float(cols['maximum'][row]),
                'retry_delay': float(cols['retry_delay'][row]),
                'retry_max_delay': float(cols['retry_max_delay'][row]),
                'retry_attempt': float(cols['retry_attempt'][row]),
                'n_retrying': float(cols['n_retrying'][row]),
            },
            'filtered': float(out['filtered'][row]),
            'target': float(out['target'][row]),
            'clamped': bool(out['clamped'][row]),
            'drop': bool(out['drop'][row]),
            'retry_backoff': float(out['retry_backoff'][row]),
        }


class FleetSampler:
    """Samples every registered pool into the batched telemetry step.

    Options (all optional):
    - monitor: a PoolMonitor (default: the process-global singleton)
    - interval: tick period in ms (default 200 = the LP cadence)
    - taps: FIR window length (default 128, the pool's own filter)
    - capacity: initial row capacity (default 8; grows by doubling)
    - collector: a metrics Collector to publish cueball_fleet_* gauges
    - record: keep a per-tick history of inputs/outputs (for tests)
    - actuate: push each tick's batched FIR output back into the
      sampled pools (receive_fleet_advisory). Default OFF. A pool
      only *uses* the advisory if it was itself constructed with
      fleetActuation=True — both ends opt in, so turning the sampler
      flag on over a fleet of stock pools changes nothing.
    - mesh: a jax.sharding.Mesh. When given, the fleet arrays live
      sharded over the mesh (same layouts as make_sharded_step) and
      the tick step is the sharded one, so the published aggregates
      compile to all-reduces over ICI. Row capacity rounds up to a
      multiple of the mesh size. The snapshot()/``/kang/fleet``
      surface reports the mesh shape.
    - meshAxes: mesh axis name(s) the pools axis shards over
      (default ('pools',); pass ('host', 'chip') for a 2-D mesh).
    - shard: a shard id. When given, the sampler only samples pools
      whose ``p_shard`` matches (the FleetRouter stamps one per owned
      pool) and the published ``cueball_fleet_*`` gauges carry a
      ``shard`` label. One such sampler runs per shard loop; the
      router reduces their fleet rows with :func:`reduce_fleet`.
    - control: run the fused control step (parallel.control) after
      every telemetry tick and OFFER its decision columns to the
      sampled pools through the guarded ``apply_control_decision``
      API. Default OFF. The step consumes the telemetry tick's device
      arrays directly (zero extra host->device copies); a pool only
      *accepts* decisions if it was constructed with
      controlActuation=True — both ends opt in, like `actuate`. Rows
      inside the FIR warm-up window (< taps ticks) are not offered
      decisions. The tick record gains a ``control`` entry with the
      fleet row, apply counts and the decision columns.
    - health: run the fleet health engine (parallel.health) after
      every telemetry tick. The sampler owns a HealthMonitor (fed by
      the claim tracer's per-backend sinks) and ticks it in step with
      the fleet, so per-backend gray verdicts and SLO burn rates land
      on the same collector, /kang/health and the SIGUSR2 dump. The
      tick record gains a ``health`` entry with the verdict record.
    - objectives: an SLOObjectives for the health engine (default
      parallel.health.DEFAULT_OBJECTIVES; ignored without `health`).
    """

    def __init__(self, options: dict | None = None):
        options = options or {}
        self.fs_monitor = options.get('monitor') or default_monitor
        self.fs_shard = options.get('shard')
        self.fs_interval = options.get('interval') or SAMPLER_INT
        self.fs_taps = options.get('taps') or 128
        self.fs_capacity = options.get('capacity') or 8
        self.fs_collector: 'Collector | None' = options.get('collector')
        self.fs_record = bool(options.get('record'))
        self.fs_actuate = bool(options.get('actuate'))
        self.fs_mesh = options.get('mesh')
        self.fs_mesh_axes = tuple(options.get('meshAxes') or ('pools',))
        if self.fs_mesh is not None:
            # Shard layouts need the pools axis divisible by the mesh;
            # doubling growth preserves any starting multiple.
            n = int(self.fs_mesh.size)
            self.fs_capacity = -(-self.fs_capacity // n) * n
        self.fs_step = None                    # jitted tick step (lazy)
        self.fs_input_shardings = None         # FleetInputs of shardings
        self.fs_input_cache: dict[str, tuple] = {}  # field -> (host, dev)
        self.fs_control = bool(options.get('control'))
        self.fs_ctrl_state = None              # ControlState (lazy)
        self.fs_ctrl_step = None               # jitted control step
        self.fs_ctrl_last: dict | None = None  # last control record
        self.fs_health = bool(options.get('health'))
        self.fs_objectives = options.get('objectives')
        self.fs_health_monitor = None          # HealthMonitor (lazy)

        self.fs_epoch = mod_utils.current_millis()
        self.fs_rows: dict[str, int] = {}      # pool uuid -> row
        self.fs_row_ticks: dict[int, int] = {}  # row -> ticks since reset
        self.fs_row_pool: dict[int, object] = {}  # row -> pool (live)
        self.fs_free: list[int] = list(range(self.fs_capacity))  # heap
        self.fs_pending_reset: set[int] = set()

        # Event-maintained signal columns (see module docstring). The
        # dirty set is SHARED with every handle this sampler hands out;
        # sample_once drains it in place (never rebinds it).
        self.fs_dirty: set[int] = set()
        self.fs_polled: set[int] = set()   # rows re-gathered every tick
        self.fs_handles: dict[int, TelemetryRowHandle] = {}
        self.fs_tick_visits = 0       # rows re-gathered last tick
        self.fs_gather_visits = 0     # cumulative, for regression tests
        self._alloc_columns(self.fs_capacity)

        # Roster generation last reconciled (monitors without the
        # counter reconcile every tick).
        self.fs_monitor_gen: object = object()

        self.fs_state = None                   # FleetState (lazy)
        self.fs_latest: dict | None = None
        self.fs_history: list[dict] = []
        self.fs_ticks = 0
        self.fs_timer = None
        self.fs_emitter = EventEmitter()
        self.fs_emitter.on('timeout', self.sample_once)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Warm up the jitted step (one synchronous tick pays the
        compile) and begin ticking on the loop."""
        if self.fs_timer is not None:
            return
        from ..pool import _Interval
        self.sample_once()
        self.fs_timer = _Interval(self.fs_interval, self.fs_emitter)

    def stop(self) -> None:
        if self.fs_timer is not None:
            self.fs_timer.cancel()
            self.fs_timer = None
        if self.fs_health_monitor is not None:
            self.fs_health_monitor.stop()
            self.fs_health_monitor = None

    # -- row management --------------------------------------------------

    def _alloc_columns(self, cap: int) -> None:
        import numpy as np
        self.fs_cols = {
            name: np.full((cap,), default, np.float32)
            for name, default in _COL_DEFAULTS.items()}
        # Head-of-queue enqueue instant, absolute ms (0 = no waiter).
        # float64: absolute wall-clock ms do not fit f32; the sojourn
        # subtraction happens in f64 and only the result narrows.
        self.fs_head_ts = np.zeros((cap,), np.float64)
        # Loop-lag p99 (us) of the loop serving each row's pool, read
        # from the wiretap sampler during the O(dirty) patch pass. A
        # side array like fs_head_ts, NOT a _COL_DEFAULTS column: the
        # signal columns feed FleetInputs(**placed) on device and the
        # lag never participates in the batched law — it rides the
        # host-side fleet row so the control step and health detector
        # can condition on loop saturation.
        self.fs_loop_lag = np.zeros((cap,), np.float64)
        self.fs_active = np.zeros((cap,), bool)

    def _ensure_state(self):
        from .telemetry import (_step_shardings, fleet_init,
                                make_live_step, shard_state)
        if self.fs_state is None:
            self.fs_state = fleet_init(self.fs_capacity, taps=self.fs_taps)
            if self.fs_mesh is not None:
                self.fs_state = shard_state(
                    self.fs_state, self.fs_mesh, self.fs_mesh_axes)
                _, self.fs_input_shardings, _ = _step_shardings(
                    self.fs_mesh, self.fs_mesh_axes)
            # State buffers are donated through the step, so they stay
            # device-resident and get rewritten in place every tick.
            self.fs_step = make_live_step(self.fs_mesh,
                                          self.fs_mesh_axes)
        return self.fs_state

    def _grow(self, need: int) -> None:
        import jax.numpy as jnp
        import numpy as np
        from ..ops.codel_batch import CodelState
        from .telemetry import FleetState, shard_state
        old = self.fs_capacity
        cap = old
        while cap < need:
            cap *= 2
        st = self._ensure_state()
        pad = cap - old
        self.fs_state = FleetState(
            windows=jnp.pad(st.windows, ((0, pad), (0, 0))),
            codel=CodelState(
                first_above=jnp.pad(st.codel.first_above, (0, pad)),
                drop_next=jnp.pad(st.codel.drop_next, (0, pad)),
                count=jnp.pad(st.codel.count, (0, pad)),
                dropping=jnp.pad(st.codel.dropping, (0, pad))),
            now_ms=st.now_ms)
        if self.fs_mesh is not None:
            self.fs_state = shard_state(
                self.fs_state, self.fs_mesh, self.fs_mesh_axes)
        if self.fs_ctrl_state is not None:
            from .control import ControlState, shard_control_state
            cs = ControlState(
                targets=jnp.pad(self.fs_ctrl_state.targets, (0, pad)),
                epoch=self.fs_ctrl_state.epoch,
                now_ms=self.fs_ctrl_state.now_ms)
            if self.fs_mesh is not None:
                cs = shard_control_state(cs, self.fs_mesh,
                                         self.fs_mesh_axes)
            self.fs_ctrl_state = cs
        self.fs_input_cache.clear()   # shapes changed
        for name, arr in self.fs_cols.items():
            grown = np.full((cap,), _COL_DEFAULTS[name], np.float32)
            grown[:old] = arr
            self.fs_cols[name] = grown
        head = np.zeros((cap,), np.float64)
        head[:old] = self.fs_head_ts
        self.fs_head_ts = head
        lag = np.zeros((cap,), np.float64)
        lag[:old] = self.fs_loop_lag
        self.fs_loop_lag = lag
        active = np.zeros((cap,), bool)
        active[:old] = self.fs_active
        self.fs_active = active
        self.fs_free.extend(range(old, cap))
        heapq.heapify(self.fs_free)
        self.fs_capacity = cap

    def _attach_row(self, pool, row: int) -> None:
        handle = TelemetryRowHandle(row, self.fs_dirty)
        self.fs_handles[row] = handle
        self.fs_row_pool[row] = pool
        attach = getattr(pool, 'telemetry_attach', None)
        if attach is not None:
            attach(handle)
            self.fs_dirty.add(row)     # first gather
        else:
            # No push support (e.g. a bare test double): this row is
            # re-gathered every tick, exactly the old full-walk cost
            # but only for the pools that need it.
            self.fs_polled.add(row)

    def _release_row(self, row: int) -> None:
        pool = self.fs_row_pool.pop(row, None)
        handle = self.fs_handles.pop(row, None)
        if handle is not None:
            handle.detach()
            detach = getattr(pool, 'telemetry_detach', None)
            if detach is not None:
                detach(handle)
        self.fs_polled.discard(row)
        self.fs_dirty.discard(row)
        for name, arr in self.fs_cols.items():
            arr[row] = _COL_DEFAULTS[name]
        self.fs_head_ts[row] = 0.0
        self.fs_loop_lag[row] = 0.0
        self.fs_active[row] = False

    def _assign_rows(self, pools: Mapping) -> None:
        for uuid in [u for u in self.fs_rows if u not in pools]:
            row = self.fs_rows.pop(uuid)
            self._release_row(row)
            heapq.heappush(self.fs_free, row)
        fresh = [u for u in pools if u not in self.fs_rows]
        if len(self.fs_rows) + len(fresh) > self.fs_capacity:
            self._grow(len(self.fs_rows) + len(fresh))
        for uuid in fresh:
            row = heapq.heappop(self.fs_free)
            self.fs_rows[uuid] = row
            self.fs_pending_reset.add(row)
            self.fs_row_ticks[row] = 0
            self.fs_active[row] = True
            self._attach_row(pools[uuid], row)

    # -- gathering -------------------------------------------------------

    @staticmethod
    def gather_pool_signals(pool) -> dict:
        """One pool's time-independent tick signals, using the pools'
        own formulas — the single source of truth for both the
        incremental columns (dirty-row patching) and the
        :meth:`gather_pool` oracle.

        sample: identical to ConnectionPool._lp_sample (busy + spares
        option). head_ts: the first still-waiting claim's enqueue
        instant (absolute ms; 0.0 = empty queue) — the tick turns it
        into a sojourn with one vectorized ``now - head_ts``.
        retry_*: the deepest backoff slot's ladder position, from which
        the batched law reproduces its current sm_delay."""
        sample = pool.lp_load_sample()

        head_ts = 0.0
        for hdl in pool.p_waiters:
            if hdl.is_in_state('waiting'):
                head_ts = float(hdl.ch_started)
                break

        target_delay = math.inf
        if pool.p_codel is not None:
            target_delay = float(pool.p_codel.cd_targdelay)

        n_retrying = 0
        attempt = 0.0
        delay0 = 0.0
        max_delay = 0.0
        for slots in pool.p_connections.values():
            for slot in slots:
                smgr = slot.get_socket_mgr()
                if not smgr.is_in_state('backoff'):
                    continue
                if not math.isfinite(smgr.sm_retries):
                    continue  # monitor slots: pinned, not a ladder
                n_retrying += 1
                a = float(smgr.sm_retries - smgr.sm_retries_left)
                if a >= attempt:
                    attempt = a
                    delay0 = float(smgr.sm_min_delay)
                    max_delay = float(smgr.sm_max_delay)
        return {
            'sample': float(sample), 'head_ts': head_ts,
            'target_delay': target_delay,
            'spares': float(pool.p_spares),
            'maximum': float(pool.p_max),
            'retry_delay': delay0, 'retry_max_delay': max_delay,
            'retry_attempt': attempt, 'n_retrying': float(n_retrying),
        }

    @staticmethod
    def gather_pool(pool, now: float) -> dict:
        """The oracle: one pool's tick signals gathered fresh, sojourn
        included. The incremental columns must agree with this
        element-for-element at every tick (tests assert it under
        churn); it is also what the polled fallback and the dirty-row
        patching build on, via :meth:`gather_pool_signals`."""
        g = FleetSampler.gather_pool_signals(pool)
        head_ts = g.pop('head_ts')
        g['sojourn'] = float(now - head_ts) if head_ts else 0.0
        # Present sample first, sojourn second: the published inputs
        # dict keeps its historical key order.
        return {'sample': g.pop('sample'), 'sojourn': g.pop('sojourn'),
                **g}

    def _patch_dirty_rows(self) -> None:
        """Re-read signals for every row whose pool reported an event
        since the last tick (plus the polled fallback rows). This is
        the O(changed) heart of the incremental gather."""
        patch = self.fs_dirty
        patch.update(self.fs_polled)
        self.fs_tick_visits = len(patch)
        self.fs_gather_visits += len(patch)
        if not patch:
            return
        cols = self.fs_cols
        head = self.fs_head_ts
        lag_col = self.fs_loop_lag
        row_pool = self.fs_row_pool
        # One sampler read per patch pass, not per row: every row this
        # sampler touches lives on the loop this pass runs on.
        from .. import wiretap as mod_wiretap
        loop_lag = mod_wiretap.loop_lag_p99_us()
        for row in patch:
            pool = row_pool.get(row)
            if pool is None:
                continue   # freed after the mark; row already reset
            g = self.gather_pool_signals(pool)
            head[row] = g['head_ts']
            lag_col[row] = loop_lag
            cols['samples'][row] = g['sample']
            cols['target_delay'][row] = g['target_delay']
            cols['spares'][row] = g['spares']
            cols['maximum'][row] = g['maximum']
            cols['retry_delay'][row] = g['retry_delay']
            cols['retry_max_delay'][row] = g['retry_max_delay']
            cols['retry_attempt'][row] = g['retry_attempt']
            cols['n_retrying'][row] = g['n_retrying']
        # In-place clear: the handles hold this very set object.
        patch.clear()

    def gather_once(self) -> int:
        """Run one incremental host gather outside a full tick: re-read
        signals for exactly the rows whose pools marked themselves
        dirty (plus the polled fallback rows) and fold them into the
        live columns. Returns the number of rows visited.

        This is the host-side cost a tick pays for gathering — O(dirty),
        not O(fleet) — exposed on its own so callers (the bench's
        gather curve, operators probing a quiet fleet) can weigh it
        without also paying the device step and publish."""
        self._patch_dirty_rows()
        return self.fs_tick_visits

    def _place_inputs(self, arrays: dict, now: float):
        """Host tick columns -> device FleetInputs, re-shipping only
        the fields whose values changed since the previous tick.

        Most per-pool fields are static between ticks (spares, maximum,
        CoDel targets, the retry ladder when nothing is failing); over
        a tunneled chip every avoided host->device transfer is an RTT
        saved, so unchanged columns reuse their committed device array
        from the last tick. The scalar clock always changes and always
        ships. Callers pass per-tick copies, never the live columns —
        the cache keeps the host array it committed, and comparing a
        live column against itself would always read "unchanged"."""
        import jax
        import numpy as np
        from .telemetry import FleetInputs
        placed = {}
        for name, host in arrays.items():
            cached = self.fs_input_cache.get(name)
            if cached is not None and np.array_equal(cached[0], host):
                placed[name] = cached[1]
                continue
            if self.fs_input_shardings is not None:
                dev = jax.device_put(
                    host, getattr(self.fs_input_shardings, name))
            else:
                dev = jax.device_put(host)
            self.fs_input_cache[name] = (host, dev)
            placed[name] = dev
        return FleetInputs(now_ms=np.float32(now), **placed)

    def sample_once(self) -> dict | None:
        """One synchronous tick: patch dirty rows, step, publish.
        Returns the published record (None when sampling is
        impossible)."""
        import numpy as np

        monitor = self.fs_monitor
        gen = getattr(monitor, 'pm_generation', None)
        if gen is None or gen != self.fs_monitor_gen:
            pools = monitor.pm_pools
            if self.fs_shard is not None:
                # Shard-scoped sampler: only this shard's pools. The
                # router stamps p_shard at pool construction, which
                # happens-before any tick of this sampler on the same
                # shard loop.
                pools = {u: p for u, p in pools.items()
                         if getattr(p, 'p_shard', None) == self.fs_shard}
            self._assign_rows(pools)
            self.fs_monitor_gen = gen
        abs_now = mod_utils.current_millis()
        now = abs_now - self.fs_epoch
        if now > EPOCH_LIMIT:
            from .telemetry import rebase_state
            shift = now - EPOCH_MARGIN
            self.fs_state = rebase_state(self._ensure_state(), shift)
            self.fs_epoch += shift
            now -= shift
        cap = self.fs_capacity

        self._patch_dirty_rows()

        # The always-moving piece, vectorized over the column: the
        # head-of-queue sojourn every occupied row with a waiter sees
        # this instant.
        head = self.fs_head_ts
        sojourns = np.where(
            head > 0.0, abs_now - head, 0.0).astype(np.float32)

        reset = np.zeros((cap,), bool)
        for row in self.fs_pending_reset:
            reset[row] = True
        self.fs_pending_reset.clear()

        # Per-tick snapshots: the transfer cache commits these (and
        # compares against them next tick), and the published record
        # keeps reading them after the live columns move on.
        arrays = {name: col.copy() for name, col in self.fs_cols.items()}
        arrays['sojourns'] = sojourns
        arrays['active'] = self.fs_active.copy()
        arrays['reset'] = reset

        state = self._ensure_state()
        inp = self._place_inputs(arrays, now)
        try:
            new_state, out, fleet = self.fs_step(state, inp)
        except Exception:
            # Donation marks the carried buffers deleted at dispatch,
            # BEFORE a runtime failure surfaces — retrying against
            # them would raise "Array has been deleted" on every tick
            # forever. Recover like a sampler restart: drop the state
            # (re-init next tick), flag every occupied row for reset,
            # and restart the actuation warm-up gates; then let the
            # error propagate to the timer's handler.
            self.fs_state = None
            self.fs_input_cache.clear()
            for row in self.fs_rows.values():
                self.fs_pending_reset.add(row)
                self.fs_row_ticks[row] = 0
            raise
        self.fs_state = new_state
        self.fs_ticks += 1

        fleet_np = {k: float(v) for k, v in fleet.items()}
        # Host-side column: worst loop-lag p99 across occupied rows
        # (0.0 while the wiretap sampler is unarmed). Injected after
        # the device step — the batched law never sees it — so it
        # publishes and reduces like any other _FLEET_GAUGES key.
        fleet_np['loop_lag_p99_us'] = (
            float(self.fs_loop_lag[self.fs_active].max())
            if bool(self.fs_active.any()) else 0.0)
        out_np = {k: np.asarray(v) for k, v in out.items()}
        per_pool = _TickPools(dict(self.fs_rows), arrays, out_np)
        # Per-row tick counters drive the actuation warm-up gates (both
        # the advisory push and the control step below): a row's filter
        # starts zeroed on (re)assign, so for the first `taps` ticks
        # its output under-reads the history the pool's own converged
        # filter still holds — pushing it would collapse the shrink
        # clamp after a sampler restart. Only a fully-populated window
        # (which by the parity laws equals the per-pool filter fed the
        # same samples) is advisory-grade.
        for row in self.fs_row_pool:
            self.fs_row_ticks[row] = self.fs_row_ticks.get(row, 0) + 1
        if self.fs_actuate:
            # Close the loop: hand each pool its batched decision.
            # The pool stores it unconditionally but consults it only
            # under its own fleetActuation flag (+freshness TTL).
            for row, pool in self.fs_row_pool.items():
                if self.fs_row_ticks.get(row, 0) < self.fs_taps:
                    continue
                receive = getattr(pool, 'receive_fleet_advisory', None)
                if receive is not None:
                    receive(float(out_np['filtered'][row]), abs_now)

        record = {'tick': self.fs_ticks, 'now_ms': now,
                  'fleet': fleet_np, 'pools': per_pool}
        if self.fs_control:
            record['control'] = self._control_once(inp, out, abs_now)
        if self.fs_health:
            record['health'] = self._health_once(abs_now)
        if self.fs_record:
            # History must be plain data — a lazy view per retained
            # tick would pin every tick's column copies anyway, and
            # tests diff whole records.
            record['pools'] = dict(per_pool)
        self.fs_latest = record
        if self.fs_record:
            self.fs_history.append(record)
        # Publish fleet gauges onto this sampler's collector, falling
        # back to the claim tracer's canonical metric surface when the
        # sampler was built without one (so one /metrics endpoint
        # carries both the per-pool trace gauges and the fleet row).
        collector = self.fs_collector
        if collector is None:
            collector = mod_trace.active_collector()
        if collector is not None:
            labels = ({'shard': str(self.fs_shard)}
                      if self.fs_shard is not None else None)
            for name, help_ in _FLEET_GAUGES.items():
                collector.gauge(
                    'cueball_fleet_' + name, help_).set(
                        fleet_np[name], labels)
        return record

    # -- control plane ---------------------------------------------------

    def _ensure_control(self):
        from .control import (control_init, make_control_step,
                              shard_control_state)
        if self.fs_ctrl_state is None:
            self.fs_ctrl_state = control_init(self.fs_capacity)
            if self.fs_mesh is not None:
                self.fs_ctrl_state = shard_control_state(
                    self.fs_ctrl_state, self.fs_mesh, self.fs_mesh_axes)
            # Carried control state is donated through the step, same
            # double-buffer contract as the telemetry state.
            self.fs_ctrl_step = make_control_step(self.fs_mesh,
                                                  self.fs_mesh_axes)
        return self.fs_ctrl_state

    def _control_once(self, inp, out, abs_now: float) -> dict:
        """Run the fused control step on the telemetry tick's device
        arrays and offer the decision columns to the sampled pools.

        Zero extra host->device copies: every ControlInputs field is
        either a FleetInputs array the tick already placed or the
        telemetry step's own ``filtered`` output. Only the decision
        columns come back to host (they must — actuation is a host
        concern)."""
        import numpy as np
        from .control import ControlInputs, apply_decisions
        t0 = mod_utils.current_millis()
        state = self._ensure_control()
        cinp = ControlInputs(
            samples=inp.samples, sojourns=inp.sojourns,
            filtered=out['filtered'], target_delay=inp.target_delay,
            spares=inp.spares, maximum=inp.maximum,
            active=inp.active, reset=inp.reset, now_ms=inp.now_ms)
        try:
            new_state, decisions, fleet = self.fs_ctrl_step(state, cinp)
        except Exception:
            # Same recovery as the telemetry step: donation already
            # invalidated the carried buffers, so drop the state and
            # re-init (epoch restarts; pools re-trust it after
            # CONTROL_EPOCH_TTL).
            self.fs_ctrl_state = None
            raise
        self.fs_ctrl_state = new_state
        dec_np = {k: np.asarray(v) for k, v in decisions.items()}
        fleet_np = {k: float(v) for k, v in fleet.items()}
        # Warm-up gate: only rows whose FIR window is fully populated
        # are offered decisions (same reasoning as the advisory push).
        eligible = {row: pool
                    for row, pool in self.fs_row_pool.items()
                    if self.fs_row_ticks.get(row, 0) >= self.fs_taps}
        # Health citation: the verdict the control plane saw when it
        # decided. The health tick runs after control within a sample,
        # so the citation is the previous tick's (the freshest verdict
        # that could actually have informed this decision).
        health = None
        if self.fs_health and self.fs_health_monitor is not None:
            last = self.fs_health_monitor.hm_last
            if last is not None:
                health = {'epoch': last['epoch'],
                          'at_ms': last['at_ms'],
                          'gray': list(last['gray']),
                          'burn_fast': last['fleet']['burn_fast'],
                          'burn_slow': last['fleet']['burn_slow']}
        summary = apply_decisions(eligible, dec_np, at_ms=abs_now,
                                  health=health)
        record = {'fleet': fleet_np, 'decisions': dec_np,
                  'step_ms': mod_utils.current_millis() - t0}
        record.update(summary)
        self.fs_ctrl_last = record
        collector = self.fs_collector
        if collector is None:
            collector = mod_trace.active_collector()
        if collector is not None:
            labels = ({'shard': str(self.fs_shard)}
                      if self.fs_shard is not None else None)
            vals = {'pressure': fleet_np['pressure'],
                    'mean_load': fleet_np['mean_load'],
                    'applied': summary['applied'],
                    'rejected': summary['rejected'],
                    'epoch': summary['epoch'],
                    'step_ms': record['step_ms']}
            for name, help_ in _CONTROL_GAUGES.items():
                collector.gauge('cueball_control_' + name, help_).set(
                    float(vals[name]), labels)
        return record

    # -- health plane ----------------------------------------------------

    def _ensure_health(self):
        from .health import HealthMonitor
        if self.fs_health_monitor is None:
            opts = {'collector': self.fs_collector,
                    'shard': self.fs_shard,
                    'interval': self.fs_interval}
            if self.fs_mesh is not None:
                opts['mesh'] = self.fs_mesh
                opts['meshAxes'] = self.fs_mesh_axes
            if self.fs_objectives is not None:
                opts['objectives'] = self.fs_objectives
            # start() attaches the monitor's BackendTable to the claim
            # tracer's completion sinks and registers it on the
            # /kang/health + SIGUSR2 surfaces.
            self.fs_health_monitor = HealthMonitor(opts).start()
        return self.fs_health_monitor

    def _health_once(self, abs_now: float) -> dict:
        """Tick the owned HealthMonitor in step with the fleet tick:
        drain the per-backend attribution columns, run one judged
        health step, publish the verdict record."""
        return self._ensure_health().tick(abs_now)

    # -- kang integration ------------------------------------------------

    def snapshot(self) -> dict:
        mesh = None
        if self.fs_mesh is not None:
            mesh = {
                'axes': list(self.fs_mesh_axes),
                'shape': {str(k): int(v) for k, v in zip(
                    self.fs_mesh.axis_names,
                    self.fs_mesh.devices.shape)},
                'n_devices': int(self.fs_mesh.size),
            }
        latest = self.fs_latest
        if latest is not None and not isinstance(latest['pools'], dict):
            # Materialize the lazy per-pool view for the JSON surface
            # (http_server serializes unknown mappings as repr).
            latest = dict(latest)
            latest['pools'] = dict(latest['pools'])
        control = None
        if self.fs_control:
            last = self.fs_ctrl_last
            control = {
                'enabled': True,
                'last': None if last is None else {
                    'fleet': last['fleet'], 'epoch': last['epoch'],
                    'applied': last['applied'],
                    'rejected': last['rejected'],
                    'skipped': last['skipped'],
                    'step_ms': last['step_ms'],
                },
            }
        health = None
        if self.fs_health:
            mon = self.fs_health_monitor
            health = {
                'enabled': True,
                'monitor': None if mon is None else mon.snapshot(),
            }
        return {
            'interval_ms': self.fs_interval,
            'shard': self.fs_shard,
            'capacity': self.fs_capacity,
            'ticks': self.fs_ticks,
            'rows': dict(self.fs_rows),
            'actuate': self.fs_actuate,
            'control': control,
            'health': health,
            'mesh': mesh,
            'row_ticks': dict(self.fs_row_ticks),
            'last_tick_visits': self.fs_tick_visits,
            'latest': latest,
        }


def reduce_fleet(records, mesh=None, mesh_axes=('host', 'chip')):
    """Reduce per-shard fleet aggregate rows into one fleet-wide row.

    ``records`` is a list of shard samplers' ``record['fleet']`` dicts
    (the :data:`_FLEET_GAUGES` keys). ``n_pools`` sums; the mean and
    fraction fields combine weighted by each shard's pool count;
    ``max_sojourn`` and ``loop_lag_p99_us`` take the worst shard (one
    saturated loop is the signal, a fleet-weighted mean would bury
    it). Shards with zero pools contribute nothing to the weighted
    fields.

    With a ``mesh``, the per-shard columns are placed sharded over the
    flattened ``mesh_axes`` (the same 2-D ('host', 'chip') layout the
    sharded telemetry step uses) and the reductions compile to
    all-reduces over ICI — the shard -> host -> mesh reduce tree. The
    shard axis pads to a multiple of the mesh size with zero-weight
    rows.
    """
    import numpy as np
    names = list(_FLEET_GAUGES)
    records = [r for r in records if r]
    if not records:
        return {name: 0.0 for name in names}
    cols = {name: np.asarray([float(r.get(name, 0.0)) for r in records],
                             np.float32)
            for name in names}
    if mesh is None:
        w = cols['n_pools']
        tot = float(w.sum())
        safe = tot if tot > 0.0 else 1.0
        out = {}
        for name in names:
            if name == 'n_pools':
                out[name] = tot
            elif name in ('max_sojourn', 'loop_lag_p99_us'):
                out[name] = float(cols[name].max())
            else:
                out[name] = float((cols[name] * w).sum() / safe)
        return out

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec
    pad = (-len(records)) % int(mesh.size)
    if pad:
        cols = {name: np.pad(col, (0, pad))
                for name, col in cols.items()}
    sharding = NamedSharding(mesh, PartitionSpec(tuple(mesh_axes)))
    dev = {name: jax.device_put(col, sharding)
           for name, col in cols.items()}
    w = dev['n_pools']
    tot = jnp.sum(w)
    safe = jnp.where(tot > 0.0, tot, 1.0)
    out = {}
    for name in names:
        if name == 'n_pools':
            out[name] = float(tot)
        elif name in ('max_sojourn', 'loop_lag_p99_us'):
            out[name] = float(jnp.max(dev[name]))
        else:
            out[name] = float(jnp.sum(dev[name] * w) / safe)
    return out

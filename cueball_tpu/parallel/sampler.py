"""Live bridge: registered pools -> batched TPU telemetry step.

The reference runs each pool's control laws per-pool, in-process, on a
5 Hz timer (reference lib/pool.js:251-262). The FleetSampler batches
that loop: every tick it gathers, from every ConnectionPool registered
in the process-global :data:`cueball_tpu.monitor.pool_monitor`, exactly
the signals the pool's own Python laws consume —

- the LP load sample ``busy + spares`` (same formula as
  ``ConnectionPool._lp_sample``),
- the head-of-claim-queue sojourn and CoDel target,
- the deepest slot backoff position (``sm_min_delay``/``sm_delay``
  ladder of SocketMgrFSM),

— runs the jitted :func:`~cueball_tpu.parallel.telemetry.fleet_step`
over the whole fleet at once, and publishes the per-pool decisions and
fleet aggregates through the kang snapshot (``/kang/fleet``) and the
prometheus collector (``cueball_fleet_*`` gauges).

The batched laws are the *same* laws the pools run per-claim in Python;
``tests/test_sampler.py`` asserts element-for-element agreement between
the two on live pools under load.

Rows: pools get stable rows in fixed-capacity arrays (capacity doubles
as the fleet grows, which is the only recompile); departed pools free
their row and the `reset` mask clears carried filter/CoDel state when
a row is reassigned.
"""

from __future__ import annotations

import math
import typing

from .. import utils as mod_utils
from ..events import EventEmitter
from ..monitor import pool_monitor as default_monitor

if typing.TYPE_CHECKING:
    from ..metrics import Collector

SAMPLER_INT = 200  # ms, the pools' own LP cadence (lib/pool.js:251)

# Rebase the epoch-relative clock before float32 resolution decays:
# at 2^20 ms (~17 min) the f32 ulp is 0.0625 ms, ample for the 100 ms
# CoDel control interval. MARGIN keeps post-rebase `now` large enough
# that clamped-stale timestamps keep their "very old" semantics.
EPOCH_LIMIT = float(2 ** 20)
EPOCH_MARGIN = 1000.0

_FLEET_GAUGES = {
    'n_pools': 'pools currently sampled into the fleet step',
    'mean_load': 'mean busy+spares load across the fleet',
    'mean_filtered': 'mean FIR-filtered load across the fleet',
    'overload_frac': 'fraction of pools with a CoDel drop this tick',
    'max_sojourn': 'worst head-of-queue claim sojourn (ms)',
    'retry_frac': 'fraction of pools with slots in retry backoff',
    'mean_retry_backoff': 'mean reproduced backoff delay (ms)',
}


class FleetSampler:
    """Samples every registered pool into the batched telemetry step.

    Options (all optional):
    - monitor: a PoolMonitor (default: the process-global singleton)
    - interval: tick period in ms (default 200 = the LP cadence)
    - taps: FIR window length (default 128, the pool's own filter)
    - capacity: initial row capacity (default 8; grows by doubling)
    - collector: a metrics Collector to publish cueball_fleet_* gauges
    - record: keep a per-tick history of inputs/outputs (for tests)
    - actuate: push each tick's batched FIR output back into the
      sampled pools (receive_fleet_advisory). Default OFF. A pool
      only *uses* the advisory if it was itself constructed with
      fleetActuation=True — both ends opt in, so turning the sampler
      flag on over a fleet of stock pools changes nothing.
    - mesh: a jax.sharding.Mesh. When given, the fleet arrays live
      sharded over the mesh (same layouts as make_sharded_step) and
      the tick step is the sharded one, so the published aggregates
      compile to all-reduces over ICI. Row capacity rounds up to a
      multiple of the mesh size. The snapshot()/``/kang/fleet``
      surface reports the mesh shape.
    - meshAxes: mesh axis name(s) the pools axis shards over
      (default ('pools',); pass ('host', 'chip') for a 2-D mesh).
    """

    def __init__(self, options: dict | None = None):
        options = options or {}
        self.fs_monitor = options.get('monitor') or default_monitor
        self.fs_interval = options.get('interval') or SAMPLER_INT
        self.fs_taps = options.get('taps') or 128
        self.fs_capacity = options.get('capacity') or 8
        self.fs_collector: 'Collector | None' = options.get('collector')
        self.fs_record = bool(options.get('record'))
        self.fs_actuate = bool(options.get('actuate'))
        self.fs_mesh = options.get('mesh')
        self.fs_mesh_axes = tuple(options.get('meshAxes') or ('pools',))
        if self.fs_mesh is not None:
            # Shard layouts need the pools axis divisible by the mesh;
            # doubling growth preserves any starting multiple.
            n = int(self.fs_mesh.size)
            self.fs_capacity = -(-self.fs_capacity // n) * n
        self.fs_step = None                    # jitted tick step (lazy)
        self.fs_input_shardings = None         # FleetInputs of shardings
        self.fs_input_cache: dict[str, tuple] = {}  # field -> (host, dev)

        self.fs_epoch = mod_utils.current_millis()
        self.fs_rows: dict[str, int] = {}      # pool uuid -> row
        self.fs_row_ticks: dict[int, int] = {}  # row -> ticks since reset
        self.fs_free: list[int] = list(range(self.fs_capacity))
        self.fs_pending_reset: set[int] = set()
        self.fs_state = None                   # FleetState (lazy)
        self.fs_latest: dict | None = None
        self.fs_history: list[dict] = []
        self.fs_ticks = 0
        self.fs_timer = None
        self.fs_emitter = EventEmitter()
        self.fs_emitter.on('timeout', self.sample_once)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Warm up the jitted step (one synchronous tick pays the
        compile) and begin ticking on the loop."""
        if self.fs_timer is not None:
            return
        from ..pool import _Interval
        self.sample_once()
        self.fs_timer = _Interval(self.fs_interval, self.fs_emitter)

    def stop(self) -> None:
        if self.fs_timer is not None:
            self.fs_timer.cancel()
            self.fs_timer = None

    # -- row management --------------------------------------------------

    def _ensure_state(self):
        from .telemetry import (_step_shardings, fleet_init,
                                make_live_step, shard_state)
        if self.fs_state is None:
            self.fs_state = fleet_init(self.fs_capacity, taps=self.fs_taps)
            if self.fs_mesh is not None:
                self.fs_state = shard_state(
                    self.fs_state, self.fs_mesh, self.fs_mesh_axes)
                _, self.fs_input_shardings, _ = _step_shardings(
                    self.fs_mesh, self.fs_mesh_axes)
            # State buffers are donated through the step, so they stay
            # device-resident and get rewritten in place every tick.
            self.fs_step = make_live_step(self.fs_mesh,
                                          self.fs_mesh_axes)
        return self.fs_state

    def _grow(self, need: int) -> None:
        import jax.numpy as jnp
        from ..ops.codel_batch import CodelState
        from .telemetry import FleetState, shard_state
        old = self.fs_capacity
        cap = old
        while cap < need:
            cap *= 2
        st = self._ensure_state()
        pad = cap - old
        self.fs_state = FleetState(
            windows=jnp.pad(st.windows, ((0, pad), (0, 0))),
            codel=CodelState(
                first_above=jnp.pad(st.codel.first_above, (0, pad)),
                drop_next=jnp.pad(st.codel.drop_next, (0, pad)),
                count=jnp.pad(st.codel.count, (0, pad)),
                dropping=jnp.pad(st.codel.dropping, (0, pad))),
            now_ms=st.now_ms)
        if self.fs_mesh is not None:
            self.fs_state = shard_state(
                self.fs_state, self.fs_mesh, self.fs_mesh_axes)
        self.fs_input_cache.clear()   # shapes changed
        self.fs_free.extend(range(old, cap))
        self.fs_capacity = cap

    def _assign_rows(self, pools: dict[str, object]) -> None:
        for uuid in [u for u in self.fs_rows if u not in pools]:
            row = self.fs_rows.pop(uuid)
            self.fs_free.append(row)
        fresh = [u for u in pools if u not in self.fs_rows]
        if len(self.fs_rows) + len(fresh) > self.fs_capacity:
            self._grow(len(self.fs_rows) + len(fresh))
        for uuid in fresh:
            row = self.fs_free.pop(0)
            self.fs_rows[uuid] = row
            self.fs_pending_reset.add(row)
            self.fs_row_ticks[row] = 0

    # -- gathering -------------------------------------------------------

    @staticmethod
    def gather_pool(pool, now: float) -> dict:
        """One pool's tick signals, using the pools' own formulas.

        sample: identical to ConnectionPool._lp_sample (busy + spares
        option). sojourn: first still-waiting claim's queue time.
        retry_*: the deepest backoff slot's ladder position, from which
        the batched law reproduces its current sm_delay."""
        sample = pool.lp_load_sample()

        sojourn = 0.0
        for hdl in pool.p_waiters:
            if hdl.is_in_state('waiting'):
                sojourn = now - hdl.ch_started
                break

        target_delay = math.inf
        if pool.p_codel is not None:
            target_delay = float(pool.p_codel.cd_targdelay)

        n_retrying = 0
        attempt = 0.0
        delay0 = 0.0
        max_delay = 0.0
        for slots in pool.p_connections.values():
            for slot in slots:
                smgr = slot.get_socket_mgr()
                if not smgr.is_in_state('backoff'):
                    continue
                if not math.isfinite(smgr.sm_retries):
                    continue  # monitor slots: pinned, not a ladder
                n_retrying += 1
                a = float(smgr.sm_retries - smgr.sm_retries_left)
                if a >= attempt:
                    attempt = a
                    delay0 = float(smgr.sm_min_delay)
                    max_delay = float(smgr.sm_max_delay)
        return {
            'sample': float(sample), 'sojourn': float(sojourn),
            'target_delay': target_delay,
            'spares': float(pool.p_spares),
            'maximum': float(pool.p_max),
            'retry_delay': delay0, 'retry_max_delay': max_delay,
            'retry_attempt': attempt, 'n_retrying': float(n_retrying),
        }

    def _place_inputs(self, arrays: dict, now: float):
        """Host tick columns -> device FleetInputs, re-shipping only
        the fields whose values changed since the previous tick.

        Most per-pool fields are static between ticks (spares, maximum,
        CoDel targets, the retry ladder when nothing is failing); over
        a tunneled chip every avoided host->device transfer is an RTT
        saved, so unchanged columns reuse their committed device array
        from the last tick. The scalar clock always changes and always
        ships."""
        import jax
        import numpy as np
        from .telemetry import FleetInputs
        placed = {}
        for name, host in arrays.items():
            cached = self.fs_input_cache.get(name)
            if cached is not None and np.array_equal(cached[0], host):
                placed[name] = cached[1]
                continue
            if self.fs_input_shardings is not None:
                dev = jax.device_put(
                    host, getattr(self.fs_input_shardings, name))
            else:
                dev = jax.device_put(host)
            self.fs_input_cache[name] = (host, dev)
            placed[name] = dev
        return FleetInputs(now_ms=np.float32(now), **placed)

    def sample_once(self) -> dict | None:
        """One synchronous tick: gather, step, publish. Returns the
        published record (None when sampling is impossible)."""
        import numpy as np

        pools = dict(self.fs_monitor.pm_pools)
        self._assign_rows(pools)
        abs_now = mod_utils.current_millis()
        now = abs_now - self.fs_epoch
        if now > EPOCH_LIMIT:
            from .telemetry import rebase_state
            shift = now - EPOCH_MARGIN
            self.fs_state = rebase_state(self._ensure_state(), shift)
            self.fs_epoch += shift
            now -= shift
        cap = self.fs_capacity

        f32 = lambda: np.zeros((cap,), np.float32)  # noqa: E731
        cols = {k: f32() for k in (
            'samples', 'sojourns', 'spares', 'maximum', 'retry_delay',
            'retry_max_delay', 'retry_attempt', 'n_retrying')}
        cols['target_delay'] = np.full((cap,), np.inf, np.float32)
        active = np.zeros((cap,), bool)
        reset = np.zeros((cap,), bool)
        for row in self.fs_pending_reset:
            reset[row] = True
        self.fs_pending_reset.clear()

        gathered = {}
        for uuid, pool in pools.items():
            row = self.fs_rows[uuid]
            g = self.gather_pool(pool, abs_now)
            gathered[uuid] = (row, g)
            active[row] = True
            cols['samples'][row] = g['sample']
            cols['sojourns'][row] = g['sojourn']
            cols['target_delay'][row] = g['target_delay']
            cols['spares'][row] = g['spares']
            cols['maximum'][row] = g['maximum']
            cols['retry_delay'][row] = g['retry_delay']
            cols['retry_max_delay'][row] = g['retry_max_delay']
            cols['retry_attempt'][row] = g['retry_attempt']
            cols['n_retrying'][row] = g['n_retrying']

        state = self._ensure_state()
        inp = self._place_inputs(
            dict(active=active, reset=reset, **cols), now)
        try:
            new_state, out, fleet = self.fs_step(state, inp)
        except Exception:
            # Donation marks the carried buffers deleted at dispatch,
            # BEFORE a runtime failure surfaces — retrying against
            # them would raise "Array has been deleted" on every tick
            # forever. Recover like a sampler restart: drop the state
            # (re-init next tick), flag every occupied row for reset,
            # and restart the actuation warm-up gates; then let the
            # error propagate to the timer's handler.
            self.fs_state = None
            self.fs_input_cache.clear()
            for row in self.fs_rows.values():
                self.fs_pending_reset.add(row)
                self.fs_row_ticks[row] = 0
            raise
        self.fs_state = new_state
        self.fs_ticks += 1

        fleet_np = {k: float(v) for k, v in fleet.items()}
        out_np = {k: np.asarray(v) for k, v in out.items()}
        per_pool = {}
        for uuid, (row, g) in gathered.items():
            # target_delay=inf means "CoDel off" in the arrays; publish
            # None instead (Infinity is not valid JSON and the kang
            # surface is read by strict external parsers).
            pub = dict(g)
            if not math.isfinite(pub['target_delay']):
                pub['target_delay'] = None
            per_pool[uuid] = {
                'row': row,
                'inputs': pub,
                'filtered': float(out_np['filtered'][row]),
                'target': float(out_np['target'][row]),
                'clamped': bool(out_np['clamped'][row]),
                'drop': bool(out_np['drop'][row]),
                'retry_backoff': float(out_np['retry_backoff'][row]),
            }
        if self.fs_actuate:
            # Close the loop: hand each pool its batched decision.
            # The pool stores it unconditionally but consults it only
            # under its own fleetActuation flag (+freshness TTL).
            # Warm-up gate: a row's filter starts zeroed on (re)assign,
            # so for the first `taps` ticks its output under-reads the
            # history the pool's own converged filter still holds —
            # pushing it would collapse the shrink clamp after a
            # sampler restart. Only a fully-populated window (which by
            # the parity laws equals the per-pool filter fed the same
            # samples) is advisory-grade.
            for uuid, (row, g) in gathered.items():
                ticks = self.fs_row_ticks.get(row, 0) + 1
                self.fs_row_ticks[row] = ticks
                if ticks < self.fs_taps:
                    continue
                receive = getattr(pools[uuid],
                                  'receive_fleet_advisory', None)
                if receive is not None:
                    receive(float(out_np['filtered'][row]), abs_now)

        record = {'tick': self.fs_ticks, 'now_ms': now,
                  'fleet': fleet_np, 'pools': per_pool}
        self.fs_latest = record
        if self.fs_record:
            self.fs_history.append(record)
        if self.fs_collector is not None:
            for name, help_ in _FLEET_GAUGES.items():
                self.fs_collector.gauge(
                    'cueball_fleet_' + name, help_).set(fleet_np[name])
        return record

    # -- kang integration ------------------------------------------------

    def snapshot(self) -> dict:
        mesh = None
        if self.fs_mesh is not None:
            mesh = {
                'axes': list(self.fs_mesh_axes),
                'shape': {str(k): int(v) for k, v in zip(
                    self.fs_mesh.axis_names,
                    self.fs_mesh.devices.shape)},
                'n_devices': int(self.fs_mesh.size),
            }
        return {
            'interval_ms': self.fs_interval,
            'capacity': self.fs_capacity,
            'ticks': self.fs_ticks,
            'rows': dict(self.fs_rows),
            'actuate': self.fs_actuate,
            'mesh': mesh,
            'row_ticks': dict(self.fs_row_ticks),
            'latest': self.fs_latest,
        }

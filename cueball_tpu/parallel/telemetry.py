"""Fleet telemetry step: the framework's control laws, batched + sharded.

One step consumes, for every pool in a fleet:
- a load sample (busy + spares, what the 5 Hz LP timer feeds per pool,
  reference lib/pool.js:251-262)
- the current claim-queue sojourn (ms)

and produces, per pool:
- the FIR-filtered load (128-tap EMA, reference lib/pool.js:44-100)
- the clamped rebalance target (reference lib/pool.js:573-592)
- the CoDel drop decision (reference lib/codel.js)

plus fleet-wide aggregates (mean load, overload fraction) that become
XLA all-reduces when the pools axis is sharded over a Mesh.
"""

from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.codel_batch import CodelState, codel_init, _step as codel_step
from ..ops.fir import fir_apply, gen_taps


class FleetState(typing.NamedTuple):
    windows: jax.Array      # [pools, taps] load sample ring (old->new)
    codel: CodelState       # [pools] CoDel control state
    now_ms: jax.Array       # scalar clock


def fleet_init(n_pools: int, taps: int = 128) -> FleetState:
    return FleetState(
        windows=jnp.zeros((n_pools, taps), jnp.float32),
        codel=codel_init(n_pools),
        now_ms=jnp.float32(0.0))


@functools.partial(jax.jit, static_argnames=('spares', 'maximum'))
def fleet_step(state: FleetState, samples: jax.Array,
               sojourns: jax.Array, target_delay: jax.Array,
               spares: int = 4, maximum: int = 16):
    """One telemetry tick for the whole fleet.

    samples: [pools] current busy+spares load; sojourns: [pools] claim
    sojourn ms; target_delay: [pools] per-pool CoDel target ms.
    """
    taps = gen_taps(state.windows.shape[1])

    windows = jnp.concatenate(
        [state.windows[:, 1:], samples[:, None]], axis=1)
    filtered = fir_apply(windows, taps)

    # Rebalance target with LP clamp (reference lib/pool.js:573-592):
    # shrink no faster than the filtered recent load allows.
    raw_target = samples + spares
    lp_min = jnp.ceil(filtered)
    clamped = raw_target < lp_min * 1.05
    target = jnp.where(clamped, lp_min, raw_target)
    target = jnp.minimum(target, maximum)

    now = state.now_ms + 200.0  # 5 Hz tick
    codel_state, drops = codel_step(
        target_delay, state.codel, (now, sojourns))

    # Fleet aggregates: all-reduces over the sharded pools axis.
    fleet = {
        'mean_load': jnp.mean(samples),
        'mean_filtered': jnp.mean(filtered),
        'overload_frac': jnp.mean(drops.astype(jnp.float32)),
        'max_sojourn': jnp.max(sojourns),
    }

    new_state = FleetState(windows=windows, codel=codel_state,
                           now_ms=now)
    out = {'filtered': filtered, 'target': target,
           'clamped': clamped, 'drop': drops}
    return new_state, out, fleet


def make_sharded_step(mesh: Mesh, spares: int = 4, maximum: int = 16):
    """Build a jitted step with every [pools, ...] array sharded over
    the mesh's 'pools' axis. The per-pool math is embarrassingly
    parallel (no resharding); the fleet aggregates compile to psum-style
    all-reduces over ICI."""
    pool_sharding = NamedSharding(mesh, P('pools'))
    window_sharding = NamedSharding(mesh, P('pools', None))
    scalar = NamedSharding(mesh, P())

    state_shardings = FleetState(
        windows=window_sharding,
        codel=CodelState(pool_sharding, pool_sharding, pool_sharding,
                         pool_sharding),
        now_ms=scalar)
    out_shardings = (
        state_shardings,
        {'filtered': pool_sharding, 'target': pool_sharding,
         'clamped': pool_sharding, 'drop': pool_sharding},
        {'mean_load': scalar, 'mean_filtered': scalar,
         'overload_frac': scalar, 'max_sojourn': scalar})

    return jax.jit(
        functools.partial(fleet_step, spares=spares, maximum=maximum),
        in_shardings=(state_shardings, pool_sharding, pool_sharding,
                      pool_sharding),
        out_shardings=out_shardings)


def shard_state(state: FleetState, mesh: Mesh) -> FleetState:
    pool_sharding = NamedSharding(mesh, P('pools'))
    window_sharding = NamedSharding(mesh, P('pools', None))
    scalar = NamedSharding(mesh, P())
    return FleetState(
        windows=jax.device_put(state.windows, window_sharding),
        codel=CodelState(
            *[jax.device_put(x, pool_sharding) for x in state.codel]),
        now_ms=jax.device_put(state.now_ms, scalar))

"""Fleet telemetry step: the framework's control laws, batched + sharded.

One step consumes, for every pool in a fleet (gathered live by
:class:`cueball_tpu.parallel.sampler.FleetSampler` from the process-global
pool monitor), the same signals each pool's own Python control laws see:

- a load sample (busy + spares, what the 5 Hz LP timer feeds per pool,
  reference lib/pool.js:251-262)
- the head-of-queue claim sojourn (ms) and CoDel target
- the deepest retry-backoff position among the pool's slots
  (reference lib/connection-fsm.js:361-394)
- the pool's own spares / maximum settings

and produces, per pool:

- the FIR-filtered load (128-tap EMA, reference lib/pool.js:44-100)
- the clamped rebalance target (reference lib/pool.js:573-592)
- the CoDel drop decision (reference lib/codel.js)
- the reproduced backoff delay (reference lib/connection-fsm.js:372-380)

plus fleet-wide aggregates (mean load, overload fraction, retry
pressure) that become XLA all-reduces when the pools axis is sharded
over a Mesh. Rows are a fixed-capacity [P] axis so jit traces once per
capacity; `active` masks unoccupied rows out of the aggregates and
`reset` clears carried state when a row is reassigned to a new pool.
"""

from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.backoff import backoff_at
from ..ops.codel_batch import CodelState, codel_init, _step as codel_step
from ..ops.fir import fir_apply, fir_apply_pallas, gen_taps


class FleetState(typing.NamedTuple):
    windows: jax.Array      # [pools, taps] load sample ring (old->new)
    codel: CodelState       # [pools] CoDel control state
    now_ms: jax.Array       # scalar clock of the last step


class FleetInputs(typing.NamedTuple):
    """One tick's worth of per-pool samples (all [P] unless noted)."""
    samples: jax.Array          # busy + spares load sample
    sojourns: jax.Array         # head-of-claim-queue sojourn (ms)
    target_delay: jax.Array     # CoDel target (ms); +inf = CoDel off
    spares: jax.Array           # pool `spares` option
    maximum: jax.Array          # pool `maximum` option
    retry_delay: jax.Array      # base recovery delay of deepest slot
    retry_max_delay: jax.Array  # its maxDelay cap
    retry_attempt: jax.Array    # its backoff-entry count
    n_retrying: jax.Array       # slots currently in backoff
    active: jax.Array           # bool: row occupied by a live pool
    reset: jax.Array            # bool: row newly (re)assigned
    now_ms: jax.Array           # scalar monotonic clock (ms)


def fleet_init(n_pools: int, taps: int = 128) -> FleetState:
    return FleetState(
        windows=jnp.zeros((n_pools, taps), jnp.float32),
        codel=codel_init(n_pools),
        now_ms=jnp.float32(0.0))


def fleet_inputs(n_pools: int, **kw) -> FleetInputs:
    """A FleetInputs of idle defaults (inactive rows, CoDel off);
    override any field by keyword."""
    z = jnp.zeros((n_pools,), jnp.float32)
    vals = dict(
        samples=z, sojourns=z,
        target_delay=jnp.full((n_pools,), jnp.inf, jnp.float32),
        spares=z, maximum=jnp.full((n_pools,), 16.0, jnp.float32),
        retry_delay=z, retry_max_delay=z, retry_attempt=z,
        n_retrying=z,
        active=jnp.zeros((n_pools,), bool),
        reset=jnp.zeros((n_pools,), bool),
        now_ms=jnp.float32(0.0))
    vals.update(kw)
    return FleetInputs(**{k: jnp.asarray(v) for k, v in vals.items()})


def _default_fir():
    """FIR implementation for this backend: the pallas kernel on TPU,
    the XLA einsum elsewhere (pallas would only run in interpret mode
    off-TPU). The on-TPU preference rests on a round-4 capture
    (archived BENCH_TPU_r04.json, 1.29x the einsum on v5 lite) that
    predates the code-hash guard — unverified against the current
    measured path until tools/chip_bench.py re-captures with a hash;
    bench.py re-measures both paths on every chip run."""
    return fir_apply_pallas if jax.default_backend() == 'tpu' \
        else fir_apply


def _local_step(state: FleetState, inp: FleetInputs, fir_fn=None):
    """Per-pool control laws — embarrassingly parallel over the pools
    axis (identical whether run on full arrays or one shard)."""
    if fir_fn is None:
        fir_fn = _default_fir()
    rst = inp.reset
    windows = jnp.where(rst[:, None], 0.0, state.windows)
    codel0 = CodelState(
        first_above=jnp.where(rst, 0.0, state.codel.first_above),
        drop_next=jnp.where(rst, 0.0, state.codel.drop_next),
        count=jnp.where(rst, 0.0, state.codel.count),
        dropping=jnp.where(rst, False, state.codel.dropping))

    taps = gen_taps(windows.shape[1])
    windows = jnp.concatenate(
        [windows[:, 1:], inp.samples[:, None]], axis=1)
    filtered = fir_fn(windows, taps)

    # Rebalance target with LP clamp (reference lib/pool.js:573-592):
    # shrink no faster than the filtered recent load allows.
    raw_target = inp.samples + inp.spares
    lp_min = jnp.ceil(filtered)
    clamped = raw_target < lp_min * 1.05
    target = jnp.where(clamped, lp_min, raw_target)
    target = jnp.minimum(target, inp.maximum)

    codel_state, drops = codel_step(
        inp.target_delay, codel0, (inp.now_ms, inp.sojourns))

    # Reproduced per-pool backoff delay of the deepest retrying slot
    # (reference lib/connection-fsm.js:372-380 double-and-cap ladder).
    has_retry = inp.n_retrying > 0
    retry_backoff = jnp.where(
        has_retry,
        backoff_at(inp.retry_delay, inp.retry_max_delay,
                   inp.retry_attempt),
        0.0)

    new_state = FleetState(windows=windows, codel=codel_state,
                           now_ms=inp.now_ms)
    out = {'filtered': filtered, 'target': target,
           'clamped': clamped, 'drop': drops,
           'retry_backoff': retry_backoff}
    return new_state, out


def _partial_sums(inp: FleetInputs, out: dict) -> dict:
    """Shard-local reduction terms for the fleet aggregates, masked to
    occupied rows. Combined across shards by sum (psum) except
    'max_sojourn' (pmax)."""
    act = inp.active.astype(jnp.float32)
    retrying = (inp.n_retrying > 0).astype(jnp.float32) * act
    return {
        'n': jnp.sum(act),
        'load': jnp.sum(inp.samples * act),
        'filtered': jnp.sum(out['filtered'] * act),
        'drops': jnp.sum(out['drop'].astype(jnp.float32) * act),
        'n_retry': jnp.sum(retrying),
        'backoff': jnp.sum(out['retry_backoff'] * retrying),
        'max_sojourn': jnp.max(
            jnp.where(inp.active, inp.sojourns, 0.0)),
    }


def _finalize(p: dict) -> dict:
    n = jnp.maximum(p['n'], 1.0)
    n_retry = jnp.maximum(p['n_retry'], 1.0)
    return {
        'n_pools': p['n'],
        'mean_load': p['load'] / n,
        'mean_filtered': p['filtered'] / n,
        'overload_frac': p['drops'] / n,
        'max_sojourn': p['max_sojourn'],
        'retry_frac': p['n_retry'] / n,
        'mean_retry_backoff': p['backoff'] / n_retry,
    }


def _make_step(fir_fn=None):
    """One body for all three fleet_step variants — they differ only in
    which FIR implementation _local_step uses."""
    @jax.jit
    def step(state: FleetState, inp: FleetInputs):
        new_state, out = _local_step(state, inp, fir_fn=fir_fn)
        fleet = _finalize(_partial_sums(inp, out))
        return new_state, out, fleet
    return step


#: One telemetry tick for the whole fleet (single-device or GSPMD).
#: Returns (new_state, per_pool_outputs, fleet_aggregates). FIR path is
#: backend-adaptive (_default_fir).
fleet_step = _make_step()

#: fleet_step with the FIR matvec forced onto the XLA einsum path;
#: benchmarked head-to-head against fleet_step_pallas by bench.py so
#: the adaptive default stays evidence-based.
fleet_step_xla = _make_step(fir_apply)


#: fleet_step with the FIR matvec forced onto the hand-written pallas
#: kernel (interpret mode off-TPU).
fleet_step_pallas = _make_step(fir_apply_pallas)


@jax.jit
def fleet_scan(state: FleetState, inputs: FleetInputs):
    """Run fleet_step over a whole time-window in ONE compiled call:
    `inputs` is a FleetInputs whose arrays carry a leading time axis
    ([T, P]; now_ms is [T]). Returns (final_state, per_pool_outputs
    stacked [T, P], fleet aggregates stacked [T]).

    Semantically identical to T sequential fleet_step calls (asserted
    by tests/test_ops.py) but the loop is a lax.scan, so offline
    replay/what-if analysis of recorded telemetry pays one dispatch
    for the whole window instead of one per tick (bench.py measures
    the difference as telemetry_pools_per_sec_scan)."""
    def body(carry, inp):
        new_state, out = _local_step(carry, inp)
        fleet = _finalize(_partial_sums(inp, out))
        return new_state, (out, fleet)

    final_state, (outs, fleets) = jax.lax.scan(body, state, inputs)
    return final_state, outs, fleets


@jax.jit
def rebase_state(state: FleetState, shift) -> FleetState:
    """Shift the CoDel timestamp clocks back by `shift` ms.

    The batched step keeps time in float32; feeding it an absolute
    monotonic clock (~1e9 ms on a long-lived host) would round to
    ~64 ms — worse than the 100 ms CoDel control interval. The sampler
    therefore runs an epoch-relative clock and periodically rebases the
    carried state. Timestamps older than the shift clamp to 1 ms, which
    preserves both CoDel uses of an old timestamp (`now >= t` and
    `now - t >= INTERVAL`) as long as the post-rebase `now` stays above
    INTERVAL + 1 — the sampler rebases with a 1 s margin. The 0
    sentinel ("unset") is preserved exactly."""
    shift = jnp.float32(shift)
    fa = state.codel.first_above
    dn = state.codel.drop_next
    return FleetState(
        windows=state.windows,
        codel=CodelState(
            first_above=jnp.where(
                fa > 0.0, jnp.maximum(fa - shift, 1.0), 0.0),
            drop_next=jnp.where(
                dn > 0.0, jnp.maximum(dn - shift, 1.0), dn),
            count=state.codel.count,
            dropping=state.codel.dropping),
        now_ms=jnp.maximum(state.now_ms - shift, 0.0))


# The ONE enumeration of how fleet data shards over the mesh; every
# sharded entry point below derives from these three, so a new
# FleetInputs/output field is placed in exactly one spot. `axes` names
# the mesh axes the pools dimension shards over: ('pools',) on a flat
# ICI mesh, ('host', 'chip') on a multi-host topology where the outer
# axis crosses DCN and the inner one rides ICI.

def _step_shardings(mesh: Mesh, axes: tuple = ('pools',)):
    """(state, inputs, (state, per-pool outs, aggregates)) shardings
    for one fleet_step tick."""
    pool = NamedSharding(mesh, P(axes))
    scalar = NamedSharding(mesh, P())
    state = FleetState(
        windows=NamedSharding(mesh, P(axes, None)),
        codel=CodelState(pool, pool, pool, pool),
        now_ms=scalar)
    inputs = FleetInputs(
        *([pool] * (len(FleetInputs._fields) - 1)), now_ms=scalar)
    outs = (
        state,
        {'filtered': pool, 'target': pool, 'clamped': pool,
         'drop': pool, 'retry_backoff': pool},
        {'n_pools': scalar, 'mean_load': scalar, 'mean_filtered': scalar,
         'overload_frac': scalar, 'max_sojourn': scalar,
         'retry_frac': scalar, 'mean_retry_backoff': scalar})
    return state, inputs, outs


def _prepend_time_axis(sharding: NamedSharding, mesh: Mesh):
    """Per-tick sharding -> whole-window sharding: a leading replicated
    [T] axis in front of whatever the tick layout was."""
    return NamedSharding(mesh, P(*((None,) + tuple(sharding.spec))))


def make_sharded_step(mesh: Mesh, axes: tuple = ('pools',)):
    """Build a jitted step with every [pools, ...] array sharded over
    the given mesh axes. The per-pool math is embarrassingly parallel
    (no resharding); the fleet aggregates compile to psum-style
    all-reduces — over ICI on a flat mesh, hierarchically (ICI within
    a host, DCN across hosts) on a 2-D ('host', 'chip') mesh."""
    state_shardings, input_shardings, out_shardings = \
        _step_shardings(mesh, axes)
    return jax.jit(fleet_step,
                   in_shardings=(state_shardings, input_shardings),
                   out_shardings=out_shardings)


@functools.lru_cache(maxsize=None)
def make_live_step(mesh: Mesh | None = None, axes: tuple = ('pools',)):
    """The FleetSampler's per-tick step: fleet_step with the carried
    FleetState buffers DONATED. The sampler always replaces its state
    with the returned one, so donating lets XLA write the new
    [P, taps] window ring and CoDel state into the old buffers in
    place — per tick this halves the state's HBM allocation traffic
    and removes the alloc/free churn a 200 ms cadence would otherwise
    sustain forever. With a mesh, every [pools] array additionally
    gets the same shardings as :func:`make_sharded_step`, so one live
    fleet spans all the mesh's chips and the published aggregates
    compile to all-reduces.

    Do NOT reuse a FleetState after passing it here — donation
    invalidates its buffers (jax raises on any later read).

    Memoized per (mesh, axes): every sampler in a process shares one
    compiled program instead of paying its own trace+compile."""
    if mesh is None:
        return jax.jit(fleet_step, donate_argnums=0)
    state_shardings, input_shardings, out_shardings = \
        _step_shardings(mesh, axes)
    return jax.jit(fleet_step,
                   in_shardings=(state_shardings, input_shardings),
                   out_shardings=out_shardings,
                   donate_argnums=0)


def make_sharded_scan(mesh: Mesh, axes: tuple = ('pools',)):
    """fleet_scan with the pools axis sharded over the mesh INSIDE the
    scan: each device carries its pool shard through all T ticks, so a
    whole recorded window replays data-parallel with the per-tick fleet
    aggregates still reducing over ICI (hierarchically on a 2-D
    ('host', 'chip') mesh). The dryrun asserts it matches the
    unsharded scan."""
    state_shardings, window_shardings, scan_out = \
        _scan_shardings(mesh, axes)
    return jax.jit(fleet_scan,
                   in_shardings=(state_shardings, window_shardings),
                   out_shardings=scan_out)


def _scan_shardings(mesh: Mesh, axes: tuple = ('pools',)):
    """Derive the [T, ...] window shardings from the per-tick specs."""
    state, inputs, (_, outs, fleet) = _step_shardings(mesh, axes)
    prepend = functools.partial(_prepend_time_axis, mesh=mesh)
    window = jax.tree.map(prepend, inputs)
    # Final carried state has no time axis; stacked outs/fleet do.
    return state, window, (state, jax.tree.map(prepend, outs),
                           jax.tree.map(prepend, fleet))


def shard_window(window: FleetInputs, mesh: Mesh,
                 axes: tuple = ('pools',)) -> FleetInputs:
    """Place a [T, P] tick window onto the mesh (pools axis sharded)."""
    _, window_shardings, _ = _scan_shardings(mesh, axes)
    return jax.tree.map(jax.device_put, window, window_shardings)


def make_shardmap_step(mesh: Mesh, axes: tuple = ('pools',)):
    """The SPMD form of :func:`fleet_step`: shard_map over the given
    mesh axes with hand-written collectives — per-pool laws run on the
    local shard, fleet aggregates are jax.lax.psum / pmax.

    On a flat ('pools',) mesh the reduction is one all-reduce over
    ICI. On a 2-D ('host', 'chip') mesh the reduction is staged
    innermost-first — reduce over 'chip' (ICI, within a host), then
    over 'host' (DCN) — the canonical hierarchical all-reduce for
    multi-host topologies.

    Semantically identical to fleet_step; the multichip dryrun asserts
    so (a wrong collective here genuinely fails the allclose, unlike
    GSPMD annotations which XLA always resolves to correct programs)."""
    try:
        from jax import shard_map              # jax >= 0.8
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    pool = P(axes)
    window = P(axes, None)
    scalar = P()

    def _reduce(v, op):
        # Innermost mesh axis first (ICI), outermost last (DCN).
        for ax in reversed(axes):
            v = op(v, ax)
        return v

    state_specs = FleetState(
        windows=window,
        codel=CodelState(pool, pool, pool, pool),
        now_ms=scalar)
    input_specs = FleetInputs(
        samples=pool, sojourns=pool, target_delay=pool, spares=pool,
        maximum=pool, retry_delay=pool, retry_max_delay=pool,
        retry_attempt=pool, n_retrying=pool, active=pool, reset=pool,
        now_ms=scalar)
    out_specs = (
        state_specs,
        {'filtered': pool, 'target': pool, 'clamped': pool,
         'drop': pool, 'retry_backoff': pool},
        {'n_pools': scalar, 'mean_load': scalar, 'mean_filtered': scalar,
         'overload_frac': scalar, 'max_sojourn': scalar,
         'retry_frac': scalar, 'mean_retry_backoff': scalar})

    def local(state, inp):
        new_state, out = _local_step(state, inp)
        p = _partial_sums(inp, out)
        p = {k: (_reduce(v, jax.lax.pmax) if k == 'max_sojourn'
                 else _reduce(v, jax.lax.psum))
             for k, v in p.items()}
        return new_state, out, _finalize(p)

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(state_specs, input_specs),
        out_specs=out_specs))


def shard_state(state: FleetState, mesh: Mesh,
                axes: tuple = ('pools',)) -> FleetState:
    state_shardings, _, _ = _step_shardings(mesh, axes)
    return jax.tree.map(jax.device_put, state, state_shardings)


def shard_inputs(inp: FleetInputs, mesh: Mesh,
                 axes: tuple = ('pools',)) -> FleetInputs:
    _, input_shardings, _ = _step_shardings(mesh, axes)
    return jax.tree.map(jax.device_put, inp, input_shardings)


def fold_backend_slots(cols: dict, rows: int) -> dict:
    """Fold drained per-backend slot columns into step-shaped arrays.

    ``cols`` is a BackendTable drain (parallel.health): host numpy
    columns indexed by backend row — rank-1 latency/error/shed
    accumulators and rank-2 ``*_buckets`` sketches. The backend axis
    pads out to ``rows`` (the health step's power-of-two,
    mesh-multiple capacity); padding rows are all-zero and inactive,
    so they drop out of every judged reduction. The bucket axis of
    rank-2 columns is fixed geometry and never pads."""
    import numpy as np
    out = {}
    for name, col in cols.items():
        pad = rows - len(col)
        if col.ndim == 1:
            out[name] = np.pad(col, (0, pad))
        else:
            out[name] = np.pad(col, ((0, pad), (0, 0)))
    return out

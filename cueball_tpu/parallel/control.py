"""Fleet control plane: one fused, sharded step that DECIDES.

parallel.telemetry batches the framework's control laws but only
*observes*: its outputs feed gauges and (opt-in) the rebalance shrink
clamp. This module closes the loop. One jitted step consumes the
telemetry columns already resident on device — the FleetInputs the
sampler placed for the telemetry tick plus the telemetry step's own
``filtered`` output, so at steady state the control step does zero
extra host->device copies — and emits *decision columns*:

- ``codel_target`` [P] f32: per-pool CoDel target adaptation (AIMD:
  multiplicative tighten while the pool's head sojourn sits above its
  plan, additive relax back toward the operator-configured target when
  the fleet is quiet; 0.0 = no decision for that row);
- ``plan_spares`` [P] i32: spares resize plan (one spare boosted under
  fleet-wide pressure, shed again when idle and the filtered load sits
  well below the setting);
- ``plan_target`` [P] i32: the batched rebalance target-size plan (the
  same LP-clamped law as telemetry._local_step, rounded);
- ``delta`` [P] i32: backend rebalance delta, ``plan_target`` minus
  the pool's current raw target — what the owning shard should add
  (+) or may shed (-);
- ``epoch`` scalar i32: the decision epoch, stamped into every apply
  so stale columns can be rejected downstream.

Sharding follows the HiCCL-style hierarchical decomposition the
telemetry step established, but the layout here is derived from
*regex partition rules* (:func:`match_partition_rules`, after the
pjit partition-rule idiom): one rule table names which leaves are
replicated scalars and which shard over the pools axis, and every
entry point — GSPMD jit, shard_map, host placement — derives from it.
On a 2-D ('host', 'chip') mesh the shard_map form reduces
innermost-first (chip/ICI, then host/DCN).

Bit-exact meshed-vs-plain decisions: every cross-pool reduction that
FEEDS a decision is an int32 sum (active count, over-target count) or
an f32 max — both order-independent — so the decision columns from the
sharded step match the plain step bit for bit (tests/test_control.py
soaks this at 100k rows). The published ``mean_load`` aggregate is a
float sum and carries no such guarantee; it feeds gauges only.

The carried :class:`ControlState` is donated through
:func:`make_control_step`, so the adapted-target column is rewritten
in place on device every step (double buffering handled by XLA).
Actuation is host-side and batched: :func:`apply_decisions` walks the
sampler's row->pool map and hands each pool its decision through
``ConnectionPool.apply_control_decision`` — a guarded API that
validates epoch and ranges BEFORE touching anything, marks the
telemetry row dirty via the same TelemetryRowHandle hooks every other
signal uses, and never touches pool FSM state on rejection.
"""

from __future__ import annotations

import functools
import re
import typing

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..codel import CODEL_TARGET_MAX, CODEL_TARGET_MIN

__all__ = ['ControlInputs', 'ControlState', 'apply_decisions',
           'control_init', 'control_inputs', 'control_shardings',
           'control_specs', 'control_step', 'make_control_step',
           'make_shardmap_control_step', 'match_partition_rules',
           'partition_rules', 'reduce_control', 'shard_control_inputs',
           'shard_control_state']

#: AIMD law constants. Tighten is multiplicative (x0.875 per over-target
#: step, the classic fast back-off), relax is additive (+1 ms per quiet
#: step) and capped at the pool's own configured target — the control
#: plane only ever tightens CoDel relative to what the operator set.
TIGHTEN_MULT = 0.875
RELAX_STEP_MS = 1.0
#: Fleet overload-fraction thresholds: above HOT the plan boosts one
#: spare on over-target pools; below IDLE targets relax and an unused
#: spare is shed.
PRESSURE_HOT = 0.25
PRESSURE_IDLE = 0.05


class ControlState(typing.NamedTuple):
    """Carried (donated) control-plane state."""
    targets: jax.Array     # [P] adapted CoDel target (ms; 0 = none)
    epoch: jax.Array       # scalar i32 decision epoch
    now_ms: jax.Array      # scalar f32 clock of the last step


class ControlInputs(typing.NamedTuple):
    """One control tick's inputs (all [P] f32/bool except now_ms).

    Deliberately a subset of the telemetry tick's device arrays plus
    its ``filtered`` output: the sampler hands these over without any
    further host->device transfer."""
    samples: jax.Array         # busy + spares load sample
    sojourns: jax.Array        # head-of-claim-queue sojourn (ms)
    filtered: jax.Array        # FIR-filtered load (telemetry output)
    target_delay: jax.Array    # configured CoDel target (+inf = off)
    spares: jax.Array          # pool `spares` option
    maximum: jax.Array         # pool `maximum` option
    active: jax.Array          # bool: row occupied
    reset: jax.Array           # bool: row newly (re)assigned
    now_ms: jax.Array          # scalar clock (ms)


def control_init(n_pools: int, epoch: int = 0) -> ControlState:
    return ControlState(
        targets=jnp.zeros((n_pools,), jnp.float32),
        epoch=jnp.int32(epoch),
        now_ms=jnp.float32(0.0))


def control_inputs(n_pools: int, **kw) -> ControlInputs:
    """A ControlInputs of idle defaults; override fields by keyword."""
    z = jnp.zeros((n_pools,), jnp.float32)
    vals = dict(
        samples=z, sojourns=z, filtered=z,
        target_delay=jnp.full((n_pools,), jnp.inf, jnp.float32),
        spares=z, maximum=jnp.full((n_pools,), 16.0, jnp.float32),
        active=jnp.zeros((n_pools,), bool),
        reset=jnp.zeros((n_pools,), bool),
        now_ms=jnp.float32(0.0))
    vals.update(kw)
    return ControlInputs(**{k: jnp.asarray(v) for k, v in vals.items()})


# -- the law ----------------------------------------------------------------

def _plan_local(state: ControlState, inp: ControlInputs):
    """Per-pool pre-reduction work: resolve the carried adapted target
    and flag over-target rows. Elementwise, so identical on a shard."""
    base = jnp.where(
        jnp.isfinite(inp.target_delay)
        & (inp.target_delay >= CODEL_TARGET_MIN),
        jnp.minimum(inp.target_delay, CODEL_TARGET_MAX), 0.0)
    has_codel = base > 0.0
    cur = jnp.where(inp.reset | (state.targets <= 0.0),
                    base, state.targets)
    cur = jnp.where(has_codel, cur, 0.0)
    over = inp.active & has_codel & (inp.sojourns > cur)
    return base, cur, over


def _control_sums(inp: ControlInputs, over) -> dict:
    """Shard-local reduction terms. Everything a DECISION depends on is
    an int32 sum or a max, so the cross-shard combine is bit-exact
    regardless of reduction order; 'load' (float) feeds gauges only."""
    act = inp.active
    return {
        'n': jnp.sum(act.astype(jnp.int32)),
        'n_over': jnp.sum(over.astype(jnp.int32)),
        'load': jnp.sum(jnp.where(act, inp.samples, 0.0)),
        'max_sojourn': jnp.max(jnp.where(act, inp.sojourns, 0.0)),
    }


def _decide(state: ControlState, inp: ControlInputs,
            base, cur, over, sums: dict):
    """Post-reduction elementwise decisions. `sums` holds the fleet
    totals (already combined across shards in the sharded forms)."""
    n = jnp.maximum(sums['n'], 1)
    pressure = sums['n_over'].astype(jnp.float32) / n.astype(jnp.float32)
    quiet = pressure < PRESSURE_IDLE
    has_codel = base > 0.0

    # CoDel target AIMD, quantized to integer ms so reduction noise
    # can never flip a decision: tighten while over, relax when this
    # pool is below target AND the fleet as a whole is quiet.
    tighten = over
    relax = inp.active & has_codel & ~over & quiet
    t = jnp.where(tighten, jnp.floor(cur * TIGHTEN_MULT), cur)
    t = jnp.where(relax, t + RELAX_STEP_MS, t)
    t = jnp.clip(jnp.round(t), CODEL_TARGET_MIN, base)
    t = jnp.where(inp.active & has_codel, t, 0.0)

    # Resize plans. plan_target is the telemetry rebalance law
    # (LP-clamped shrink), rounded to a whole connection count.
    raw = inp.samples + inp.spares
    lp_min = jnp.ceil(inp.filtered)
    plan = jnp.where(raw < lp_min * 1.05, lp_min, raw)
    plan = jnp.minimum(plan, inp.maximum)
    plan_target = jnp.round(plan).astype(jnp.int32)
    hot = pressure >= PRESSURE_HOT
    boost = jnp.where(hot & over, 1.0, 0.0)
    shed = jnp.where(quiet & (inp.filtered + 1.0 < inp.spares), 1.0, 0.0)
    plan_spares = jnp.clip(jnp.round(inp.spares + boost - shed),
                           0.0, inp.maximum).astype(jnp.int32)
    delta = plan_target - jnp.round(raw).astype(jnp.int32)

    epoch = state.epoch + jnp.int32(1)
    new_state = ControlState(targets=t, epoch=epoch, now_ms=inp.now_ms)
    decisions = {
        'codel_target': t,
        'plan_spares': plan_spares,
        'plan_target': plan_target,
        'delta': delta,
        'epoch': epoch,
    }
    fleet = {
        'n_pools': sums['n'].astype(jnp.float32),
        'pressure': pressure,
        'mean_load': sums['load'] / n.astype(jnp.float32),
        'max_sojourn': sums['max_sojourn'],
    }
    return new_state, decisions, fleet


def _step(state: ControlState, inp: ControlInputs):
    """The fused single-program control step (plain / GSPMD form)."""
    base, cur, over = _plan_local(state, inp)
    sums = _control_sums(inp, over)
    return _decide(state, inp, base, cur, over, sums)


#: One fused control tick for the whole fleet (single-device or GSPMD).
#: Returns (new_state, decision_columns, fleet_aggregates).
control_step = jax.jit(_step)


# -- regex partition rules --------------------------------------------------

def _path_str(path) -> str:
    """'/'-joined tree path: NamedTuple fields and dict keys by name."""
    parts = []
    for k in path:
        if hasattr(k, 'name'):
            parts.append(str(k.name))
        elif hasattr(k, 'key'):
            parts.append(str(k.key))
        elif hasattr(k, 'idx'):
            parts.append(str(k.idx))
        else:                                      # pragma: no cover
            parts.append(str(k))
    return '/'.join(parts)


def match_partition_rules(rules, tree):
    """Map a rule table of ``(regex, PartitionSpec)`` pairs over a
    pytree of abstract leaves, yielding the PartitionSpec tree. First
    matching rule wins (re.search over the '/'-joined leaf path);
    rank-0 leaves are never partitioned; an unmatched leaf raises, so
    a new state/decision column must be placed deliberately."""
    def pick(path, leaf):
        if len(getattr(leaf, 'shape', ())) == 0:
            return P()
        name = _path_str(path)
        for rx, spec in rules:
            if re.search(rx, name):
                return spec
        raise ValueError('no partition rule matches %r' % name)
    return jax.tree_util.tree_map_with_path(pick, tree)


def partition_rules(axes: tuple = ('pools',)):
    """The ONE enumeration of how control-plane data shards: scalars
    (clock, epoch, fleet aggregates) replicate; every per-pool column
    shards over the mesh axes."""
    return (
        (r'(^|/)(now_ms|epoch|n_pools|pressure|mean_load|max_sojourn)$',
         P()),
        (r'.*', P(axes)),
    )


@functools.lru_cache(maxsize=None)
def control_specs(axes: tuple = ('pools',)):
    """(state, inputs, outputs) PartitionSpec trees, derived by running
    the rule table over abstract templates of the step."""
    rules = partition_rules(axes)
    state_t = jax.eval_shape(lambda: control_init(8))
    inp_t = jax.eval_shape(lambda: control_inputs(8))
    out_t = jax.eval_shape(_step, state_t, inp_t)
    return (match_partition_rules(rules, state_t),
            match_partition_rules(rules, inp_t),
            match_partition_rules(rules, out_t))


def control_shardings(mesh: Mesh, axes: tuple = ('pools',)):
    """control_specs bound to a mesh as NamedShardings."""
    place = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    return tuple(jax.tree.map(place, t, is_leaf=lambda x:
                              isinstance(x, P))
                 for t in control_specs(axes))


@functools.lru_cache(maxsize=None)
def make_control_step(mesh: Mesh | None = None,
                      axes: tuple = ('pools',)):
    """The live control step: jitted, carried state DONATED, and (with
    a mesh) every per-pool column sharded per the regex rules, so the
    fleet counts compile to hierarchical all-reduces. Do not reuse a
    ControlState after passing it here — donation invalidates it.
    Memoized per (mesh, axes) like telemetry.make_live_step."""
    if mesh is None:
        return jax.jit(_step, donate_argnums=0)
    state_sh, inp_sh, out_sh = control_shardings(mesh, axes)
    return jax.jit(_step, in_shardings=(state_sh, inp_sh),
                   out_shardings=out_sh, donate_argnums=0)


def make_shardmap_control_step(mesh: Mesh, axes: tuple = ('pools',)):
    """SPMD form with hand-written collectives: per-pool law on the
    local shard, the decision-feeding counts reduced innermost mesh
    axis first (chip/ICI) then outermost (host/DCN) — the hierarchical
    all-reduce. Decision columns are asserted identical to the plain
    step (int/max reductions are order-independent)."""
    try:
        from jax import shard_map              # jax >= 0.8
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    state_specs, inp_specs, out_specs = control_specs(axes)

    def _reduce(v, op):
        for ax in reversed(axes):
            v = op(v, ax)
        return v

    def local(state, inp):
        base, cur, over = _plan_local(state, inp)
        sums = _control_sums(inp, over)
        sums = {k: (_reduce(v, jax.lax.pmax) if k == 'max_sojourn'
                    else _reduce(v, jax.lax.psum))
                for k, v in sums.items()}
        return _decide(state, inp, base, cur, over, sums)

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(state_specs, inp_specs),
        out_specs=out_specs))


def shard_control_state(state: ControlState, mesh: Mesh,
                        axes: tuple = ('pools',)) -> ControlState:
    state_sh, _, _ = control_shardings(mesh, axes)
    return jax.tree.map(jax.device_put, state, state_sh)


def shard_control_inputs(inp: ControlInputs, mesh: Mesh,
                         axes: tuple = ('pools',)) -> ControlInputs:
    _, inp_sh, _ = control_shardings(mesh, axes)
    return jax.tree.map(jax.device_put, inp, inp_sh)


# -- batched host actuation -------------------------------------------------

def apply_decisions(pools_by_row, decisions, at_ms=None,
                    health=None) -> dict:
    """Apply one step's decision columns to live pools.

    ``pools_by_row`` maps row index -> pool (the sampler's
    ``fs_row_pool``); ``decisions`` is the step's decision dict (device
    or host arrays). Every pool is offered its row's decision through
    ``apply_control_decision`` — the guarded API that validates the
    epoch and every field BEFORE mutating anything — and flags its own
    telemetry row dirty on accept, so the next tick re-gathers exactly
    the rows that moved. Pools without the API are skipped. ``health``
    (an optional fleet health citation, see parallel.health) is
    forwarded alongside accepted decisions for the pool's audit
    trail. Returns
    ``{'applied': n, 'rejected': n, 'skipped': n, 'epoch': e}``."""
    import numpy as np
    ct = np.asarray(decisions['codel_target'])
    sp = np.asarray(decisions['plan_spares'])
    epoch = int(decisions['epoch'])
    extra = {} if health is None else {'health': health}
    applied = rejected = skipped = 0
    for row, pool in pools_by_row.items():
        apply = getattr(pool, 'apply_control_decision', None)
        if apply is None:
            skipped += 1
            continue
        target = float(ct[row])
        ok = apply(epoch,
                   codel_target=target if target > 0.0 else None,
                   spares=int(sp[row]), at_ms=at_ms, **extra)
        if ok:
            applied += 1
        else:
            rejected += 1
    return {'applied': applied, 'rejected': rejected,
            'skipped': skipped, 'epoch': epoch}


def reduce_control(records) -> dict:
    """Combine per-shard control summaries (record['control'] dicts)
    into one fleet row: counts sum, pressure/mean_load combine weighted
    by pool count, max_sojourn takes the worst shard."""
    records = [r for r in records if r]
    out = {'n_pools': 0.0, 'pressure': 0.0, 'mean_load': 0.0,
           'max_sojourn': 0.0, 'applied': 0, 'rejected': 0,
           'skipped': 0}
    if not records:
        return out
    tot = sum(float(r['fleet']['n_pools']) for r in records)
    safe = tot if tot > 0.0 else 1.0
    for r in records:
        f = r['fleet']
        w = float(f['n_pools'])
        out['n_pools'] += w
        out['pressure'] += f['pressure'] * w / safe
        out['mean_load'] += f['mean_load'] * w / safe
        out['max_sojourn'] = max(out['max_sojourn'], f['max_sojourn'])
        for k in ('applied', 'rejected', 'skipped'):
            out[k] += int(r.get(k, 0))
    return out

"""Fleet health analytics: the step that JUDGES.

parallel.telemetry batches the control laws and parallel.control closes
the actuation loop, but neither names a culprit: an operator staring at
``/metrics`` still cannot answer "which backend is gray?" or "is my SLO
burning?". This module turns the raw per-backend attribution columns —
folded out of drained claim spans by :class:`BackendTable` — into
*judgments*, as one jitted pass over a backends axis sharded exactly
like the control step:

- **per-backend robust stats**: an EWMA of mean claim service latency
  and a decayed log-bucket latency sketch per backend row, updated
  elementwise so every mesh form computes identical values;
- **anomaly detection**: each backend's EWMA is quantized onto an
  integer log-latency score (16 units per doubling); the fleet baseline
  is the MEDIAN score and its MAD, both computed from int32 score
  histograms reduced across shards — order-independent sums, so the
  z-score verdicts are bit-exact plain vs GSPMD vs shard_map (the same
  discipline as parallel.control). A backend is flagged gray when its
  score sits ``Z_THRESHOLD`` robust deviations AND at least one full
  latency doubling above the median, with streak hysteresis
  (``ENTER_STREAK`` ticks to flag, ``EXIT_STREAK`` clean ticks to
  clear) so a single slow tick never pages anyone;
- **SLO burn rates**: declared objectives (:class:`SLOObjectives`:
  claim success rate and claim p99 latency) are evaluated per tick
  from int32 fleet sums into instantaneous burn rates, smoothed into
  fast- and slow-window EWMAs with the classic multiwindow alert
  thresholds (fast > 14.4x budget pages, slow > 6x opens a ticket).

Row 0 of the backends axis is RESERVED for unattributed traffic
(claims that never reached a backend: timeouts, sheds before claim):
it feeds the SLO sums but is masked out of gray detection via the
``eligible`` input column, so an overloaded claim queue cannot frame
an innocent backend.

Host glue lives here too: :class:`BackendTable` accumulates the
per-backend columns from the trace layer's backend sinks (rows keyed
by ``trace.backend_index`` so the native flag stamp and the Python
recorder agree), :class:`HealthMonitor` drives the step and publishes
``cueball_backend_health{backend=...}`` / ``cueball_slo_burn_rate``
gauges plus the ``/kang/health`` snapshot, and :func:`reduce_health`
merges per-shard verdicts for the FleetRouter.
"""

from __future__ import annotations

import collections
import functools
import math
import threading
import typing

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .control import match_partition_rules

__all__ = ['BackendTable', 'DEFAULT_OBJECTIVES', 'HealthInputs',
           'HealthMonitor', 'HealthState', 'SLOObjectives',
           'active_monitors', 'health_init', 'health_inputs',
           'health_partition_rules', 'health_snapshot', 'health_specs',
           'health_step', 'latency_bucket', 'make_health_step',
           'make_shardmap_health_step', 'reduce_health',
           'shard_health_inputs', 'shard_health_state']

#: Latency sketch geometry: bucket k spans latencies whose
#: log2(1 + ms) falls in [k/4, (k+1)/4) — quarter-doubling buckets, so
#: 64 buckets reach past 65 s. Integer counts per bucket are the ONLY
#: thing reduced across shards, which is what buys bit-exactness.
LAT_BINS = 64
BUCKET_SCALE = 4.0
#: Score geometry: 16 units per latency doubling, 256 bins (~2^16 ms).
SCORE_BINS = 256
SCORE_SCALE = 16.0

#: EWMA smoothing for per-backend mean latency and the sketch decay
#: (per health tick, not per second: the monitor owns the cadence).
EWMA_ALPHA = 0.3
SKETCH_DECAY = 0.9

#: Gray verdict: z-score over the fleet median/MAD baseline, AND an
#: absolute floor of one full doubling over the median score so tight
#: fleets (MAD ~ 0) cannot page on noise, AND a minimum population to
#: baseline against.
Z_THRESHOLD = 3.5
GRAY_FLOOR_Q = int(SCORE_SCALE)
MIN_BASELINE = 4
ENTER_STREAK = 3
EXIT_STREAK = 5

#: Burn-rate smoothing and the multiwindow alert thresholds
#: (fast window pages, slow window files a ticket).
FAST_ALPHA = 0.5
SLOW_ALPHA = 0.05
FAST_BURN_ALERT = 14.4
SLOW_BURN_ALERT = 6.0


class SLOObjectives(typing.NamedTuple):
    """Declared service-level objectives, baked statically into the
    jitted step (hashable, so step builders memoize per objective)."""
    success_target: float = 0.999   # claim success rate
    claim_p99_ms: float = 250.0     # claim latency p99 bound (ms)


DEFAULT_OBJECTIVES = SLOObjectives()


class HealthState(typing.NamedTuple):
    """Carried (donated) per-backend health state."""
    ewma_ms: jax.Array      # [B] f32 EWMA of mean service latency
    lat_hist: jax.Array     # [B, LAT_BINS] f32 decayed latency sketch
    gray_streak: jax.Array  # [B] i32 consecutive flagged ticks
    ok_streak: jax.Array    # [B] i32 consecutive clean ticks
    gray: jax.Array         # [B] bool current verdict
    burn_fast_err: jax.Array  # scalar f32 fast-window error burn
    burn_slow_err: jax.Array  # scalar f32 slow-window error burn
    burn_fast_lat: jax.Array  # scalar f32 fast-window latency burn
    burn_slow_lat: jax.Array  # scalar f32 slow-window latency burn
    epoch: jax.Array        # scalar i32 verdict epoch
    now_ms: jax.Array       # scalar f32 clock of the last step


class HealthInputs(typing.NamedTuple):
    """One health tick's per-backend attribution columns (drained from
    a :class:`BackendTable`; all [B] except the sketches and clock)."""
    lat_sum: jax.Array        # f32 sum of ok service latencies (ms)
    lat_count: jax.Array      # i32 ok claims with a service latency
    lat_buckets: jax.Array    # [B, LAT_BINS] i32 service sketch adds
    claim_buckets: jax.Array  # [B, LAT_BINS] i32 claim-latency adds
    errors: jax.Array         # i32 failed claims attributed here
    shed: jax.Array           # i32 CoDel sheds attributed here
    active: jax.Array         # bool: row carries traffic (feeds SLO)
    eligible: jax.Array       # bool: row may be judged gray
    reset: jax.Array          # bool: row newly (re)assigned
    now_ms: jax.Array         # scalar clock (ms)


def health_init(n_backends: int, epoch: int = 0) -> HealthState:
    # Each leaf gets its own buffer: the live step donates the whole
    # state, and aliased leaves would be "donated twice".
    def zi():
        return jnp.zeros((n_backends,), jnp.int32)
    return HealthState(
        ewma_ms=jnp.zeros((n_backends,), jnp.float32),
        lat_hist=jnp.zeros((n_backends, LAT_BINS), jnp.float32),
        gray_streak=zi(), ok_streak=zi(),
        gray=jnp.zeros((n_backends,), bool),
        burn_fast_err=jnp.float32(0.0), burn_slow_err=jnp.float32(0.0),
        burn_fast_lat=jnp.float32(0.0), burn_slow_lat=jnp.float32(0.0),
        epoch=jnp.int32(epoch), now_ms=jnp.float32(0.0))


def health_inputs(n_backends: int, **kw) -> HealthInputs:
    """A HealthInputs of idle defaults; override fields by keyword."""
    zb = jnp.zeros((n_backends,), bool)
    vals = dict(
        lat_sum=jnp.zeros((n_backends,), jnp.float32),
        lat_count=jnp.zeros((n_backends,), jnp.int32),
        lat_buckets=jnp.zeros((n_backends, LAT_BINS), jnp.int32),
        claim_buckets=jnp.zeros((n_backends, LAT_BINS), jnp.int32),
        errors=jnp.zeros((n_backends,), jnp.int32),
        shed=jnp.zeros((n_backends,), jnp.int32),
        active=zb, eligible=zb, reset=zb,
        now_ms=jnp.float32(0.0))
    vals.update(kw)
    return HealthInputs(**{k: jnp.asarray(v) for k, v in vals.items()})


def latency_bucket(ms: float) -> int:
    """The sketch bucket for one latency (host-side mirror of the
    on-device geometry; also resolves SLO thresholds at trace time)."""
    if not ms > 0.0:
        return 0
    return min(int(math.log2(1.0 + ms) * BUCKET_SCALE), LAT_BINS - 1)


# -- the law ----------------------------------------------------------------

def _observe_local(state: HealthState, inp: HealthInputs):
    """Per-backend pre-reduction work: EWMA + sketch update and the
    integer log-latency score. Elementwise, so identical on a shard."""
    mean = inp.lat_sum / jnp.maximum(
        inp.lat_count.astype(jnp.float32), 1.0)
    have = inp.active & (inp.lat_count > 0)
    prev = jnp.where(inp.reset, 0.0, state.ewma_ms)
    ewma = jnp.where(
        have,
        jnp.where(prev > 0.0, prev + EWMA_ALPHA * (mean - prev), mean),
        prev)
    hist = jnp.where(inp.reset[:, None], 0.0, state.lat_hist)
    hist = hist * SKETCH_DECAY + inp.lat_buckets.astype(jnp.float32)
    score = jnp.clip(
        jnp.round(SCORE_SCALE * jnp.log2(1.0 + ewma)),
        0, SCORE_BINS - 1).astype(jnp.int32)
    considered = inp.eligible & ~inp.reset & (ewma > 0.0)
    return ewma, hist, score, considered


def _health_sums(inp: HealthInputs, score, considered) -> dict:
    """Shard-local reduction terms. Everything a VERDICT depends on is
    an int32 sum (score/deviation/latency histograms, counts), so the
    cross-shard combine is bit-exact regardless of reduction order."""
    con = considered.astype(jnp.int32)
    act = inp.active
    onehot = (score[:, None]
              == jnp.arange(SCORE_BINS, dtype=jnp.int32)[None, :])
    return {
        'score_hist': jnp.sum(onehot.astype(jnp.int32) * con[:, None],
                              axis=0),
        'n': jnp.sum(con),
        'claim_hist': jnp.sum(
            inp.claim_buckets * act.astype(jnp.int32)[:, None], axis=0),
        'ok': jnp.sum(jnp.where(act, inp.lat_count, 0)),
        'errors': jnp.sum(jnp.where(act, inp.errors, 0)),
        'shed': jnp.sum(jnp.where(act, inp.shed, 0)),
    }


def _hist_median(hist, n):
    """Median of an integer histogram: the first bin whose cumulative
    count reaches rank (n+1)//2. Pure int compares — bit-exact."""
    c = jnp.cumsum(hist)
    rank = jnp.maximum((n + jnp.int32(1)) // 2, 1)
    return jnp.argmax(c >= rank).astype(jnp.int32)


def _deviation_hist(score, considered, med):
    """Second-pass histogram of |score - median| (for the MAD)."""
    dev = jnp.clip(jnp.abs(score - med), 0, SCORE_BINS - 1)
    onehot = (dev[:, None]
              == jnp.arange(SCORE_BINS, dtype=jnp.int32)[None, :])
    return jnp.sum(
        onehot.astype(jnp.int32) * considered.astype(jnp.int32)[:, None],
        axis=0)


def _judge(state: HealthState, inp: HealthInputs, ewma, hist, score,
           considered, sums: dict, med, mad,
           objectives: SLOObjectives):
    """Post-reduction verdicts. `sums`/`med`/`mad` are fleet totals
    (already combined across shards in the sharded forms)."""
    enough = sums['n'] >= MIN_BASELINE
    z = (score - med).astype(jnp.float32) / jnp.maximum(
        mad, 1).astype(jnp.float32)
    raw = (considered & enough & (z > Z_THRESHOLD)
           & (score >= med + GRAY_FLOOR_Q))

    gray_streak = jnp.where(
        raw, jnp.where(inp.reset, 0, state.gray_streak) + 1, 0)
    ok_streak = jnp.where(
        raw, 0, jnp.where(inp.reset, 0, state.ok_streak) + 1)
    prev_gray = jnp.where(inp.reset, False, state.gray)
    gray = jnp.where(gray_streak >= ENTER_STREAK, True,
                     jnp.where(ok_streak >= EXIT_STREAK, False,
                               prev_gray))
    gray = gray & considered

    # SLO burn. Error objective: failed / attempted claims against the
    # success budget. Latency objective: the fraction of claims over
    # the declared p99 bound against its 1% budget. Both rates come
    # from replicated int sums, so every mesh form smooths identically.
    ops = sums['ok'] + sums['errors']
    opsf = jnp.maximum(ops, 1).astype(jnp.float32)
    err_rate = sums['errors'].astype(jnp.float32) / opsf
    c = jnp.cumsum(sums['claim_hist'])
    tot = c[-1]
    rank99 = jnp.maximum(tot - tot // 100, 1)
    k99 = jnp.argmax(c >= rank99).astype(jnp.int32)
    p99_ms = jnp.exp2((k99.astype(jnp.float32) + 1.0)
                      / BUCKET_SCALE) - 1.0
    kt = latency_bucket(objectives.claim_p99_ms)
    over_frac = ((tot - c[kt]).astype(jnp.float32)
                 / jnp.maximum(tot, 1).astype(jnp.float32))
    err_budget = max(1.0 - objectives.success_target, 1e-9)
    burn_err = jnp.where(ops > 0, err_rate / err_budget, 0.0)
    burn_lat = jnp.where(tot > 0, over_frac / 0.01, 0.0)

    f_err = state.burn_fast_err + FAST_ALPHA * (
        burn_err - state.burn_fast_err)
    s_err = state.burn_slow_err + SLOW_ALPHA * (
        burn_err - state.burn_slow_err)
    f_lat = state.burn_fast_lat + FAST_ALPHA * (
        burn_lat - state.burn_fast_lat)
    s_lat = state.burn_slow_lat + SLOW_ALPHA * (
        burn_lat - state.burn_slow_lat)

    epoch = state.epoch + jnp.int32(1)
    new_state = HealthState(
        ewma_ms=ewma, lat_hist=hist, gray_streak=gray_streak,
        ok_streak=ok_streak, gray=gray,
        burn_fast_err=f_err, burn_slow_err=s_err,
        burn_fast_lat=f_lat, burn_slow_lat=s_lat,
        epoch=epoch, now_ms=inp.now_ms)
    verdicts = {
        'gray': gray,
        'z': z,
        'score': score,
        'ewma_ms': ewma,
        'epoch': epoch,
    }
    fleet = {
        'n_backends': sums['n'],
        'median_score': med,
        'mad_score': mad,
        'claim_p99_ms': p99_ms,
        'err_rate': err_rate,
        'over_frac': over_frac,
        'ops': ops,
        'errors': sums['errors'],
        'shed': sums['shed'],
        'burn_fast': jnp.maximum(f_err, f_lat),
        'burn_slow': jnp.maximum(s_err, s_lat),
        'alert_page': (f_err > FAST_BURN_ALERT)
        | (f_lat > FAST_BURN_ALERT),
        'alert_ticket': (s_err > SLOW_BURN_ALERT)
        | (s_lat > SLOW_BURN_ALERT),
    }
    return new_state, verdicts, fleet


def _make_law(objectives: SLOObjectives):
    """The fused single-program health step (plain / GSPMD form) with
    the objectives baked in as compile-time constants."""
    def step(state: HealthState, inp: HealthInputs):
        ewma, hist, score, considered = _observe_local(state, inp)
        sums = _health_sums(inp, score, considered)
        med = _hist_median(sums['score_hist'], sums['n'])
        dev = _deviation_hist(score, considered, med)
        mad = _hist_median(dev, sums['n'])
        new_state, verdicts, fleet = _judge(
            state, inp, ewma, hist, score, considered, sums, med, mad,
            objectives)
        fleet['n_gray'] = jnp.sum(verdicts['gray'].astype(jnp.int32))
        return new_state, verdicts, fleet
    return step


#: One fused health tick for the whole fleet (single-device or GSPMD)
#: under DEFAULT_OBJECTIVES. Returns (new_state, verdicts, fleet).
health_step = jax.jit(_make_law(DEFAULT_OBJECTIVES))


# -- partition rules --------------------------------------------------------

def health_partition_rules(axes: tuple = ('pools',)):
    """The ONE enumeration of how health data shards: the rank-2
    latency sketches shard rows over the mesh axes (buckets
    replicated), every per-backend column shards over the axes, and
    scalars (clock, epoch, baselines, burn rates) replicate."""
    return (
        (r'(^|/)(lat_hist|lat_buckets|claim_buckets)$', P(axes, None)),
        (r'.*', P(axes)),
    )


@functools.lru_cache(maxsize=None)
def health_specs(axes: tuple = ('pools',)):
    """(state, inputs, outputs) PartitionSpec trees, derived by running
    the rule table over abstract templates of the step."""
    rules = health_partition_rules(axes)
    state_t = jax.eval_shape(lambda: health_init(8))
    inp_t = jax.eval_shape(lambda: health_inputs(8))
    out_t = jax.eval_shape(_make_law(DEFAULT_OBJECTIVES),
                           state_t, inp_t)
    return (match_partition_rules(rules, state_t),
            match_partition_rules(rules, inp_t),
            match_partition_rules(rules, out_t))


def health_shardings(mesh: Mesh, axes: tuple = ('pools',)):
    """health_specs bound to a mesh as NamedShardings."""
    place = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    return tuple(jax.tree.map(place, t, is_leaf=lambda x:
                              isinstance(x, P))
                 for t in health_specs(axes))


@functools.lru_cache(maxsize=None)
def make_health_step(mesh: Mesh | None = None,
                     axes: tuple = ('pools',),
                     objectives: SLOObjectives = DEFAULT_OBJECTIVES):
    """The live health step: jitted, carried state DONATED, and (with
    a mesh) every per-backend column sharded per the regex rules so
    the histogram sums compile to hierarchical all-reduces. Do not
    reuse a HealthState after passing it here — donation invalidates
    it. Memoized per (mesh, axes, objectives)."""
    step = _make_law(objectives)
    if mesh is None:
        return jax.jit(step, donate_argnums=0)
    state_sh, inp_sh, out_sh = health_shardings(mesh, axes)
    return jax.jit(step, in_shardings=(state_sh, inp_sh),
                   out_shardings=out_sh, donate_argnums=0)


def make_shardmap_health_step(
        mesh: Mesh, axes: tuple = ('pools',),
        objectives: SLOObjectives = DEFAULT_OBJECTIVES):
    """SPMD form with hand-written collectives: elementwise stats on
    the local shard, then TWO all-reduce phases (score histogram for
    the median, deviation histogram for the MAD) plus the verdict
    count, each reduced innermost mesh axis first (chip/ICI) then
    outermost (host/DCN). All int32 sums — bit-exact vs the plain
    step (tests/test_health.py soaks this at 100k rows)."""
    try:
        from jax import shard_map              # jax >= 0.8
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    state_specs, inp_specs, out_specs = health_specs(axes)

    def _reduce(v, op):
        for ax in reversed(axes):
            v = op(v, ax)
        return v

    def local(state, inp):
        ewma, hist, score, considered = _observe_local(state, inp)
        sums = {k: _reduce(v, jax.lax.psum)
                for k, v in _health_sums(inp, score,
                                         considered).items()}
        med = _hist_median(sums['score_hist'], sums['n'])
        dev = _reduce(_deviation_hist(score, considered, med),
                      jax.lax.psum)
        mad = _hist_median(dev, sums['n'])
        new_state, verdicts, fleet = _judge(
            state, inp, ewma, hist, score, considered, sums, med, mad,
            objectives)
        fleet['n_gray'] = _reduce(
            jnp.sum(verdicts['gray'].astype(jnp.int32)), jax.lax.psum)
        return new_state, verdicts, fleet

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(state_specs, inp_specs),
        out_specs=out_specs))


def shard_health_state(state: HealthState, mesh: Mesh,
                       axes: tuple = ('pools',)) -> HealthState:
    state_sh, _, _ = health_shardings(mesh, axes)
    return jax.tree.map(jax.device_put, state, state_sh)


def shard_health_inputs(inp: HealthInputs, mesh: Mesh,
                        axes: tuple = ('pools',)) -> HealthInputs:
    _, inp_sh, _ = health_shardings(mesh, axes)
    return jax.tree.map(jax.device_put, inp, inp_sh)


# -- host attribution table -------------------------------------------------

class BackendTable:
    """Per-backend accumulation columns, fed by the trace layer.

    Rows are keyed by ``trace.backend_index`` — the SAME registry the
    native emitter stamps into slot flags — so a claim attributed by
    the C ring and one attributed by the Python recorder land in the
    same row. Row 0 is the reserved unattributed bucket (key ``''``).
    Implements the backend-sink protocol (``observe`` /
    ``observe_shed``, called from trace drains on arbitrary threads);
    ``drain`` hands one tick's columns to the monitor and zeroes the
    accumulators atomically."""

    __slots__ = ('bt_lock', 'bt_lat_sum', 'bt_lat_count',
                 'bt_lat_buckets', 'bt_claim_buckets', 'bt_errors',
                 'bt_shed', 'bt_seen', 'bt_fresh')

    def __init__(self, capacity: int = 8):
        import numpy as np
        self.bt_lock = threading.Lock()
        n = max(int(capacity), 1)
        self.bt_lat_sum = np.zeros(n, np.float64)
        self.bt_lat_count = np.zeros(n, np.int64)
        self.bt_lat_buckets = np.zeros((n, LAT_BINS), np.int64)
        self.bt_claim_buckets = np.zeros((n, LAT_BINS), np.int64)
        self.bt_errors = np.zeros(n, np.int64)
        self.bt_shed = np.zeros(n, np.int64)
        self.bt_seen = np.zeros(n, bool)
        self.bt_fresh: set = set()

    def _row(self, key) -> int:
        from .. import trace as mod_trace
        row = mod_trace.backend_index(key or '')
        if row >= len(self.bt_lat_sum):
            self._grow(row + 1)
        if not self.bt_seen[row]:
            self.bt_seen[row] = True
            self.bt_fresh.add(row)
        return row

    def _grow(self, need: int):
        import numpy as np
        n = len(self.bt_lat_sum)
        while n < need:
            n *= 2
        pad = n - len(self.bt_lat_sum)
        self.bt_lat_sum = np.concatenate(
            [self.bt_lat_sum, np.zeros(pad, np.float64)])
        self.bt_lat_count = np.concatenate(
            [self.bt_lat_count, np.zeros(pad, np.int64)])
        self.bt_lat_buckets = np.concatenate(
            [self.bt_lat_buckets, np.zeros((pad, LAT_BINS), np.int64)])
        self.bt_claim_buckets = np.concatenate(
            [self.bt_claim_buckets,
             np.zeros((pad, LAT_BINS), np.int64)])
        self.bt_errors = np.concatenate(
            [self.bt_errors, np.zeros(pad, np.int64)])
        self.bt_shed = np.concatenate(
            [self.bt_shed, np.zeros(pad, np.int64)])
        self.bt_seen = np.concatenate(
            [self.bt_seen, np.zeros(pad, bool)])

    # -- the backend-sink protocol (trace.add_backend_sink) ------------

    def observe(self, key, service_ms, claim_ms, ok: bool):
        """One finished claim: `service_ms` is the lease (in-service)
        duration for successful claims, `claim_ms` the whole claim
        span; either may be None when the span never got there."""
        with self.bt_lock:
            row = self._row(key)
            if ok and service_ms is not None:
                self.bt_lat_sum[row] += float(service_ms)
                self.bt_lat_count[row] += 1
                self.bt_lat_buckets[
                    row, latency_bucket(float(service_ms))] += 1
            elif not ok:
                self.bt_errors[row] += 1
            if claim_ms is not None:
                self.bt_claim_buckets[
                    row, latency_bucket(float(claim_ms))] += 1

    def observe_shed(self, key):
        with self.bt_lock:
            self.bt_shed[self._row(key)] += 1

    def drain(self) -> dict:
        """Swap out one tick's columns (numpy, host-side) and zero the
        accumulators. 'active'/'eligible'/'reset' are the step's row
        masks; row count is whatever the table has grown to."""
        import numpy as np
        with self.bt_lock:
            n = len(self.bt_lat_sum)
            out = {
                'lat_sum': self.bt_lat_sum.astype(np.float32),
                'lat_count': self.bt_lat_count.astype(np.int32),
                'lat_buckets': self.bt_lat_buckets.astype(np.int32),
                'claim_buckets':
                    self.bt_claim_buckets.astype(np.int32),
                'errors': self.bt_errors.astype(np.int32),
                'shed': self.bt_shed.astype(np.int32),
                'active': self.bt_seen.copy(),
            }
            eligible = self.bt_seen.copy()
            eligible[0] = False
            out['eligible'] = eligible
            reset = np.zeros(n, bool)
            for row in self.bt_fresh:
                reset[row] = True
            out['reset'] = reset
            self.bt_fresh = set()
            self.bt_lat_sum[:] = 0.0
            self.bt_lat_count[:] = 0
            self.bt_lat_buckets[:] = 0
            self.bt_claim_buckets[:] = 0
            self.bt_errors[:] = 0
            self.bt_shed[:] = 0
        return out


#: Gauge families the monitor publishes (docs/observability.md).
_HEALTH_GAUGES = {
    'cueball_backend_health':
        'backend verdict: 0 healthy, 1 flagged gray',
    'cueball_backend_latency_ewma_ms':
        'EWMA of mean claim service latency per backend (ms)',
    'cueball_slo_burn_rate':
        'SLO burn rate (budget multiples) per objective and window',
}

_MONITORS: list = []
_MONITORS_LOCK = threading.Lock()


class HealthMonitor:
    """Drives the health step over a BackendTable and fans verdicts
    out to every surface: gauges, /kang/health, the SIGUSR2 dump and
    (via :func:`reduce_health`) the FleetRouter.

    Options: ``objectives`` (SLOObjectives), ``collector`` (metrics
    Collector; falls back to the active trace collector), ``mesh`` +
    ``meshAxes`` (shard the step), ``shard`` (gauge label),
    ``history`` (verdict ring length), ``interval`` (advisory tick
    period, ms — the owner calls :meth:`tick`)."""

    def __init__(self, options: dict | None = None):
        options = dict(options or {})
        self.hm_objectives: SLOObjectives = (
            options.get('objectives') or DEFAULT_OBJECTIVES)
        self.hm_collector = options.get('collector')
        self.hm_mesh = options.get('mesh')
        self.hm_mesh_axes = tuple(options.get('meshAxes', ('pools',)))
        self.hm_shard = options.get('shard')
        self.hm_interval = float(options.get('interval', 1000.0))
        self.hm_table = options.get('table') or BackendTable()
        self.hm_history: collections.deque = collections.deque(
            maxlen=int(options.get('history', 64)))
        self.hm_state: HealthState | None = None
        self.hm_rows = 0
        self.hm_last: dict | None = None
        self.hm_started = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> 'HealthMonitor':
        """Attach the table to the trace layer's completion sinks and
        register on the module's active-monitor list (the /kang/health
        and SIGUSR2 surfaces)."""
        from .. import trace as mod_trace
        if not self.hm_started:
            mod_trace.add_backend_sink(self.hm_table)
            with _MONITORS_LOCK:
                _MONITORS.append(self)
            self.hm_started = True
        return self

    def stop(self):
        from .. import trace as mod_trace
        if self.hm_started:
            mod_trace.remove_backend_sink(self.hm_table)
            with _MONITORS_LOCK:
                if self in _MONITORS:
                    _MONITORS.remove(self)
            self.hm_started = False

    # -- the tick ------------------------------------------------------

    def _rows_for(self, n: int) -> int:
        rows = 8
        while rows < n:
            rows *= 2
        if self.hm_mesh is not None:
            mult = int(self.hm_mesh.size)
            rows = ((rows + mult - 1) // mult) * mult
        return rows

    def _pad_state(self, state: HealthState, rows: int) -> HealthState:
        def pad(leaf):
            if getattr(leaf, 'ndim', 0) == 0:
                return leaf
            widths = [(0, rows - leaf.shape[0])] + [
                (0, 0)] * (leaf.ndim - 1)
            return jnp.pad(leaf, widths)
        return jax.tree.map(pad, state)

    def tick(self, now_ms: float | None = None) -> dict:
        """Drain the table, run one judged step, publish verdicts.
        Returns the host-side record (also kept as ``hm_last``)."""
        from .. import trace as mod_trace
        from .. import utils as mod_utils
        from .telemetry import fold_backend_slots
        if now_ms is None:
            now_ms = mod_utils.current_millis()
        # The native recorder attributes lazily: completed claims sit
        # in the C ring until a drain replays them into the sinks.
        runtime = mod_trace._runtime
        if runtime is not None:
            runtime._drain_native()
        cols = self.hm_table.drain()
        rows = self._rows_for(len(cols['lat_sum']))
        if self.hm_state is None or rows != self.hm_rows:
            if self.hm_state is None:
                state = health_init(rows)
            else:
                state = self._pad_state(self.hm_state, rows)
            if self.hm_mesh is not None:
                state = shard_health_state(state, self.hm_mesh,
                                           self.hm_mesh_axes)
            self.hm_state, self.hm_rows = state, rows

        inp = health_inputs(
            rows, now_ms=jnp.float32(now_ms % (2.0 ** 20)),
            **fold_backend_slots(cols, rows))
        if self.hm_mesh is not None:
            inp = shard_health_inputs(inp, self.hm_mesh,
                                      self.hm_mesh_axes)
        step = make_health_step(self.hm_mesh, self.hm_mesh_axes,
                                self.hm_objectives)
        state = self.hm_state
        self.hm_state = None      # donation: never reuse on failure
        new_state, verdicts, fleet = step(state, inp)
        self.hm_state = new_state

        record = self._publish(verdicts, fleet, now_ms)
        return record

    def _publish(self, verdicts, fleet, now_ms: float) -> dict:
        from .. import trace as mod_trace
        import numpy as np
        v = {k: np.asarray(x) for k, x in verdicts.items()}
        f = {k: np.asarray(x).item() for k, x in fleet.items()}
        backends = {}
        for row in np.nonzero(np.asarray(v['gray']) |
                              (v['ewma_ms'] > 0.0))[0]:
            key = mod_trace.backend_key_for(int(row))
            if key is None:
                continue
            backends[key or '(unattributed)'] = {
                'gray': bool(v['gray'][row]),
                'z': float(v['z'][row]),
                'score': int(v['score'][row]),
                'ewma_ms': float(v['ewma_ms'][row]),
            }
        record = {
            'epoch': int(v['epoch']),
            'at_ms': float(now_ms),
            'backends': backends,
            'gray': sorted(k for k, b in backends.items()
                           if b['gray']),
            'fleet': f,
        }
        self.hm_last = record
        self.hm_history.append({
            'epoch': record['epoch'], 'at_ms': record['at_ms'],
            'gray': record['gray'], 'n_gray': int(f['n_gray']),
            'burn_fast': float(f['burn_fast']),
            'burn_slow': float(f['burn_slow']),
            'alert_page': bool(f['alert_page']),
            'alert_ticket': bool(f['alert_ticket']),
        })

        collector = self.hm_collector
        if collector is None:
            collector = mod_trace.active_collector()
        if collector is not None:
            shard = ({'shard': str(self.hm_shard)}
                     if self.hm_shard is not None else {})
            hg = _HEALTH_GAUGES
            for key, b in backends.items():
                labels = dict(shard, backend=key)
                collector.gauge(
                    'cueball_backend_health',
                    hg['cueball_backend_health']).set(
                        1.0 if b['gray'] else 0.0, labels)
                collector.gauge(
                    'cueball_backend_latency_ewma_ms',
                    hg['cueball_backend_latency_ewma_ms']).set(
                        b['ewma_ms'], labels)
            for objective, fast, slow in (
                    ('success', 'burn_fast', 'burn_slow'),):
                collector.gauge(
                    'cueball_slo_burn_rate',
                    hg['cueball_slo_burn_rate']).set(
                        f[fast], dict(shard, objective=objective,
                                      window='fast'))
                collector.gauge(
                    'cueball_slo_burn_rate',
                    hg['cueball_slo_burn_rate']).set(
                        f[slow], dict(shard, objective=objective,
                                      window='slow'))
        return record

    # -- surfaces ------------------------------------------------------

    def snapshot(self) -> dict:
        """The /kang/health JSON row for this monitor."""
        return {
            'objectives': {
                'success_target': self.hm_objectives.success_target,
                'claim_p99_ms': self.hm_objectives.claim_p99_ms,
            },
            'shard': self.hm_shard,
            'interval_ms': self.hm_interval,
            'last': self.hm_last,
            'history': list(self.hm_history),
        }


def active_monitors() -> list:
    """Every started HealthMonitor in this process (newest last)."""
    with _MONITORS_LOCK:
        return list(_MONITORS)


def health_snapshot() -> dict:
    """The GET /kang/health payload: one row per active monitor plus
    the fleet merge (same shape reduce_health hands the router)."""
    monitors = active_monitors()
    return {
        'n_monitors': len(monitors),
        'monitors': [m.snapshot() for m in monitors],
        'fleet': reduce_health([m.hm_last for m in monitors]),
    }


def reduce_health(records) -> dict:
    """Combine per-shard health records (HealthMonitor.tick dicts)
    into one fleet row: gray sets union, counts sum, rates combine
    weighted by ops, burn rates and p99 take the worst shard."""
    records = [r for r in records if r]
    out = {'n_backends': 0, 'n_gray': 0, 'gray': [],
           'ops': 0, 'errors': 0, 'shed': 0, 'err_rate': 0.0,
           'claim_p99_ms': 0.0, 'burn_fast': 0.0, 'burn_slow': 0.0,
           'alert_page': False, 'alert_ticket': False}
    if not records:
        return out
    gray: set = set()
    tot_ops = sum(int(r['fleet']['ops']) for r in records)
    safe = float(tot_ops) if tot_ops > 0 else 1.0
    for r in records:
        f = r['fleet']
        gray.update(r.get('gray', ()))
        out['n_backends'] += int(f['n_backends'])
        for k in ('ops', 'errors', 'shed'):
            out[k] += int(f[k])
        out['err_rate'] += float(f['err_rate']) * int(f['ops']) / safe
        out['claim_p99_ms'] = max(out['claim_p99_ms'],
                                  float(f['claim_p99_ms']))
        out['burn_fast'] = max(out['burn_fast'], float(f['burn_fast']))
        out['burn_slow'] = max(out['burn_slow'], float(f['burn_slow']))
        out['alert_page'] |= bool(f['alert_page'])
        out['alert_ticket'] |= bool(f['alert_ticket'])
    out['gray'] = sorted(gray)
    out['n_gray'] = len(gray)
    return out

"""httpx drop-in: route an ``httpx.AsyncClient`` through cueball pools.

The reference's single biggest adoption property is that ``HttpAgent``
is a drop-in node ``http.Agent``: an existing app adopts cueball by
changing one constructor option, and every request it makes from then
on rides pooled, service-discovered, health-checked connections
(reference lib/agent.js:30-94; README.adoc:35-141 shows the one-line
adoption). Python's HTTP clients don't share node's Agent seam; the
seam httpx exposes is the transport. This module is therefore the
faithful analogue::

    import httpx
    from cueball_tpu.integrations.httpx import CueballTransport

    client = httpx.AsyncClient(transport=CueballTransport({
        'spares': 2, 'maximum': 8,
        'recovery': {'default': {'timeout': 2000, 'retries': 3,
                                 'delay': 100, 'maxDelay': 2000}},
    }))
    r = await client.get('http://my-service.example/')   # pooled

Lifecycle mapping (what reference lib/agent.js:275-396 does for node's
request events, re-expressed for httpx's request/response model):

- request start -> ``pool.claim()`` on the lazily-created pool for the
  URL's (scheme, host, port); httpx's *pool* timeout bounds the claim.
- response fully read on a keep-alive connection -> ``handle.release()``
  (the reference's ``'free'`` -> ``releaseConn``).
- close-delimited response, protocol error, or read timeout ->
  ``handle.close()`` (the reference's ``'close'`` handler).
- cancellation (``asyncio.CancelledError``) -> ``handle.close()`` (the
  reference's ``'abort'`` -> ``claimHandle.cancel()``; a mid-request
  cancel leaves the connection state unknown, so close not release).
- claim failures surface as httpx transport errors so retry/error
  handling written for stock httpx keeps working: ``ClaimTimeoutError``
  -> ``httpx.PoolTimeout``; ``NoBackendsError`` / ``PoolFailedError`` /
  ``PoolStoppingError`` -> ``httpx.ConnectError``.

Health checking, dead-backend monitoring, CoDel shedding, DNS SRV/A
discovery and the rest all come from the pools underneath — configure
them with the same agent options the reference documents (``ping``,
``pingInterval``, ``resolvers``, ``tcpKeepAliveInitialDelay``, TLS
passthrough fields...).

Request and response bodies are buffered (the pool hands out exclusive
claims per request, so no interleaving is lost); apps that stream
multi-GB bodies through httpx should keep a stock transport for those
endpoints via httpx mounts.
"""

from __future__ import annotations

import asyncio

import httpx

from .. import errors as mod_errors
from ..agent import CueBallAgent, _read_response
from . import apply_default_pool_policy

_SCHEME_PORT = {'http': 80, 'https': 443}


class _TimeoutReader:
    """StreamReader proxy applying httpx's read-timeout semantics: the
    timeout bounds each individual read operation, not the whole
    response (a steadily-streaming large body must not trip it)."""

    def __init__(self, reader: asyncio.StreamReader,
                 timeout: float | None):
        self._reader = reader
        self._timeout = timeout

    async def readline(self) -> bytes:
        return await asyncio.wait_for(self._reader.readline(),
                                      self._timeout)

    async def readexactly(self, n: int) -> bytes:
        # Chunk-wise, so the timeout bounds each arrival gap rather
        # than the whole (possibly large) body.
        if self._timeout is None:
            return await self._reader.readexactly(n)
        buf = bytearray()
        while len(buf) < n:
            chunk = await asyncio.wait_for(
                self._reader.read(n - len(buf)), self._timeout)
            if not chunk:
                raise asyncio.IncompleteReadError(bytes(buf), n)
            buf.extend(chunk)
        return bytes(buf)

    async def read(self, n: int = -1) -> bytes:
        if self._timeout is None:
            return await self._reader.read(n)
        if n >= 0:
            return await asyncio.wait_for(self._reader.read(n),
                                          self._timeout)
        # read-to-EOF (close-delimited bodies): chunk-wise, like
        # readexactly, so the timeout bounds each arrival gap.
        buf = bytearray()
        while True:
            chunk = await asyncio.wait_for(
                self._reader.read(65536), self._timeout)
            if not chunk:
                return bytes(buf)
            buf.extend(chunk)


def _classify_timeout(e: TimeoutError,
                      read_timeout: float | None) -> httpx.TransportError:
    """On Python >= 3.11 ``asyncio.TimeoutError`` IS the builtin
    ``TimeoutError``, and an OS-level ETIMEDOUT (TCP retransmit
    give-up, surfacing from drain() or a read) instantiates the same
    class. Only a wait_for expiry — errno-less, and only armed when a
    read timeout was configured — is httpx.ReadTimeout; the OS flavor
    is a connection failure, httpx.ReadError."""
    if read_timeout is not None and getattr(e, 'errno', None) is None:
        return httpx.ReadTimeout('no data within %gs' % read_timeout)
    return httpx.ReadError(str(e) or 'connection timed out')


class CueballTransport(httpx.AsyncBaseTransport):
    """``httpx.AsyncBaseTransport`` whose connections come from cueball
    ConnectionPools (one pool per (scheme, host, port), created lazily
    like reference lib/agent.js:105-211).

    `options` are CueBallAgent options minus ``defaultPort`` (derived
    from the URL scheme). Unlike the agent (which, like the reference,
    requires ``recovery``), the transport defaults ``recovery`` to a
    conservative policy ({timeout: 2000, retries: 3, delay: 100,
    maxDelay: 2000}) and ``spares``/``maximum`` to 2/8, so that the
    one-line adoption works with zero cueball-specific configuration.

    For a host whose backends need a custom resolver (e.g. a static
    list for failover), pre-create its pool exactly as reference
    consumers do (lib/agent.js:464-488)::

        transport.agent_for('http').create_pool('svc.local',
            {'resolver': my_resolver})

    A pool pre-created that way (keyed by bare host) serves any port
    for that host — its resolver, not the URL, decides the backends.
    Pools created lazily from URLs are keyed (host, port) and serve
    only that port.
    """

    def __init__(self, options: dict | None = None):
        self._options = apply_default_pool_policy(options)
        self._agents: dict[str, CueBallAgent] = {}
        # (scheme, host) pairs whose *bare-host* pool this transport
        # created lazily from a default-port URL. A bare-host pool NOT
        # in this set was pre-created by the app (create_pool) and may
        # serve any port for its host; explicit-port pools need no
        # tracking (their key already encodes the port).
        self._lazy_bare_hosts: set[tuple[str, str]] = set()
        self._closed = False

    # -- pool plumbing ----------------------------------------------------

    def agent_for(self, scheme: str) -> CueBallAgent:
        """The underlying CueBallAgent for a scheme (created lazily);
        exposed so apps can pre-create pools / read stats."""
        if self._closed:
            # Creating (or handing out) an agent after aclose() would
            # leak pools nothing will ever stop.
            raise httpx.TransportError('CueballTransport is closed')
        agent = self._agents.get(scheme)
        if agent is None:
            opts = dict(self._options)
            opts.setdefault('defaultPort', _SCHEME_PORT[scheme])
            agent = CueBallAgent(opts, scheme)
            self._agents[scheme] = agent
        return agent

    async def _claim(self, scheme: str, host: str, port: int,
                     timeout_ms: float | None):
        """Claim a pooled connection for (scheme, host, port).

        The agent keys pools by bare host for reference parity
        (lib/agent.js keys this.pools by hostname); URLs carry
        explicit ports, so pools created here are keyed 'host:port'
        unless the port is the scheme default. An app-pre-created
        bare-host pool is preferred for its host whatever the URL
        port (its resolver owns the backend choice); a *lazily*
        created default-port pool is not consulted for other ports —
        falling back to it would silently send a :8080 request to
        port 80."""
        agent = self.agent_for(scheme)
        key = host if port == agent.default_port else \
            '%s:%d' % (host, port)
        pool = agent.pools.get(key)
        if pool is None:
            bare = agent.pools.get(host)
            if bare is not None and \
                    (scheme, host) not in self._lazy_bare_hosts:
                pool = bare
        if pool is None:
            pool = agent._add_pool(host, {'port': port,
                                          'poolKey': key})
            if key == host:
                self._lazy_bare_hosts.add((scheme, host))
        claim_opts = {}
        # A CoDel pool derives its own claim deadline and (like the
        # reference, lib/pool.js:874-885) forbids an explicit one, so
        # the pool timeout is never passed INTO the claim. It still
        # binds, though — httpx semantics, including the client's
        # default pool=5s: the whole claim is raced against it from
        # OUTSIDE the pool and maps to PoolTimeout. Callers pairing a
        # long targetClaimDelay with queue waits beyond 5s must raise
        # or disable the client's pool timeout (docs/api.md).
        if timeout_ms is not None and not pool.codel_enabled():
            claim_opts['timeout'] = timeout_ms
        if agent.cba_err_on_empty is not None:
            claim_opts['errorOnEmpty'] = agent.cba_err_on_empty
        if timeout_ms is not None and pool.codel_enabled():
            try:
                return await asyncio.wait_for(pool.claim(claim_opts),
                                              timeout_ms / 1000.0)
            except asyncio.TimeoutError as e:
                raise mod_errors.ClaimTimeoutError(pool) from e
        return await pool.claim(claim_opts)

    # -- the transport contract -------------------------------------------

    async def handle_async_request(self,
                                   request: httpx.Request) -> httpx.Response:
        if self._closed:
            raise httpx.TransportError('CueballTransport is closed')
        scheme = request.url.scheme
        if scheme not in _SCHEME_PORT:
            raise httpx.UnsupportedProtocol(
                'CueballTransport handles http/https, not %r' % scheme)
        host = request.url.host
        port = request.url.port or _SCHEME_PORT[scheme]

        timeouts = request.extensions.get('timeout', {}) or {}
        pool_timeout = timeouts.get('pool')
        read_timeout = timeouts.get('read')

        body = await request.aread()
        payload = self._serialize(request, body)

        try:
            handle, socket = await self._claim(
                scheme, host, port,
                pool_timeout * 1000.0 if pool_timeout is not None
                else None)
        except mod_errors.ClaimTimeoutError as e:
            raise httpx.PoolTimeout(str(e)) from e
        except (mod_errors.NoBackendsError,
                mod_errors.PoolFailedError,
                mod_errors.PoolStoppingError) as e:
            raise httpx.ConnectError(str(e)) from e

        try:
            socket.writer.write(payload)
            await socket.writer.drain()
            resp, keep_alive = await _read_response(
                _TimeoutReader(socket.reader, read_timeout),
                request.method)
        except asyncio.TimeoutError as e:
            handle.close()
            raise _classify_timeout(e, read_timeout) from e
        except asyncio.CancelledError:
            handle.close()
            raise
        except (ConnectionError, EOFError, OSError, ValueError) as e:
            handle.close()
            raise httpx.ReadError(str(e)) from e
        except BaseException:
            handle.close()
            raise

        if keep_alive:
            handle.release()
        else:
            handle.close()

        return httpx.Response(
            status_code=resp.status,
            headers=resp.raw_headers,
            content=resp.body,
            request=request,
            extensions={'http_version': b'HTTP/1.1',
                        'reason_phrase': resp.reason.encode('latin-1')})

    @staticmethod
    def _serialize(request: httpx.Request, body: bytes) -> bytes:
        """One HTTP/1.1 request head + body, preserving httpx's header
        order and duplicates. httpx frames unknown-length content as
        chunked; the body is buffered here, so that framing is
        rewritten as Content-Length."""
        target = request.url.raw_path.decode('ascii')
        lines = ['%s %s HTTP/1.1' % (request.method, target)]
        saw_length = False
        for name, value in request.headers.raw:
            lname = name.lower()
            if lname == b'transfer-encoding' and value.lower() == b'chunked':
                continue
            if lname == b'content-length':
                saw_length = True
            lines.append('%s: %s' % (name.decode('latin-1'),
                                     value.decode('latin-1')))
        if body and not saw_length:
            lines.append('content-length: %d' % len(body))
        return ('\r\n'.join(lines) + '\r\n\r\n').encode('latin-1') + body

    async def aclose(self) -> None:
        if self._closed:
            return
        self._closed = True
        agents = list(self._agents.values())
        self._agents = {}
        for agent in agents:
            if not agent.is_stopped():
                await agent.stop()


class CueballSyncTransport(httpx.BaseTransport):
    """The synchronous twin of :class:`CueballTransport`: a stock
    *sync* ``httpx.Client`` adopts cueball pools with one argument::

        client = httpx.Client(transport=CueballSyncTransport({...}))

    cueball's FSMs live on an asyncio loop; this transport owns a
    dedicated background loop thread and bridges each request onto it
    with ``run_coroutine_threadsafe``. Many sync threads may share one
    transport — their requests serialize onto the single loop thread,
    where the usual pool concurrency (spares, claims, failover,
    CoDel) applies exactly as in the async form. Options, lifecycle
    mapping, timeout semantics and error translation are all
    :class:`CueballTransport`'s."""

    def __init__(self, options: dict | None = None):
        import threading
        self._async = CueballTransport(options)
        self._loop = asyncio.new_event_loop()
        self._closing = False
        started = threading.Event()

        def run():
            asyncio.set_event_loop(self._loop)
            self._loop.call_soon(started.set)
            self._loop.run_forever()

        self._thread = threading.Thread(
            target=run, name='cueball-httpx-sync', daemon=True)
        self._thread.start()
        started.wait()

    @property
    def async_transport(self) -> CueballTransport:
        """The underlying async transport (pre-create pools / read
        stats through its agents — but call its methods only from the
        transport's own loop thread, e.g. via :meth:`call`)."""
        return self._async

    def call(self, fn, *args, **kwargs):
        """Run ``fn(*args, **kwargs)`` on the transport's loop thread
        and return its result (awaiting it first if fn returns an
        awaitable). Needed for anything that constructs cueball FSMs —
        resolvers, ``create_pool`` — since those require a running
        loop::

            transport.call(
                lambda: transport.async_transport.agent_for('http')
                .create_pool('svc', {'resolver': make_resolver()}))
        """
        import inspect

        async def wrapper():
            result = fn(*args, **kwargs)
            if inspect.isawaitable(result):
                result = await result
            return result
        return asyncio.run_coroutine_threadsafe(
            wrapper(), self._loop).result()

    def handle_request(self, request: httpx.Request) -> httpx.Response:
        import concurrent.futures
        if self._closing or self._loop.is_closed():
            # Same error class as the async twin's closed check, so
            # httpx-targeted error handling behaves identically.
            raise httpx.TransportError('CueballTransport is closed')
        # Load the (possibly iterator) sync body here, on the calling
        # thread: afterwards the request carries a ByteStream, which
        # serves the async path's aread() too.
        request.read()
        fut = asyncio.run_coroutine_threadsafe(
            self._async.handle_async_request(request), self._loop)
        try:
            while True:
                try:
                    # Bounded waits, re-checking liveness: a request
                    # that slipped past the closed check while another
                    # thread ran close() must error, not hang on a
                    # stopped loop.
                    return fut.result(timeout=0.5)
                except concurrent.futures.TimeoutError:
                    if self._closing or self._loop.is_closed():
                        fut.cancel()
                        raise httpx.TransportError(
                            'CueballTransport is closed') from None
        except BaseException:
            # Caller-side unwind (KeyboardInterrupt, thread teardown):
            # cancel the in-flight coroutine so its claim is released
            # — the sync analogue of the async path's CancelledError
            # -> handle.close() mapping.
            fut.cancel()
            raise

    def close(self) -> None:
        if self._closing or self._loop.is_closed():
            return
        self._closing = True
        asyncio.run_coroutine_threadsafe(
            self._async.aclose(), self._loop).result()
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join()
        self._loop.close()

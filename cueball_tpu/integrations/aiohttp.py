"""aiohttp drop-in: route a stock ``aiohttp.ClientSession`` through
cueball pools.

The second half of the ecosystem drop-in story (see
:mod:`cueball_tpu.integrations.httpx` for the first): aiohttp's
pluggable seam is the connector, so this module provides::

    import aiohttp
    from cueball_tpu.integrations.aiohttp import CueballConnector

    session = aiohttp.ClientSession(connector=CueballConnector({
        'spares': 2, 'maximum': 8,
        'recovery': {'default': {'timeout': 2000, 'retries': 3,
                                 'delay': 100, 'maxDelay': 2000}},
    }))
    async with session.get('http://my-service.example/') as r:  # pooled
        ...

Mapping of aiohttp's connector contract onto cueball (mirroring how
reference lib/agent.js:275-396 maps node's request lifecycle onto
claim handles):

- ``connect(req, ...)`` -> ``pool.claim()`` on the pool for the
  request's (host, port, is_ssl); the ClientTimeout.connect value
  bounds the claim. The claimed cueball connection owns an aiohttp
  ``ResponseHandler`` protocol, which is exactly what aiohttp drives
  for the request/response cycle — parsing, streaming bodies and
  chunked uploads all behave stock.
- aiohttp releases a reusable connection -> ``handle.release()``; a
  connection flagged ``should_close`` (or explicitly closed) ->
  ``handle.close()``. The base connector's own keep-alive cache is
  bypassed entirely — cueball is the sole pooler, so its spares
  policy, backoff, dead-backend monitoring and rebalancing govern.
- claim failures surface as aiohttp client errors so stock error
  handling keeps working: ``ClaimTimeoutError`` ->
  ``aiohttp.ConnectionTimeoutError``; ``NoBackendsError`` /
  ``PoolFailedError`` / ``PoolStoppingError`` ->
  ``aiohttp.ClientConnectionError``.

Not supported through this connector: proxies and certificate
fingerprint pinning (both raise ``ClientConnectionError``); use a
stock connector for those endpoints.
"""

from __future__ import annotations

import asyncio
import ssl as mod_ssl

import aiohttp
from aiohttp.client_proto import ResponseHandler

from .. import errors as mod_errors
from ..events import EventEmitter
from ..pool import ConnectionPool
from ..resolver import pool_resolver
from . import apply_default_pool_policy


class _WatchedHandler(ResponseHandler):
    """ResponseHandler that reports connection loss to the owning
    pooled connection even while it sits idle in the pool (same need
    as agent._WatchedProtocol: a backend FIN must evict the idle
    connection, not fester until the next claim)."""

    def __init__(self, loop, owner):
        super().__init__(loop)
        self._cb_owner = owner

    def connection_lost(self, exc):
        super().connection_lost(exc)
        self._cb_owner._on_lost(exc)


class AioPooledConnection(EventEmitter):
    """Cueball connection-interface object owning one aiohttp
    ResponseHandler protocol (the constructSocket analogue,
    reference lib/agent.js:146-197)."""

    def __init__(self, backend: dict, ssl_ctx, server_hostname):
        super().__init__()
        self.backend = backend
        self.proto: ResponseHandler | None = None
        self.destroyed = False
        self._ssl_ctx = ssl_ctx
        self._server_hostname = server_hostname
        self._task = asyncio.ensure_future(self._connect())

    async def _connect(self):
        try:
            loop = asyncio.get_running_loop()
            kwargs = {}
            if self._ssl_ctx is not None:
                kwargs['ssl'] = self._ssl_ctx
                kwargs['server_hostname'] = self._server_hostname
            # aiohttp owns TLS/proto negotiation here; the seam's
            # create_stream verb can't express it yet.
            _, proto = await loop.create_connection(  # cblint: ignore=C110
                lambda: _WatchedHandler(loop, self),
                self.backend['address'], self.backend['port'],
                **kwargs)
            self.proto = proto
            self.emit('connect')
        except (OSError, mod_ssl.SSLError) as e:
            self.emit('error', e)
        except asyncio.CancelledError:
            pass

    def _on_lost(self, exc):
        if self.destroyed:
            return
        if exc is not None:
            self.emit('error', exc)
        else:
            self.emit('close')

    def destroy(self):
        self.destroyed = True
        if self.proto is not None:
            self.proto.close()
        elif not self._task.done():
            self._task.cancel()

    def unref(self):
        pass

    def ref(self):
        pass


class CueballConnector(aiohttp.BaseConnector):
    """``aiohttp.BaseConnector`` whose connections come from cueball
    ConnectionPools (one per (host, port, TLS settings), created
    lazily — requests with different ``ssl`` arguments to the same
    host get different pools, so an ``ssl=False`` request can never
    be served an unverified connection pooled for a verified one, and
    vice versa).

    `options` are pool options (``spares``, ``maximum``,
    ``recovery``, ``resolvers``, ``service``, ``log``, ...);
    ``recovery`` defaults to a conservative policy and
    ``spares``/``maximum`` to 2/8 so one-line adoption needs zero
    cueball-specific configuration.

    For a host whose backends need a custom resolver (failover over a
    static list, SRV discovery under a different name...), pre-create
    its pool::

        connector.create_pool('svc.local', 80,
                              resolver=my_resolver)

    Must be constructed inside a running event loop (the aiohttp
    convention for connectors and sessions alike).
    """

    def __init__(self, options: dict | None = None, **kwargs):
        super().__init__(**kwargs)
        self._cb_options = apply_default_pool_policy(options)
        self._cb_pools: dict[tuple, ConnectionPool] = {}
        self._cb_resolvers: dict[tuple, object] = {}
        self._cb_claims: dict[ResponseHandler, object] = {}
        self._cb_closing = False   # set synchronously by close()

    # -- pool plumbing ----------------------------------------------------

    @staticmethod
    def _ssl_key(sslobj):
        """Normalize a ConnectionKey.ssl value into a hashable pool-key
        component. Distinct TLS settings MUST map to distinct pools —
        sharing would let an ssl=False request's pool serve unverified
        connections to a later verified request."""
        if sslobj is True or sslobj is None:
            return 'default'
        if sslobj is False:
            return 'noverify'
        if isinstance(sslobj, mod_ssl.SSLContext):
            return sslobj          # keyed (and kept alive) by identity
        raise aiohttp.ClientConnectionError(
            'CueballConnector does not support ssl=%r '
            '(fingerprint pinning needs a stock connector)' % (sslobj,))

    def _ssl_context_for(self, key):
        if not key.is_ssl:
            return None, None
        server_hostname = key.host
        sslobj = key.ssl
        if isinstance(sslobj, mod_ssl.SSLContext):
            return sslobj, server_hostname
        if sslobj is False:
            ctx = mod_ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = mod_ssl.CERT_NONE
            return ctx, server_hostname
        return mod_ssl.create_default_context(), server_hostname

    def create_pool(self, host: str, port: int, *, is_ssl: bool = False,
                    resolver=None, ssl_ctx=None) -> ConnectionPool:
        """Pre-create the pool for (host, port[, is_ssl]) with a custom
        resolver (the create_pool analogue,
        reference lib/agent.js:464-488). With ``ssl_ctx`` the pool
        serves requests passing that same context as their ``ssl``;
        otherwise (is_ssl) it serves default-verification requests."""
        key = (host, port, is_ssl,
               (ssl_ctx if ssl_ctx is not None else 'default')
               if is_ssl else None)
        if key in self._cb_pools:
            raise RuntimeError(
                'a pool already exists for %s:%d (ssl=%s)' %
                (host, port, is_ssl))
        return self._make_pool(key, host, port, resolver=resolver,
                               ssl_ctx=ssl_ctx)

    def get_pool(self, host: str, port: int, is_ssl: bool = False,
                 sslobj=None) -> ConnectionPool | None:
        key = (host, port, is_ssl,
               self._ssl_key(sslobj) if is_ssl else None)
        return self._cb_pools.get(key)

    def _make_pool(self, key: tuple, host: str, port: int,
                   resolver=None, ssl_ctx=None,
                   server_hostname=None) -> ConnectionPool:
        # The one chokepoint every pool-creation path funnels through
        # (connect() and the public create_pool()): after close() has
        # begun, a fresh pool+resolver would be stored into the
        # already-torn-down dicts and never stopped.
        if self._closed or self._cb_closing:
            raise RuntimeError('CueballConnector is closed')
        opts = self._cb_options
        is_ssl = key[2]
        if resolver is None:
            resolver = pool_resolver(
                host, port,
                service=opts.get('service') or
                ('_https._tcp' if is_ssl else '_http._tcp'),
                recovery=opts['recovery'],
                resolvers=opts.get('resolvers'),
                log=opts.get('log'))

        def construct(backend):
            return AioPooledConnection(backend, ssl_ctx,
                                       server_hostname or host)

        pool_opts = {
            'domain': host,
            'resolver': resolver,
            'constructor': construct,
            'maximum': opts['maximum'],
            'spares': opts['spares'],
            'recovery': opts['recovery'],
        }
        for passthrough in ('log', 'collector', 'checker',
                            'checkTimeout', 'targetClaimDelay',
                            'maxChurnRate'):
            if passthrough in opts:
                pool_opts[passthrough] = opts[passthrough]
        pool = ConnectionPool(pool_opts)
        if resolver.is_in_state('stopped'):
            resolver.start()
        self._cb_pools[key] = pool
        self._cb_resolvers[key] = resolver
        return pool

    # -- the connector contract -------------------------------------------

    async def connect(self, req, traces, timeout):
        """Claim a pooled connection and hand aiohttp its protocol
        (replaces BaseConnector.connect: cueball is the sole pooler,
        the base keep-alive cache is never used)."""
        # _cb_closing is set synchronously at the top of close():
        # aiohttp's own _closed flips only at the END of the async
        # teardown, and a connect() in that window would re-create a
        # pool+resolver in the just-emptied dict that nothing would
        # ever stop (the httpx twin sets its flag synchronously too).
        if self._closed or self._cb_closing:
            raise aiohttp.ClientConnectionError('Connector is closed.')
        if req.proxy:
            raise aiohttp.ClientConnectionError(
                'CueballConnector does not support proxies; mount a '
                'stock connector for proxied endpoints')
        ckey = req.connection_key
        key = (ckey.host, ckey.port, ckey.is_ssl,
               self._ssl_key(ckey.ssl) if ckey.is_ssl else None)
        pool = self._cb_pools.get(key)
        if pool is None:
            ssl_ctx, server_hostname = self._ssl_context_for(ckey)
            pool = self._make_pool(key, ckey.host, ckey.port,
                                   ssl_ctx=ssl_ctx,
                                   server_hostname=server_hostname)

        claim_opts = {}
        connect_timeout = getattr(timeout, 'connect', None)
        if connect_timeout is not None and not pool.codel_enabled():
            claim_opts['timeout'] = connect_timeout * 1000.0

        if traces:
            for trace in traces:
                await trace.send_connection_create_start()
        try:
            if connect_timeout is not None and pool.codel_enabled():
                # CoDel pools forbid an explicit claim timeout, but
                # the caller's connect timeout still binds: race the
                # whole claim from outside (same contract as the
                # httpx transport; docs/api.md integrations).
                try:
                    handle, sock = await asyncio.wait_for(
                        pool.claim(claim_opts), connect_timeout)
                except asyncio.TimeoutError as e:
                    raise mod_errors.ClaimTimeoutError(pool) from e
            else:
                handle, sock = await pool.claim(claim_opts)
        except mod_errors.ClaimTimeoutError as e:
            raise aiohttp.ConnectionTimeoutError(str(e)) from e
        except (mod_errors.NoBackendsError,
                mod_errors.PoolFailedError,
                mod_errors.PoolStoppingError) as e:
            raise aiohttp.ClientConnectionError(str(e)) from e
        if traces:
            for trace in traces:
                await trace.send_connection_create_end()

        proto = sock.proto
        if self._closed or proto is None or not proto.is_connected():
            handle.close()
            raise aiohttp.ClientConnectionError(
                'Connector is closed.' if self._closed else
                'claimed connection is no longer connected')
        self._cb_claims[proto] = handle
        return aiohttp.connector.Connection(self, ckey, proto,
                                            self._loop)

    def _release(self, key, protocol, *, should_close: bool = False):
        """aiohttp hands the connection back: map onto the claim
        handle (reference 'free'/'close' handlers,
        lib/agent.js:297-340)."""
        handle = self._cb_claims.pop(protocol, None)
        if handle is None:
            return
        if should_close or protocol.should_close:
            handle.close()
        else:
            handle.release()

    def _cb_reclaim(self):
        for proto, handle in list(self._cb_claims.items()):
            self._cb_claims.pop(proto, None)
            if handle.is_in_state('claimed'):
                handle.close()

    def close(self, *, abort_ssl: bool = False):
        """Stop every pool (and its resolver), reclaiming outstanding
        claims, then run the base teardown. New connect()s are
        rejected from this point on, not from the end of the task."""
        self._cb_closing = True
        return self._loop.create_task(self._cb_close(abort_ssl))

    async def _cb_close(self, abort_ssl: bool):
        pools = list(self._cb_pools.values())
        resolvers = list(self._cb_resolvers.values())
        self._cb_pools = {}
        self._cb_resolvers = {}
        self._cb_reclaim()
        for pool in pools:
            if not (pool.is_in_state('stopping') or
                    pool.is_in_state('stopped')):
                pool.stop()
        for pool in pools:
            while not pool.is_in_state('stopped'):
                self._cb_reclaim()
                await asyncio.sleep(0.01)
        for res in resolvers:
            if not res.is_in_state('stopped'):
                res.stop()
        await super().close(abort_ssl=abort_ssl)

"""Ecosystem drop-in integrations.

The reference's flagship adoption property is that its HttpAgent is a
drop-in node ``http.Agent`` — existing apps route their traffic through
cueball pools by changing one constructor option
(reference lib/agent.js:30-94, README.adoc:35-141). These modules are
the Python-ecosystem analogues, built on the pluggable seams Python
HTTP clients actually expose:

- :mod:`cueball_tpu.integrations.httpx` —
  ``httpx.AsyncBaseTransport`` backed by cueball ConnectionPools.
- :mod:`cueball_tpu.integrations.aiohttp` —
  ``aiohttp.BaseConnector`` backed by cueball ConnectionPools.

Each submodule imports its host library at module import time (not at
package import), so cueball_tpu itself never requires httpx/aiohttp.
"""


def apply_default_pool_policy(options: dict | None) -> dict:
    """The shared zero-config pool policy for drop-in integrations:
    unlike the agent (which, like the reference, requires `recovery`),
    one-line adoption must work with no cueball-specific configuration,
    so both integrations default to 2 spares, 8 maximum, and a
    conservative recovery."""
    opts = dict(options or {})
    opts.setdefault('spares', 2)
    opts.setdefault('maximum', 8)
    opts.setdefault('recovery', {'default': {
        'timeout': 2000, 'retries': 3,
        'delay': 100, 'maxDelay': 2000}})
    return opts

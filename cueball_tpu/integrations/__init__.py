"""Ecosystem drop-in integrations.

The reference's flagship adoption property is that its HttpAgent is a
drop-in node ``http.Agent`` — existing apps route their traffic through
cueball pools by changing one constructor option
(reference lib/agent.js:30-94, README.adoc:35-141). These modules are
the Python-ecosystem analogues, built on the pluggable seams Python
HTTP clients actually expose:

- :mod:`cueball_tpu.integrations.httpx` —
  ``httpx.AsyncBaseTransport`` backed by cueball ConnectionPools.
- :mod:`cueball_tpu.integrations.aiohttp` —
  ``aiohttp.BaseConnector`` backed by cueball ConnectionPools.

Each submodule imports its host library at module import time (not at
package import), so cueball_tpu itself never requires httpx/aiohttp.
"""

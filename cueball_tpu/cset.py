"""ConnectionSet: pool variant for multiplexed protocols.

Rebuild of reference `lib/set.js`. Where a Pool hands out exclusive
claims, a Set maintains at most one connection per distinct backend
(singleton planning) and advertises whole connections to the consumer via
'added'(key, conn, handle) / 'removed'(key, conn, handle) events; the
consumer drains and then releases/closes the handle. Used for protocols
that multiplex many requests over one socket (LDAP, HTTP/2, custom RPC)
where claim/release bookkeeping per request makes no sense
(reference docs/api.adoc for ConnectionSet; lib/set.js:34-140).

Key behaviors preserved:
- serial-numbered connection keys `key + '.' + serial`
  (reference lib/set.js:480-535)
- never deliberately remove the last working connection
  (reference lib/set.js:417-435)
- `assert_emit` crash-if-unhandled for 'added'/'removed'
  (reference lib/set.js:471-479)
- `set_target()` dynamic resize (reference lib/set.js:351-355)
- consumer-driven drain: 'removed' is emitted, then the consumer calls
  handle.release()/close() when the connection is actually drained.
"""

from __future__ import annotations

import logging
import math

from . import trace as mod_trace
from . import utils as mod_utils
from .connection_fsm import ConnectionSlotFSM, obtain_claim_handle
from .events import EventEmitter
from .fsm import FSM
from .pool import _Interval
from .runq import defer


class ConnectionSet(FSM):
    """Reference CueBallConnectionSet (lib/set.js:34-140)."""

    def __init__(self, options: dict):
        if not isinstance(options, dict):
            raise AssertionError('options must be a dict')
        constructor = options.get('constructor')
        # Same transport seam as ConnectionPool: options['transport']
        # supplies the constructor when none is passed explicitly.
        self.cs_transport = None
        if options.get('transport') is not None:
            from . import transport as mod_transport
            self.cs_transport = mod_transport.get_transport(
                options['transport'])
            if constructor is None:
                constructor = self.cs_transport.connector
        if not callable(constructor):
            raise AssertionError('options.constructor must be callable')

        self.cs_uuid = mod_utils.make_uuid()
        self.cs_constructor = constructor

        if options.get('resolver') is None:
            raise AssertionError('options.resolver is required')
        self.cs_resolver = options['resolver']

        recovery = options.get('recovery')
        mod_utils.assert_recovery_set(recovery or {})
        if not recovery or 'default' not in recovery:
            raise AssertionError('options.recovery.default is required')
        self.cs_recovery = recovery

        self.cs_conn_handles_err = bool(
            options.get('connectionHandlesError'))

        self.cs_log = mod_utils.make_child_logger(
            options.get('log') or logging.getLogger('cueball.cset'),
            component='CueBallConnectionSet',
            domain=options.get('domain'),
            service=options.get('service'), cset=self.cs_uuid)
        self.cs_domain = options.get('domain')

        self.cs_collector = mod_utils.create_error_metrics(options)

        target = options.get('target')
        maximum = options.get('maximum')
        if not isinstance(target, int) or not isinstance(maximum, int):
            raise AssertionError(
                'options.target and options.maximum must be numbers')
        self.cs_target = target
        self.cs_max = maximum

        self.cs_keys: list[str] = []
        self.cs_backends: dict[str, dict] = {}
        self.cs_fsm: dict[str, ConnectionSlotFSM] = {}
        self.cs_dead: dict[str, bool] = {}

        # Serial numbers generate per-connection keys
        # (reference lib/set.js:80-95).
        self.cs_serials: dict[str, int] = {}
        self.cs_connections: dict[str, object] = {}
        self.cs_connection_keys: dict[str, list[str]] = {}
        self.cs_lconns: dict[str, 'LogicalConnection'] = {}

        self.cs_last_rebalance = None
        self.cs_in_rebalance = False
        self.cs_rebal_scheduled = False
        self.cs_counters: dict[str, int] = {}
        self.cs_last_error = None

        self.cs_rebal_timer = EventEmitter()
        self.cs_rebal_timer_inst = _Interval(10000, self.cs_rebal_timer)

        shuffle_intvl = options.get('decoherenceInterval')
        if shuffle_intvl is None or shuffle_intvl < 60:
            shuffle_intvl = 60
        self.cs_shuffle_timer = EventEmitter()
        self.cs_shuffle_timer_inst = _Interval(
            shuffle_intvl * 1000, self.cs_shuffle_timer)

        super().__init__('starting')

    # -- resolver plumbing ------------------------------------------------

    def on_resolver_added(self, k: str, backend: dict) -> None:
        backend['key'] = k
        assert k not in self.cs_keys, 'Resolver key is a duplicate'
        idx = mod_utils.get_rng().randrange(len(self.cs_keys) + 1)
        self.cs_keys.insert(idx, k)
        self.cs_backends[k] = backend
        self.rebalance()

    def on_resolver_removed(self, k: str) -> None:
        assert k in self.cs_keys, \
            'Resolver removed key that is not present in cs_keys'
        self.cs_keys.remove(k)
        self.cs_backends.pop(k, None)
        self.cs_dead.pop(k, None)

        fsm = self.cs_fsm.get(k)
        if fsm is not None:
            fsm.set_unwanted()

        for ck in list(self.cs_connection_keys.get(k) or []):
            lconn = self.cs_lconns[ck]
            if not lconn.is_in_state('stopped'):
                lconn.drain()

    def is_declared_dead(self, backend: str) -> bool:
        return self.cs_dead.get(backend) is True

    isDeclaredDead = is_declared_dead

    def should_retry_backend(self, backend: str) -> bool:
        return backend in self.cs_backends

    # -- states ------------------------------------------------------------

    def state_starting(self, S):
        S.validTransitions(['failed', 'running', 'stopping'])
        from .monitor import pool_monitor
        pool_monitor.register_set(self)

        S.on(self.cs_resolver, 'added', self.on_resolver_added)
        S.on(self.cs_resolver, 'removed', self.on_resolver_removed)

        if self.cs_resolver.is_in_state('failed'):
            self.cs_log.warning('resolver has already failed, cset will '
                                'start up in "failed" state')
            self.cs_last_error = self.cs_resolver.get_last_error()
            S.gotoState('failed')
            return

        def on_res_changed(st):
            if st == 'failed':
                self.cs_log.warning(
                    'underlying resolver failed, moving cset to '
                    '"failed" state')
                self.cs_last_error = self.cs_resolver.get_last_error()
                S.gotoState('failed')
        S.on(self.cs_resolver, 'stateChanged', on_res_changed)

        if self.cs_resolver.is_in_state('running'):
            for k, backend in self.cs_resolver.list().items():
                self.on_resolver_added(k, backend)

        S.on(self, 'connectedToBackend', lambda *a:
             S.gotoState('running'))

        def on_closed_backend(*a):
            dead = len(self.cs_dead)
            if dead >= len(self.cs_keys):
                self.cs_log.warning(
                    'cset has exhausted all retries, now moving to '
                    '"failed" state (%d dead)', dead)
                S.gotoState('failed')
        S.on(self, 'closedBackend', on_closed_backend)

        S.goto_state_on(self, 'stopAsserted', 'stopping')

    def state_failed(self, S):
        S.validTransitions(['running', 'stopping'])
        S.on(self.cs_resolver, 'added', self.on_resolver_added)
        S.on(self.cs_resolver, 'removed', self.on_resolver_removed)
        S.on(self.cs_shuffle_timer, 'timeout', self.reshuffle)

        def on_connected(*a):
            assert not self.cs_resolver.is_in_state('failed')
            self.cs_log.info('successfully connected to a backend, '
                             'moving back to running state')
            S.gotoState('running')
        S.on(self, 'connectedToBackend', on_connected)

        S.goto_state_on(self, 'stopAsserted', 'stopping')

        # Pending-event re-check (same race as the pool's failed state):
        # a connection that reached 'idle'/'busy' in this loop turn
        # emitted connectedToBackend before we started listening.
        for fsm in self.cs_fsm.values():
            if fsm.is_in_state('idle') or fsm.is_in_state('busy'):
                self.cs_log.info(
                    'entered failed with a live connection already up; '
                    'returning to running')
                S.gotoState('running')
                return

    def state_running(self, S):
        S.validTransitions(['failed', 'stopping'])
        S.on(self.cs_resolver, 'added', self.on_resolver_added)
        S.on(self.cs_resolver, 'removed', self.on_resolver_removed)
        S.on(self.cs_rebal_timer, 'timeout', self.rebalance)
        S.on(self.cs_shuffle_timer, 'timeout', self.reshuffle)

        def on_closed_backend(*a):
            dead = len(self.cs_dead)
            if dead >= len(self.cs_keys):
                self.cs_log.warning(
                    'cset has exhausted all retries, now moving to '
                    '"failed" state (%d dead)', dead)
                S.gotoState('failed')
        S.on(self, 'closedBackend', on_closed_backend)

        S.goto_state_on(self, 'stopAsserted', 'stopping')

    def state_stopping(self, S):
        S.validTransitions(['stopped'])
        fsms = list(self.cs_fsm.values())
        self.cs_backends = {}
        remaining = {'n': len(fsms)}

        def done_one():
            remaining['n'] -= 1
            if remaining['n'] == 0:
                S.gotoState('stopped')

        if not fsms:
            S.immediate(lambda: S.gotoState('stopped'))
            return

        for fsm in fsms:
            k = fsm.csf_backend['key']
            cks = list(self.cs_connection_keys.get(k) or [])

            if fsm.is_in_state('stopped') or fsm.is_in_state('failed'):
                done_one()
            else:
                def on_changed(s, _fsm=fsm):
                    if s in ('stopped', 'failed'):
                        done_one()
                S.on(fsm, 'stateChanged', on_changed)
                fsm.set_unwanted()

            # Drain advertised connections async, avoiding FSM loops when
            # stop() is called from an 'added' handler
            # (reference lib/set.js:306-317).
            for ck in cks:
                def drain_one(_ck=ck):
                    lconn = self.cs_lconns.get(_ck)
                    if lconn is not None and \
                            not lconn.is_in_state('stopped'):
                        lconn.drain()
                # Deliberately NOT S.immediate: the drain must still run
                # if the set reaches 'stopped' before the tick fires.
                defer(drain_one)

    def state_stopped(self, S):
        S.validTransitions([])
        from .monitor import pool_monitor
        pool_monitor.unregister_set(self)
        self.cs_keys = []
        self.cs_fsm = {}
        self.cs_connections = {}
        self.cs_backends = {}
        self.cs_rebal_timer_inst.cancel()
        self.cs_shuffle_timer_inst.cancel()

    # -- public interface --------------------------------------------------

    def reshuffle(self) -> None:
        if len(self.cs_keys) <= 1:
            return
        taken = self.cs_keys.pop()
        idx = mod_utils.get_rng().randrange(len(self.cs_keys) + 1)
        if len(self.cs_keys) > self.cs_target and idx < self.cs_target:
            self.cs_log.info('random shuffle puts backend "%s" at idx %d',
                             taken, idx)
        self.cs_keys.insert(idx, taken)
        self.rebalance()

    def stop(self) -> None:
        self.emit('stopAsserted')

    def set_target(self, target: int) -> None:
        """Dynamically resize the set (reference lib/set.js:351-355)."""
        self.cs_target = target
        self.rebalance()

    setTarget = set_target

    def get_last_error(self):
        return self.cs_last_error

    getLastError = get_last_error

    def get_connections(self) -> list:
        """Currently-advertised live connections."""
        conns = []
        for lconn in self.cs_lconns.values():
            if lconn.is_in_state('advertised'):
                conns.append(lconn.lc_conn)
        return conns

    getConnections = get_connections

    def _incr_counter(self, counter: str) -> None:
        mod_utils.update_error_metrics(
            self.cs_collector, self.cs_uuid, counter)
        self.cs_counters[counter] = self.cs_counters.get(counter, 0) + 1

    _incrCounter = _incr_counter

    def assert_emit(self, event, *args) -> bool:
        """Emit that crashes if unhandled: Sets are useless without
        'added'/'removed' consumers (reference lib/set.js:471-479)."""
        if self.listener_count(event) < 1:
            raise RuntimeError('Event "%s" on ConnectionSet must be '
                               'handled' % event)
        return self.emit(event, *args)

    assertEmit = assert_emit

    # -- rebalancing -------------------------------------------------------

    def rebalance(self, *_a) -> None:
        if len(self.cs_keys) < 1:
            return
        if self.is_in_state('stopping') or self.is_in_state('stopped'):
            return
        if self.cs_rebal_scheduled is not False:
            return
        self.cs_rebal_scheduled = True
        defer(self._rebalance)

    def _rebalance(self) -> None:
        """Singleton-mode planning over one-slot-per-backend
        (reference lib/set.js:385-469)."""
        if self.cs_in_rebalance is not False:
            return
        self.cs_in_rebalance = True
        self.cs_rebal_scheduled = False

        conns: dict[str, list] = {}
        total = 0
        working = 0
        for k in self.cs_keys:
            conns[k] = []
            fsm = self.cs_fsm.get(k)
            if fsm is not None:
                conns[k].append(fsm)
                if fsm.is_in_state('busy') or fsm.is_in_state('idle'):
                    working += 1
                total += 1

        plan = mod_utils.plan_rebalance(
            conns, self.cs_dead, self.cs_target, self.cs_max, True)

        if plan['remove'] or plan['add']:
            self.cs_log.debug(
                'rebalancing cset, remove %d, add %d (target = %d, '
                'total = %d)', len(plan['remove']), len(plan['add']),
                self.cs_target, total)

        for fsm in plan['remove']:
            # Never deliberately remove the last working connection
            # (reference lib/set.js:417-435).
            if (fsm.is_in_state('busy') or fsm.is_in_state('idle')) and \
                    working <= 1:
                continue

            k = fsm.csf_backend['key']
            if fsm.is_in_state('busy') or fsm.is_in_state('idle'):
                working -= 1
            fsm.set_unwanted()

            if fsm.is_in_state('stopped') or fsm.is_in_state('failed'):
                self.cs_fsm.pop(k, None)
                total -= 1

            for ck in list(self.cs_connection_keys.get(k) or []):
                lconn = self.cs_lconns[ck]
                if not lconn.is_in_state('stopped'):
                    lconn.drain()

        for k in plan['add']:
            total += 1
            if total > (self.cs_max + 1):
                continue
            # Never more than one slot per backend.
            if k in self.cs_fsm:
                continue
            self.add_connection(k)

        self.cs_in_rebalance = False
        self.cs_last_rebalance = mod_utils.wall_time()

    def create_logi_conn(self, key: str) -> None:
        """Allocate the next serial-numbered logical connection for a
        backend slot (reference lib/set.js:480-535)."""
        fsm = self.cs_fsm[key]
        if key not in self.cs_serials:
            self.cs_serials[key] = 1
        self.cs_connection_keys.setdefault(key, [])

        serial = self.cs_serials[key]
        self.cs_serials[key] += 1
        ckey = '%s.%d' % (key, serial)
        self.cs_connection_keys[key].append(ckey)

        lconn = LogicalConnection({
            'set': self,
            'log': self.cs_log,
            'key': key,
            'ckey': ckey,
            'fsm': fsm,
        })
        self.cs_lconns[ckey] = lconn

        def on_changed(st):
            if st != 'stopped':
                return
            # Clean up, then roll the serial if this slot may produce
            # another connection.
            self.cs_lconns.pop(ckey, None)
            cks = self.cs_connection_keys[key]
            assert ckey in cks
            cks.remove(ckey)

            if key not in self.cs_backends:
                return
            if fsm.is_in_state('failed') or fsm.is_in_state('stopped'):
                return
            self.create_logi_conn(key)
        lconn.on('stateChanged', on_changed)

    def add_connection(self, key: str) -> None:
        if self.is_in_state('stopping') or self.is_in_state('stopped'):
            return

        backend = self.cs_backends[key]
        backend['key'] = key

        fsm = ConnectionSlotFSM({
            'constructor': self.cs_constructor,
            'backend': backend,
            'log': self.cs_log,
            'pool': self,
            'recovery': self.cs_recovery,
            'monitor': self.cs_dead.get(key) is True,
        })
        assert key not in self.cs_fsm
        self.cs_fsm[key] = fsm

        self.create_logi_conn(key)

        # Rebalance when a slot reaches or leaves idle — the points where
        # planning can meaningfully change (reference lib/set.js:558-585).
        state = {'was_idle': False}

        def on_changed(new_state):
            if new_state == 'idle':
                self.emit('connectedToBackend', key, fsm)
                if key in self.cs_dead:
                    del self.cs_dead[key]
                self.rebalance()
                state['was_idle'] = True
                return

            if state['was_idle']:
                state['was_idle'] = False
                self.rebalance()

            if new_state == 'failed':
                # No dead flag for backends gone from the resolver.
                if key in self.cs_backends:
                    self.cs_dead[key] = True
                    err = fsm.get_socket_mgr().get_last_error()
                    if err is not None:
                        self.cs_last_error = err

            if new_state in ('stopped', 'failed'):
                self.cs_fsm.pop(key, None)
                self.emit('closedBackend', fsm)
                self.rebalance()

        fsm.on('stateChanged', on_changed)
        fsm.start()

    addConnection = add_connection


class LogicalConnection(FSM):
    """Per-connection-key lifecycle in a Set:
    init -> advertised -> draining -> stopped
    (reference lib/set.js:632-820). Emits 'added'/'removed' on the Set at
    exactly the right times and owns the ClaimHandle."""

    def __init__(self, options: dict):
        self.lc_set = options['set']
        self.lc_key = options['key']
        self.lc_fsm = options['fsm']
        self.lc_smgr = options['fsm'].get_socket_mgr()
        self.lc_conn = None
        self.lc_ckey = options['ckey']
        self.lc_hdl = None
        self.lc_log = options['log']
        super().__init__('init')

    def drain(self) -> None:
        assert not self.is_in_state('stopped')
        self.emit('drainAsserted')

    def state_init(self, S):
        S.validTransitions(['advertised', 'stopped'])

        def on_claimed(err, hdl=None, conn=None):
            assert not err
            assert hdl is self.lc_hdl
            self.lc_conn = conn
            S.gotoState('advertised')

        self.lc_hdl = obtain_claim_handle({
            'pool': self.lc_set,
            'claimStack': ('Error\n'
                           ' at claim\n'
                           ' at ConnectionSet.add_connection\n'
                           ' at ConnectionSet.add_connection'),
            'callback': S.callback(on_claimed),
            'log': self.lc_log,
            'throwError': not self.lc_set.cs_conn_handles_err,
            'claimTimeout': math.inf,
        })
        tracer = mod_trace._runtime
        if tracer is not None:
            # Set claims trace too (the ConnectionSet stands in as the
            # 'pool'; ClaimTrace getattr-guards every pool access).
            tracer.claim_begin(self.lc_hdl, self.lc_set)

        # Keep trying until claimed; fine to retry here since 'added' has
        # not been emitted yet for this ckey
        # (reference lib/set.js:735-757).
        def on_hdl_changed(st):
            if st == 'waiting' and self.lc_hdl.is_in_state('waiting'):
                if self.lc_fsm.is_in_state('idle'):
                    self.lc_hdl.try_(self.lc_fsm)
            elif st in ('failed', 'cancelled'):
                S.gotoState('stopped')
        S.on(self.lc_hdl, 'stateChanged', on_hdl_changed)

        def on_fsm_changed(st):
            if st == 'idle' and self.lc_fsm.is_in_state('idle'):
                if self.lc_hdl.is_in_state('waiting'):
                    self.lc_hdl.try_(self.lc_fsm)
            elif st == 'failed':
                S.gotoState('stopped')
        S.on(self.lc_fsm, 'stateChanged', on_fsm_changed)

        # Drained before ever advertising: straight to stopped.
        S.goto_state_on(self, 'drainAsserted', 'stopped')

    def state_advertised(self, S):
        S.validTransitions(['draining', 'stopped'])

        # Users may .close() at any time, but .release() only after
        # 'removed' (reference lib/set.js:757-791, docs/api.adoc).
        def on_hdl_changed(st):
            if st == 'closed':
                S.gotoState('stopped')
            elif st == 'released':
                raise RuntimeError(
                    'The .release() method may not be called on a '
                    'ConnectionSet handle before "removed" has been '
                    'emitted')
        S.on(self.lc_hdl, 'stateChanged', on_hdl_changed)

        def on_smgr_changed(st):
            if st != 'connected':
                S.gotoState('draining')
        S.on(self.lc_smgr, 'stateChanged', on_smgr_changed)

        S.goto_state_on(self, 'drainAsserted', 'draining')

        self.lc_set.assert_emit(
            'added', self.lc_ckey, self.lc_conn, self.lc_hdl)

    def state_draining(self, S):
        S.validTransitions(['stopped'])

        def on_hdl_changed(st):
            if st in ('closed', 'released', 'cancelled'):
                S.gotoState('stopped')
        S.on(self.lc_hdl, 'stateChanged', on_hdl_changed)

        self.lc_set.assert_emit(
            'removed', self.lc_ckey, self.lc_conn, self.lc_hdl)

    def state_stopped(self, S):
        S.validTransitions([])
        if self.lc_hdl is not None and (
                self.lc_hdl.is_in_state('waiting') or
                self.lc_hdl.is_in_state('claiming')):
            self.lc_hdl.cancel()

"""Moore finite-state-machine runtime.

Replaces the reference's external `mooremachine` dependency (reference
docs/internals.adoc:115-131). Everything stateful in this framework is an
explicit Moore machine: behaviour is a function of the current state only,
state entry functions register all event handlers for that state through a
disposable handle, and every handler is torn down on state exit. This
"design out the races" discipline is load-bearing: the reference's hardest
bugs were async-ordering races between interacting FSMs (reference
CHANGES.adoc #92 #108 #111 #144), and the survey calls out the ordering
semantics of async `stateChanged` emission as critical (reference
lib/pool.js:938-945, lib/connection-fsm.js:881-889).

Semantics replicated:
- States are methods named ``state_<name>`` taking a :class:`StateHandle`.
  Sub-states (``"stopping.backends"``) map to ``state_stopping_backends``.
- Entering a state synchronously runs its entry function; ``stateChanged``
  is emitted *asynchronously* (loop.call_soon, the setImmediate analogue),
  once per transition, in transition order.
- ``S.on(emitter, event, cb)``, ``S.timeout(ms, cb)``, ``S.interval(ms,
  cb)``, ``S.immediate(cb)`` register disposables that are removed /
  cancelled when the FSM leaves the state; callbacks are additionally
  gated so a stale callback that already fired into the loop is a no-op.
- ``S.validTransitions([...])`` whitelists exits (reference usage e.g.
  lib/pool.js:316); an illegal transition raises.
- State history ring buffer (mooremachine keeps these for core-dump
  debugging; the reference test suite asserts on ``fsm_history``,
  reference test/pool.test.js:373-374).
- A module-level transition-trace hook stands in for mooremachine's
  dtrace USDT probes on transitions (reference docs/internals.adoc:125-131).
"""

from __future__ import annotations

import asyncio
import typing

from .events import EventEmitter, _native
from . import runq
from . import utils as mod_utils

# Module-level transition trace hooks: fn(fsm, old_state, new_state).
# The dtrace-probe analogue (reference docs/internals.adoc:125-131):
# attach a tracer at runtime with add_transition_tracer() and every FSM
# transition in the process reports here with negligible cost when empty.
_TRANSITION_TRACERS: list[typing.Callable] = []

# Bound to cueball_tpu.profile while its sampler runs, so SIGPROF
# samples landing inside a state-entry function attribute to the fsm
# phase (the native engine marks the phase in C; this seam covers the
# pure engine).
_prof = None


def add_transition_tracer(fn: typing.Callable) -> None:
    _TRANSITION_TRACERS.append(fn)


def remove_transition_tracer(fn: typing.Callable) -> None:
    try:
        _TRANSITION_TRACERS.remove(fn)
    except ValueError:
        pass


def get_loop() -> asyncio.AbstractEventLoop:
    try:
        return asyncio.get_running_loop()
    except RuntimeError:
        raise RuntimeError(
            'cueball_tpu FSMs schedule timers and deferred events on the '
            'asyncio event loop; construct and drive them from within a '
            'running loop (e.g. inside asyncio.run())') from None


class _PyStateHandle:
    """Handle passed to each state entry function (pure-Python
    fallback; see the native-backed StateHandle below).

    All registrations made through the handle live exactly as long as the
    FSM remains in the state that created them. Disposables are stored
    raw — an ``(emitter, event, listener)`` tuple or a zero-arg callable
    — to keep the per-transition listener churn cheap (this engine is
    the claim hot path's inner loop).
    """

    __slots__ = ('_fsm', '_state', '_disposables', '_valid',
                 '_transitioned')

    def __init__(self, fsm: 'FSM', state: str):
        self._fsm = fsm
        self._state = state
        self._disposables: list = []
        self._valid: list[str] | None = None
        self._transitioned = False

    # -- liveness --------------------------------------------------------

    def is_current(self) -> bool:
        return self._fsm._fsm_state_handle is self

    # Gates wrap callbacks the framework registers through a StateHandle;
    # they are never user listeners, so they must read as internal to
    # count_listeners (the claimed-connection leak/raise checks,
    # reference lib/connection-fsm.js:786-808).
    def _gate(self, cb: typing.Callable) -> typing.Callable:
        """Wrap cb so it only runs while this state is current."""
        def gated(*args, **kwargs):
            if self.is_current():
                return cb(*args, **kwargs)
            return None
        gated._cueball_internal = True
        return gated

    callback = _gate  # public alias, mooremachine's S.callback()

    # -- registrations ---------------------------------------------------

    def on(self, emitter: EventEmitter, event: str,
           cb: typing.Callable) -> None:
        gated = self._gate(cb)
        emitter.on(event, gated)
        self._disposables.append((emitter, event, gated))

    def _add_disposable(self, d: typing.Callable) -> None:
        self._disposables.append(d)

    # -- transitions -----------------------------------------------------

    def valid_transitions(self, states: list[str]) -> None:
        self._valid = list(states)

    validTransitions = valid_transitions

    def goto_state(self, state: str) -> None:
        if not self.is_current() or self._transitioned:
            # A stale handle must never move the machine (mooremachine
            # throws here too); this is the core race guard. A handle
            # that already requested a transition counts as stale even
            # if the hop is still queued (re-entrant gotoState).
            raise RuntimeError(
                '%s: gotoState(%s) called from stale state handle for '
                'state "%s" (now in "%s")' % (
                    self._fsm, state, self._state, self._fsm.get_state()))
        self._transitioned = True
        self._fsm._goto_state(state)

    gotoState = goto_state

    # -- teardown --------------------------------------------------------

    def _dispose_all(self) -> None:
        # Steal the list before invoking anything: a disposable that
        # re-enters _dispose_all must see a fresh list, not re-run the
        # sequence being iterated (mirrors the C StateHandleBase).
        lst = self._disposables
        self._disposables = []
        for i, d in enumerate(lst):
            try:
                if type(d) is tuple:
                    d[0].remove_listener(d[1], d[2])
                else:
                    d()
            except BaseException:
                # Keep the not-yet-run disposables reachable for a
                # retry rather than leaking their registrations.
                self._disposables.extend(lst[i:])
                raise


class _TimerRegistrationsMixin:
    """Timer/scheduling registrations shared by both StateHandle
    implementations, built on _gate/_add_disposable/is_current."""

    __slots__ = ()

    def timeout(self, ms: float, cb: typing.Callable) -> object:
        loop = get_loop()
        handle = loop.call_later(ms / 1000.0, self._gate(cb))
        self._add_disposable(handle.cancel)
        return handle

    def interval(self, ms: float, cb: typing.Callable) -> object:
        loop = get_loop()
        state = {'handle': None, 'cancelled': False}
        gated = self._gate(cb)

        def fire():
            if state['cancelled'] or not self.is_current():
                return
            gated()
            if not state['cancelled'] and self.is_current():
                state['handle'] = loop.call_later(ms / 1000.0, fire)

        state['handle'] = loop.call_later(ms / 1000.0, fire)

        def cancel():
            state['cancelled'] = True
            if state['handle'] is not None:
                state['handle'].cancel()

        self._add_disposable(cancel)
        return state

    def immediate(self, cb: typing.Callable) -> None:
        # The gate already makes the callback a no-op once the state is
        # exited, so the deferral rides the shared engine pump (one
        # scheduled callback per tick) with no cancel disposable needed.
        get_loop()  # fail fast with the helpful no-loop message
        runq.defer(self._gate(cb))

    def goto_state_on(self, emitter: EventEmitter, event: str,
                      state: str) -> None:
        self.on(emitter, event, lambda *a: self.goto_state(state))

    gotoStateOn = goto_state_on

    def goto_state_timeout(self, ms: float, state: str) -> None:
        self.timeout(ms, lambda: self.goto_state(state))

    gotoStateTimeout = goto_state_timeout


if _native is None:
    class StateHandle(_TimerRegistrationsMixin, _PyStateHandle):
        __slots__ = ()
else:
    class StateHandle(_TimerRegistrationsMixin,
                      _native.StateHandleBase):
        """Native-backed state handle: gate construction, listener
        registration/disposal bookkeeping, and the stale-handle
        transition guard run in C (native/emitter.c StateHandleBase);
        timer registrations remain in Python via the mixin."""
        __slots__ = ()

        # The C goto_state_on (closure-free GotoGate) must win over the
        # mixin's lambda-based version in the MRO.
        goto_state_on = _native.StateHandleBase.goto_state_on
        gotoStateOn = goto_state_on


def _state_method_name(state: str) -> str:
    return 'state_' + state.replace('.', '_')


class FSM(EventEmitter):
    """Base Moore machine.

    Subclasses define ``state_<name>(self, S)`` entry methods and call
    ``super().__init__(initial_state)``; the initial state is entered
    synchronously during construction.
    """

    HISTORY_LENGTH = 8

    def __init__(self, initial_state: str):
        super().__init__()
        self._fsm_state: str | None = None
        self._fsm_state_handle: StateHandle | None = None
        self._fsm_history: list[str] = []
        self._fsm_history_at: list[float] = []
        self._fsm_all_state_events: list[str] = []
        self._fsm_in_transition = False
        self._fsm_pending: list[str] = []
        self._goto_state(initial_state)

    # -- introspection ---------------------------------------------------

    def get_state(self) -> str:
        assert self._fsm_state is not None
        return self._fsm_state

    getState = get_state

    def is_in_state(self, state: str) -> bool:
        """True if in `state` or one of its sub-states."""
        cur = self._fsm_state
        if cur is None:
            return False
        if cur == state:
            return True
        # Sub-state check without the `state + '.'` concat (this runs
        # ~14x per claim/release cycle).
        n = len(state)
        return len(cur) > n and cur[n] == '.' and cur.startswith(state)

    isInState = is_in_state

    def get_history(self) -> list[str]:
        return list(self._fsm_history)

    def get_history_timed(self) -> list[tuple[str, float]]:
        """History with entry timestamps (epoch ms) — the debugging
        aid reference changelog #119 added via mooremachine (how long
        did each state, e.g. a claim's 'waiting', actually take)."""
        return list(zip(self._fsm_history, self._fsm_history_at))

    # -- all-state events ------------------------------------------------

    def all_state_event(self, event: str) -> None:
        """Declare an event every state must handle (mooremachine's
        allStateEvent). Emitting it with no registered listener raises,
        which converts a silently-dropped signal into a crash."""
        self._fsm_all_state_events.append(event)

    allStateEvent = all_state_event

    if _native is None:
        # With the native core, the undelivered-all-state-event crash
        # is enforced inside EventEmitter.emit itself (emitter.c
        # emit_check_all_state); no Python override needed.
        def emit(self, event: str, *args) -> bool:
            delivered = super().emit(event, *args)
            if not delivered and event in self._fsm_all_state_events:
                raise RuntimeError(
                    '%r: event "%s" (declared all-state) emitted in '
                    'state "%s" with no handler' % (
                        self, event, self._fsm_state))
            return delivered

    # -- transitions -----------------------------------------------------

    def _check_transition(self, state: str) -> None:
        handle = self._fsm_state_handle
        if handle is not None and handle._valid is not None:
            if state not in handle._valid:
                raise RuntimeError(
                    '%r: invalid transition "%s" -> "%s" (valid: %r)' % (
                        self, self._fsm_state, state, handle._valid))

    def _py_goto_state(self, state: str) -> None:
        self._check_transition(state)

        # Re-entrant gotoState (a state entry function that transitions
        # from within itself) is serialized: queue and run after the
        # current entry completes, preserving transition order. Queued
        # hops are re-validated against the whitelist of the state they
        # actually depart from, at departure time.
        if self._fsm_in_transition:
            self._fsm_pending.append(state)
            return

        self._fsm_in_transition = True
        try:
            self._run_transition(state)
            while self._fsm_pending:
                nxt = self._fsm_pending.pop(0)
                self._check_transition(nxt)
                self._run_transition(nxt)
        finally:
            self._fsm_in_transition = False
            # A failed transition must not leave stale queued hops to
            # replay on a later, unrelated goto_state.
            self._fsm_pending.clear()

    def _py_run_transition(self, state: str) -> None:
        old = self._fsm_state
        if self._fsm_state_handle is not None:
            self._fsm_state_handle._dispose_all()
            self._fsm_state_handle = None

        # Per-class cache of state-name -> unbound entry function; the
        # string munge + getattr is measurable on the claim hot path.
        cls = type(self)
        cache = cls.__dict__.get('_fsm_entry_cache')
        if cache is None:
            cache = {}
            cls._fsm_entry_cache = cache
        entry = cache.get(state)
        if entry is None:
            entry = getattr(cls, _state_method_name(state), None)
            if entry is None:
                raise RuntimeError(
                    '%r: unknown state "%s"' % (self, state))
            cache[state] = entry

        self._fsm_state = state
        self._fsm_history.append(state)
        self._fsm_history_at.append(
            mod_utils.wall_time() * 1000.0)
        if len(self._fsm_history) > self.HISTORY_LENGTH:
            del self._fsm_history[0]
            del self._fsm_history_at[0]

        new_handle = StateHandle(self, state)
        self._fsm_state_handle = new_handle

        for tracer in _TRANSITION_TRACERS:
            tracer(self, old, state)

        prof = _prof
        if prof is None:
            entry(self, new_handle)
        else:
            tok = prof.push_phase('fsm')
            try:
                entry(self, new_handle)
            finally:
                prof.pop_phase(tok)

        # Async (setImmediate-analogue) stateChanged emission; ordering
        # across rapid transitions is preserved by the pump's FIFO.
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # No loop (e.g. pure-unit tests of sync FSMs): emit inline.
            self.emit('stateChanged', state)
        else:
            runq.defer(self.emit, 'stateChanged', state)

    if _native is None:
        _goto_state = _py_goto_state
        _run_transition = _py_run_transition
    else:
        def _goto_state(self, state: str) -> None:
            # C port of _py_goto_state (native/emitter.c
            # fsm_goto_state): whitelist check, re-entrant transition
            # serialization, and finally-cleanup all run in C. The
            # Python body above remains the reference semantics and
            # the CUEBALL_NO_NATIVE fallback. fsm_configure() hands
            # this exact function to C so StateHandle.goto_state can
            # skip the wrapper when it is not overridden.
            _native.fsm_goto_state(self, state)

        def _run_transition(self, state: str) -> None:
            # C port of _py_run_transition (native/emitter.c
            # fsm_run_transition); the Python body above remains the
            # reference semantics and the CUEBALL_NO_NATIVE fallback.
            _native.fsm_run_transition(self, state)

    def __repr__(self) -> str:
        return '<%s state=%s>' % (type(self).__name__, self._fsm_state)


if _native is not None:
    # The C is_in_state (emitter.c Emitter_is_in_state) is a frameless
    # C call for the single most-called predicate on the claim path;
    # semantics match the Python body above exactly.
    FSM.is_in_state = _native.EventEmitter.is_in_state
    FSM.isInState = _native.EventEmitter.is_in_state
    # Inject the Python-side pieces the C transition engine needs: the
    # concrete StateHandle class, the (shared, mutable) tracer list,
    # asyncio's running-loop accessor, and the stock transition
    # functions (so the C engine runs its inlined ports only for
    # classes that do NOT override them — a subclass _goto_state,
    # _check_transition, or _run_transition is always dispatched).
    _native.fsm_configure(StateHandle, _TRANSITION_TRACERS,
                          asyncio.get_running_loop, FSM._goto_state,
                          FSM._check_transition, FSM._run_transition)

"""Scenario harness: schedules, herds, envelopes, replay dumps.

A ``Scenario`` binds a name + seed to a fault schedule and runs a
coroutine under the virtual loop with the FSM transition trace
captured — the same ``fsm.add_transition_tracer`` tuple stream
tests/test_runq_conformance.py pins — so any run is replayable
byte-identically from its seed. On failure it writes a JSON dump
(seed, schedule, error) and appends a one-command replay hint to the
exception, per the corpus contract in docs/netsim.md.

Also here: the thundering-herd client-arrival generator (burst and
Poisson arrivals through the real ``pool.claim_cb`` path, per-client
outcome + latency records) and small envelope statistics
(``quantile``, Jain's fairness index) scenarios assert against.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys

from .. import fsm as mod_fsm
from .. import trace as mod_trace
from .. import utils as mod_utils
from .clock import VirtualClock, run as vrun

DUMP_DIR_ENV = 'CUEBALL_SCENARIO_DUMP_DIR'
DEFAULT_DUMP_DIR = '.netsim-failures'


class Scenario:
    """One named, seeded, scheduled virtual-time run."""

    def __init__(self, name: str, seed: int):
        self.name = name
        self.seed = seed
        self.clock = VirtualClock()
        self.schedule: list[tuple[float, str, object]] = []
        self.fired: list[tuple[float, str]] = []
        self.trace: list[tuple[str, str, str]] = []
        self._loop = None

    def at(self, t_s: float, label: str, fn) -> 'Scenario':
        """Run ``fn()`` at virtual time ``t_s`` (from run start).
        Usable both before ``run`` and from inside the running
        coroutine — in the latter case the timer is armed on the live
        loop immediately."""
        self.schedule.append((t_s, label, fn))
        if self._loop is not None:
            delay = max(0.0, t_s - self.clock.monotonic())
            self._loop.call_later(delay, self._fire, label, fn)
        return self

    def _fire(self, label: str, fn) -> None:
        self.fired.append((self.clock.monotonic(), label))
        fn()

    def metadata(self) -> dict:
        return {
            'scenario': self.name,
            'seed': self.seed,
            'schedule': [[t, label] for t, label, _ in self.schedule],
        }

    def run(self, main, timeout_s: float | None = None):
        """Run ``main`` (a no-arg callable returning a coroutine)
        under the virtual loop with the schedule armed and the FSM
        transition trace captured into ``self.trace``. ``timeout_s``
        bounds VIRTUAL time."""

        async def wrapper():
            loop = asyncio.get_running_loop()
            for t_s, label, fn in self.schedule:
                loop.call_later(t_s, self._fire, label, fn)
            self._loop = loop
            coro = main()
            if timeout_s is not None:
                return await asyncio.wait_for(coro, timeout_s)
            return await coro

        def tracer(fsm_obj, old, new):
            self.trace.append((type(fsm_obj).__name__, old, new))

        mod_fsm.add_transition_tracer(tracer)
        mod_trace.set_run_metadata(self.metadata())
        try:
            return vrun(wrapper(), seed=self.seed, clock=self.clock)
        except BaseException as err:
            self._dump_failure(err)
            raise
        finally:
            self._loop = None
            mod_fsm.remove_transition_tracer(tracer)
            mod_trace.set_run_metadata(None)

    def _dump_failure(self, err: BaseException) -> None:
        """Persist everything needed to replay this exact run and
        print the one-command replay recipe."""
        dump_dir = os.environ.get(DUMP_DIR_ENV, DEFAULT_DUMP_DIR)
        path = os.path.join(
            dump_dir, '%s-seed%d.json' % (self.name, self.seed))
        record = dict(self.metadata())
        record.update({
            'error': '%s: %s' % (type(err).__name__, err),
            'virtual_time_s': self.clock.monotonic(),
            'fired': [[t, label] for t, label in self.fired],
            'transitions': len(self.trace),
            'replay': 'python -m pytest "tests/scenarios" -k '
                      '"%s and %d" -q' % (self.name, self.seed),
        })
        if mod_trace._runtime is not None:
            # Tracing was on for this run: embed the slowest completed
            # claim/DNS traces (full span lists, the NDJSON records
            # parsed back) so the dump shows WHERE the slow claims
            # spent their time, not just that the envelope broke.
            # trace_ring() drains the native ring first, so this works
            # identically under either recorder.
            try:
                done = [t for t in mod_trace.trace_ring()
                        if t.root.end is not None]
                done.sort(key=lambda t: t.root.end - t.root.start,
                          reverse=True)
                record['trace_summary'] = mod_trace.summary()
                record['slowest_traces'] = [
                    [json.loads(line) for line in t.ndjson_lines()]
                    for t in done[:3]]
                # Phase ledger of the same slowest claims: the dump
                # answers "queue wait or service time?" without the
                # reader re-deriving it from raw spans. Pure replay
                # arithmetic — no sampler under VirtualClock.
                from .. import profile as mod_profile
                ledgers = mod_profile.phase_ledger(done)
                if ledgers:
                    record['phase_ledger'] = {
                        'summary': mod_profile.ledger_summary(ledgers),
                        'slowest_claims': sorted(
                            ledgers, key=lambda led: led['wall_ms'],
                            reverse=True)[:3],
                    }
            except Exception:
                pass  # the dump must never mask the original error
        wiretap = sys.modules.get('cueball_tpu.wiretap')
        if wiretap is not None and wiretap.wiretap_enabled():
            # The wire ledger was live during this scenario: embed the
            # per-seam counters and socket_wait wire totals so the
            # dump answers "did the bytes move, and where did the
            # connect time go" next to the slow traces.
            try:
                record['wiretap'] = {
                    'transports': wiretap.snapshot(),
                    'wire_ms': wiretap.wire_totals(),
                    'loop_lag': wiretap.loop_lag_stats(),
                }
            except Exception:
                pass  # same rule: never mask the original error
        health = sys.modules.get('cueball_tpu.parallel.health')
        if health is not None:
            # The health engine ran during this scenario: embed every
            # active monitor's verdict history, so the dump answers
            # "which backend was judged gray, and when" next to the
            # slow traces. Late-bound like the other jax surfaces —
            # a scenario that never imported it pays nothing.
            try:
                monitors = health.active_monitors()
                if monitors:
                    record['health'] = {
                        'fleet': health.reduce_health(
                            [m.hm_last for m in monitors]),
                        'history': [list(m.hm_history)
                                    for m in monitors],
                    }
            except Exception:
                pass  # same rule: never mask the original error
        try:
            os.makedirs(dump_dir, exist_ok=True)
            with open(path, 'w') as f:
                json.dump(record, f, indent=2)
                f.write('\n')
            sys.stderr.write(
                'netsim scenario %r seed=%d FAILED at virtual '
                't=%.3fs — dump: %s\n  replay: %s\n' % (
                    self.name, self.seed, self.clock.monotonic(),
                    path, record['replay']))
        except OSError:
            pass          # dumping is best-effort; the assert rules


# ---------------------------------------------------------------------------
# Thundering-herd client arrivals

async def herd(pool, count: int, rate_per_s: float | None = None,
               timeout_ms: float = 2000.0, hold_s: float | None = None,
               rng=None, cohort=None) -> list[dict]:
    """Launch ``count`` claim attempts against ``pool`` — a burst at
    t=0 when ``rate_per_s`` is None, else Poisson arrivals at that
    rate — through the real claim_cb path. Each client claims with
    ``timeout_ms``, holds for ``hold_s`` (None = one simulated request
    via SimConnection.request(), or 1ms), then releases. Returns one
    record per client: {idx, cohort, t_arrive_s, ok, err, latency_ms}.
    """
    if rng is None:
        rng = mod_utils.get_rng()
    loop = asyncio.get_running_loop()
    clk = mod_utils.get_clock()

    async def one(idx: int, delay_s: float) -> dict:
        await asyncio.sleep(delay_s)
        rec = {'idx': idx, 't_arrive_s': clk.monotonic(),
               'cohort': cohort(idx) if cohort else None,
               'ok': False, 'err': None, 'latency_ms': None}
        t0 = mod_utils.current_millis()
        fut = loop.create_future()

        def cb(err, hdl=None, conn=None):
            if not fut.done():
                fut.set_result((err, hdl, conn))
        pool.claim_cb({'timeout': timeout_ms}, cb)
        err, hdl, conn = await fut
        rec['latency_ms'] = mod_utils.current_millis() - t0
        if err is not None:
            rec['err'] = type(err).__name__
            return rec
        listener = conn.on('error', lambda e=None: None)
        try:
            if hold_s is not None:
                await asyncio.sleep(hold_s)
            elif hasattr(conn, 'request'):
                await conn.request()
            else:
                await asyncio.sleep(0.001)
        finally:
            conn.remove_listener('error', listener)
            try:
                hdl.release()
            except Exception as rel_err:
                rec['err'] = type(rel_err).__name__
                return rec
        rec['ok'] = True
        return rec

    delay = 0.0
    tasks = []
    for i in range(count):
        if rate_per_s is not None:
            delay += rng.expovariate(rate_per_s)
        tasks.append(asyncio.ensure_future(one(i, delay)))
    return list(await asyncio.gather(*tasks))


# ---------------------------------------------------------------------------
# Envelope statistics

def quantile(values, q: float) -> float:
    """Nearest-rank quantile; q in [0, 1]."""
    if not values:
        raise ValueError('quantile of empty sequence')
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
    return ordered[idx]


def jain_index(values) -> float:
    """Jain's fairness index over per-cohort rates: 1.0 = perfectly
    fair, 1/n = one cohort got everything."""
    values = list(values)
    if not values or all(v == 0 for v in values):
        return 1.0
    num = sum(values) ** 2
    den = len(values) * sum(v * v for v in values)
    return num / den


def success_rates(outcomes, key='cohort') -> dict:
    """Per-cohort success rate from herd() records."""
    totals: dict = {}
    oks: dict = {}
    for rec in outcomes:
        c = rec[key]
        totals[c] = totals.get(c, 0) + 1
        oks[c] = oks.get(c, 0) + (1 if rec['ok'] else 0)
    return {c: oks[c] / totals[c] for c in totals}

"""Scriptable DNS for netsim: zones, chaos clients, middleboxes.

Three integration levels, lowest fidelity first:

- ``ScriptedDnsClient``: plugs in as ``options['dnsClient']`` on a
  DNSResolver and answers from a script function, one outcome object
  per query. The per-case fakes the suite grew organically
  (tests/fake_dns.py, the soak chaos client) are thin shims over this.
- ``ChaosDnsClient``: a ScriptedDnsClient whose outcomes are drawn
  from a seeded rng over a weighted band table — answers with short
  TTLs, NXDOMAIN/NODATA/NOTIMP/REFUSED/SERVFAIL, timeouts.
- ``SimWire``: a ``dns_client.DnsTransport`` middlebox. The REAL
  DnsClient encodes queries; SimWire parses them, consults a
  ``SimZone``, and encodes wire-format responses — optionally
  misbehaving per resolver (FORMERR on EDNS, TC-bit truncation,
  cut-off packets, SERVFAIL, blackholes). This exercises the
  _query_wire failure branches (EDNS fallback, TC->TCP retry,
  malformed-packet handling, shared deadlines) that no socket-free
  test could reach before.

All delays are asyncio timers, so under a VirtualLoop they cost no
wall time; all randomness comes from an injected rng. See
docs/netsim.md.
"""

from __future__ import annotations

import asyncio
import ipaddress
import struct

from ..dns_client import (CLASS_IN, TYPE_CODES, TYPE_NAMES, DnsError,
                          DnsMessage, DnsTimeoutError, DnsTransport,
                          _decode_name, encode_name)


def _rr(name, rtype, ttl, target, port=None, priority=0, weight=10):
    rr = {'name': name, 'type': rtype, 'ttl': ttl, 'target': target,
          'port': port}
    if rtype == 'SRV':
        rr['priority'] = priority
        rr['weight'] = weight
    return rr


class DnsOutcome:
    """One query's scripted result. ``rcode`` other than NOERROR is
    delivered as a DnsError; ``timeout`` waits out the query budget
    and delivers DnsTimeoutError; ``delay_ms`` defers delivery on the
    (virtual) loop."""

    def __init__(self, answers=None, authority=None, additionals=None,
                 rcode: str = 'NOERROR', delay_ms: float = 0.0,
                 timeout: bool = False):
        self.answers = list(answers or [])
        self.authority = list(authority or [])
        self.additionals = list(additionals or [])
        self.rcode = rcode
        self.delay_ms = delay_ms
        self.timeout = timeout


class ScriptedDnsClient:
    """DnsClient-shaped object (lookup(opts, cb)) answering from a
    script: ``script(opts) -> DnsOutcome``. Records every opts dict in
    ``history`` for exact-sequence assertions, like the legacy
    tests/fake_dns.py surface."""

    def __init__(self, script=None):
        self.history: list[dict] = []
        if script is not None:
            self.script = script

    def script(self, opts: dict) -> DnsOutcome:
        raise NotImplementedError(
            'pass script= or subclass ScriptedDnsClient')

    def lookup(self, opts: dict, cb) -> None:
        loop = asyncio.get_running_loop()
        self.history.append(opts)
        out = self.script(opts)
        domain = opts['domain']
        if out.timeout:
            loop.call_later(opts.get('timeout', 5000) / 1000.0, cb,
                            DnsTimeoutError(domain), None)
            return
        msg = DnsMessage(1234, 'NOERROR', False, out.answers,
                         out.authority, out.additionals)
        err = None
        if out.rcode != 'NOERROR':
            err = DnsError(out.rcode, domain)
        if out.delay_ms > 0:
            loop.call_later(out.delay_ms / 1000.0, cb, err, msg)
        else:
            loop.call_soon(cb, err, msg)


# Default outcome distribution for ChaosDnsClient: cumulative
# probability bands over the rcode policy matrix, mirroring the soak
# distribution the resolver chaos test established.
CHAOS_BANDS = (
    (0.50, 'answer'),
    (0.62, 'NXDOMAIN'),
    (0.72, 'nodata'),
    (0.79, 'NOTIMP'),
    (0.86, 'REFUSED'),
    (0.93, 'SERVFAIL'),
    (1.01, 'timeout'),
)


class ChaosDnsClient(ScriptedDnsClient):
    """Seeded random outcomes over the full rcode policy matrix.
    Answers use ``ttl``-second TTLs (default 1) so the resolver's
    sleep state re-queries continuously."""

    def __init__(self, rng, bands=CHAOS_BANDS, ttl: int = 1):
        super().__init__()
        self.rng = rng
        self.bands = bands
        self.ttl = ttl
        self.queries = 0

    def script(self, opts: dict) -> DnsOutcome:
        self.queries += 1
        domain, qtype = opts['domain'], opts['type']
        roll = self.rng.random()
        kind = next(k for ceil, k in self.bands if roll < ceil)
        if kind == 'answer':
            answers = []
            if qtype == 'SRV':
                for i in range(self.rng.randint(1, 3)):
                    answers.append(_rr(domain, 'SRV', self.ttl,
                                       't%d.chaos' % i, 100 + i))
            elif qtype == 'A':
                for i in range(self.rng.randint(1, 2)):
                    answers.append(_rr(domain, 'A', self.ttl,
                                       '10.0.0.%d' % (1 + i)))
            elif qtype == 'AAAA' and self.rng.random() < 0.5:
                answers.append(_rr(domain, 'AAAA', self.ttl, 'fd00::1'))
            return DnsOutcome(answers=answers)
        if kind == 'nodata':
            authority = []
            if self.rng.random() < 0.5:
                authority.append(_rr(domain, 'SOA', self.ttl, None))
            return DnsOutcome(authority=authority)
        if kind == 'timeout':
            return DnsOutcome(timeout=True)
        return DnsOutcome(rcode=kind)


# ---------------------------------------------------------------------------
# Authoritative zone data

class SimZone:
    """Mutable authoritative record store. Distinguishes NXDOMAIN
    (never-seen name) from NODATA (known name, no records of the
    queried type), the distinction the resolver's policy matrix keys
    on. Mutate mid-run (set_records / remove) to model flapping."""

    def __init__(self, soa_minimum: int = 5):
        self._records: dict[tuple[str, str], list[dict]] = {}
        self._names: set[str] = set()
        self.soa_minimum = soa_minimum

    @staticmethod
    def _key(domain: str, qtype: str) -> tuple[str, str]:
        return (domain.rstrip('.').lower(), qtype.upper())

    def add(self, domain: str, qtype: str, target, ttl: int = 60,
            port: int | None = None, priority: int = 0,
            weight: int = 10) -> None:
        key = self._key(domain, qtype)
        self._names.add(key[0])
        self._records.setdefault(key, []).append(
            _rr(key[0], key[1], ttl, target, port, priority, weight))

    def add_srv_backend(self, service: str, target: str, port: int,
                        address: str, ttl: int = 60,
                        addr_ttl: int = 60) -> None:
        """One backend = one SRV record plus its address record."""
        self.add(service, 'SRV', target, ttl=ttl, port=port)
        rtype = 'AAAA' if ':' in address else 'A'
        self.add(target, rtype, address, ttl=addr_ttl)

    def set_records(self, domain: str, qtype: str,
                    records: list[dict]) -> None:
        key = self._key(domain, qtype)
        self._names.add(key[0])
        self._records[key] = list(records)

    def remove(self, domain: str, qtype: str | None = None) -> None:
        """Drop records; the name stays known (NODATA, not NXDOMAIN)."""
        name = domain.rstrip('.').lower()
        for key in list(self._records):
            if key[0] == name and qtype in (None, key[1]):
                del self._records[key]

    def forget(self, domain: str) -> None:
        """Drop the name entirely: subsequent queries see NXDOMAIN."""
        self.remove(domain)
        self._names.discard(domain.rstrip('.').lower())

    def resolve(self, domain: str, qtype: str) \
            -> tuple[str, list[dict], list[dict]]:
        """-> (rcode, answers, authority)."""
        key = self._key(domain, qtype)
        if key[0] not in self._names:
            return 'NXDOMAIN', [], []
        answers = list(self._records.get(key) or [])
        if answers:
            return 'NOERROR', answers, []
        soa = _rr(key[0], 'SOA', self.soa_minimum, None)
        soa['minimum'] = self.soa_minimum
        return 'NOERROR', [], [soa]


# ---------------------------------------------------------------------------
# Wire codec for the middlebox transport

def parse_query(payload: bytes) -> tuple[int, str, str, bool]:
    """-> (qid, domain, qtype, has_edns_opt) from an encoded query."""
    qid, _flags, qd, _an, _ns, ar = struct.unpack('>HHHHHH',
                                                  payload[:12])
    if qd != 1:
        raise ValueError('expected exactly one question')
    domain, off = _decode_name(payload, 12)
    qtype_code, _qclass = struct.unpack('>HH', payload[off:off + 4])
    qtype = TYPE_NAMES.get(qtype_code, str(qtype_code))
    return qid, domain, qtype, ar > 0


_RCODE_CODES = {'NOERROR': 0, 'FORMERR': 1, 'SERVFAIL': 2,
                'NXDOMAIN': 3, 'NOTIMP': 4, 'REFUSED': 5}


def _encode_rdata(rr: dict) -> bytes:
    rtype = rr['type']
    if rtype == 'A':
        return bytes(int(b) for b in rr['target'].split('.'))
    if rtype == 'AAAA':
        return ipaddress.IPv6Address(rr['target']).packed
    if rtype == 'SRV':
        return struct.pack('>HHH', rr.get('priority', 0),
                           rr.get('weight', 10), rr['port']) + \
            encode_name(rr['target'])
    if rtype == 'SOA':
        minimum = rr.get('minimum', rr.get('ttl', 5))
        return encode_name('ns.' + rr['name']) + \
            encode_name('hostmaster.' + rr['name']) + \
            struct.pack('>IIIII', 1, 3600, 600, 86400, minimum)
    raise ValueError('cannot encode rdata for type %r' % rtype)


def _encode_rr(rr: dict) -> bytes:
    rdata = _encode_rdata(rr)
    return encode_name(rr['name']) + struct.pack(
        '>HHIH', TYPE_CODES[rr['type']], CLASS_IN, rr['ttl'],
        len(rdata)) + rdata


def encode_response(qid: int, domain: str, qtype: str,
                    rcode: str = 'NOERROR', answers=None,
                    authority=None, additionals=None,
                    tc: bool = False) -> bytes:
    """Encode a wire-format response (uncompressed names) that
    dns_client.parse_response round-trips. Inverse of build_query —
    the encoder the repo never needed until responses had to be
    synthesized."""
    answers = list(answers or [])
    authority = list(authority or [])
    additionals = list(additionals or [])
    flags = 0x8000 | 0x0100 | _RCODE_CODES[rcode]  # QR | RD | rcode
    if tc:
        flags |= 0x0200
    header = struct.pack('>HHHHHH', qid, flags, 1, len(answers),
                         len(authority), len(additionals))
    question = encode_name(domain) + struct.pack(
        '>HH', TYPE_CODES[qtype], CLASS_IN)
    body = b''.join(_encode_rr(rr)
                    for rr in answers + authority + additionals)
    return header + question + body


class SimWire(DnsTransport):
    """Wire-level middlebox: serves a SimZone to the REAL DnsClient
    through the DnsTransport seam, with per-resolver misbehavior.

    ``behaviors`` maps a resolver host (the string DnsClient was given,
    sans port) to one of:

    - ``'ok'`` — answer from the zone (the default)
    - ``'formerr-edns'`` — FORMERR any query carrying an EDNS OPT;
      answer the plain-RFC1035 retry (legacy middlebox, RFC 6891 6.2.2)
    - ``'notimp-edns'`` — same but NOTIMP
    - ``'tc-udp'`` — set the TC bit and serve an empty answer section
      over UDP; serve fully over TCP (truncating middlebox)
    - ``'truncate'`` — cut the response bytes mid-record (malformed)
    - ``'servfail'`` — SERVFAIL everything
    - ``'blackhole'`` — never answer (the query times out)
    """

    def __init__(self, zone: SimZone, behaviors: dict | None = None,
                 latency_s: float = 0.001):
        self.zone = zone
        self.behaviors = dict(behaviors or {})
        self.latency_s = latency_s
        self.log: list[tuple] = []

    def _behavior(self, resolver: str) -> str:
        return self.behaviors.get(resolver, 'ok')

    def _answer(self, qid: int, domain: str, qtype: str,
                tc: bool = False, empty: bool = False) -> bytes:
        rcode, answers, authority = self.zone.resolve(domain, qtype)
        if empty:
            answers = []
        return encode_response(qid, domain, qtype, rcode=rcode,
                               answers=answers, authority=authority,
                               tc=tc)

    async def _common(self, proto: str, resolver: str, payload: bytes,
                      timeout_s: float) -> bytes:
        qid, domain, qtype, has_opt = parse_query(payload)
        behavior = self._behavior(resolver)
        self.log.append((proto, resolver, domain, qtype, behavior))
        if behavior == 'blackhole':
            await asyncio.sleep(timeout_s)
            raise asyncio.TimeoutError()
        await asyncio.sleep(self.latency_s)
        if behavior == 'servfail':
            return encode_response(qid, domain, qtype,
                                   rcode='SERVFAIL')
        if behavior in ('formerr-edns', 'notimp-edns') and has_opt:
            rcode = 'FORMERR' if behavior == 'formerr-edns' \
                else 'NOTIMP'
            return encode_response(qid, domain, qtype, rcode=rcode)
        if behavior == 'truncate':
            full = self._answer(qid, domain, qtype)
            return full[:max(13, len(full) - 7)]
        if behavior == 'tc-udp' and proto == 'udp':
            return self._answer(qid, domain, qtype, tc=True,
                                empty=True)
        return self._answer(qid, domain, qtype)

    async def udp(self, resolver: str, port: int, payload: bytes,
                  timeout_s: float) -> bytes:
        return await self._common('udp', resolver, payload, timeout_s)

    async def tcp(self, resolver: str, port: int, payload: bytes,
                  timeout_s: float) -> bytes:
        return await self._common('tcp', resolver, payload, timeout_s)

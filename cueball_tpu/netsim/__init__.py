"""Deterministic hostile-network simulator (ROADMAP item 4).

A seeded, virtual-time network fabric that plugs in UNDER the
framework's seams — ``options['constructor']``, ``options['resolver']``
/ ``options['dnsClient']``, and the ``dns_client.DnsTransport`` wire
seam — without touching pool/cset/FSM code. One seed determines a
whole run: the virtual clock (``netsim.clock``) drives every timer,
the injected rng (``utils.set_rng``) feeds every random draw, and the
FSM transition trace is byte-identical across replays.

    from cueball_tpu import netsim

    fabric = netsim.Fabric()
    sc = netsim.Scenario('regional-failover', seed=7)
    sc.at(5.0, 'partition', lambda: fabric.partition(['b1', 'b2']))
    sc.at(9.0, 'heal', lambda: fabric.heal())
    sc.run(main)          # main() -> coroutine using the fabric

See docs/netsim.md for the architecture and the scenario-writing
guide; the corpus lives in tests/scenarios/.
"""

from .clock import (LoopStarvedError, VIRTUAL_EPOCH, VirtualClock,
                    VirtualLoop, run)
from .dns import (CHAOS_BANDS, ChaosDnsClient, DnsOutcome,
                  ScriptedDnsClient, SimWire, SimZone, encode_response,
                  parse_query)
from .fabric import (ConnectionResetError2, Fabric, LinkModel,
                     ManualConnection, SimConnection)
from .scenario import (Scenario, herd, jain_index, quantile,
                       success_rates)

__all__ = [
    'CHAOS_BANDS', 'ChaosDnsClient', 'ConnectionResetError2',
    'DnsOutcome', 'Fabric', 'LinkModel', 'LoopStarvedError',
    'ManualConnection', 'Scenario', 'ScriptedDnsClient',
    'SimConnection', 'SimWire', 'SimZone', 'VIRTUAL_EPOCH',
    'VirtualClock', 'VirtualLoop', 'encode_response', 'herd',
    'jain_index', 'parse_query', 'quantile', 'run',
    'success_rates',
]

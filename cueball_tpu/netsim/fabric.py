"""The simulated data plane: links, connections, fault schedules.

A ``Fabric`` stands in for the network between a pool and its
backends. ``fabric.constructor`` plugs straight into the pool/cset
``options['constructor']`` seam; each call yields a ``SimConnection``
whose connect handshake and failure behavior follow the backend's
``LinkModel`` — latency/jitter, probabilistic connect loss,
connect-hang, RST-on-accept, slow-loris handshakes — on virtual
timers, with every random draw from the fabric's injected rng.

Fault schedules mutate fabric state mid-run:

- ``partition(keys)`` / ``heal(keys)`` — full partition: new connects
  hang (SYN blackholed) and established connections die. Asymmetric
  variant (``kill_established=False``): the return path is lost so
  new handshakes hang, but established flows keep working — the
  classic gray middlebox.
- ``down(key)`` / ``up(key)`` — a backend process restarting: RST on
  connect, established connections reset. ``rolling_restart``
  schedules this across the fleet one backend at a time.
- ``set_gray(fraction, mult)`` — N% of backends turn 100x slow
  without failing: connects still succeed, service times stretch.

Nothing here touches pool/cset/FSM code: the fabric only speaks the
connection contract (connect/error/close events + destroy/ref/unref)
defined by connection_fsm. See docs/netsim.md.
"""

from __future__ import annotations

from .. import utils as mod_utils
from .. import wiretap as mod_wiretap
from ..events import EventEmitter
from ..fsm import get_loop


class LinkModel:
    """Per-backend network behavior. ``connect`` is one of 'ok',
    'hang', 'rst', 'slow' (slow-loris: the handshake dribbles out and
    completes only after ``slow_s``). ``loss`` is the probability a
    connect attempt dies with a reset after the latency. ``service``
    is the base request service time; ``service_mult`` stretches it
    for gray-failure modeling."""

    def __init__(self, latency_ms: float = 1.0, jitter_ms: float = 0.0,
                 loss: float = 0.0, connect: str = 'ok',
                 slow_s: float = 300.0, service_ms: float = 1.0,
                 service_mult: float = 1.0, trickle_segments: int = 0,
                 trickle_ms: float = 5.0):
        self.latency_ms = latency_ms
        self.jitter_ms = jitter_ms
        self.loss = loss
        self.connect = connect
        self.slow_s = slow_s
        self.service_ms = service_ms
        self.service_mult = service_mult
        # Claim-handshake trickle: the peer dribbles the claim-time
        # handshake out in `trickle_segments` segments of `trickle_ms`
        # each (SimConnection.cb_claim_ready), modeling a middlebox
        # that fragments and delays segments mid-handshake without
        # failing the connection.
        self.trickle_segments = trickle_segments
        self.trickle_ms = trickle_ms

    def delay_s(self, rng) -> float:
        d = self.latency_ms
        if self.jitter_ms > 0:
            d += rng.random() * self.jitter_ms
        return d / 1000.0


class ConnectionResetError2(Exception):
    """RST from the simulated peer (name avoids shadowing the
    builtin ConnectionResetError, which some call sites catch)."""


class SimConnection(EventEmitter):
    """One simulated TCP connection, driven entirely by virtual
    timers. Emits 'connect' / 'error' / 'close' per the slot-FSM
    contract; ``request()`` models one unit of application work at
    the link's (possibly gray-stretched) service time."""

    def __init__(self, fabric: 'Fabric', backend: dict):
        super().__init__()
        self.fabric = fabric
        self.backend = backend
        self.key = backend.get('key') or '%s:%s' % (
            backend.get('address'), backend.get('port'))
        # Alias key: pools hand the constructor THEIR hashed backend
        # key, so fabric config/faults may instead name backends by
        # 'address:port' — both resolve.
        self.akey = ('%s:%s' % (backend['address'],
                                backend.get('port'))
                     if backend.get('address') is not None else None)
        self.connected = False
        self.dead = False
        self.refd = True
        self._timer = None
        # The claim-readiness probe is bound as an INSTANCE attribute,
        # and only when this connection's link actually trickles: the
        # slot FSM probes via getattr on every single claim, so a
        # class-level method would tax the hot path of every netsim
        # soak (~14us/claim) for a fault mode almost no run uses.
        # Consequence: trickle config must be in place before the
        # connection is created — links mutated afterwards affect
        # only connections made from then on, like every other
        # connect-time link property.
        lm = fabric._links.get(self.key)
        if lm is None and self.akey is not None:
            lm = fabric._links.get(self.akey)
        if lm is not None and lm.trickle_segments:
            self.cb_claim_ready = self._cb_claim_ready
        fabric._register(self)
        self._schedule_handshake()

    # -- handshake ------------------------------------------------------

    def _schedule_handshake(self) -> None:
        link = self.fabric.link_for(self)
        rng = self.fabric.rng
        if self.fabric._conn_in(self, self.fabric._partitioned) or \
                link.connect == 'hang':
            return          # SYN into the void; pool timeout decides
        delay = link.delay_s(rng)
        if link.connect == 'rst' or \
                self.fabric._conn_in(self, self.fabric._down):
            self._timer = get_loop().call_later(
                delay, self._fail,
                ConnectionResetError2('connection refused by %s'
                                      % self.key))
            return
        if link.connect == 'slow':
            delay += link.slow_s
        elif link.loss > 0 and rng.random() < link.loss:
            self._timer = get_loop().call_later(
                delay, self._fail,
                ConnectionResetError2('connect lost to %s' % self.key))
            return
        self._timer = get_loop().call_later(delay, self._complete)

    # Ledger label connection_fsm stamps wire records with
    # (TcpStreamConnection carries its transport's name the same way).
    wt_transport = 'fabric'

    # Wire marks for the wiretap socket_wait decomposition: class
    # default None (no handshake completed); _complete stamps
    # (ready, dispatched) with ready == dispatched — a virtual link
    # has no loop-dispatch gap, the whole connect latency is
    # kernel_wait, which is what keeps the asyncio/fabric ledgers
    # comparable.
    wt_marks = None

    def _complete(self) -> None:
        if self.dead:
            return
        self.connected = True
        if mod_wiretap.wiretap_enabled():
            now = mod_utils.current_millis()
            self.wt_marks = (now, now)
        self.emit('connect')

    def _fail(self, err) -> None:
        if self.dead:
            return
        self.connected = False
        self.emit('error', err)

    # -- connection contract --------------------------------------------

    def ref(self) -> None:
        self.refd = True

    def unref(self) -> None:
        self.refd = False

    def destroy(self) -> None:
        if self.dead:
            return
        self.dead = True
        self.connected = False
        if self._timer is not None:
            self._timer.cancel()
        self.fabric._unregister(self)
        self.emit('close')

    # -- claim-readiness probe --------------------------------------------

    def _cb_claim_ready(self, done) -> None:
        """Transport claim-readiness probe (connection_fsm state_busy
        seam), bound to ``cb_claim_ready`` at construction when the
        link trickles. With ``trickle_segments`` configured, the
        claim-time handshake dribbles out in N virtual segments of
        ``trickle_ms`` each before completing — the middlebox that
        fragments and delays segments mid-handshake without failing
        the connection. Without trickle, ``done(True)`` fires
        synchronously, byte-identical to the plain accept path."""
        if self.dead or not self.connected:
            done(False)
            return
        link = self.fabric.link_for(self)
        segments = int(link.trickle_segments or 0)
        if segments <= 0:
            done(True)
            return

        # Wire accounting: the dribbled handshake is time spent
        # waiting on the (virtual) kernel, not parsing — when wiretap
        # is on, the elapsed probe time lands in the fabric
        # transport's kernel_wait total. The claim-ledger PHASES view
        # is unchanged (the probe runs inside the handshake phase).
        if mod_wiretap.wiretap_enabled():
            probe_start = mod_utils.current_millis()
            inner_done = done

            def done(ok, _inner=inner_done, _t0=probe_start):
                mod_wiretap.wire_wait(
                    'fabric', mod_utils.current_millis() - _t0)
                _inner(ok)

        def step(k):
            if self.dead or not self.connected:
                done(False)
                return
            if k >= segments:
                done(True)
                return
            self._timer = get_loop().call_later(
                link.trickle_ms / 1000.0, step, k + 1)

        step(0)

    # -- application work ------------------------------------------------

    def service_time_s(self) -> float:
        link = self.fabric.link_for(self)
        base = link.service_ms * link.service_mult
        if link.jitter_ms > 0:
            base += self.fabric.rng.random() * link.jitter_ms
        return base / 1000.0

    async def request(self) -> None:
        """One request-response at the link's current service time."""
        import asyncio
        await asyncio.sleep(self.service_time_s())


class ManualConnection(SimConnection):
    """SimConnection whose handshake the TEST drives: nothing happens
    until connect()/emit is called, the tests/fakes.py DummyConnection
    contract, now with fabric registration so fault schedules can
    reach manually-driven connections too."""

    def _schedule_handshake(self) -> None:
        pass

    def connect(self) -> None:
        assert self.dead is False
        self._complete()


class Fabric:
    """The simulated network: per-backend links, live-connection
    registry, and the fault-schedule API."""

    def __init__(self, rng=None):
        self._rng = rng
        self.default_link_args: dict = {}
        self._links: dict[str, LinkModel] = {}
        self._partitioned: set[str] = set()
        self._down: set[str] = set()
        self._conns: dict[str, list[SimConnection]] = {}
        self.connection_class = SimConnection

    @property
    def rng(self):
        """Resolved at DRAW time, not construction time: fabrics are
        typically built before Scenario.run installs the seeded rng
        seam, and capturing early would silently break replay."""
        if self._rng is not None:
            return self._rng
        from .. import utils as mod_utils
        return mod_utils.get_rng()

    # -- link config -----------------------------------------------------

    def link(self, key: str) -> LinkModel:
        lm = self._links.get(key)
        if lm is None:
            lm = LinkModel(**self.default_link_args)
            self._links[key] = lm
        return lm

    def link_for(self, conn: SimConnection) -> LinkModel:
        """Resolve a connection's link: its backend key first, then
        its 'address:port' alias, else lazily create a default."""
        lm = self._links.get(conn.key)
        if lm is None and conn.akey is not None:
            lm = self._links.get(conn.akey)
        return lm if lm is not None else self.link(conn.key)

    def set_link(self, key: str, **kwargs) -> LinkModel:
        """``key`` is either the backend dict's 'key' or the
        'address:port' alias — connections resolve both."""
        lm = LinkModel(**dict(self.default_link_args, **kwargs))
        self._links[key] = lm
        return lm

    # -- constructor seam -------------------------------------------------

    def constructor(self, backend: dict) -> SimConnection:
        """Pass ``fabric.constructor`` as options['constructor']."""
        return self.connection_class(self, backend)

    def _register(self, conn: SimConnection) -> None:
        self._conns.setdefault(conn.key, []).append(conn)

    def _unregister(self, conn: SimConnection) -> None:
        lst = self._conns.get(conn.key)
        if lst and conn in lst:
            lst.remove(conn)

    def connections(self, key: str | None = None) \
            -> list[SimConnection]:
        if key is not None:
            out = list(self._conns.get(key) or [])
            for k, lst in self._conns.items():
                if k != key:
                    out.extend(c for c in lst if c.akey == key)
            return out
        return [c for lst in self._conns.values() for c in lst]

    # -- fault schedule ----------------------------------------------------

    @staticmethod
    def _conn_in(conn: SimConnection, keyset: set) -> bool:
        return conn.key in keyset or (conn.akey is not None
                                      and conn.akey in keyset)

    def is_partitioned(self, key: str) -> bool:
        return key in self._partitioned

    def is_down(self, key: str) -> bool:
        return key in self._down

    def _kill(self, key: str, err) -> None:
        for conn in self.connections(key):
            conn._fail(err)

    def partition(self, keys, kill_established: bool = True) -> None:
        """Full partition: new connects hang. With
        ``kill_established=False`` this is the asymmetric case —
        established flows survive, new handshakes blackhole."""
        for key in keys:
            self._partitioned.add(key)
            if kill_established:
                self._kill(key, ConnectionResetError2(
                    'partition severed %s' % key))

    def heal(self, keys=None) -> None:
        if keys is None:
            self._partitioned.clear()
        else:
            self._partitioned.difference_update(keys)

    def down(self, key: str) -> None:
        """Backend process stops: RST on connect, established reset."""
        self._down.add(key)
        self._kill(key, ConnectionResetError2(
            'connection reset: %s went down' % key))

    def up(self, key: str) -> None:
        self._down.discard(key)

    def set_gray(self, keys, mult: float = 100.0) -> list[str]:
        """Stretch service times on ``keys`` (a list, or a float
        fraction of all known links chosen by the fabric rng) by
        ``mult`` without failing anything — gray failure. Returns the
        affected keys."""
        if isinstance(keys, float):
            pool = sorted(self._links)
            count = max(1, round(len(pool) * keys))
            keys = self.rng.sample(pool, count)
        for key in keys:
            self.link(key).service_mult = mult
        return list(keys)

    def clear_gray(self) -> None:
        for lm in self._links.values():
            lm.service_mult = 1.0

"""Virtual time: the clock and event loop under every netsim run.

A scenario must be (a) fast — a million-op soak in seconds — and
(b) deterministic — the same seed walks the same schedule. Both fall
out of the same move: no netsim run ever sleeps on a wall clock.
``VirtualClock`` is a number that only moves when the loop has nothing
runnable, and ``VirtualLoop`` is a stock asyncio selector loop whose
``time()`` reads that number and whose selector, instead of blocking
in ``select()``, polls real fds with a zero timeout and then jumps the
clock straight to the next timer deadline. Every ``call_later``,
``asyncio.sleep``, ``wait_for`` and FSM ``S.timeout`` in the framework
then runs at full CPU speed in strict deadline order.

The loop shim pairs with the process-wide clock seam in
``cueball_tpu.utils``: ``run()`` installs the same VirtualClock behind
``utils.current_millis()`` / ``utils.wall_time()`` (CoDel, traces,
TTL arithmetic) and a seeded ``random.Random`` behind
``utils.get_rng()``, so one seed pins the whole run. See
docs/netsim.md.
"""

from __future__ import annotations

import asyncio
import random
import selectors

from .. import utils as mod_utils

# Fixed wall-clock origin for virtual runs: TTL deadlines and trace
# timestamps are reproducible run to run (2023-11-14T22:13:20Z).
VIRTUAL_EPOCH = 1_700_000_000.0


class VirtualClock:
    """A clock that moves only when advanced. Satisfies the
    utils.set_clock interface (monotonic()/wall(), seconds)."""

    def __init__(self, start: float = 0.0,
                 epoch: float = VIRTUAL_EPOCH):
        self._mono = start
        self._epoch = epoch

    def monotonic(self) -> float:
        return self._mono

    def wall(self) -> float:
        return self._epoch + self._mono

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError('cannot advance a clock backwards')
        self._mono += dt


class LoopStarvedError(RuntimeError):
    """The virtual loop has no ready callback, no timer, and no
    network to wait on: real asyncio would block forever. Raised
    instead so a deadlocked scenario fails fast with a diagnosis
    rather than hanging the suite."""


class _VirtualSelector:
    """Selector shim: poll real fds without blocking, then account the
    wait the loop asked for by advancing the virtual clock instead of
    sleeping through it."""

    def __init__(self, inner: selectors.BaseSelector,
                 clock: VirtualClock):
        self._inner = inner
        self._clock = clock

    def select(self, timeout=None):
        ready = self._inner.select(0)
        if ready:
            return ready
        if timeout is None:
            raise LoopStarvedError(
                'virtual loop starved: no ready callbacks and no '
                'timers pending — a scenario coroutine is awaiting '
                'something nothing will ever deliver')
        if timeout > 0:
            self._clock.advance(timeout)
        return []

    def __getattr__(self, name):
        return getattr(self._inner, name)


class VirtualLoop(asyncio.SelectorEventLoop):
    """Asyncio loop on virtual time. Drop-in: everything scheduled via
    ``loop.call_later``/``loop.time`` — FSM timers, CoDel pacers, DNS
    deadlines — sees the virtual clock and fires in deadline order at
    CPU speed."""

    def __init__(self, clock: VirtualClock | None = None):
        self.vclock = clock if clock is not None else VirtualClock()
        inner = selectors.DefaultSelector()
        super().__init__(_VirtualSelector(inner, self.vclock))

    def time(self) -> float:
        return self.vclock.monotonic()


def run(coro, seed: int = 0, clock: VirtualClock | None = None):
    """Run ``coro`` to completion on a fresh VirtualLoop with the
    process-wide clock and RNG seams pointed at virtual time and a
    ``random.Random(seed)``; restores both on exit. The netsim
    equivalent of ``asyncio.run()`` — one call makes a run fully
    deterministic in its ``seed``."""
    clock = clock if clock is not None else VirtualClock()
    loop = VirtualLoop(clock)
    old_clock = mod_utils.set_clock(clock)
    old_rng = mod_utils.set_rng(random.Random(seed))
    try:
        asyncio.set_event_loop(loop)
        return loop.run_until_complete(coro)
    finally:
        asyncio.set_event_loop(None)
        try:
            loop.close()
        finally:
            mod_utils.set_clock(old_clock)
            mod_utils.set_rng(old_rng)

"""Shard workers: one event loop per shard, plus the lifecycle FSM.

A shard is an asyncio loop that owns a disjoint set of pools. Three
backends implement the same small surface (``launch`` / ``run`` /
``request_stop`` / ``alive`` / ``is_stopped``):

- ``ThreadWorker`` (default): a daemon thread running its own loop.
  The runq pump and the native trace recorder are both per-loop /
  GIL-serialized already, so nothing else needs to know.
- ``InlineWorker``: shares the caller's loop. Exists for netsim — a
  virtual-time scenario cannot free-run real threads — and gives the
  router a zero-thread mode where routing is a dict lookup plus a
  direct call.
- ``ProcWorker`` (in ``proc.py``): a ``spawn`` child process, the only
  backend that escapes the GIL for CPU-bound claim traffic.

The ``ShardFSM`` runs on the ROUTER's loop and models the worker's
lifecycle; the worker signals it strictly via
``loop.call_soon_threadsafe`` so no FSM method ever executes off the
router loop. Every cross-loop completion is tracked in a pending table
that is failed with ``ShardDeadError`` the moment the shard's loop
exits, which is what guarantees a claim in flight on a dying shard
errors out instead of deadlocking.
"""

from __future__ import annotations

import asyncio
import importlib
import os
import sys
import threading

from ..errors import ShardDeadError
from ..fsm import FSM

START_TIMEOUT_MS = 10_000.0
DRAIN_TIMEOUT_MS = 10_000.0
# How often the running-state watchdog polls thread/process liveness
# and the draining state polls for loop exit.
WATCHDOG_MS = 500.0
DRAIN_POLL_MS = 10.0


def resolve_job(spec):
    """A job is either a callable or a ``'module:function'`` spec
    string (the only form a spawn child can receive — closures don't
    pickle)."""
    if callable(spec):
        return spec
    mod, sep, name = spec.partition(':')
    if not sep or not mod or not name:
        raise ValueError('job spec must be "module:function", got %r'
                         % (spec,))
    fn = getattr(importlib.import_module(mod), name)
    if not callable(fn):
        raise TypeError('job spec %r is not callable' % (spec,))
    return fn


def _try_set_affinity(core) -> bool:
    if core is None or not hasattr(os, 'sched_setaffinity'):
        return False
    try:
        os.sched_setaffinity(0, {int(core)})
        return True
    except (OSError, ValueError):
        return False


class _PendingTable:
    """Futures owned by a caller loop, awaiting completion posted from
    the shard side. Thread-safe; ``fail_all`` is the no-deadlock
    guarantee on shard death."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._entries: dict[int, tuple] = {}

    def add(self, caller_loop, fut) -> int:
        with self._lock:
            self._next += 1
            rid = self._next
            self._entries[rid] = (caller_loop, fut)
        return rid

    def _pop(self, rid):
        with self._lock:
            return self._entries.pop(rid, None)

    def post_result(self, rid, value) -> None:
        ent = self._pop(rid)
        if ent is None:
            return
        loop, fut = ent

        def done():
            if not fut.done():
                fut.set_result(value)
        loop.call_soon_threadsafe(done)

    def post_error(self, rid, exc) -> None:
        ent = self._pop(rid)
        if ent is None:
            return
        loop, fut = ent

        def done():
            if not fut.done():
                fut.set_exception(exc)
        loop.call_soon_threadsafe(done)

    def fail_all(self, exc_factory) -> None:
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
        for loop, fut in entries:
            def done(fut=fut):
                if not fut.done():
                    fut.set_exception(exc_factory())
            try:
                loop.call_soon_threadsafe(done)
            except RuntimeError:
                pass


class ShardWorker:
    """Common surface; see module docstring for the backend contract."""

    backend = 'abstract'

    def __init__(self, shard_id: int, router_loop, affinity=None):
        self.sw_id = int(shard_id)
        self.sw_router_loop = router_loop
        self.sw_affinity = affinity
        self.sw_pending = _PendingTable()
        self.loop = None

    # Backend hooks -------------------------------------------------------

    def launch(self, on_ready, on_error) -> None:
        raise NotImplementedError

    def request_stop(self) -> None:
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    def is_stopped(self) -> bool:
        raise NotImplementedError

    def _dead_error(self, detail=''):
        return ShardDeadError(self.sw_id, detail)

    async def run(self, job, *args, **kwargs):
        raise NotImplementedError


class InlineWorker(ShardWorker):
    """Shard sharing the caller's loop (netsim / zero-thread mode)."""

    backend = 'inline'

    def __init__(self, shard_id, router_loop, affinity=None):
        super().__init__(shard_id, router_loop, affinity)
        self.loop = router_loop
        self._stopped = False

    def launch(self, on_ready, on_error) -> None:
        self._stopped = False
        # Defer readiness one tick so the FSM finishes entering
        # 'starting' before the 'ready' event lands.
        self.loop.call_soon(on_ready)

    def request_stop(self) -> None:
        self._stopped = True

    def alive(self) -> bool:
        return not self._stopped

    def is_stopped(self) -> bool:
        return self._stopped

    async def run(self, job, *args, **kwargs):
        if self._stopped:
            raise self._dead_error('inline shard stopped')
        res = resolve_job(job)(*args, **kwargs)
        if asyncio.iscoroutine(res):
            res = await res
        return res

    def post(self, fn, *args) -> None:
        """Fire-and-forget on the shard loop (same loop here)."""
        if self._stopped:
            raise self._dead_error('inline shard stopped')
        fn(*args)


class ThreadWorker(ShardWorker):
    """Daemon thread running a private asyncio loop. Relaunchable: a
    restart after failure builds a fresh thread and loop."""

    backend = 'thread'

    def __init__(self, shard_id, router_loop, affinity=None):
        super().__init__(shard_id, router_loop, affinity)
        self._thread = None
        self._loop_exited = True

    def launch(self, on_ready, on_error) -> None:
        self._loop_exited = False
        self._thread = threading.Thread(
            target=self._main, args=(on_ready, on_error),
            name='cueball-shard-%d' % self.sw_id, daemon=True)
        self._thread.start()

    def _main(self, on_ready, on_error) -> None:
        _try_set_affinity(self.sw_affinity)
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self.loop = loop
        from .. import trace as mod_trace
        mod_trace.set_shard_id(self.sw_id)
        loop.call_soon(self.sw_router_loop.call_soon_threadsafe, on_ready)
        try:
            loop.run_forever()
        except BaseException as exc:  # loop machinery itself blew up
            try:
                self.sw_router_loop.call_soon_threadsafe(on_error, exc)
            except RuntimeError:
                pass
        finally:
            self._loop_exited = True
            # A native transport plane bound to this loop holds a C
            # poller thread and an add_reader registration; tear it
            # down before the loop object dies (only when the module
            # was ever loaded — don't drag the extension in here).
            nt = sys.modules.get('cueball_tpu.native_transport')
            if nt is not None:
                try:
                    nt.close_plane(loop)
                except Exception:
                    pass
            try:
                loop.close()
            except RuntimeError:
                pass
            # Anything still awaiting this shard must fail fast, not
            # hang on a loop that will never pump again.
            self.sw_pending.fail_all(
                lambda: self._dead_error('loop exited'))
            mod_trace.set_shard_id(None)

    def request_stop(self) -> None:
        loop = self.loop
        if loop is None or self._loop_exited:
            return
        try:
            loop.call_soon_threadsafe(loop.stop)
        except RuntimeError:
            pass

    def alive(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._loop_exited)

    def is_stopped(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    async def run(self, job, *args, **kwargs):
        """Run a job on the shard loop; awaitable from the caller's
        loop. Coroutine results are awaited in the shard."""
        if not self.alive():
            raise self._dead_error('worker thread not running')
        caller_loop = asyncio.get_running_loop()
        fut = caller_loop.create_future()
        rid = self.sw_pending.add(caller_loop, fut)
        fn = resolve_job(job)
        pending = self.sw_pending

        def invoke():
            try:
                res = fn(*args, **kwargs)
            except BaseException as exc:
                pending.post_error(rid, exc)
                return
            if asyncio.iscoroutine(res):
                task = asyncio.ensure_future(res)

                def finished(task):
                    if task.cancelled():
                        pending.post_error(
                            rid, self._dead_error('job cancelled'))
                    elif task.exception() is not None:
                        pending.post_error(rid, task.exception())
                    else:
                        pending.post_result(rid, task.result())
                task.add_done_callback(finished)
            else:
                pending.post_result(rid, res)

        try:
            self.loop.call_soon_threadsafe(invoke)
        except RuntimeError as exc:
            self.sw_pending.post_error(rid, self._dead_error('loop closed'))
            raise self._dead_error('loop closed') from exc
        return await fut

    def post(self, fn, *args) -> None:
        if not self.alive():
            raise self._dead_error('worker thread not running')
        try:
            self.loop.call_soon_threadsafe(fn, *args)
        except RuntimeError as exc:
            raise self._dead_error('loop closed') from exc


class ShardFSM(FSM):
    """Lifecycle of one worker shard, driven on the router's loop.

    ::

        init -> starting -> running -> draining -> stopped
                   |           |          |
                   v           v          v
                 failed <---(loop died / drain timeout)
                   |
                   +--> starting (restart) / draining (stop)

    The worker signals readiness and errors via
    ``call_soon_threadsafe`` onto the router loop; every listener here
    is state-gated, so late signals from a superseded launch are
    no-ops.
    """

    def __init__(self, worker: ShardWorker):
        self.sf_worker = worker
        self.sf_last_error = None
        super().__init__('init')

    # External API (router-side) -----------------------------------------

    def start(self) -> None:
        self.emit('startAsserted')

    def stop(self) -> None:
        self.emit('stopAsserted')

    # States --------------------------------------------------------------

    def state_init(self, S):
        S.validTransitions(['starting'])
        S.gotoStateOn(self, 'startAsserted', 'starting')

    def state_starting(self, S):
        S.validTransitions(['running', 'failed'])
        S.gotoStateOn(self, 'ready', 'running')
        S.gotoStateOn(self, 'launchError', 'failed')
        S.gotoStateTimeout(START_TIMEOUT_MS, 'failed')

        def on_error(exc=None):
            self.sf_last_error = exc
            self.emit('launchError')
        self.sf_worker.launch(S.callback(lambda: self.emit('ready')),
                              S.callback(on_error))

    def state_running(self, S):
        S.validTransitions(['draining', 'failed'])
        S.gotoStateOn(self, 'stopAsserted', 'draining')
        S.gotoStateOn(self, 'workerDied', 'failed')

        def watchdog():
            if not self.sf_worker.alive():
                self.sf_last_error = self.sf_worker._dead_error(
                    'watchdog: loop exited while running')
                self.emit('workerDied')
        S.interval(WATCHDOG_MS, watchdog)

    def state_draining(self, S):
        S.validTransitions(['stopped', 'failed'])
        S.gotoStateOn(self, 'drained', 'stopped')
        S.gotoStateTimeout(DRAIN_TIMEOUT_MS, 'failed')
        self.sf_worker.request_stop()

        def check():
            if self.sf_worker.is_stopped():
                self.emit('drained')
        S.immediate(check)
        S.interval(DRAIN_POLL_MS, check)

    def state_failed(self, S):
        S.validTransitions(['starting', 'draining'])
        # A failed shard can be relaunched (the router then rebuilds
        # the pools it owned) or drained as part of router stop.
        S.gotoStateOn(self, 'startAsserted', 'starting')
        S.gotoStateOn(self, 'stopAsserted', 'draining')

    def state_stopped(self, S):
        S.validTransitions([])

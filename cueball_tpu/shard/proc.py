"""Spawn-process shard backend: the only one that escapes the GIL.

The child (``_child_main``) pins its core, stamps its shard id into
the trace TLS, and runs a private asyncio loop forever; a daemon
reader thread receives ``('call', rid, spec, args, kwargs)`` messages
and schedules them onto the loop, so pool timers stay live between
jobs. Jobs are ``'module:function'`` spec strings (closures don't
pickle) called as ``fn(ctx, *args)`` where ``ctx`` is the child's
context dict (``shard``/``loop``/``pools``/``state``); coroutine
results are awaited on the child loop. Each child owns a genuinely
separate native trace ring and metric collector — the router merges
them at export time (``_export_traces``), never on the hot path.
"""

from __future__ import annotations

import asyncio
import multiprocessing
import os
import threading

from ..errors import CueBallError
from .worker import ShardWorker, _try_set_affinity, resolve_job


def _child_main(conn, shard_id: int, affinity) -> None:
    _try_set_affinity(affinity)
    from .. import trace as mod_trace
    mod_trace.set_shard_id(shard_id)
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    ctx = {'shard': shard_id, 'loop': loop, 'pools': {}, 'state': {}}
    send_lock = threading.Lock()

    def send(msg):
        with send_lock:
            try:
                conn.send(msg)
            except (OSError, ValueError, BrokenPipeError):
                pass

    def fail(rid, exc):
        send(('err', rid, '%s: %s' % (type(exc).__name__, exc)))

    def dispatch(msg):
        _kind, rid, spec, args, kwargs = msg
        try:
            res = resolve_job(spec)(ctx, *args, **(kwargs or {}))
        except BaseException as exc:
            fail(rid, exc)
            return
        if asyncio.iscoroutine(res):
            task = asyncio.ensure_future(res)

            def finished(task):
                if task.cancelled():
                    send(('err', rid, 'CancelledError: job cancelled'))
                elif task.exception() is not None:
                    fail(rid, task.exception())
                else:
                    send(('ok', rid, task.result()))
            task.add_done_callback(finished)
        else:
            send(('ok', rid, res))

    def reader():
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                loop.call_soon_threadsafe(loop.stop)
                return
            if msg[0] == 'stop':
                send(('ok', msg[1], None))
                loop.call_soon_threadsafe(loop.stop)
                return
            loop.call_soon_threadsafe(dispatch, msg)

    threading.Thread(target=reader, daemon=True).start()
    send(('ready', 0, None))
    try:
        loop.run_forever()
    finally:
        try:
            loop.close()
        except Exception:
            pass
        try:
            conn.close()
        except Exception:
            pass


class ProcWorker(ShardWorker):
    """Parent-side handle on a spawn child. A daemon reader thread
    resolves pending futures from child replies and fails them all
    with ``ShardDeadError`` when the pipe drops."""

    backend = 'spawn'

    def __init__(self, shard_id, router_loop, affinity=None):
        super().__init__(shard_id, router_loop, affinity)
        self._proc = None
        self._conn = None
        self._dead = True

    def launch(self, on_ready, on_error) -> None:
        ctx = multiprocessing.get_context('spawn')
        parent_conn, child_conn = ctx.Pipe()
        self._conn = parent_conn
        self._dead = False
        self._proc = ctx.Process(
            target=_child_main,
            args=(child_conn, self.sw_id, self.sw_affinity),
            name='cueball-shard-%d' % self.sw_id, daemon=True)
        self._proc.start()
        child_conn.close()
        threading.Thread(target=self._read_loop,
                         args=(on_ready, on_error), daemon=True).start()

    def _read_loop(self, on_ready, on_error) -> None:
        conn = self._conn
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            kind, rid, payload = msg
            if kind == 'ready':
                try:
                    self.sw_router_loop.call_soon_threadsafe(on_ready)
                except RuntimeError:
                    pass
            elif kind == 'ok':
                self.sw_pending.post_result(rid, payload)
            else:
                self.sw_pending.post_error(rid, CueBallError(
                    'shard %d job failed: %s' % (self.sw_id, payload)))
        self._dead = True
        self.sw_pending.fail_all(
            lambda: self._dead_error('child process exited'))

    def request_stop(self) -> None:
        if self._conn is None or self._dead:
            return
        try:
            self._conn.send(('stop', 0))
        except (OSError, ValueError, BrokenPipeError):
            pass

    def alive(self) -> bool:
        return (self._proc is not None and self._proc.is_alive()
                and not self._dead)

    def is_stopped(self) -> bool:
        return self._proc is None or not self._proc.is_alive()

    async def run(self, job, *args, **kwargs):
        if not isinstance(job, str):
            raise TypeError(
                'spawn jobs must be "module:function" spec strings')
        if not self.alive():
            raise self._dead_error('child process not running')
        caller_loop = asyncio.get_running_loop()
        fut = caller_loop.create_future()
        rid = self.sw_pending.add(caller_loop, fut)
        try:
            self._conn.send(('call', rid, job, args, kwargs))
        except (OSError, ValueError, BrokenPipeError):
            self.sw_pending.post_error(
                rid, self._dead_error('pipe closed'))
        return await fut


# -- child-side jobs the router/bench dispatch by spec ---------------------

def _ping(ctx):
    return {'shard': ctx['shard'], 'pid': os.getpid(),
            'affinity': (sorted(os.sched_getaffinity(0))
                         if hasattr(os, 'sched_getaffinity') else None)}


def _construct_pool(ctx, name, factory_spec, shard_id):
    obj = resolve_job(factory_spec)()
    pool = obj[0] if isinstance(obj, tuple) else obj
    pool.p_shard = shard_id
    ctx['pools'][name] = pool
    return {'name': name, 'shard': shard_id}


async def _destroy_pool(ctx, name, timeout_s):
    pool = ctx['pools'].pop(name)
    pool.stop()
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout_s
    while not pool.is_in_state('stopped'):
        if loop.time() > deadline:
            raise CueBallError('pool %r did not stop' % name)
        await asyncio.sleep(0.05)
    return None


def _pool_job(ctx, name, spec, args, kwargs):
    pool = ctx['pools'][name]
    return resolve_job(spec)(pool, *args, **(kwargs or {}))


def _export_traces(ctx):
    from .. import trace as mod_trace
    return mod_trace.export_ndjson()

"""Shard-per-core fleet scale-out: K event-loop shards behind a
consistent-hash claim router.

The single-loop engine tops out on asyncio tick cost, not pool
bookkeeping (see docs/claim-path-profile.md round 7). This package
scales out instead of up: a :class:`FleetRouter` fronts K worker
shards — each with its own asyncio loop, runq pump and trace context —
owning disjoint sets of ConnectionPools assigned by a consistent-hash
ring on the pool key. Claims never cross a loop boundary on the hot
path; cross-shard traffic happens only at pool create/destroy and at
telemetry/export time. See docs/sharding.md.
"""

from .ring import HashRing
from .router import (FleetRouter, RoutedClaim, active_routers)
from .worker import ShardFSM
from ..errors import ShardDeadError

__all__ = [
    'HashRing',
    'FleetRouter',
    'RoutedClaim',
    'ShardFSM',
    'ShardDeadError',
    'active_routers',
]

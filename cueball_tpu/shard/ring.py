"""Consistent-hash ring assigning pool keys to shard ids.

The ring is the only routing state the FleetRouter holds: each shard
contributes ``replicas`` virtual points hashed from ``(seed, shard,
replica)``, and a pool key lands on the first point clockwise from the
key's own hash. Adding or removing one shard therefore moves only the
keys in the arcs that shard's points own (~1/K of the keyspace), which
is what lets the router rebuild just the affected pools on a shard
restart instead of re-homing the whole fleet.

Hashing is keyed BLAKE2b, never Python's ``hash()`` and never the
``utils`` RNG seam: placement must be reproducible across processes
(the spawn backend re-derives it) and must consume zero draws from the
seeded stream so netsim replays stay byte-identical sharded vs plain.
"""

from __future__ import annotations

import bisect
import hashlib

DEFAULT_REPLICAS = 64


def _hash64(data: bytes, seed: int) -> int:
    h = hashlib.blake2b(data, digest_size=8,
                        key=seed.to_bytes(8, 'little', signed=False))
    return int.from_bytes(h.digest(), 'big')


class HashRing:
    """Consistent-hash ring over integer shard ids."""

    def __init__(self, shards: int | list[int] = 1,
                 replicas: int = DEFAULT_REPLICAS, seed: int = 0):
        if replicas < 1:
            raise ValueError('replicas must be >= 1')
        self.hr_replicas = int(replicas)
        self.hr_seed = int(seed) & 0xffffffffffffffff
        # Sorted, parallel arrays: point hash -> owning shard id.
        self._points: list[int] = []
        self._owners: list[int] = []
        self._shards: set[int] = set()
        ids = range(shards) if isinstance(shards, int) else shards
        for sid in ids:
            self.add_shard(sid)

    # -- membership ------------------------------------------------------

    def shards(self) -> list[int]:
        return sorted(self._shards)

    def __len__(self) -> int:
        return len(self._shards)

    def add_shard(self, shard_id: int) -> None:
        sid = int(shard_id)
        if sid < 0:
            raise ValueError('shard ids must be >= 0')
        if sid in self._shards:
            return
        self._shards.add(sid)
        for rep in range(self.hr_replicas):
            pt = _hash64(b'shard:%d:%d' % (sid, rep), self.hr_seed)
            i = bisect.bisect_left(self._points, pt)
            # Ties between distinct shards are broken deterministically
            # by shard id so insertion order never changes placement.
            while (i < len(self._points) and self._points[i] == pt
                    and self._owners[i] < sid):
                i += 1
            self._points.insert(i, pt)
            self._owners.insert(i, sid)

    def remove_shard(self, shard_id: int) -> None:
        sid = int(shard_id)
        if sid not in self._shards:
            return
        self._shards.discard(sid)
        keep = [(p, o) for p, o in zip(self._points, self._owners)
                if o != sid]
        self._points = [p for p, _ in keep]
        self._owners = [o for _, o in keep]

    # -- assignment ------------------------------------------------------

    def assign(self, key: str) -> int:
        """Owning shard id for ``key``; raises LookupError when the
        ring is empty."""
        if not self._points:
            raise LookupError('hash ring has no shards')
        kh = _hash64(('key:%s' % key).encode('utf-8'), self.hr_seed)
        i = bisect.bisect_right(self._points, kh)
        if i == len(self._points):
            i = 0
        return self._owners[i]

    def assignment(self, keys) -> dict:
        return {k: self.assign(k) for k in keys}

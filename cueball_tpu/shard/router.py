"""FleetRouter: K worker shards behind a consistent-hash claim router.

The router owns routing state only — a ``HashRing`` mapping pool keys
to shard ids, one ``ShardWorker`` + ``ShardFSM`` per shard, and a
record per pool (name, key, owning shard, and how to rebuild it). All
pool/FSM policy runs unchanged inside the owning shard's loop: a
claim or release NEVER crosses a loop boundary on the hot path. The
only cross-shard traffic is pool create/destroy, telemetry sampling,
trace/metric export, and lifecycle control.

Hot-path contract per backend:

- ``inline``: routing is a dict lookup plus a direct ``claim_cb``
  call on the caller's own loop (this is what netsim scenarios use,
  and why sharded runs replay byte-identical to plain ones).
- ``thread``: ``claim_cb``/``claim`` marshal once onto the shard loop
  and the callback marshals once back; CPU-bound users should instead
  ``submit()`` the whole claim/release loop into the shard.
- ``spawn``: jobs are ``'module:function'`` spec strings executed in
  the child process (closures don't pickle); per-claim routing is not
  offered — the unit of dispatch is a job.
"""

from __future__ import annotations

import asyncio

from ..errors import CueBallError, ShardDeadError
from .ring import HashRing
from .worker import (InlineWorker, ShardFSM, ShardWorker,  # noqa: F401
                     ThreadWorker)

_BACKENDS = ('thread', 'inline', 'spawn')

# Routers that are started and not yet stopped; the debug/kang/metrics
# surfaces walk this to merge per-shard views into one output.
_ACTIVE_ROUTERS: list = []


def active_routers() -> list:
    return list(_ACTIVE_ROUTERS)


class _PoolRecord:
    __slots__ = ('name', 'key', 'shard_id', 'options', 'factory',
                 'pool', 'aux')

    def __init__(self, name, key, shard_id, options, factory):
        self.name = name
        self.key = key
        self.shard_id = shard_id
        self.options = options
        self.factory = factory
        self.pool = None
        self.aux = None


class RoutedClaim:
    """Handle returned by ``FleetRouter.claim``: the pool's real claim
    handle plus enough routing to release it on the owning shard's
    loop (releasing from the caller's loop would run pool timers on
    the wrong loop)."""

    __slots__ = ('rc_router', 'rc_name', 'rc_shard', 'handle',
                 'connection')

    def __init__(self, router, name, shard_id, handle, connection):
        self.rc_router = router
        self.rc_name = name
        self.rc_shard = shard_id
        self.handle = handle
        self.connection = connection

    async def release(self):
        await self.rc_router.submit(self.rc_name,
                                    lambda _pool: self.handle.release())

    async def close(self):
        await self.rc_router.submit(self.rc_name,
                                    lambda _pool: self.handle.close())


class FleetRouter:
    """K event-loop shards, each owning a disjoint set of pools."""

    def __init__(self, options: dict | None = None):
        options = dict(options or {})
        self.fr_nshards = int(options.get('shards', 1))
        if self.fr_nshards < 1:
            raise ValueError('shards must be >= 1')
        self.fr_backend = options.get('backend', 'thread')
        if self.fr_backend not in _BACKENDS:
            raise ValueError('backend must be one of %r' % (_BACKENDS,))
        self.fr_seed = int(options.get('seed', 0))
        self.fr_affinity = options.get('affinity')  # list[int] | None
        self.fr_ring = HashRing(
            self.fr_nshards,
            replicas=int(options.get('replicas', 64)),
            seed=self.fr_seed)
        self.fr_loop = None
        self.fr_workers: dict[int, ShardWorker] = {}
        self.fr_fsms: dict[int, ShardFSM] = {}
        self.fr_pools: dict[str, _PoolRecord] = {}
        self.fr_samplers: dict[int, object] = {}
        self.fr_submits: dict[int, int] = {}
        self.fr_collector = None
        self.fr_started = False

    # -- lifecycle --------------------------------------------------------

    def _make_worker(self, sid: int) -> ShardWorker:
        affinity = None
        if self.fr_affinity:
            affinity = self.fr_affinity[sid % len(self.fr_affinity)]
        if self.fr_backend == 'inline':
            return InlineWorker(sid, self.fr_loop, affinity)
        if self.fr_backend == 'thread':
            return ThreadWorker(sid, self.fr_loop, affinity)
        from .proc import ProcWorker
        return ProcWorker(sid, self.fr_loop, affinity)

    async def start(self, timeout_s: float = 15.0) -> None:
        if self.fr_started:
            raise CueBallError('FleetRouter already started')
        self.fr_loop = asyncio.get_running_loop()
        for sid in range(self.fr_nshards):
            worker = self._make_worker(sid)
            self.fr_workers[sid] = worker
            self.fr_fsms[sid] = ShardFSM(worker)
            self.fr_submits[sid] = 0
        for fsm in self.fr_fsms.values():
            fsm.start()
        for fsm in self.fr_fsms.values():
            await self._wait_state(fsm, ('running', 'failed'), timeout_s)
        failed = [sid for sid, f in self.fr_fsms.items()
                  if not f.is_in_state('running')]
        if failed:
            await self.stop()
            raise CueBallError('shards failed to start: %r' % (failed,))
        self.fr_started = True
        _ACTIVE_ROUTERS.append(self)

    async def stop(self, timeout_s: float = 15.0) -> None:
        for fsm in self.fr_fsms.values():
            if fsm.get_state() == 'init':
                continue
            # 'starting' cannot take stopAsserted; let it settle first.
            await self._wait_state(
                fsm, ('running', 'failed', 'draining', 'stopped'),
                timeout_s)
            if fsm.get_state() in ('running', 'failed'):
                fsm.stop()
        for fsm in self.fr_fsms.values():
            if fsm.get_state() == 'init':
                continue
            await self._wait_state(fsm, ('stopped', 'failed'), timeout_s)
        self.fr_started = False
        if self in _ACTIVE_ROUTERS:
            _ACTIVE_ROUTERS.remove(self)
        if self.fr_collector is not None:
            self.detach_metrics()

    async def _wait_state(self, fsm, states, timeout_s: float) -> None:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while fsm.get_state() not in states:
            if loop.time() > deadline:
                raise CueBallError(
                    'timed out waiting for shard state %r (in %r)' % (
                        states, fsm.get_state()))
            await asyncio.sleep(0.005)

    async def restart_shard(self, shard_id: int,
                            timeout_s: float = 15.0) -> None:
        """Relaunch a failed shard and rebuild the pools it owned.
        The old pool objects lived on the dead loop; they are dropped
        (and unregistered from the monitor) and re-created from their
        recorded options/factory on the fresh loop."""
        fsm = self.fr_fsms[shard_id]
        if fsm.is_in_state('running'):
            return
        if not fsm.is_in_state('failed'):
            raise CueBallError(
                'can only restart a failed shard (in %r)'
                % fsm.get_state())
        owned = [r for r in self.fr_pools.values()
                 if r.shard_id == shard_id]
        from ..monitor import pool_monitor
        for rec in owned:
            if rec.pool is not None:
                try:
                    pool_monitor.unregister_pool(rec.pool)
                except Exception:
                    pass
                rec.pool = None
                rec.aux = None
        self.fr_samplers.pop(shard_id, None)
        fsm.start()
        await self._wait_state(fsm, ('running', 'failed'), timeout_s)
        if not fsm.is_in_state('running'):
            raise CueBallError('shard %d failed to restart' % shard_id)
        for rec in owned:
            await self._build_pool(rec)

    # -- pool management --------------------------------------------------

    @staticmethod
    def pool_key(name: str, options: dict | None = None) -> str:
        """Ring key: service name + stable hash of the options. Option
        values that aren't plain scalars (constructors, resolvers)
        contribute their type name only, so the key is reproducible
        across processes."""
        if not options:
            return name
        import hashlib
        parts = []
        for k in sorted(options):
            v = options[k]
            if isinstance(v, (str, int, float, bool, type(None))):
                parts.append('%s=%r' % (k, v))
            else:
                parts.append('%s=<%s>' % (k, type(v).__name__))
        digest = hashlib.blake2b('|'.join(parts).encode('utf-8'),
                                 digest_size=8).hexdigest()
        return '%s#%s' % (name, digest)

    def shard_of(self, name: str) -> int:
        rec = self.fr_pools.get(name)
        if rec is not None:
            return rec.shard_id
        return self.fr_ring.assign(name)

    def _construct(self, rec: _PoolRecord):
        # Runs inside the owning shard's loop.
        if rec.factory is not None:
            obj = rec.factory()
        else:
            from ..pool import ConnectionPool
            obj = ConnectionPool(dict(rec.options))
        aux = None
        if isinstance(obj, tuple):
            pool, aux = obj[0], obj[1:]
        else:
            pool = obj
        pool.p_shard = rec.shard_id
        return pool, aux

    async def _build_pool(self, rec: _PoolRecord) -> None:
        worker = self.fr_workers[rec.shard_id]
        if worker.backend == 'spawn':
            rec.aux = await worker.run(
                'cueball_tpu.shard.proc:_construct_pool',
                rec.name, rec.factory, rec.shard_id)
        else:
            rec.pool, rec.aux = await worker.run(self._construct, rec)

    async def create_pool(self, name: str, options: dict | None = None,
                          factory=None) -> _PoolRecord:
        """Create a pool on the shard its key hashes to. Exactly one
        of ``options`` (a ConnectionPool options dict) or ``factory``
        (a zero-arg callable — or, for the spawn backend, a
        ``'module:function'`` spec — returning the pool or a tuple
        ``(pool, *aux)``) must be given."""
        if not self.fr_started:
            raise CueBallError('FleetRouter is not started')
        if name in self.fr_pools:
            raise CueBallError('pool %r already exists' % name)
        if (options is None) == (factory is None):
            raise ValueError('exactly one of options/factory required')
        key = self.pool_key(name, options)
        sid = self.fr_ring.assign(key)
        fsm = self.fr_fsms[sid]
        if not fsm.is_in_state('running'):
            raise ShardDeadError(sid, 'create_pool(%r)' % name)
        rec = _PoolRecord(name, key, sid, options, factory)
        self.fr_pools[name] = rec
        try:
            await self._build_pool(rec)
        except BaseException:
            self.fr_pools.pop(name, None)
            raise
        return rec

    async def destroy_pool(self, name: str,
                           timeout_s: float = 60.0) -> None:
        rec, worker, _fsm = self._lookup(name)

        async def stop_job(pool):
            pool.stop()
            loop = asyncio.get_running_loop()
            deadline = loop.time() + timeout_s
            while not pool.is_in_state('stopped'):
                if loop.time() > deadline:
                    raise CueBallError(
                        'pool %r did not stop in %.0fs' % (name,
                                                           timeout_s))
                await asyncio.sleep(0.05)

        if worker.backend == 'spawn':
            await worker.run('cueball_tpu.shard.proc:_destroy_pool',
                             name, timeout_s)
        else:
            await worker.run(stop_job, rec.pool)
        self.fr_pools.pop(name, None)

    def get_pool(self, name: str):
        """The live pool object (None for spawn shards — the object
        lives in the child process)."""
        return self.fr_pools[name].pool

    def _lookup(self, name: str):
        rec = self.fr_pools.get(name)
        if rec is None:
            raise KeyError('no pool named %r' % name)
        fsm = self.fr_fsms[rec.shard_id]
        worker = self.fr_workers[rec.shard_id]
        if not fsm.is_in_state('running') or not worker.alive():
            raise ShardDeadError(rec.shard_id, 'pool %r' % name)
        return rec, worker, fsm

    # -- routed work ------------------------------------------------------

    def claim_cb(self, name: str, options=None, cb=None):
        """Route a callback-style claim to the owning shard. On the
        same loop (inline backend, or calls made from inside the
        shard) this is a direct ``pool.claim_cb`` call and returns the
        claim handle; cross-loop the claim is posted to the shard and
        ``cb`` is marshalled back to the calling loop (returns None)."""
        if callable(options) and cb is None:
            cb, options = options, {}
        rec, worker, _fsm = self._lookup(name)
        if worker.backend == 'spawn':
            raise CueBallError(
                'per-claim routing is not available on the spawn '
                'backend; submit a job instead')
        self.fr_submits[rec.shard_id] += 1
        caller_loop = asyncio.get_running_loop()
        if worker.loop is caller_loop:
            return rec.pool.claim_cb(options, cb)

        def cb_marshalled(*a):
            caller_loop.call_soon_threadsafe(cb, *a)
        worker.post(rec.pool.claim_cb, options, cb_marshalled)
        return None

    async def claim(self, name: str, options: dict | None = None):
        """Awaitable claim routed to the owning shard; returns a
        ``RoutedClaim`` whose ``release()``/``close()`` run on that
        shard's loop."""
        rec, worker, _fsm = self._lookup(name)
        if worker.backend == 'spawn':
            raise CueBallError(
                'per-claim routing is not available on the spawn '
                'backend; submit a job instead')
        self.fr_submits[rec.shard_id] += 1
        pool = rec.pool
        hdl, conn = await worker.run(pool.claim, options or {})
        return RoutedClaim(self, name, rec.shard_id, hdl, conn)

    async def claim_many(self, name: str, n: int,
                         options: dict | None = None):
        """Batched claim routed to the owning shard: one cross-loop
        hop claims the whole batch via ``pool.claim_many`` (the
        per-claim marshalling is what claim-per-call spends most of
        its budget on for thread shards). Returns a list of
        ``RoutedClaim``s; all-or-nothing like ``pool.claim_many``."""
        rec, worker, _fsm = self._lookup(name)
        if worker.backend == 'spawn':
            raise CueBallError(
                'per-claim routing is not available on the spawn '
                'backend; submit a job instead')
        self.fr_submits[rec.shard_id] += 1
        pool = rec.pool
        pairs = await worker.run(pool.claim_many, n, options or {})
        return [RoutedClaim(self, name, rec.shard_id, hdl, conn)
                for hdl, conn in pairs]

    async def release_many(self, claims) -> None:
        """Release a batch of RoutedClaims, one hop per owning shard
        (grouped) instead of one per claim."""
        by_shard: dict = {}
        for rc in claims:
            by_shard.setdefault((rc.rc_shard, rc.rc_name),
                                []).append(rc)
        for (_sid, name), group in by_shard.items():
            handles = [rc.handle for rc in group]

            def release_job(pool, hs=handles):
                pool.release_many(hs)
            await self.submit(name, release_job)

    async def submit(self, name: str, job, *args, **kwargs):
        """Run ``job(pool, *args, **kwargs)`` on the shard owning pool
        ``name`` and return its result. For the spawn backend ``job``
        must be a ``'module:function'`` spec; the child resolves it
        and passes its own pool object."""
        rec, worker, _fsm = self._lookup(name)
        self.fr_submits[rec.shard_id] += 1
        if worker.backend == 'spawn':
            return await worker.run('cueball_tpu.shard.proc:_pool_job',
                                    name, job, args, kwargs)
        return await worker.run(job, rec.pool, *args, **kwargs)

    async def run_on(self, shard_id: int, job, *args, **kwargs):
        """Run a job on a specific shard regardless of pool routing
        (telemetry, benchmarks). Spawn jobs receive the child context
        dict as their first argument."""
        fsm = self.fr_fsms[shard_id]
        worker = self.fr_workers[shard_id]
        if not fsm.is_in_state('running') or not worker.alive():
            raise ShardDeadError(shard_id, 'run_on')
        self.fr_submits[shard_id] += 1
        return await worker.run(job, *args, **kwargs)

    # -- telemetry / merged surfaces --------------------------------------

    def shard_states(self) -> dict:
        return {sid: fsm.get_state()
                for sid, fsm in sorted(self.fr_fsms.items())}

    def snapshot(self) -> dict:
        pools = {}
        for name, rec in sorted(self.fr_pools.items()):
            pools[name] = {'shard': rec.shard_id, 'key': rec.key}
        snap = {
            'backend': self.fr_backend,
            'nshards': self.fr_nshards,
            'seed': self.fr_seed,
            'states': {str(k): v for k, v in self.shard_states().items()},
            'submits': {str(k): v
                        for k, v in sorted(self.fr_submits.items())},
            'pools': pools,
        }
        # Merged health verdicts, when any shard sampler runs the
        # health plane. Reading hm_last cross-thread is safe: ticks
        # rebind the record wholesale, never mutate it in place.
        verdicts = [s.fs_health_monitor.hm_last
                    for s in self.fr_samplers.values()
                    if s.fs_health_monitor is not None]
        if any(v is not None for v in verdicts):
            from ..parallel.health import reduce_health
            snap['health'] = reduce_health(verdicts)
        return snap

    def attach_metrics(self, collector) -> None:
        """Publish per-shard gauges (shard-labelled) on ``collector``
        at scrape time via a collect hook."""
        if self.fr_collector is not None:
            raise CueBallError('metrics already attached')
        self.fr_collector = collector
        collector.add_collect_hook(self._publish_metrics)

    def detach_metrics(self) -> None:
        if self.fr_collector is None:
            return
        self.fr_collector.remove_collect_hook(self._publish_metrics)
        self.fr_collector = None

    def _publish_metrics(self) -> None:
        c = self.fr_collector
        if c is None:
            return
        up = c.gauge('cueball_shard_up',
                     'Shard event loop liveness (1 = running)')
        npools = c.gauge('cueball_shard_pools',
                         'Connection pools owned by the shard')
        nsub = c.gauge('cueball_shard_submits',
                       'Jobs/claims routed to the shard since start')
        counts = {sid: 0 for sid in self.fr_fsms}
        for rec in self.fr_pools.values():
            counts[rec.shard_id] = counts.get(rec.shard_id, 0) + 1
        for sid, fsm in self.fr_fsms.items():
            labels = {'shard': str(sid)}
            up.set(1.0 if fsm.is_in_state('running') else 0.0, labels)
            npools.set(float(counts.get(sid, 0)), labels)
            nsub.set(float(self.fr_submits.get(sid, 0)), labels)

    def _sample_shard(self, shard_id: int):
        # Runs inside the shard loop: the sampler's row arrays are
        # mutated by pool-event hooks on this loop, so sampling here
        # keeps everything single-threaded.
        sampler = self.fr_samplers.get(shard_id)
        if sampler is None:
            from ..parallel.sampler import FleetSampler
            sampler = FleetSampler({'shard': shard_id})
            self.fr_samplers[shard_id] = sampler
        return sampler.sample_once()

    def _control_shard(self, shard_id: int):
        # Runs inside the shard loop (via run_on): the shard's sampler
        # gains the control plane if it didn't have it, ticks once, and
        # the actuation — apply_control_decision on each owned pool,
        # which marks telemetry rows dirty — happens right here on the
        # loop that owns those pools, never cross-thread.
        sampler = self.fr_samplers.get(shard_id)
        if sampler is None:
            from ..parallel.sampler import FleetSampler
            sampler = FleetSampler({'shard': shard_id, 'control': True})
            self.fr_samplers[shard_id] = sampler
        else:
            sampler.fs_control = True
        rec = sampler.sample_once()
        return rec.get('control') if rec else None

    async def control_fleet(self):
        """One control-plane pass: each running shard runs the fused
        control step over its own pools ON ITS OWN LOOP (via run_on)
        and applies the decision columns there; the per-shard summaries
        reduce shard->host. Not offered for the spawn backend (children
        run their own samplers)."""
        if self.fr_backend == 'spawn':
            raise CueBallError(
                'control_fleet is not available on the spawn backend; '
                'children run their own control planes')
        records = []
        for sid, fsm in sorted(self.fr_fsms.items()):
            if not fsm.is_in_state('running'):
                continue
            rec = await self.run_on(sid, self._control_shard, sid)
            if rec:
                records.append(rec)
        from ..parallel.control import reduce_control
        return reduce_control(records)

    def _health_shard(self, shard_id: int):
        # Runs inside the shard loop: the shard's sampler gains the
        # health plane if it didn't have it and ticks once; the
        # HealthMonitor drains the claim tracer's attribution columns
        # and judges them on this loop.
        sampler = self.fr_samplers.get(shard_id)
        if sampler is None:
            from ..parallel.sampler import FleetSampler
            sampler = FleetSampler({'shard': shard_id, 'health': True})
            self.fr_samplers[shard_id] = sampler
        else:
            sampler.fs_health = True
        rec = sampler.sample_once()
        return rec.get('health') if rec else None

    async def health_fleet(self):
        """One health pass: each running shard ticks its HealthMonitor
        on its own loop, then the per-shard verdict records merge
        shard->host with :func:`parallel.health.reduce_health` (gray
        sets union, burn rates take the worst shard). Not offered for
        the spawn backend (children judge their own backends)."""
        if self.fr_backend == 'spawn':
            raise CueBallError(
                'health_fleet is not available on the spawn backend; '
                'children run their own health monitors')
        records = []
        for sid, fsm in sorted(self.fr_fsms.items()):
            if not fsm.is_in_state('running'):
                continue
            rec = await self.run_on(sid, self._health_shard, sid)
            if rec:
                records.append(rec)
        from ..parallel.health import reduce_health
        return reduce_health(records)

    def _profile_shard(self, shard_id: int):
        # Runs inside the shard loop: fold the shard's completed claim
        # traces into one mergeable cost-attribution record. Thread
        # shards share the process trace ring, so the record filters by
        # the shard stamp the claim spans already carry.
        from .. import profile as mod_profile
        return mod_profile.profile_record(shard=shard_id)

    async def profile_fleet(self):
        """One profile pass: each running shard folds its phase
        ledgers into a cost-attribution record on its own loop, then
        the records merge shard->host with
        :func:`profile.reduce_profile` (totals sum, coverage re-derived
        wall-weighted) — the same reduction shape as
        :meth:`health_fleet`. Not offered for the spawn backend
        (children expose /kang/profile and /metrics; merge their
        scrapes with metrics.merge_expositions)."""
        if self.fr_backend == 'spawn':
            raise CueBallError(
                'profile_fleet is not available on the spawn backend; '
                'scrape the children and merge with merge_expositions')
        records = []
        for sid, fsm in sorted(self.fr_fsms.items()):
            if not fsm.is_in_state('running'):
                continue
            rec = await self.run_on(sid, self._profile_shard, sid)
            if rec:
                records.append(rec)
        from .. import profile as mod_profile
        return mod_profile.reduce_profile(records)

    def _wiretap_shard(self, shard_id: int):
        # Runs inside the shard loop: the loop-lag stats are loop-local
        # (the whole point of the column), so they must be read from
        # the shard's own loop; the transport ledger itself is
        # process-global and rides along once in the reduction.
        from .. import wiretap as mod_wiretap
        return mod_wiretap.wiretap_record(shard=shard_id)

    async def wiretap_fleet(self):
        """One wiretap pass: each running shard reports its loop-lag
        stats from its own loop, then the records merge shard->host
        with :func:`wiretap.reduce_wiretap` (lag folds worst-case —
        one saturated loop is the signal — and the process-global
        transport ledger rides along once). Mirrors
        :meth:`profile_fleet`; not offered for the spawn backend
        (children expose /kang/transport and /metrics; merge their
        scrapes with metrics.merge_expositions)."""
        if self.fr_backend == 'spawn':
            raise CueBallError(
                'wiretap_fleet is not available on the spawn backend; '
                'scrape the children and merge with merge_expositions')
        records = []
        for sid, fsm in sorted(self.fr_fsms.items()):
            if not fsm.is_in_state('running'):
                continue
            rec = await self.run_on(sid, self._wiretap_shard, sid)
            if rec:
                records.append(rec)
        from .. import wiretap as mod_wiretap
        return mod_wiretap.reduce_wiretap(records)

    async def sample_fleet(self, mesh=None, mesh_axes=('host', 'chip')):
        """One per-shard FleetSampler pass each on its own loop, then
        the shard->host reduction (and host->mesh when ``mesh`` is
        given). Not offered for the spawn backend."""
        if self.fr_backend == 'spawn':
            raise CueBallError(
                'sample_fleet is not available on the spawn backend; '
                'children publish their own collectors')
        records = []
        for sid, fsm in sorted(self.fr_fsms.items()):
            if not fsm.is_in_state('running'):
                continue
            rec = await self.fr_workers[sid].run(self._sample_shard, sid)
            if rec:
                records.append(rec['fleet'])
        from ..parallel.sampler import reduce_fleet
        return reduce_fleet(records, mesh=mesh, mesh_axes=mesh_axes)

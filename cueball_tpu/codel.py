"""Controlled-Delay (CoDel) overload shedding for the claim queue.

Rebuild of reference `lib/codel.js` (which adapts the ACM CoDel reference
pseudocode, https://queue.acm.org/appendices/codel.html, to claim-queue
sojourn times). The pool feeds each waiter's enqueue time to
``overloaded()`` at dequeue; while the queue's minimum sojourn stays above
the target for a full control interval, claims are dropped at a rate whose
interval shrinks proportionally to 1/sqrt(count), steering the queue delay
toward the target. ``get_max_idle()`` supplies the claim timeout: 10x the
target in a healthy system, 3x when persistently overloaded
(reference lib/codel.js:100-118).
"""

from __future__ import annotations

import math

from .utils import current_millis

CODEL_INTERVAL = 100  # ms control interval (reference lib/codel.js:16)

# Bounds on any EXTERNALLY-set target (the fleet control plane's
# actuation path, parallel.control). The reference never mutates the
# target after construction; set_target exists solely for that batched
# path, and the guard keeps a wild decision column from ever driving
# the target to 0 (drop everything) or unbounded (shed nothing).
CODEL_TARGET_MIN = 1.0
CODEL_TARGET_MAX = 60_000.0

# Pacer cadence (ms) for the pool's continuous-evaluation shave-mode law.
# Classic CoDel evaluates its control law at every dequeue of a busy
# queue; a connection pool dequeues only when a connection is released,
# so with long checkout holds the drop decisions quantize onto the
# release cadence (plus the 100 ms re-arm interval) and the achieved
# claim sojourn sits well above targetClaimDelay. While the service
# process is demonstrably live, the pool runs a shave-mode law between
# dequeues at this cadence: CoDel's entry condition (head above target
# for a full control interval), then shed every above-target waiter per
# tick, with hysteretic exit. ControlledDelay itself is untouched and
# still consulted at dequeue sites. See docs/internals.md (CoDel
# section) and Pool._arm_codel_pacer.
CODEL_PACE = 10


class ControlledDelay:
    def __init__(self, target_claim_delay: float):
        if not isinstance(target_claim_delay, (int, float)) or \
                isinstance(target_claim_delay, bool) or \
                not math.isfinite(target_claim_delay):
            raise AssertionError('targetClaimDelay must be a finite number')
        self.cd_targdelay = target_claim_delay
        self.cd_first_above_time = 0.0
        self.cd_drop_next = 0.0
        self.cd_count = 0
        self.cd_dropping = False
        self.cd_last_empty: float | None = None
        # Last overloaded() decision detail, for claim-trace 'codel'
        # event spans: (sojourn_ms, dropping_mode, drop_count).
        self.cd_last_sojourn = 0.0
        self.cd_last_decision: bool | None = None

    def set_target(self, target_ms: float) -> None:
        """Guarded external target set (control-plane actuation only).

        Raises ValueError out of range; on success only the target
        moves — the drop-law state (first_above/drop_next/count) is
        carried, so a tightened target takes effect through the normal
        interval machinery instead of causing a drop burst."""
        if not isinstance(target_ms, (int, float)) or \
                isinstance(target_ms, bool) or \
                not math.isfinite(target_ms) or \
                not CODEL_TARGET_MIN <= target_ms <= CODEL_TARGET_MAX:
            raise ValueError(
                'codel target must be in [%g, %g] ms, got %r'
                % (CODEL_TARGET_MIN, CODEL_TARGET_MAX, target_ms))
        self.cd_targdelay = float(target_ms)

    setTarget = set_target

    def can_drop(self, now: float, start: float) -> bool:
        sojourn = now - start
        if sojourn < self.cd_targdelay:
            self.cd_first_above_time = 0.0
        elif self.cd_first_above_time == 0.0:
            self.cd_first_above_time = now + CODEL_INTERVAL
        elif now >= self.cd_first_above_time:
            return True
        return False

    def get_drop_next(self, now: float) -> float:
        return now + CODEL_INTERVAL / math.sqrt(self.cd_count)

    def overloaded(self, start: float) -> bool:
        """Given a claim's enqueue time, decide drop-on-dequeue
        (reference lib/codel.js:52-86)."""
        now = current_millis()
        self.cd_last_sojourn = now - start
        ok_to_drop = self.can_drop(now, start)
        drop_claim = False

        if self.cd_dropping:
            if not ok_to_drop:
                self.cd_dropping = False
            elif now >= self.cd_drop_next:
                drop_claim = True
                self.cd_count += 1
        elif ok_to_drop and (
                (now - self.cd_drop_next < CODEL_INTERVAL) or
                (now - self.cd_first_above_time >= CODEL_INTERVAL)):
            drop_claim = True
            self.cd_dropping = True
            if now - self.cd_drop_next < CODEL_INTERVAL:
                self.cd_count = self.cd_count - 2 if self.cd_count > 2 else 1
            else:
                self.cd_count = 1
            self.cd_drop_next = self.get_drop_next(now)

        self.cd_last_decision = drop_claim
        return drop_claim

    def empty(self) -> None:
        """The wait queue fully drained (reference lib/codel.js:88-94)."""
        self.cd_last_empty = current_millis()
        self.cd_first_above_time = 0.0

    def get_max_idle(self) -> float:
        """Max queue-sit time before a waiter is timed out: 10x target
        normally, 3x under persistent overload (reference
        lib/codel.js:96-118)."""
        bound = self.cd_targdelay * 10
        now = current_millis()
        if self.cd_last_empty is not None and \
                self.cd_last_empty < (now - bound):
            return self.cd_targdelay * 3
        return bound

    getMaxIdle = get_max_idle

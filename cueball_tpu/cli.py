"""cbresolve: resolve a name the way the framework's pools would.

Rebuild of reference `bin/cbresolve` (396 LoC): static or DNS mode,
--follow live add/remove stream, optional kang debug listener. Usage
(reference bin/cbresolve:41-61):

    cbresolve HOSTNAME[:PORT]              # DNS-based lookup
    cbresolve -S IP[:PORT]...              # static IPs

Options: -f/--follow, -p/--port, -r/--resolvers, -s/--service,
-t/--timeout, -k/--kang-port. Logging off by default; enable with
LOG_LEVEL (reference bin/cbresolve:66-70). DEBUG=1 prints full
tracebacks on failure (reference bin/cbresolve:388-392).
"""

from __future__ import annotations

import argparse
import asyncio
import datetime
import logging
import os
import sys

from .resolver import (StaticIpResolver, config_for_ip_or_domain,
                       parse_ip_or_domain)
from . import utils as mod_utils


def _utc_now_iso() -> str:
    """Timestamp for --follow output, read through the utils clock
    seam so netsim-driven runs stay replayable (cbflow A003)."""
    return datetime.datetime.fromtimestamp(
        mod_utils.wall_time(), datetime.timezone.utc).isoformat()


def parse_time_interval(s: str) -> int:
    """Duration string -> milliseconds: a positive integer with an
    optional "ms"/"s"/"m" suffix ("500", "30s", "5m"); bare numbers are
    milliseconds (reference bin/cbresolve:301-328 parseTimeInterval)."""
    import re
    m = re.match(r'^([1-9][0-9]*)(ms|s|m)?$', s)
    if m is None:
        raise argparse.ArgumentTypeError(
            'invalid time interval: %s' % s)
    n = int(m.group(1))
    unit = m.group(2)
    if unit == 's':
        n *= 1000
    elif unit == 'm':
        n *= 60000
    return n


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog='cbresolve',
        description='Locate services in DNS using the cueball resolver.')
    p.add_argument('names', nargs='+', metavar='HOSTNAME[:PORT]',
                   help='name to resolve (or IPs with -S)')
    p.add_argument('-S', '--static', action='store_true',
                   help='static IP mode')
    p.add_argument('-f', '--follow', action='store_true',
                   help='periodically re-resolve and report changes')
    p.add_argument('-p', '--port', type=int, default=None,
                   help='default backend port')
    p.add_argument('-r', '--resolvers', default=None,
                   help='comma-separated list of DNS resolvers')
    p.add_argument('-s', '--service', default=None,
                   help='"service" name for SRV lookups (_foo._tcp)')
    p.add_argument('-t', '--timeout', type=parse_time_interval,
                   default=5000, metavar='TIMEOUT',
                   help='timeout for lookups (e.g. 500, 500ms, 30s, 5m;'
                        ' bare numbers are milliseconds)')
    p.add_argument('-k', '--kang-port', type=int, default=None,
                   help='start a kang debug listener on this port')
    return p


def _parse_ip_port(s: str, default_port: int | None):
    """IP[:PORT] for -S mode (reference bin/cbresolve:279-299)."""
    spec = parse_ip_or_domain(s)
    if isinstance(spec, Exception):
        raise SystemExit('cbresolve: %s' % spec)
    if spec['kind'] != 'static':
        raise SystemExit(
            'cbresolve: not an IP address: %s' % s)
    be = spec['config']['backends'][0]
    if be['port'] is None:
        be['port'] = default_port if default_port is not None else 80
    return be


async def _amain(args) -> int:
    logging.basicConfig(
        level=os.environ.get('LOG_LEVEL', 'CRITICAL').upper())

    rconfig: dict = {}
    if args.port is not None:
        if args.port < 0 or args.port > 65535:
            print('cbresolve: bad value for -p/--port: %d' % args.port,
                  file=sys.stderr)
            return 2
        rconfig['defaultPort'] = args.port
    if args.resolvers:
        rconfig['resolvers'] = [
            ip for ip in args.resolvers.split(',') if ip]
    if args.service:
        rconfig['service'] = args.service
    rconfig['recovery'] = {
        'default': {'timeout': args.timeout, 'retries': 3, 'delay': 250,
                    'maxDelay': 2000},
    }

    if args.static:
        backends = [_parse_ip_port(s, args.port) for s in args.names]
        resolver = StaticIpResolver({
            'defaultPort': args.port if args.port is not None else 80,
            'backends': backends})
    else:
        if len(args.names) != 1:
            print('cbresolve: exactly one HOSTNAME for DNS mode',
                  file=sys.stderr)
            return 2
        spec = config_for_ip_or_domain({
            'input': args.names[0], 'resolverConfig': rconfig})
        if isinstance(spec, Exception):
            print('cbresolve: %s' % spec, file=sys.stderr)
            return 2
        resolver = spec['cons'](spec['mergedConfig'])

    backends_seen: dict[str, dict] = {}
    done = asyncio.get_running_loop().create_future()

    def on_added(key, backend):
        backends_seen[key] = backend
        if args.follow:
            print('%s added   %16s:%-5d (%s)' % (
                _utc_now_iso(),
                backend['address'], backend['port'], key))
        else:
            print('%-16s %5d %s' % (
                backend['address'], backend['port'], key))

    def on_removed(key):
        old = backends_seen.pop(key, None)
        if args.follow and old is not None:
            print('%s removed %16s:%-5d (%s)' % (
                _utc_now_iso(),
                old['address'], old['port'], key))

    resolver.on('added', on_added)
    resolver.on('removed', on_removed)

    def on_state(st):
        if st == 'running' and not args.follow:
            if not done.done():
                done.set_result(0)
        elif st == 'failed':
            err = resolver.get_last_error()
            if os.environ.get('DEBUG'):
                import traceback
                traceback.print_exception(err)
            else:
                print('error: %s' % err, file=sys.stderr)
            if not done.done():
                done.set_result(1)
    resolver.on('stateChanged', on_state)

    kang_server = None
    if args.kang_port is not None:
        from .http_server import serve_monitor
        kang_server = await serve_monitor(port=args.kang_port)

    resolver.start()

    if args.follow:
        # Run until interrupted.
        try:
            await asyncio.Future()
        except asyncio.CancelledError:
            pass
        return 0

    rc = await done
    resolver.stop()
    if kang_server is not None:
        kang_server.close()
    return rc


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    try:
        return asyncio.run(_amain(args))
    except KeyboardInterrupt:
        return 130


if __name__ == '__main__':
    sys.exit(main())

"""HTTP agent: pooled HTTP(S) client transport.

Rebuild of reference `lib/agent.js`. The reference plugs into Node's
http.Agent contract; the asyncio-native equivalent is an HTTP/1.1 client
whose transport claims connections from a cueball ConnectionPool per
hostname:

- pools are created lazily per host on first request
  (reference lib/agent.js:105-211), or eagerly via ``initialDomains``
- the socket constructor builds TCP or TLS connections with SNI and
  TCP keep-alive (reference lib/agent.js:146-197)
- request lifecycle maps onto the claim handle: response fully read on
  a keep-alive connection -> release; close-delimited response or
  error/cancel -> close (reference lib/agent.js:275-396)
- optional HTTP ping health checks run a GET over idle pooled sockets;
  a 5xx closes the connection, anything else releases it
  (reference lib/agent.js:398-455, PingAgent at lib/agent.js:530-569)

Public surface parity: ``request()`` (the addRequest analogue),
``get_pool``, ``create_pool``, ``stop``, ``is_stopped``
(reference lib/agent.js:275,458,464,213,497).
"""

from __future__ import annotations

import asyncio
import logging
import ssl as mod_ssl

from . import transport as mod_transport
from . import utils as mod_utils
from .events import EventEmitter
from .fsm import get_loop
from .pool import ConnectionPool
from .resolver import pool_resolver
# Back-compat alias: agent grew this protocol before the seam did.
from .transport import WatchedStreamProtocol as _WatchedProtocol

# TLS fields passed through from agent options to the socket constructor
# (reference lib/agent.js:96-97).
PASS_FIELDS = ['certfile', 'keyfile', 'ca', 'ciphers', 'servername',
               'rejectUnauthorized']


class HttpSocket(EventEmitter):
    """Connection-interface object over a transport TCP/TLS stream
    (the constructSocket analogue, reference lib/agent.js:146-197).
    All raw socket work — opening the stream, keep-alive sockopts —
    goes through the Transport seam; this class owns only the HTTP
    agent's connection contract (events, destroy, reader/writer)."""

    def __init__(self, backend: dict, tls: dict | None = None,
                 tcp_keepalive_delay: float | None = None,
                 transport: mod_transport.Transport | None = None):
        super().__init__()
        self.backend = backend
        self.transport = mod_transport.get_transport(transport)
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None
        self.local_port: int | None = None
        self.tls = tls
        self.tcp_keepalive_delay = tcp_keepalive_delay
        self.destroyed = False
        self._task = asyncio.ensure_future(self._connect())

    def _on_connection_lost(self, exc):
        if self.destroyed:
            return
        if exc is not None:
            self.emit('error', exc)
        else:
            self.emit('close')

    def _ssl_context(self):
        ctx = mod_ssl.create_default_context()
        tls = self.tls or {}
        if tls.get('ca'):
            ctx.load_verify_locations(cadata=tls['ca'])
        if tls.get('certfile'):
            ctx.load_cert_chain(tls['certfile'], tls.get('keyfile'))
        if tls.get('ciphers'):
            ctx.set_ciphers(tls['ciphers'])
        if tls.get('rejectUnauthorized') is False:
            ctx.check_hostname = False
            ctx.verify_mode = mod_ssl.CERT_NONE
        return ctx

    async def _connect(self):
        try:
            loop = asyncio.get_running_loop()
            ssl_ctx = None
            server_hostname = None
            if self.tls is not None:
                ssl_ctx = self._ssl_context()
                # SNI servername override (reference lib/agent.js:158).
                server_hostname = self.tls.get('servername') or \
                    self.backend.get('name') or self.backend['address']
            reader = asyncio.StreamReader(loop=loop)
            stream, protocol = await self.transport.create_stream(
                lambda: _WatchedProtocol(reader, self, loop),
                self.backend['address'], self.backend['port'],
                ssl=ssl_ctx, server_hostname=server_hostname)
            self.reader = reader
            self.writer = asyncio.StreamWriter(
                stream, protocol, reader, loop)
            self.local_port = self.transport.configure_keepalive(
                stream, delay_ms=self.tcp_keepalive_delay)
            self.emit('connect')
        except (OSError, mod_ssl.SSLError) as e:
            self.emit('error', e)
        except asyncio.CancelledError:
            pass

    def destroy(self):
        self.destroyed = True
        if self.writer is not None:
            self.writer.close()
        elif not self._task.done():
            self._task.cancel()

    def unref(self):
        pass

    def ref(self):
        pass


class HttpResponse:
    def __init__(self, status: int, reason: str, headers: dict,
                 body: bytes, raw_headers: list | None = None):
        self.status = status
        self.status_code = status
        self.reason = reason
        self.headers = headers
        # Ordered (name, value) pairs with duplicates preserved
        # (Set-Cookie needs this); the dict above keeps the
        # last-wins convenience view.
        self.raw_headers = raw_headers if raw_headers is not None \
            else list(headers.items())
        self.body = body

    def text(self, encoding='utf-8') -> str:
        return self.body.decode(encoding, 'replace')


async def _read_response(reader: asyncio.StreamReader,
                         method: str) -> tuple[HttpResponse, bool]:
    """Parse one HTTP/1.1 response; returns (response, keep_alive)."""
    status_line = await reader.readline()
    if not status_line:
        raise ConnectionResetError('connection closed before response')
    parts = status_line.decode('latin-1').rstrip('\r\n').split(' ', 2)
    version = parts[0]
    status = int(parts[1])
    reason = parts[2] if len(parts) > 2 else ''

    headers: dict[str, str] = {}
    raw_headers: list[tuple[str, str]] = []
    while True:
        line = await reader.readline()
        if line in (b'\r\n', b'\n', b''):
            break
        k, _, v = line.decode('latin-1').partition(':')
        headers[k.strip().lower()] = v.strip()
        raw_headers.append((k.strip(), v.strip()))

    keep_alive = version != 'HTTP/1.0'
    conn_hdr = headers.get('connection', '').lower()
    if conn_hdr == 'close':
        keep_alive = False
    elif conn_hdr == 'keep-alive':
        keep_alive = True

    body = b''
    if method == 'HEAD' or status in (204, 304) or 100 <= status < 200:
        pass
    elif headers.get('transfer-encoding', '').lower() == 'chunked':
        chunks = []
        while True:
            szline = await reader.readline()
            if not szline.strip():
                # EOF mid-stream is truncation, not a terminator.
                raise ConnectionResetError(
                    'connection closed mid-chunked-response')
            size = int(szline.split(b';')[0].strip(), 16)
            if size == 0:
                # trailers until blank line
                while True:
                    t = await reader.readline()
                    if t in (b'\r\n', b'\n', b''):
                        break
                break
            chunks.append(await reader.readexactly(size))
            await reader.readexactly(2)  # CRLF
        body = b''.join(chunks)
    elif 'content-length' in headers:
        body = await reader.readexactly(int(headers['content-length']))
    else:
        body = await reader.read()
        keep_alive = False

    return HttpResponse(status, reason, headers, body,
                        raw_headers=raw_headers), keep_alive


class CueBallAgent(EventEmitter):
    """Base agent (reference CueBallAgent, lib/agent.js:30-94)."""

    def __init__(self, options: dict, protocol: str):
        super().__init__()
        if not isinstance(options, dict):
            raise AssertionError('options must be a dict')
        default_port = options.get('defaultPort')
        if not isinstance(default_port, int):
            raise AssertionError('options.defaultPort must be a number')
        spares = options.get('spares')
        maximum = options.get('maximum')
        if not isinstance(spares, int) or not isinstance(maximum, int):
            raise AssertionError(
                'options.spares and options.maximum must be numbers')
        recovery = options.get('recovery')
        if not isinstance(recovery, dict):
            raise AssertionError('options.recovery is required')
        mod_utils.assert_recovery(recovery.get('default'),
                                  'recovery.default')

        self.collector = mod_utils.create_error_metrics(options)

        self.default_port = default_port
        self.protocol = protocol + ':'
        self.service = options.get('service') or '_%s._tcp' % protocol
        self.cba_upgraded: set = set()

        self.tcp_ka_delay = options.get('tcpKeepAliveInitialDelay')
        self.cba_transport = mod_transport.get_transport(
            options.get('transport'))
        self.pools: dict[str, ConnectionPool] = {}
        self.pool_resolvers: dict[str, object] = {}
        self.resolvers = options.get('resolvers')
        self.log = mod_utils.make_child_logger(
            options.get('log') or logging.getLogger('cueball.agent'),
            component='CueBallAgent')
        self.cba_stopped = False
        self.maximum = maximum
        self.spares = spares
        self.cba_ping = options.get('ping')
        self.cba_ping_interval = options.get('pingInterval')
        self.cba_recovery = recovery
        self.cba_err_on_empty = options.get('errorOnEmpty')
        self.cba_tls = {f: options[f] for f in PASS_FIELDS
                        if f in options} \
            if protocol == 'https' else None

        for host in (options.get('initialDomains') or []):
            self._add_pool(host, {})

    # -- pool management --------------------------------------------------

    def _make_socket(self, host: str):
        tls = None
        if self.cba_tls is not None:
            tls = dict(self.cba_tls)
            tls.setdefault('servername', host)

        def construct(backend):
            return HttpSocket(backend, tls=tls,
                              tcp_keepalive_delay=self.tcp_ka_delay,
                              transport=self.cba_transport)
        return construct

    def _add_pool(self, host: str, options: dict) -> ConnectionPool:
        # The reference keys this.pools by bare hostname
        # (lib/agent.js:105-211); integration layers that must
        # distinguish ports pass an explicit poolKey instead of
        # reaching into the dicts.
        key = options.get('poolKey') or host
        port = options.get('port') or self.default_port
        resolver = options.get('resolver')
        if resolver is None:
            resolver = pool_resolver(
                host, port, service=self.service,
                recovery=self.cba_recovery, resolvers=self.resolvers,
                log=self.log)

        pool_opts = {
            'domain': host,
            'resolver': resolver,
            'constructor': self._make_socket(host),
            'maximum': self.maximum,
            'spares': self.spares,
            'log': self.log,
            'recovery': self.cba_recovery,
            'collector': self.collector,
        }
        if self.cba_ping is not None:
            pool_opts['checker'] = self._make_checker(host)
            pool_opts['checkTimeout'] = self.cba_ping_interval or 30000
        if options.get('targetClaimDelay') is not None:
            pool_opts['targetClaimDelay'] = options['targetClaimDelay']
        pool = ConnectionPool(pool_opts)
        if resolver.is_in_state('stopped'):
            resolver.start()
        self.pools[key] = pool
        self.pool_resolvers[key] = resolver
        return pool

    def get_pool(self, host: str) -> ConnectionPool | None:
        return self.pools.get(host)

    getPool = get_pool

    def create_pool(self, host: str, options: dict | None = None) -> None:
        """Pre-create the pool for a host; a duplicate is an error
        (reference lib/agent.js:464-488)."""
        assert not self.cba_stopped, 'agent has been stopped'
        if host in self.pools:
            raise RuntimeError(
                'Attempting to create a pool for a hostname that '
                'already has one: %s' % host)
        self._add_pool(host, options or {})

    createPool = create_pool

    def is_stopped(self) -> bool:
        return self.cba_stopped

    isStopped = is_stopped

    async def stop(self) -> None:
        """Stop all pools and their resolvers
        (reference lib/agent.js:213-265)."""
        assert not self.cba_stopped, 'agent already stopped'
        self.cba_stopped = True
        # Outstanding upgraded sockets hold their slot busy by design;
        # a pool cannot reach 'stopped' until they close, so shutdown
        # reclaims them (the reference never re-manages upgraded
        # sockets at all, lib/agent.js:361-381).
        def reclaim_upgraded():
            for handle in list(self.cba_upgraded):
                if handle.is_in_state('claimed'):
                    handle.close()

        reclaim_upgraded()
        pools = list(self.pools.values())
        resolvers = list(self.pool_resolvers.values())
        for pool in pools:
            pool.stop()
        for pool in pools:
            while not pool.is_in_state('stopped'):
                # An upgrade() that was in flight when stop() began
                # registers its handle only as its claim/response
                # resolves; keep reclaiming while we wait or the pool
                # can never reach 'stopped'.
                reclaim_upgraded()
                await asyncio.sleep(0.01)
        self.cba_upgraded.clear()
        for res in resolvers:
            if not res.is_in_state('stopped'):
                res.stop()
        self.pools = {}
        self.pool_resolvers = {}

    # -- health checking --------------------------------------------------

    def _make_checker(self, host: str):
        def checker(handle, socket):
            # Fire-and-forget by design: the health check owns its
            # whole lifecycle (it releases the claim handle on every
            # path and reports failure through the FSM, never by
            # raising), and the pool's checker callback is sync.
            asyncio.ensure_future(  # cbflow: ignore=A004
                self._check_socket(host, handle, socket))
        return checker

    async def _check_socket(self, host: str, handle, socket) -> None:
        """GET the ping path over this very socket; 5xx or failure
        closes it, success releases it
        (reference lib/agent.js:398-455)."""
        t1 = get_loop().time()
        try:
            resp = await asyncio.wait_for(
                self._do_request_on('GET', host, self.cba_ping, {},
                                    b'', socket),
                timeout=30)
            resp_obj, keep_alive = resp
            latency = (get_loop().time() - t1) * 1000
            if 500 <= resp_obj.status < 600:
                self.log.warning(
                    'health check on %s got %d (latency %.0fms), '
                    'closing', host, resp_obj.status, latency)
                handle.close()
            elif not keep_alive:
                handle.close()
            else:
                # Success stays below INFO (reference changelog #105:
                # per-interval success at INFO was pure noise) and
                # names the pool's domain + latency/path/status
                # (reference changelog #109).
                self.log.debug(
                    'health check on pool "%s" ok (status %d, '
                    'latency %.0fms, path %s)', host,
                    resp_obj.status, latency, self.cba_ping)
                handle.release()
        except Exception as e:
            self.log.warning('health check on %s failed: %r', host, e)
            try:
                handle.close()
            except RuntimeError:
                pass

    # -- requests ---------------------------------------------------------

    async def _do_request_on(self, method: str, host: str, path: str,
                             headers: dict, body: bytes, socket):
        hdrs = {'host': host, 'connection': 'keep-alive'}
        hdrs.update({k.lower(): v for k, v in (headers or {}).items()})
        if body:
            hdrs['content-length'] = str(len(body))
        lines = ['%s %s HTTP/1.1' % (method, path)]
        lines += ['%s: %s' % (k, v) for k, v in hdrs.items()]
        payload = ('\r\n'.join(lines) + '\r\n\r\n').encode('latin-1') + \
            (body or b'')
        socket.writer.write(payload)
        await socket.writer.drain()
        return await _read_response(socket.reader, method)

    async def _claim_for(self, host: str, port: int | None,
                         timeout: float | None):
        """Shared claim plumbing for request()/upgrade(): stopped
        check, lazy pool creation, claim options."""
        if self.cba_stopped:
            raise RuntimeError('agent has been stopped')
        pool = self.pools.get(host)
        if pool is None:
            pool = self._add_pool(host, {'port': port})

        claim_opts = {}
        if timeout is not None:
            claim_opts['timeout'] = timeout
        if self.cba_err_on_empty is not None:
            claim_opts['errorOnEmpty'] = self.cba_err_on_empty
        return await pool.claim(claim_opts)

    async def request(self, method: str, host: str, path: str = '/',
                      headers: dict | None = None, body: bytes = b'',
                      port: int | None = None,
                      timeout: float | None = None) -> HttpResponse:
        """Claim a pooled connection to `host`, run one HTTP request,
        and release/close per keep-alive semantics (the addRequest
        analogue, reference lib/agent.js:275-396)."""
        handle, socket = await self._claim_for(host, port, timeout)
        try:
            resp, keep_alive = await self._do_request_on(
                method, host, path, headers or {}, body, socket)
        except asyncio.CancelledError:
            # Request aborted mid-flight: connection state unknown.
            handle.close()
            raise
        except Exception:
            handle.close()
            raise
        if keep_alive:
            handle.release()
        else:
            handle.close()
        return resp

    async def upgrade(self, host: str, path: str = '/',
                      headers: dict | None = None,
                      protocol: str = 'websocket',
                      port: int | None = None,
                      timeout: float | None = None):
        """Issue an HTTP/1.1 Upgrade on a pooled connection.

        The reference removes an upgraded socket from agent management
        until it closes ('agentRemove' hold,
        reference lib/agent.js:361-381); here, on a 101 response the
        claimed handle is simply never released — the slot stays busy,
        the caller owns the raw socket for the new protocol and MUST
        call handle.close() when finished. Returns
        (response, socket, handle) on 101; (response, None, None)
        otherwise (connection recycled per keep-alive as usual).
        """
        hdrs = {'connection': 'Upgrade', 'upgrade': protocol}
        hdrs.update({k.lower(): v for k, v in (headers or {}).items()})

        handle, socket = await self._claim_for(host, port, timeout)
        try:
            resp, keep_alive = await self._do_request_on(
                'GET', host, path, hdrs, b'', socket)
        except BaseException:
            handle.close()
            raise
        if resp.status == 101:
            # Track the detached handle so agent.stop() can reclaim
            # the slot if the caller never closes it.
            self.cba_upgraded.add(handle)
            handle.on('stateChanged',
                      lambda st: self.cba_upgraded.discard(handle)
                      if st in ('released', 'closed') else None)
            return resp, socket, handle
        if keep_alive:
            handle.release()
        else:
            handle.close()
        return resp, None, None

    async def get(self, host: str, path: str = '/', **kw) -> HttpResponse:
        return await self.request('GET', host, path, **kw)

    async def post(self, host: str, path: str = '/', body: bytes = b'',
                   **kw) -> HttpResponse:
        return await self.request('POST', host, path, body=body, **kw)


class HttpAgent(CueBallAgent):
    """reference lib/agent.js:501-507"""

    def __init__(self, options: dict):
        super().__init__(options, 'http')


class HttpsAgent(CueBallAgent):
    """reference lib/agent.js:509-515"""

    def __init__(self, options: dict):
        super().__init__(options, 'https')

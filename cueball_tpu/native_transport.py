"""Python control plane for the native C transport data plane.

``native/transport.c`` owns an epoll/io_uring readiness loop on its
own thread and moves connect/read/write/DNS bytes without touching
the Python event loop; completions surface in batches through a
preallocated SPSC ring. This module is the thin dispatcher on top:

- :class:`NativePlane` — one per asyncio loop. Registers the C
  loop's completion eventfd with ``loop.add_reader`` so the whole
  batch drains in ONE pump crossing per loop tick, then fans each
  completion out to the owning connection/operation.
- :class:`NativeConnection` — the connection-contract twin of
  ``transport.TcpStreamConnection`` (emits 'connect'/'error'/'close',
  destroy/ref/unref, wiretap wire marks) whose bytes never cross the
  Python loop until a consumer asks for them.
- :class:`RealNativeTransport` — the five-seam ``Transport``
  implementation registered over the ``'native'`` stub when the
  extension exports the transport symbols. connector/dns_udp/dns_tcp
  ride the C plane; serve/create_stream fall back to asyncio plumbing
  (documented in docs/transport.md — the pool claim path and the DNS
  wire are the hot paths this PR moves off-loop) while still
  accounting to the 'native' ledger rows.

Wire accounting: the C side counts seam events into per-seam atomic
counters (same field order as ``wiretap.SeamStats.__slots__``); the
plane folds counter deltas into the live ``TransportLedger`` at every
drain and via a registered wiretap pull source, so ``snapshot()`` /
``wire_totals()`` see up-to-date native rows without a Python-side
callback per byte.

Determinism: a plane refuses to exist under a non-system clock
(netsim's virtual time cannot drive a kernel poller), mirroring
``profile.start_sampler``. The fabric transport stays the
deterministic arm; the parity suite pins the two against each other.

This module is C110-licensed (tools/cblint.py) to touch sockets: it
IS the byte-moving seam when the native backend is selected.
"""

from __future__ import annotations

import asyncio
import atexit
import errno as mod_errno
import os
import socket as mod_socket
import threading

from . import runq as mod_runq
from . import utils as mod_utils
from . import wiretap as mod_wiretap
from .errors import TransportNotAvailableError
from .events import EventEmitter
from .transport import Transport

_native = None
if not os.environ.get('CUEBALL_NO_NATIVE'):
    try:
        from . import _cueball_native as _native_mod
    except ImportError:
        _native_mod = None
    # A stale .so built before the transport unit landed has the
    # emitter surface but no txloop_new: treat it as absent rather
    # than blowing up at first use.
    if _native_mod is not None and hasattr(_native_mod, 'txloop_new'):
        _native = _native_mod

#: Profiler seam (cbflow A005): profile._bind_seams points this at the
#: live sampler so drain crossings attribute to their phase.
_prof = None

#: Completion-ring drain batch per pump crossing; matches the C-side
#: default ring capacity.
DRAIN_BATCH = 1024

_planes: dict = {}            # asyncio loop -> NativePlane
_planes_lock = threading.Lock()


def native_available() -> bool:
    """True when the extension is importable and exports the
    transport data-plane symbols (txloop_new/transport_probe)."""
    return _native is not None


def transport_probe() -> dict:
    """Build/runtime feature matrix: {'epoll': bool,
    'io_uring_built': bool, 'io_uring_runtime': bool}."""
    if _native is None:
        return {'epoll': False, 'io_uring_built': False,
                'io_uring_runtime': False}
    return _native.transport_probe()


def _oserror(status: int) -> OSError:
    """Map a negative-errno completion status to the OSError subclass
    asyncio would raise for the same failure (OSError.__new__ picks
    ConnectionRefusedError etc. from the errno)."""
    e = -status if status < 0 else status
    return OSError(e, os.strerror(e))


class NativePlane:
    """One C transport loop bound to one asyncio loop: owns the
    TransportLoop object, the completion-drain pump, and the id ->
    connection/operation dispatch tables."""

    def __init__(self, loop, backend: str = 'auto',
                 ring_cap: int = 1024):
        self.loop = loop
        self.tx = _native.txloop_new(ring_cap=ring_cap,
                                     backend=backend)
        self.conns: dict = {}     # conn_id -> NativeConnection
        self.ops: dict = {}       # op_id -> Future | callable
        self.closed = False
        self.drains = 0
        # Per-seam counter baseline for ledger folding: deltas since
        # the last fold are added to the live SeamStats, so enabling
        # wiretap mid-flight starts counting from that moment (same
        # semantics as the asyncio arm).
        self._folded: dict = {}
        self._fold_baseline()
        loop.add_reader(self.tx.fileno(), self._on_wake)

    # -- completion pump -------------------------------------------------

    def _on_wake(self) -> None:
        self.drain()

    def drain(self) -> int:
        """The one pump crossing per tick: pull the completion batch
        out of the SPSC ring and dispatch every entry."""
        if self.closed:
            return 0
        prof = _prof
        tok = prof.push_phase('runq_pump') if prof is not None else None
        try:
            batch = self.tx.drain(DRAIN_BATCH)
            for kind, cid, status, t_ready, payload in batch:
                self._dispatch(kind, cid, status, t_ready, payload)
        finally:
            if tok is not None:
                prof.pop_phase(tok)
        self.drains += 1
        self._fold_counters()
        return len(batch)

    def _dispatch(self, kind, cid, status, t_ready, payload) -> None:
        tx = _native
        if kind == tx.TX_CONNECT:
            conn = self.conns.get(cid)
            if conn is None or conn.destroyed:
                return
            if status == 0:
                # (kernel-ready, dispatched): t_ready was stamped by
                # the C thread the instant SO_ERROR cleared; the
                # second mark is now, after the pump crossing — the
                # wiretap socket_wait decomposition reads the gap as
                # loop_dispatch.
                conn.wt_marks = (t_ready, mod_utils.current_millis())
                conn.emit('connect')
            else:
                self.conns.pop(cid, None)
                conn.emit('error', _oserror(status))
        elif kind in (tx.TX_READ, tx.TX_DNS_UDP, tx.TX_DNS_TCP):
            fut = self.ops.pop(cid, None)
            if fut is None or fut.done():
                return
            if status == 0:
                fut.set_result(payload if payload is not None else b'')
            elif status == -mod_errno.ETIMEDOUT:
                fut.set_exception(asyncio.TimeoutError())
            else:
                fut.set_exception(_oserror(status))
        elif kind == tx.TX_DATA:
            conn = self.conns.get(cid)
            if conn is None or conn.destroyed:
                return
            # Push-vs-pull is decided by listener presence: only drain
            # the C receive buffer into a 'data' emit when someone is
            # subscribed. A pull-mode conn (read_exactly) must find the
            # bytes still in the C buffer — eagerly consuming here
            # loses the race where the peer's response lands before
            # the reader parks its op, stranding the read forever.
            if not conn.listeners('data'):
                return
            data = self.tx.read_available(cid)
            if data:
                conn.emit('data', data)
        elif kind == tx.TX_CLOSE:
            conn = self.conns.pop(cid, None)
            if conn is None or conn.destroyed:
                return
            conn.emit('close')
        elif kind == tx.TX_ERROR:
            conn = self.conns.pop(cid, None)
            if conn is None or conn.destroyed:
                return
            conn.emit('error', _oserror(status))
        elif kind == tx.TX_TIMER:
            cb = self.ops.pop(cid, None)
            if cb is not None and not self.closed:
                cb()

    # -- wire-ledger folding ---------------------------------------------

    def _fold_baseline(self) -> None:
        self._folded = {seam: dict(fields) for seam, fields
                        in self.tx.counters().items()}

    def _fold_counters(self) -> None:
        """Add C-side counter deltas to the live TransportLedger's
        'native' SeamStats rows. When wiretap is off the baseline
        still advances, so pre-enable traffic is never retro-counted
        (matching the asyncio arm, which simply doesn't count while
        disabled)."""
        cur = self.tx.counters()
        folded = self._folded
        enabled = mod_wiretap.wiretap_enabled()
        for seam, fields in cur.items():
            last = folded.get(seam, {})
            if enabled:
                deltas = [(field, value - last.get(field, 0))
                          for field, value in fields.items()]
                # Only materialize a ledger row once the seam has
                # actually moved (snapshot() reports touched seams;
                # an all-zero native dns row would break set parity
                # with the asyncio arm).
                if any(d for _f, d in deltas):
                    st = mod_wiretap.seam_stats('native', seam)
                    if st is not None:
                        for field, delta in deltas:
                            if delta:
                                setattr(st, field,
                                        getattr(st, field) + delta)
            folded[seam] = fields

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self.loop.remove_reader(self.tx.fileno())
        except Exception:
            pass                  # loop already closed
        for op in list(self.ops.values()):
            if isinstance(op, asyncio.Future) and not op.done():
                op.cancel()
        self.ops.clear()
        for conn in list(self.conns.values()):
            conn.destroyed = True
        self.conns.clear()
        self.tx.shutdown()

    def stats(self) -> dict:
        return self.tx.stats()


def get_plane(loop=None, backend: str | None = None) -> NativePlane:
    """The NativePlane for ``loop`` (default: the running loop),
    created on first use. Refuses when the extension lacks transport
    symbols or a non-system clock is installed (netsim virtual time
    cannot drive a kernel poller — same refusal profile.start_sampler
    makes)."""
    if _native is None:
        raise TransportNotAvailableError('resolve', transport='native')
    if not isinstance(mod_utils.get_clock(), mod_utils.SystemClock):
        raise TransportNotAvailableError(
            'resolve', transport='native',
            cause=RuntimeError('non-system clock installed (netsim?)'))
    if loop is None:
        loop = asyncio.get_running_loop()
    with _planes_lock:
        plane = _planes.get(loop)
        if plane is not None and not plane.closed:
            return plane
        # Prune planes whose loops are gone before adding a new one.
        for stale_loop in [l for l, p in _planes.items()
                           if p.closed or l.is_closed()]:
            stale = _planes.pop(stale_loop)
            if not stale.closed:
                stale.close()
        plane = NativePlane(
            loop, backend=backend
            or os.environ.get('CUEBALL_NATIVE_POLLER', 'auto'))
        _planes[loop] = plane
    return plane


def peek_plane(loop) -> NativePlane | None:
    """The existing (open) plane for ``loop``, or None — never
    creates one. The runq wheel hook uses this so timers only ride
    the C plane on loops that already run native transport."""
    with _planes_lock:
        plane = _planes.get(loop)
    if plane is None or plane.closed:
        return None
    return plane


def close_plane(loop) -> bool:
    """Tear down the plane bound to ``loop`` from the loop's own
    thread. Returns True when a live plane was closed."""
    with _planes_lock:
        plane = _planes.pop(loop, None)
    if plane is None or plane.closed:
        return False
    plane.close()
    return True


def close_plane_threadsafe(loop) -> bool:
    """Request teardown of any plane bound to ``loop`` from ANY
    thread (shard teardown reaches worker loops from the router
    thread). Both the lookup and the close must run on the owning
    loop — a foreign-thread lookup would race plane creation, and
    ``remove_reader`` is not thread-safe — so the whole operation is
    marshalled across with ``call_soon_threadsafe`` (the
    A001-licensed crossing for this module). Returns True when the
    close was dispatched (or, for a dead loop, performed inline)."""
    if not loop.is_closed():
        try:
            loop.call_soon_threadsafe(_close_on_loop, loop)
            return True
        except RuntimeError:
            pass                  # lost the race with loop.close()
    # Dead loop: nothing pumps add_reader anymore, close inline.
    with _planes_lock:
        plane = _planes.pop(loop, None)
    if plane is None or plane.closed:
        return False
    plane.close()
    return True


def _close_on_loop(loop) -> None:
    close_plane(loop)


@atexit.register
def _close_all_planes() -> None:
    with _planes_lock:
        planes = list(_planes.values())
        _planes.clear()
    for plane in planes:
        try:
            plane.close()
        except Exception:
            pass


# -- runq claim-deadline timers on the C plane ------------------------------

def _native_wheel_timer(loop, delay_ms: float, fire) -> bool:
    """runq.set_native_timer hook: arm a timer-wheel bucket deadline
    on the C plane's deadline heap instead of ``loop.call_later``.
    Returns False (caller falls back to call_later) when the loop has
    no live plane — netsim loops and plain asyncio pools keep their
    exact current behavior."""
    plane = peek_plane(loop)
    if plane is None:
        return False
    try:
        op_id = plane.tx.timer(max(delay_ms, 0.0))
    except RuntimeError:
        return False              # plane shutting down mid-arm
    plane.ops[op_id] = fire
    return True


# -- connection contract ----------------------------------------------------

class NativeConnection(EventEmitter):
    """Connection-contract object over the C data plane: the native
    twin of ``transport.TcpStreamConnection`` / netsim's
    SimConnection. Emits 'connect' once the C thread reports the
    socket writable, 'error'/'close' on loss, 'data' when coalesced
    bytes arrive. Seam accounting (events/connects/errors/closes and
    byte counts) happens entirely C-side and reaches the wiretap
    ledger via the plane's counter fold."""

    def __init__(self, transport, backend: dict, plane: NativePlane):
        super().__init__()
        self.transport = transport
        self.backend = backend
        self.destroyed = False
        self.wt_marks = None
        self.wt_transport = transport.name
        self._plane = plane
        self.conn_id = None
        host = str(backend['address'])
        port = int(backend['port'])
        try:
            cid = plane.tx.connect(host, port, 0.0)
        except ValueError:
            # Non-numeric host: resolve here (one-time, submit path,
            # not per-byte) and hand the C plane a literal.
            try:
                infos = mod_socket.getaddrinfo(
                    host, port, type=mod_socket.SOCK_STREAM)
                cid = plane.tx.connect(infos[0][4][0], port, 0.0)
            except OSError as e:
                # Contract: connect failures surface as an 'error'
                # emit after the constructor returns (the FSM attaches
                # listeners first), never as a constructor raise.
                plane.loop.call_soon(self._emit_error, e)
                return
        self.conn_id = cid
        plane.conns[cid] = self

    def _emit_error(self, exc) -> None:
        if not self.destroyed:
            self.emit('error', exc)

    def write(self, data: bytes) -> int:
        """Submit bytes; small writes to an open, unblocked socket go
        inline (one syscall, zero crossings), larger or blocked ones
        are buffered and flushed by the C thread."""
        if self.destroyed or self.conn_id is None:
            return 0
        return self._plane.tx.write(self.conn_id, data)

    async def read_exactly(self, n: int,
                           timeout_ms: float = 0.0) -> bytes:
        """Exactly-n read: satisfied from the C-side receive buffer
        with zero crossings when the bytes already landed, else
        parked on the plane until the C thread completes it."""
        if self.destroyed or self.conn_id is None:
            raise _oserror(mod_errno.ENOTCONN)
        got = self._plane.tx.read(self.conn_id, n, timeout_ms)
        if isinstance(got, bytes):
            return got
        fut = self._plane.loop.create_future()
        self._plane.ops[got] = fut
        return await fut

    def read_available(self) -> bytes:
        if self.destroyed or self.conn_id is None:
            return b''
        return self._plane.tx.read_available(self.conn_id)

    def on(self, event, listener):
        out = super().on(event, listener)
        # Late push-mode subscriber: bytes that landed before the
        # first 'data' listener attached are still sitting in the C
        # buffer (the pump leaves them for pull-mode readers). Flush
        # them to the new listener asynchronously so attach order
        # doesn't lose data.
        if event == 'data' and not self.destroyed \
                and self.conn_id is not None:
            def catch_up():
                if self.destroyed or self.conn_id is None:
                    return
                data = self._plane.tx.read_available(self.conn_id)
                if data:
                    self.emit('data', data)
            self._plane.loop.call_soon(catch_up)
        return out

    def destroy(self) -> None:
        if self.destroyed:
            return
        self.destroyed = True
        if self.conn_id is not None:
            self._plane.conns.pop(self.conn_id, None)
            if not self._plane.closed:
                try:
                    self._plane.tx.close_conn(self.conn_id)
                except RuntimeError:
                    pass          # plane shut down under us

    def ref(self):
        pass

    def unref(self):
        pass


# -- the five-seam transport ------------------------------------------------

class RealNativeTransport(Transport):
    """The native backend behind the ``Transport`` seam contract.
    connector / dns_udp / dns_tcp run on the C data plane;
    create_stream / serve are asyncio-backed fallbacks accounted to
    the 'native' ledger rows (the HTTP agent and kang endpoint are
    not claim-path-hot; see docs/transport.md §Native backend)."""

    name = 'native'

    @property
    def available(self) -> bool:
        return native_available()

    def __init__(self, backend: str | None = None):
        self._poller = backend

    def _plane(self, loop=None) -> NativePlane:
        return get_plane(loop, backend=self._poller)

    # -- pool constructor seam -------------------------------------------

    def connector(self, backend: dict) -> NativeConnection:
        plane = self._plane()
        return NativeConnection(self, backend, plane)

    # -- stream seam (asyncio fallback, native-accounted) ----------------

    async def create_stream(self, protocol_factory, host, port,
                            ssl=None, server_hostname=None):
        st = mod_wiretap.seam_stats(self.name, 'create_stream')
        if st is not None:
            st.events += 1
        try:
            result = await self._open_stream(
                protocol_factory, host, port, ssl=ssl,
                server_hostname=server_hostname)
        except OSError:
            if st is not None:
                st.errors += 1
            raise
        if st is not None:
            st.connects += 1
        return result

    async def _open_stream(self, protocol_factory, host, port,
                           ssl=None, server_hostname=None):
        loop = asyncio.get_running_loop()
        kwargs = {}
        if ssl is not None:
            kwargs['ssl'] = ssl
            kwargs['server_hostname'] = server_hostname
        return await loop.create_connection(
            protocol_factory, host, port, **kwargs)

    def configure_keepalive(self, stream_transport,
                            delay_ms: float | None = None) -> int | None:
        sock = stream_transport.get_extra_info('socket')
        if sock is None:
            return None
        sock.setsockopt(mod_socket.SOL_SOCKET,
                        mod_socket.SO_KEEPALIVE, 1)
        if delay_ms is not None and hasattr(mod_socket,
                                            'TCP_KEEPIDLE'):
            sock.setsockopt(mod_socket.IPPROTO_TCP,
                            mod_socket.TCP_KEEPIDLE,
                            max(1, int(delay_ms / 1000)))
        return sock.getsockname()[1]

    # -- server seam (asyncio fallback, native-accounted) ----------------

    async def serve(self, client_connected_cb, host, port):
        st = mod_wiretap.seam_stats(self.name, 'serve')
        if st is not None:
            st.events += 1
            inner_cb = client_connected_cb

            def client_connected_cb(reader, writer):
                st.connects += 1
                return inner_cb(reader, writer)

        return await asyncio.start_server(
            client_connected_cb, host, port)

    # -- DNS wire seam (C plane) -----------------------------------------

    async def dns_udp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        return await self._dns(False, resolver, port, payload,
                               timeout_s)

    async def dns_tcp(self, resolver: str, port: int, payload: bytes,
                      timeout_s: float) -> bytes:
        return await self._dns(True, resolver, port, payload,
                               timeout_s)

    async def _dns(self, tcp: bool, resolver: str, port: int,
                   payload: bytes, timeout_s: float) -> bytes:
        plane = self._plane()
        submit = plane.tx.dns_tcp if tcp else plane.tx.dns_udp
        host = str(resolver)
        timeout_ms = max(float(timeout_s), 0.0) * 1000.0
        try:
            op_id = submit(host, int(port), payload, timeout_ms)
        except ValueError:
            # Non-numeric resolver name: resolve without blocking the
            # loop, then hand the C plane a literal.
            socktype = (mod_socket.SOCK_STREAM if tcp
                        else mod_socket.SOCK_DGRAM)
            infos = await plane.loop.getaddrinfo(host, int(port),
                                                 type=socktype)
            op_id = submit(infos[0][4][0], int(port), payload,
                           timeout_ms)
        fut = plane.loop.create_future()
        plane.ops[op_id] = fut
        return await fut

    # -- identity --------------------------------------------------------

    def host_ident(self) -> str:
        return mod_socket.gethostname()


# -- wiretap pull source ----------------------------------------------------

def _pull_wire_counters() -> None:
    """wiretap wire-source hook: fold every live plane's counters so
    snapshot()/wire_totals() read current native rows even between
    drains."""
    with _planes_lock:
        planes = list(_planes.values())
    for plane in planes:
        if not plane.closed:
            plane._fold_counters()


mod_wiretap.register_wire_source(_pull_wire_counters)
mod_runq.set_native_timer(_native_wheel_timer)


__all__ = ['NativePlane', 'NativeConnection', 'RealNativeTransport',
           'native_available', 'transport_probe', 'get_plane',
           'peek_plane', 'close_plane', 'close_plane_threadsafe',
           'DRAIN_BATCH']

"""Runtime observability attach (reference lib/utils.js:59-99 dtrace
probe analogue): signal/env toggles for stack capture, whole-process FSM
history dumps, and contextual child loggers."""

import asyncio
import logging
import os
import re
import signal

import pytest

import cueball_tpu as cb
from cueball_tpu import debug as mod_debug
from cueball_tpu import profile as mod_profile
from cueball_tpu import utils as mod_utils
from cueball_tpu.events import EventEmitter

from conftest import run_async


@pytest.fixture(autouse=True)
def _sampler_off():
    """The SIGUSR2 toggle doubles as the profiler attach point, so any
    test flipping it an odd number of times would leak a running
    SIGPROF sampler (and its accumulated samples) into the suite."""
    yield
    mod_profile.stop_sampler()
    mod_profile.reset_samples()


class InstantConnection(EventEmitter):
    def __init__(self, backend):
        super().__init__()
        self.backend = backend
        asyncio.get_running_loop().call_soon(lambda: self.emit('connect'))

    def destroy(self):
        pass

    def unref(self):
        pass


def build_pool(**opts):
    res = cb.StaticIpResolver({
        'backends': [{'address': '127.0.0.1', 'port': 1111}]})
    pool = cb.ConnectionPool({
        'domain': 'debug.test', 'resolver': res,
        'constructor': InstantConnection,
        'spares': 1, 'maximum': 2,
        'recovery': {'default': {'timeout': 1000, 'retries': 1,
                                 'delay': 50}},
        **opts})
    res.start()
    return pool, res


async def settle(pool):
    while not pool.is_in_state('running'):
        await asyncio.sleep(0.005)


def test_dump_covers_pool_slots_and_history():
    async def t():
        pool, res = build_pool()
        await settle(pool)
        report = cb.dump_fsm_histories()
        assert 'domain=debug.test' in report
        assert '(pool)' in report and 'state=running' in report
        # Slot + socket-manager lines with their history rings.
        assert 'slot ' in report and 'smgr' in report
        # History entries carry dwell annotations (changelog #119),
        # e.g. 'starting(3ms)->running'.
        assert re.search(r'starting\(\d+ms\)->running', report)
        assert re.search(r'connecting\(\d+ms\)->connected', report)
        pool.stop()
    run_async(t())


def test_signal_toggles_capture_and_dumps(caplog):
    async def t():
        pool, res = build_pool()
        await settle(pool)
        assert not mod_utils.stack_traces_enabled()
        prev = cb.install_debug_handler(signal.SIGUSR2)
        try:
            with caplog.at_level(logging.WARNING, logger='cueball.debug'):
                os.kill(os.getpid(), signal.SIGUSR2)
                await asyncio.sleep(0.05)   # let the handler run
                assert mod_utils.stack_traces_enabled()

                # While enabled, a claim captures a REAL stack.
                hdl, conn = await pool.claim()
                assert 'test_debug' in '\n'.join(hdl.ch_claim_stack)
                hdl.release()

                os.kill(os.getpid(), signal.SIGUSR2)
                await asyncio.sleep(0.05)
                assert not mod_utils.stack_traces_enabled()

                # Back off: claims carry the fixed placeholder again.
                hdl, conn = await pool.claim()
                assert 'disabled' in hdl.ch_claim_stack[0]
                hdl.release()
        finally:
            mod_debug.uninstall_debug_handler(prev, signal.SIGUSR2)
            mod_utils.disable_stack_traces()
        dumps = [r for r in caplog.records
                 if 'debug signal' in r.getMessage()]
        assert len(dumps) == 2
        assert 'domain=debug.test' in dumps[0].getMessage()
        pool.stop()
    run_async(t())


def test_init_from_env():
    assert not mod_utils.stack_traces_enabled()
    try:
        mod_debug.init_from_env({'CUEBALL_STACK_TRACES': '1'})
        assert mod_utils.stack_traces_enabled()
    finally:
        mod_utils.disable_stack_traces()
    # '0' and empty are off; no signal handler requested -> no change.
    mod_debug.init_from_env({'CUEBALL_STACK_TRACES': '0'})
    assert not mod_utils.stack_traces_enabled()

    prev = signal.getsignal(signal.SIGUSR1)
    try:
        mod_debug.init_from_env({'CUEBALL_DEBUG_SIGNAL': 'USR1'})
        assert signal.getsignal(signal.SIGUSR1) is mod_debug._on_debug_signal
    finally:
        signal.signal(signal.SIGUSR1, prev)


def test_child_loggers_carry_backend_context(caplog):
    """Log records from slot/smgr/pool level carry bound context the way
    the reference's bunyan child loggers do (reference
    lib/pool.js:148-157, lib/connection-fsm.js:149-154)."""
    async def t():
        pool, res = build_pool()
        await settle(pool)
        slots = next(iter(pool.p_connections.values()))
        smgr = slots[0].csf_smgr
        with caplog.at_level(logging.INFO, logger='cueball'):
            pool.p_log.info('pool-side message')
            smgr.sm_log.info('smgr-side message')
        pool_rec = next(r for r in caplog.records
                        if 'pool-side' in r.getMessage())
        smgr_rec = next(r for r in caplog.records
                        if 'smgr-side' in r.getMessage())
        # Context rides the record for structured handlers...
        assert pool_rec.cueball.get('domain') == 'debug.test'
        assert smgr_rec.cueball.get('address') == '127.0.0.1'
        assert smgr_rec.cueball.get('port') == 1111
        # ...and is prefixed into the message for plain formatters.
        assert 'address=127.0.0.1' in smgr_rec.getMessage()
        pool.stop()
    run_async(t())


def test_soak_live_toggle_under_claim_load():
    """Claim/release continuously while an external 'operator' flips the
    debug signal several times mid-flight: every claim completes, and
    each handle's captured stack matches the capture mode in force when
    it was claimed."""
    async def t():
        pool, res = build_pool()
        await settle(pool)
        prev = cb.install_debug_handler(signal.SIGUSR2)
        real, fake = 0, 0
        try:
            for i in range(120):
                if i % 30 == 15:
                    os.kill(os.getpid(), signal.SIGUSR2)
                    await asyncio.sleep(0)
                hdl, conn = await pool.claim()
                if 'disabled' in hdl.ch_claim_stack[0]:
                    fake += 1
                else:
                    real += 1
                hdl.release()
        finally:
            mod_debug.uninstall_debug_handler(prev, signal.SIGUSR2)
            mod_utils.disable_stack_traces()
        # 4 toggles at 15/45/75/105: ~half the claims in each mode.
        assert real >= 30 and fake >= 30
        pool.stop()
    run_async(t())


def test_dump_covers_sets_and_resolvers():
    async def t():
        from test_cset import make_cset
        from test_pool import Ctx
        ctx = Ctx()
        cset, inner, _resolver = make_cset(ctx, target=1, maximum=2)
        inner.emit('added', 'b1', {'address': '10.0.0.9', 'port': 5})
        await asyncio.sleep(0.05)
        d = cb.DNSResolver({
            'domain': 'dump.example', 'service': '_x._tcp',
            'defaultPort': 1,
            'recovery': {'default': {'timeout': 1000, 'retries': 1,
                                     'delay': 50}}})
        report = cb.dump_fsm_histories()
        assert 'set ' in report and '(set)' in report
        assert 'dns_res ' in report and 'dump.example' in report
        cset.stop()
        d.stop()
    run_async(t())


def test_emit_dump_inline_without_loop(caplog):
    """Signal delivered to a process with no running asyncio loop:
    the handler toggles and dumps inline."""
    assert not mod_utils.stack_traces_enabled()
    try:
        with caplog.at_level(logging.WARNING, logger='cueball.debug'):
            mod_debug._on_debug_signal(signal.SIGUSR2, None)
        assert mod_utils.stack_traces_enabled()
        assert any('debug signal' in r.getMessage()
                   for r in caplog.records)
    finally:
        mod_utils.disable_stack_traces()


def test_init_from_env_bad_signal_logs_and_continues(caplog):
    with caplog.at_level(logging.WARNING, logger='cueball.debug'):
        mod_debug.init_from_env({'CUEBALL_DEBUG_SIGNAL': 'USR9'})
    assert any('not installed' in r.getMessage() for r in caplog.records)


def test_signal_dump_includes_trace_ring(caplog):
    """With tracing enabled, the SIGUSR2 dump shows the last slow
    claims next to the FSM states (the 'where did latency go' half of
    the live-attach story)."""
    async def t():
        from cueball_tpu import trace as mod_trace
        pool, res = build_pool()
        await settle(pool)
        mod_trace.enable_tracing()
        prev = cb.install_debug_handler(signal.SIGUSR2)
        try:
            hdl, conn = await pool.claim()
            hdl.release()
            await asyncio.sleep(0.02)
            with caplog.at_level(logging.WARNING, logger='cueball.debug'):
                os.kill(os.getpid(), signal.SIGUSR2)
                await asyncio.sleep(0.05)
        finally:
            mod_debug.uninstall_debug_handler(prev, signal.SIGUSR2)
            mod_utils.disable_stack_traces()
            mod_trace.disable_tracing()
        dump = next(r.getMessage() for r in caplog.records
                    if 'debug signal' in r.getMessage())
        # FSM states and the trace section ride the same dump.
        assert 'domain=debug.test' in dump
        assert '-- claim traces' in dump
        assert re.search(r'claim\s+\d+\.\dms\s+released', dump)
        pool.stop()
    run_async(t())


def test_signal_arms_sampler_and_dump_shows_profiler(caplog):
    """The debug toggle IS the profiler attach point (`make profile`):
    the first SIGUSR2 arms the SIGPROF sampler, the second disarms it,
    and the dump that follows carries the profiler section (sampler
    state + the claims' phase ledgers)."""
    async def t():
        from cueball_tpu import trace as mod_trace
        pool, res = build_pool()
        await settle(pool)
        mod_trace.enable_tracing()
        prev = cb.install_debug_handler(signal.SIGUSR2)
        try:
            os.kill(os.getpid(), signal.SIGUSR2)
            await asyncio.sleep(0.05)
            assert mod_profile.sampler_running()
            hdl, conn = await pool.claim()
            hdl.release()
            await asyncio.sleep(0.02)
            with caplog.at_level(logging.WARNING, logger='cueball.debug'):
                os.kill(os.getpid(), signal.SIGUSR2)   # disarm + dump
                await asyncio.sleep(0.05)
            assert not mod_profile.sampler_running()
        finally:
            mod_debug.uninstall_debug_handler(prev, signal.SIGUSR2)
            mod_utils.disable_stack_traces()
            mod_trace.disable_tracing()
        dumps = [r.getMessage() for r in caplog.records
                 if 'debug signal' in r.getMessage()]
        # The first delivery's dump shows the sampler armed; the
        # disarming delivery's dump shows it stopped, with the claim's
        # ledger alongside.
        assert 'sampler: running engine=' in dumps[0]
        dump = dumps[-1]
        assert dump is not dumps[0]
        assert '-- claim-path profiler --' in dump
        assert re.search(r'sampler: stopped samples=\d+', dump)
        assert 'ledger:' in dump and 'coverage=' in dump
        pool.stop()
    run_async(t())


def test_dump_omits_profiler_section_when_idle():
    """Sampler never armed, no completed claims: the profiler section
    is absent and the dump is otherwise unchanged (absent-but-
    well-formed, like the health and trace sections)."""
    async def t():
        pool, res = build_pool()
        await settle(pool)
        report = cb.dump_fsm_histories()
        assert '-- claim-path profiler --' not in report
        assert 'domain=debug.test' in report
        assert '(pool)' in report and 'state=running' in report
        pool.stop()
    run_async(t())


def test_signal_dump_defers_to_running_loop(caplog):
    """With an asyncio loop running, _on_debug_signal must NOT dump
    inline (buffered log writes are not reentrancy-safe at interrupt
    points): the toggle lands synchronously, the dump only after the
    loop runs its call_soon_threadsafe callbacks."""
    async def t():
        assert not mod_utils.stack_traces_enabled()
        try:
            with caplog.at_level(logging.WARNING, logger='cueball.debug'):
                mod_debug._on_debug_signal(signal.SIGUSR2, None)
                assert mod_utils.stack_traces_enabled()
                assert not any('debug signal' in r.getMessage()
                               for r in caplog.records)
                await asyncio.sleep(0.05)
                assert any('debug signal' in r.getMessage()
                           for r in caplog.records)
        finally:
            mod_utils.disable_stack_traces()
    run_async(t())


def test_fsm_line_survives_broken_objects():
    class Broken:
        def get_state(self):
            raise RuntimeError('nope')

        def get_history(self):
            raise RuntimeError('nope')
    line = mod_debug._fsm_line('x', Broken())
    assert 'state=?' in line


def _spawn_dump_pool():
    """Spawn-child pool factory ('test_debug:_spawn_dump_pool'): must
    be module-level so the child process can import it by spec."""
    return build_pool()


def test_dump_renders_spawn_router_and_health_with_dead_child():
    """SIGUSR2 dump while a spawn-backend FleetRouter is live: the
    fleet_router section (shard FSM states + pool->shard tags) and the
    new health section render from parent-side state only — killing a
    child outright must not hang or break the dump."""
    import time as mod_time

    from cueball_tpu.parallel import health as mod_health
    from cueball_tpu.shard import FleetRouter

    async def main():
        router = FleetRouter({'shards': 2, 'backend': 'spawn'})
        await router.start(timeout_s=60.0)
        monitor = None
        try:
            rec = await router.create_pool(
                'svc.dump', factory='test_debug:_spawn_dump_pool')
            # A health monitor with one judged tick, so the dump's
            # health section has a verdict line to render.
            monitor = mod_health.HealthMonitor().start()
            monitor.hm_table.observe('spawn-b0', 5.0, 6.0, True)
            monitor.tick()

            # Kill the OTHER shard's child dead — no stop handshake.
            dead = 1 - rec.shard_id
            router.fr_workers[dead]._proc.terminate()
            router.fr_workers[dead]._proc.join(timeout=10)

            # Arm the sampler too: the profiler section must render
            # from parent-side state even with a corpse in the fleet.
            assert mod_profile.start_sampler()

            t0 = mod_time.monotonic()
            report = cb.dump_fsm_histories()
            # Parent-side state only: never an IPC round-trip, so the
            # dump returns fast even with a corpse in the fleet.
            assert mod_time.monotonic() - t0 < 2.0
            assert 'fleet_router backend=spawn shards=2' in report
            assert 'shard 0' in report and 'shard 1' in report
            assert re.search(
                r'pool svc\.dump\s+-> shard %d' % rec.shard_id, report)
            assert '-- fleet health (1 monitor(s)) --' in report
            assert re.search(r'epoch=1 backends=\d+ gray=-', report)
            assert '-- claim-path profiler --' in report
            assert re.search(r'sampler: running engine=\w+', report)
        finally:
            if monitor is not None:
                monitor.stop()
            mod_profile.stop_sampler()
            try:
                await router.stop()
            except Exception:
                pass    # a terminated child may fail the handshake
    run_async(main(), timeout=120.0)


def test_kang_health_and_profile_reject_malformed_params():
    """/kang/health and /kang/profile answer malformed query params
    with 400 JSON error bodies, the /kang/traces convention: unknown
    parameters, non-integer or negative limits, unknown phase names.
    Valid inputs (including the limit=0 edge) still serve 200."""
    from cueball_tpu.http_server import serve_monitor
    from test_monitor import _get

    async def main():
        server = await serve_monitor()
        port = server.sockets[0].getsockname()[1]
        try:
            status, body = await _get(port, '/kang/health?limit=abc')
            assert status == 400
            assert body == {'error': "limit must be an integer, "
                                     "got 'abc'"}
            status, body = await _get(port, '/kang/health?limit=-2')
            assert status == 400
            assert body == {'error': 'limit must be >= 0, got -2'}
            status, body = await _get(port, '/kang/health?bogus=1')
            assert status == 400
            assert body == {'error': 'unknown parameter(s) bogus; '
                                     'supported: limit'}
            # One bad parameter rejects even when the other is fine.
            status, body = await _get(port,
                                      '/kang/health?limit=1&bogus=1')
            assert status == 400 and 'unknown parameter' in body['error']

            status, body = await _get(port, '/kang/profile?phase=nope')
            assert status == 400
            assert body['error'].startswith("unknown phase 'nope'")
            assert 'handshake' in body['error']
            status, body = await _get(port, '/kang/profile?limit=1')
            assert status == 400
            assert body == {'error': 'unknown parameter(s) limit; '
                                     'supported: phase'}

            status, body = await _get(port, '/kang/health?limit=0')
            assert status == 200 and body['monitors'] == []
            status, body = await _get(port, '/kang/health?limit=5')
            assert status == 200
            status, body = await _get(port,
                                      '/kang/profile?phase=handshake')
            assert status == 200
        finally:
            server.close()
            await server.wait_closed()
    run_async(main())

"""Seeded randomized soak of the ConnectionSet FSM stack.

Companion to tests/test_soak.py for the Set side: LogicalConnection's
init→advertised→draining→stopped lifecycle plus the consumer drain
contract are driven with random topology churn, connection fates,
target resizes, and lazily-returned drain handles. Invariants: every
'added' is eventually paired with a 'removed' for the same logical
connection key, handles released late still drain cleanly, and the
set always quiesces to 'stopped'. Seeds fixed for reproducibility."""

import asyncio
import random

import pytest

from conftest import run_async, settle, wait_for_state
from soak_common import TopoChaos
from test_cset import make_cset
from test_pool import Ctx


async def _soak(seed, actions=300):
    rng = random.Random(seed)
    ctx = Ctx()
    cset, inner, resolver = make_cset(ctx, target=2, maximum=5,
                                      retries=2, timeout=200, delay=20)
    chaos = TopoChaos(rng, ctx, inner)
    advertised = {}          # logical key -> (conn, handle)
    added_keys = []
    removed_keys = []
    pending_release = [0]

    def on_added(key, conn, hdl):
        added_keys.append(key)
        advertised[key] = (conn, hdl)
        conn.on('error', lambda e=None: None)

    def on_removed(key, conn, hdl):
        removed_keys.append(key)
        advertised.pop(key, None)
        # Consumer drain: sometimes instant, sometimes lazy — the set
        # must wait for the handle either way.
        if rng.random() < 0.5:
            hdl.release()
        else:
            pending_release[0] += 1

            def later():
                pending_release[0] -= 1
                hdl.release()
            asyncio.get_running_loop().call_later(
                rng.uniform(0.01, 0.08), later)

    cset.on('added', on_added)
    cset.on('removed', on_removed)

    chaos.add_backend()
    await settle()

    for step in range(actions):
        roll = rng.random()
        if roll < 0.35:
            chaos.connect_random()
        elif roll < 0.45:
            chaos.error_random(step)
        elif roll < 0.52:
            chaos.close_random()
        elif roll < 0.65:
            chaos.add_backend()
        elif roll < 0.75:
            chaos.remove_backend()
        else:
            cset.set_target(rng.randint(1, 4))
        if step % 10 == 0:
            # Ordering-insensitive invariant: until 'removed' is
            # delivered and the consumer releases, every advertised
            # handle is still a claimed lease the set must honor.
            for key, (_c, h) in advertised.items():
                assert h.is_in_state('claimed'), (
                    '%s handle in %s' % (key, h.get_state()))
            await settle()

    # Quiesce: connect stragglers, then stop. 'removed' fires for every
    # advertised connection during stopping; lazy releases drain after.
    chaos.connect_stragglers()
    await settle()
    cset.stop()
    await wait_for_state(cset, 'stopped', timeout=10)
    deadline = asyncio.get_running_loop().time() + 2.0
    while pending_release[0] and \
            asyncio.get_running_loop().time() < deadline:
        await asyncio.sleep(0.02)

    assert not advertised, ('connections still advertised after stop: '
                            '%r' % list(advertised))
    assert sorted(added_keys) == sorted(removed_keys), (
        'added/removed pairing broken: %d added, %d removed' % (
            len(added_keys), len(removed_keys)))


@pytest.mark.parametrize('seed', [11, 47, 2003])
def test_soak_cset_random_chaos(seed):
    run_async(_soak(seed), timeout=60)

"""Pool-monitor / kang tests over real HTTP (ported from reference
test/monitor.test.js): empty registry, pool appears with per-state
connection counts, set appears, dns resolver appears, teardown."""

import asyncio
import json

from cueball_tpu.http_server import serve_monitor
from cueball_tpu.monitor import pool_monitor
from cueball_tpu import metrics as mod_metrics

from conftest import run_async, settle, wait_for_state
from test_pool import Ctx, make_pool
from test_cset import make_cset


async def _read_response(reader):
    status_line = await reader.readline()
    status = int(status_line.split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b'\r\n', b'\n', b''):
            break
        k, _, v = line.decode().partition(':')
        headers[k.strip().lower()] = v.strip()
    body = await reader.readexactly(int(headers['content-length']))
    return status, headers, body


async def _get(port, path):
    reader, writer = await asyncio.open_connection('127.0.0.1', port)
    writer.write(b'GET %s HTTP/1.1\r\nHost: x\r\n\r\n' %
                 path.encode())
    await writer.drain()
    status, headers, body = await _read_response(reader)
    writer.close()
    return status, json.loads(body) if \
        headers.get('content-type', '').startswith('application/json') \
        else body.decode()


def test_kang_snapshot_lifecycle():
    async def t():
        server = await serve_monitor()
        port = server.sockets[0].getsockname()[1]

        # Types listing.
        status, types = await _get(port, '/kang/types')
        assert status == 200
        assert types == ['pool', 'set', 'dns_res']

        # A pool appears with per-state connection counts.
        ctx = Ctx()
        pool, inner = make_pool(ctx, spares=2, maximum=2)
        inner.emit('added', 'b1', {})
        await settle()
        for c in ctx.connections:
            c.connect()
        await asyncio.sleep(0.05)

        status, ids = await _get(port, '/kang/objects/pool')
        assert status == 200
        assert pool.p_uuid in ids

        status, obj = await _get(port, '/kang/obj/pool/%s' % pool.p_uuid)
        assert status == 200
        assert obj['state'] == 'running'
        assert obj['connections']['b1'] == {'idle': 2}
        assert obj['dead_backends'] == []
        assert obj['options']['spares'] == 2
        assert obj['options']['maximum'] == 2

        # A set appears too.
        ctx2 = Ctx()
        cset, inner2, resolver2 = make_cset(ctx2, target=1, maximum=2)
        cset.on('added', lambda *a: None)
        cset.on('removed', lambda k, conn, hdl: hdl.release())
        inner2.emit('added', 'bX', {})
        await settle()
        for c in ctx2.connections:
            c.connect()
        await asyncio.sleep(0.05)

        status, ids = await _get(port, '/kang/objects/set')
        assert cset.cs_uuid in ids
        status, obj = await _get(port, '/kang/obj/set/%s' % cset.cs_uuid)
        assert obj['state'] == 'running'
        assert list(obj['fsms'].values())[0] == {'busy': 1}
        assert obj['target'] == 1

        # Full snapshot includes both.
        status, snap = await _get(port, '/kang/snapshot')
        assert pool.p_uuid in snap['types']['pool']
        assert cset.cs_uuid in snap['types']['set']

        # Teardown unregisters.
        pool.stop()
        cset.stop()
        resolver2.stop()
        await wait_for_state(pool, 'stopped')
        await wait_for_state(cset, 'stopped')
        status, ids = await _get(port, '/kang/objects/pool')
        assert pool.p_uuid not in ids
        status, ids = await _get(port, '/kang/objects/set')
        assert cset.cs_uuid not in ids

        # Unknown type is a clean 404.
        status, _ = await _get(port, '/kang/objects/bogus')
        assert status == 404

        server.close()
    run_async(t())


def test_metrics_endpoint():
    async def t():
        coll = mod_metrics.create_collector({'component': 'cueball'})
        c = coll.counter('cueball_events', help='Total cueball events')
        c.increment({'evt': 'claim-timeout'})
        server = await serve_monitor(collector=coll)
        port = server.sockets[0].getsockname()[1]
        status, text = await _get(port, '/metrics')
        assert status == 200
        assert '# TYPE cueball_events counter' in text
        assert 'evt="claim-timeout"' in text
        server.close()
    run_async(t())


def test_dns_resolver_registered():
    async def t():
        from cueball_tpu.dns_resolver import DNSResolver
        from cueball_tpu import dns_resolver as mod_dns
        import sys
        sys.path.insert(0, 'tests')
        from fake_dns import FakeDnsClient
        orig = mod_dns.have_global_v6
        mod_dns.have_global_v6 = lambda: False
        try:
            res = DNSResolver({
                'domain': 'a.ok', 'service': '_foo._tcp',
                'resolvers': ['1.2.3.4'],
                'recovery': {'default': {'timeout': 1000, 'retries': 2,
                                         'delay': 100}},
                'dnsClient': FakeDnsClient()})
            res.start()
            await wait_for_state(res, 'running')
            inner = res.r_fsm
            obj = pool_monitor.get_dns_resolver(inner.r_uuid)
            assert obj['domain'] == 'a.ok'
            assert obj['state'] == 'sleep'
            assert 'srv' in obj['next']
            assert len(obj['backends']) == 1
            res.stop()
            await wait_for_state(res, 'stopped')
        finally:
            mod_dns.have_global_v6 = orig
    run_async(t())


async def _get_on(reader, writer, path, headers=b''):
    writer.write(b'GET %s HTTP/1.1\r\nHost: x\r\n%s\r\n' %
                 (path.encode(), headers))
    await writer.drain()
    return await _read_response(reader)


def test_kang_service_ident_handshake():
    """/kang/snapshot leads with the kang agent service block
    (reference: toKangOptions feeds the same fields to the kang server,
    lib/pool-monitor.js:60-79)."""
    async def t():
        import os
        server = await serve_monitor()
        port = server.sockets[0].getsockname()[1]
        status, snap = await _get(port, '/kang/snapshot')
        assert status == 200
        svc = snap['service']
        assert svc['name'] == 'cueball'
        assert svc['component'] == 'cueball_tpu'
        assert svc['version'] == '1.0.0'
        assert svc['pid'] == os.getpid()
        assert svc['ident']
        assert 'stats' in snap and 'types' in snap
        server.close()
    run_async(t())


def test_http_keepalive_and_errors():
    """One connection serves many requests (HTTP/1.1 persistent);
    Connection: close, bad requests, and non-GET are handled."""
    async def t():
        server = await serve_monitor()
        port = server.sockets[0].getsockname()[1]

        reader, writer = await asyncio.open_connection('127.0.0.1', port)
        # Three sequential requests on the SAME connection.
        for _ in range(3):
            status, hdrs, body = await _get_on(reader, writer,
                                               '/kang/types')
            assert status == 200
            assert hdrs['connection'] == 'keep-alive'
            assert json.loads(body) == ['pool', 'set', 'dns_res']
        # Query strings are stripped for routing.
        status, hdrs, _ = await _get_on(reader, writer,
                                        '/kang/types?x=1')
        assert status == 200
        # 405 on non-GET, still keeps the connection.
        writer.write(b'POST /kang/types HTTP/1.1\r\nHost: x\r\n\r\n')
        await writer.drain()
        line = await reader.readline()
        assert b'405' in line
        while (await reader.readline()) not in (b'\r\n', b'\n', b''):
            pass
        await reader.readexactly(len(b'{"error": "GET only"}'))
        # Connection: close is honored.
        status, hdrs, _ = await _get_on(reader, writer, '/kang/types',
                                        headers=b'Connection: close\r\n')
        assert status == 200 and hdrs['connection'] == 'close'
        assert await reader.read(1) == b''   # server closed
        writer.close()

        # Malformed request line -> 400, closed.
        reader, writer = await asyncio.open_connection('127.0.0.1', port)
        writer.write(b'NONSENSE\r\n\r\n')
        await writer.drain()
        line = await reader.readline()
        assert b'400' in line
        writer.close()

        # HTTP/1.0 defaults to close.
        reader, writer = await asyncio.open_connection('127.0.0.1', port)
        writer.write(b'GET /kang/types HTTP/1.0\r\n\r\n')
        await writer.drain()
        line = await reader.readline()
        assert b'200' in line
        writer.close()

        server.close()
    run_async(t())


def test_http_body_drain_and_oversize_line():
    """A bodied non-GET must not desync keep-alive (its body is drained,
    not parsed as the next request line), and a request line beyond the
    stream limit answers 400 instead of crashing the handler."""
    async def t():
        server = await serve_monitor()
        port = server.sockets[0].getsockname()[1]

        # POST with a body, then a pipelined legitimate GET on the same
        # connection: the GET must be answered 200, not parsed as
        # 'helloGET ...'.
        reader, writer = await asyncio.open_connection('127.0.0.1', port)
        writer.write(b'POST /kang/types HTTP/1.1\r\nHost: x\r\n'
                     b'Content-Length: 5\r\n\r\nhello'
                     b'GET /kang/types HTTP/1.1\r\nHost: x\r\n\r\n')
        await writer.drain()
        status, _, _ = await _read_response(reader)
        assert status == 405
        status, _, body = await _read_response(reader)
        assert status == 200
        assert json.loads(body) == ['pool', 'set', 'dns_res']
        writer.close()

        # Oversized request line: 400, no unhandled ValueError.
        reader, writer = await asyncio.open_connection('127.0.0.1', port)
        writer.write(b'GET /' + b'a' * 70000 + b' HTTP/1.1\r\n\r\n')
        await writer.drain()
        line = await reader.readline()
        assert b'400' in line
        writer.close()

        # Chunked request: answered, then the connection closes.
        reader, writer = await asyncio.open_connection('127.0.0.1', port)
        writer.write(b'GET /kang/types HTTP/1.1\r\nHost: x\r\n'
                     b'Transfer-Encoding: chunked\r\n\r\n')
        await writer.drain()
        status, hdrs, _ = await _read_response(reader)
        assert status == 200 and hdrs['connection'] == 'close'
        assert await reader.read(1) == b''
        writer.close()

        server.close()
    run_async(t())


def test_fleet_detach_and_unregister_asserts():
    async def t():
        # Fleet section is absent until a sampler attaches.
        assert pool_monitor.fleet_snapshot() == {'attached': False}

        class FakeSampler:
            def snapshot(self):
                return {'ticks': 7}
        pool_monitor.attach_fleet_sampler(FakeSampler())
        snap = pool_monitor.fleet_snapshot()
        assert snap['attached'] is True and snap['ticks'] == 7
        pool_monitor.detach_fleet_sampler()
        assert pool_monitor.fleet_snapshot() == {'attached': False}

        # Unregistering something never registered is a hard assert
        # (reference lib/pool-monitor.js mod_assert.ok guards).
        class Ghost:
            p_uuid = 'no-such-pool'
            cs_uuid = 'no-such-set'
            r_uuid = 'no-such-res'
        import pytest
        with pytest.raises(AssertionError):
            pool_monitor.unregister_pool(Ghost())
        with pytest.raises(AssertionError):
            pool_monitor.unregister_set(Ghost())
        with pytest.raises(AssertionError):
            pool_monitor.unregister_dns_resolver(Ghost())
        with pytest.raises(ValueError):
            pool_monitor.list_objects('bogus')
        with pytest.raises(ValueError):
            pool_monitor.get('bogus', 'x')
    run_async(t())


def test_http_parse_error_matrix():
    """Each malformed-request class answers 400 and closes: bad
    version, header without a colon, bad/oversized Content-Length,
    header flood, EOF mid-headers."""
    async def t():
        server = await serve_monitor()
        port = server.sockets[0].getsockname()[1]

        async def send_raw(payload):
            reader, writer = await asyncio.open_connection(
                '127.0.0.1', port)
            writer.write(payload)
            await writer.drain()
            line = await reader.readline()
            writer.close()
            return line

        assert b'400' in await send_raw(
            b'GET /kang/types HTTP/2.0\r\n\r\n')
        assert b'400' in await send_raw(
            b'GET /kang/types HTTP/1.1\r\nno-colon-here\r\n\r\n')
        assert b'400' in await send_raw(
            b'GET /x HTTP/1.1\r\nContent-Length: frog\r\n\r\n')
        assert b'400' in await send_raw(
            b'GET /x HTTP/1.1\r\nContent-Length: 9999999999\r\n\r\n')
        flood = b''.join(b'H%d: v\r\n' % i for i in range(80))
        assert b'400' in await send_raw(
            b'GET /x HTTP/1.1\r\n' + flood + b'\r\n')

        # Exactly _MAX_HEADERS headers is allowed (the terminator line
        # doesn't count against the cap — ADVICE r3 off-by-one), one
        # more is a flood.
        from cueball_tpu.http_server import _MAX_HEADERS
        at_cap = b''.join(b'H%d: v\r\n' % i for i in range(_MAX_HEADERS))
        assert b'200' in await send_raw(
            b'GET /kang/types HTTP/1.1\r\n' + at_cap + b'\r\n')
        over = at_cap + b'Hx: v\r\n'
        assert b'400' in await send_raw(
            b'GET /kang/types HTTP/1.1\r\n' + over + b'\r\n')

        # EOF mid-headers: connection just closes, no crash.
        reader, writer = await asyncio.open_connection('127.0.0.1', port)
        writer.write(b'GET /kang/types HTTP/1.1\r\nHost: x\r\n')
        await writer.drain()
        writer.close()
        await asyncio.sleep(0.05)

        # Server is still healthy afterwards.
        status, types = await _get(port, '/kang/types')
        assert status == 200 and types == ['pool', 'set', 'dns_res']
        server.close()
    run_async(t())

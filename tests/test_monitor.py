"""Pool-monitor / kang tests over real HTTP (ported from reference
test/monitor.test.js): empty registry, pool appears with per-state
connection counts, set appears, dns resolver appears, teardown."""

import asyncio
import json

from cueball_tpu.http_server import serve_monitor
from cueball_tpu.monitor import pool_monitor
from cueball_tpu import metrics as mod_metrics

from conftest import run_async, settle, wait_for_state
from test_pool import Ctx, make_pool
from test_cset import make_cset


async def _get(port, path):
    reader, writer = await asyncio.open_connection('127.0.0.1', port)
    writer.write(b'GET %s HTTP/1.1\r\nHost: x\r\n\r\n' %
                 path.encode())
    await writer.drain()
    status_line = await reader.readline()
    status = int(status_line.split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b'\r\n', b'\n', b''):
            break
        k, _, v = line.decode().partition(':')
        headers[k.strip().lower()] = v.strip()
    body = await reader.readexactly(int(headers['content-length']))
    writer.close()
    return status, json.loads(body) if \
        headers.get('content-type', '').startswith('application/json') \
        else body.decode()


def test_kang_snapshot_lifecycle():
    async def t():
        server = await serve_monitor()
        port = server.sockets[0].getsockname()[1]

        # Types listing.
        status, types = await _get(port, '/kang/types')
        assert status == 200
        assert types == ['pool', 'set', 'dns_res']

        # A pool appears with per-state connection counts.
        ctx = Ctx()
        pool, inner = make_pool(ctx, spares=2, maximum=2)
        inner.emit('added', 'b1', {})
        await settle()
        for c in ctx.connections:
            c.connect()
        await asyncio.sleep(0.05)

        status, ids = await _get(port, '/kang/objects/pool')
        assert status == 200
        assert pool.p_uuid in ids

        status, obj = await _get(port, '/kang/obj/pool/%s' % pool.p_uuid)
        assert status == 200
        assert obj['state'] == 'running'
        assert obj['connections']['b1'] == {'idle': 2}
        assert obj['dead_backends'] == []
        assert obj['options']['spares'] == 2
        assert obj['options']['maximum'] == 2

        # A set appears too.
        ctx2 = Ctx()
        cset, inner2, resolver2 = make_cset(ctx2, target=1, maximum=2)
        cset.on('added', lambda *a: None)
        cset.on('removed', lambda k, conn, hdl: hdl.release())
        inner2.emit('added', 'bX', {})
        await settle()
        for c in ctx2.connections:
            c.connect()
        await asyncio.sleep(0.05)

        status, ids = await _get(port, '/kang/objects/set')
        assert cset.cs_uuid in ids
        status, obj = await _get(port, '/kang/obj/set/%s' % cset.cs_uuid)
        assert obj['state'] == 'running'
        assert list(obj['fsms'].values())[0] == {'busy': 1}
        assert obj['target'] == 1

        # Full snapshot includes both.
        status, snap = await _get(port, '/kang/snapshot')
        assert pool.p_uuid in snap['types']['pool']
        assert cset.cs_uuid in snap['types']['set']

        # Teardown unregisters.
        pool.stop()
        cset.stop()
        resolver2.stop()
        await wait_for_state(pool, 'stopped')
        await wait_for_state(cset, 'stopped')
        status, ids = await _get(port, '/kang/objects/pool')
        assert pool.p_uuid not in ids
        status, ids = await _get(port, '/kang/objects/set')
        assert cset.cs_uuid not in ids

        # Unknown type is a clean 404.
        status, _ = await _get(port, '/kang/objects/bogus')
        assert status == 404

        server.close()
    run_async(t())


def test_metrics_endpoint():
    async def t():
        coll = mod_metrics.create_collector({'component': 'cueball'})
        c = coll.counter('cueball_events', help='Total cueball events')
        c.increment({'evt': 'claim-timeout'})
        server = await serve_monitor(collector=coll)
        port = server.sockets[0].getsockname()[1]
        status, text = await _get(port, '/metrics')
        assert status == 200
        assert '# TYPE cueball_events counter' in text
        assert 'evt="claim-timeout"' in text
        server.close()
    run_async(t())


def test_dns_resolver_registered():
    async def t():
        from cueball_tpu.dns_resolver import DNSResolver
        from cueball_tpu import dns_resolver as mod_dns
        import sys
        sys.path.insert(0, 'tests')
        from fake_dns import FakeDnsClient
        orig = mod_dns.have_global_v6
        mod_dns.have_global_v6 = lambda: False
        try:
            res = DNSResolver({
                'domain': 'a.ok', 'service': '_foo._tcp',
                'resolvers': ['1.2.3.4'],
                'recovery': {'default': {'timeout': 1000, 'retries': 2,
                                         'delay': 100}},
                'dnsClient': FakeDnsClient()})
            res.start()
            await wait_for_state(res, 'running')
            inner = res.r_fsm
            obj = pool_monitor.get_dns_resolver(inner.r_uuid)
            assert obj['domain'] == 'a.ok'
            assert obj['state'] == 'sleep'
            assert 'srv' in obj['next']
            assert len(obj['backends']) == 1
            res.stop()
            await wait_for_state(res, 'stopped')
        finally:
            mod_dns.have_global_v6 = orig
    run_async(t())

"""Claim-path profiler (cueball_tpu/profile.py): phase-ledger
invariants (phase_sum ~= wall, coverage >= 0.95 on the fast and queued
paths under both recorders), flamegraph byte-identity native vs pure
on a seeded netsim run, SIGPROF sampler lifecycle + netsim
auto-disable, the per-shard record merge, and the surfaced histograms
on /metrics."""

import asyncio

import pytest

import cueball_tpu as cb
from cueball_tpu import metrics as mod_metrics
from cueball_tpu import profile as mod_profile
from cueball_tpu import trace as mod_trace
from cueball_tpu import utils as mod_utils

from conftest import run_async
from test_debug import build_pool, settle


@pytest.fixture(autouse=True)
def _profiler_off():
    """Tracing and the sampler are process-global: never leak either
    (or accumulated sample counts) across tests."""
    yield
    mod_profile.stop_sampler()
    mod_profile.reset_samples()
    mod_profile._samples.clear()
    mod_trace.disable_tracing()


async def _run_claims(pool, n, queued=False):
    if not queued:
        for _ in range(n):
            hdl, conn = await pool.claim({'timeout': 1000})
            hdl.release()
        return
    done = asyncio.Event()
    count = [0]

    def make_claim():
        def cb(err, hdl=None, conn=None):
            assert err is None, err
            count[0] += 1
            hdl.release()
            if count[0] >= n:
                if not done.is_set():
                    done.set()
                return
            make_claim()
        pool.claim_cb({}, cb)

    for _ in range(min(8, n)):
        make_claim()
    await done.wait()


def _ledger_run(native, queued):
    async def t():
        mod_trace.enable_tracing(ring_size=256, sample_rate=1.0,
                                 native=native)
        pool, res = build_pool()
        await settle(pool)
        await _run_claims(pool, 50, queued=queued)
        await asyncio.sleep(0.05)
        ledgers = mod_profile.phase_ledger()
        pool.stop()
        return ledgers
    return run_async(t())


@pytest.mark.parametrize('queued', [False, True])
@pytest.mark.parametrize('native', [
    pytest.param(True, marks=pytest.mark.skipif(
        not mod_trace._NATIVE_TRACE_OK, reason='C engine not loaded')),
    False])
def test_ledger_phase_sum_and_coverage(native, queued):
    """The tentpole invariant: per claim, the named phases partition
    wall time (sum == wall up to float addition) and coverage sits at
    >= 0.95 on the fast AND the queued path, under both recorders."""
    ledgers = _ledger_run(native, queued)
    assert len(ledgers) >= 50
    for led in ledgers:
        total = sum(led['phases'].values())
        assert abs(total - led['wall_ms']) <= \
            max(1e-6, 1e-9 * led['wall_ms'])
        assert set(led['phases']) == set(mod_profile.PHASES)
        assert led['coverage'] >= 0.95, led
        assert led['outcome'] == 'released'
    summ = mod_profile.ledger_summary(ledgers)
    assert summ['claims'] == len(ledgers)
    assert summ['coverage'] >= 0.95
    # The sampler-attributed columns are present (non-null) even when
    # the sampler never ran.
    for phase in ('codel', 'runq_pump', 'fsm'):
        assert summ['phase_ms'][phase] == 0.0


def test_claim_ledger_rejects_open_and_foreign_traces():
    tr = mod_trace.Trace(None, attrs={'kind': 'dns'})
    assert mod_profile.claim_ledger(tr) is None      # still open
    tr.root.end = tr.root.start + 1.0
    assert mod_profile.claim_ledger(tr) is None      # kind != claim


def test_ledger_summary_empty():
    summ = mod_profile.ledger_summary([])
    assert summ['claims'] == 0 and summ['wall_ms'] == 0.0
    assert summ['coverage'] == 1.0


def test_reduce_profile_merges_shard_records():
    a = {'claims': 2, 'wall_ms': 10.0,
         'phase_ms': {'queue_wait': 4.0, 'lease': 6.0},
         'coverage': 1.0, 'shard': 0}
    b = {'claims': 1, 'wall_ms': 10.0,
         'phase_ms': {'queue_wait': 1.0, 'lease': 8.0},
         'coverage': 0.9, 'shard': 1}
    merged = mod_profile.reduce_profile([a, b, None])
    assert merged['n_shards'] == 2
    assert merged['claims'] == 3
    assert merged['wall_ms'] == 20.0
    assert merged['phase_ms']['queue_wait'] == 5.0
    assert merged['phase_ms']['lease'] == 14.0
    # Wall-weighted coverage: (10*1.0 + 10*0.9) / 20.
    assert abs(merged['coverage'] - 0.95) < 1e-9
    assert merged['shards'] == [a, b]


def _seeded_flamegraph(native, seed=1234):
    from cueball_tpu import netsim
    from cueball_tpu.pool import ConnectionPool
    from cueball_tpu.resolver import StaticIpResolver

    fabric = netsim.Fabric()

    async def run():
        mod_trace.enable_tracing(ring_size=64, sample_rate=1.0,
                                 native=native)
        res = StaticIpResolver({'backends': [
            {'address': '10.0.0.1', 'port': 80},
            {'address': '10.0.0.2', 'port': 80}]})
        pool = ConnectionPool({
            'domain': 'svc.sim',
            'constructor': fabric.constructor,
            'resolver': res,
            'spares': 2,
            'maximum': 4,
            'recovery': {'default': {'retries': 2, 'timeout': 500,
                                     'delay': 100, 'maxDelay': 400}},
        })
        res.start()
        while not pool.is_in_state('running'):
            await asyncio.sleep(0.05)
        # The sampler must refuse to arm under the VirtualClock: a
        # scenario's replay may not depend on host-time signals.
        assert mod_profile.start_sampler() is False
        assert 'clock' in \
            mod_profile.sampler_stats()['disabled_reason']
        for i in range(6):
            hdl, conn = await pool.claim({'timeout': 1000.0})
            await asyncio.sleep(0.005 * (i % 3 + 1))
            hdl.release()
        await asyncio.sleep(0.1)
        text = mod_profile.flamegraph()
        pool.stop()
        while not pool.is_in_state('stopped'):
            await asyncio.sleep(0.05)
        res.stop()
        mod_trace.disable_tracing()
        return text

    return netsim.run(run(), seed=seed)


@pytest.mark.skipif(not mod_trace._NATIVE_TRACE_OK,
                    reason='C engine not loaded')
def test_flamegraph_native_pure_byte_identity():
    """Acceptance: on a seeded netsim scenario the /kang/profile
    payload is byte-identical between the native and pure recorders —
    the ledger half is pure replay arithmetic and the sampler is
    auto-disabled, so no host-dependent bytes can leak in."""
    a = _seeded_flamegraph(native=True)
    b = _seeded_flamegraph(native=False)
    assert a == b
    assert a.startswith('claim;')
    for line in a.strip().splitlines():
        stack, _, weight = line.rpartition(' ')
        assert stack and int(weight) > 0


def test_sampler_lifecycle_and_stats():
    assert not mod_profile.sampler_running()
    assert mod_profile.start_sampler(interval_ms=2.0) is True
    assert mod_profile.sampler_running()
    # Idempotent while running.
    assert mod_profile.start_sampler() is True
    stats = mod_profile.sampler_stats()
    assert stats['running'] and stats['engine'] in ('native', 'pure')
    # Burn a little CPU so SIGPROF (CPU-time based) fires.
    t0 = mod_utils.wall_time()
    while mod_utils.wall_time() - t0 < 0.2:
        sum(range(500))
    assert mod_profile.stop_sampler() is True
    assert not mod_profile.sampler_running()
    assert mod_profile.stop_sampler() is False
    assert mod_profile.sampler_stats()['samples'] > 0


def test_sampler_phase_seams_bind_and_unbind():
    from cueball_tpu import connection_fsm as mod_cfsm
    from cueball_tpu import fsm as mod_fsm
    from cueball_tpu import pool as mod_pool
    from cueball_tpu import runq as mod_runq
    assert mod_profile.start_sampler() is True
    try:
        for mod in (mod_pool, mod_cfsm, mod_runq, mod_fsm):
            assert mod._prof is mod_profile
        tok = mod_profile.push_phase('codel')
        mod_profile.pop_phase(tok)
    finally:
        mod_profile.stop_sampler()
    for mod in (mod_pool, mod_cfsm, mod_runq, mod_fsm):
        assert mod._prof is None


def test_push_phase_rejects_unknown_name():
    with pytest.raises(KeyError):
        mod_profile.push_phase('not-a-phase')


def test_profile_record_filters_by_shard():
    led_local = {'shard': None, 'wall_ms': 1.0, 'coverage': 1.0,
                 'phases': {p: 0.0 for p in mod_profile.PHASES}}
    led_s0 = dict(led_local, shard=0)
    led_s1 = dict(led_local, shard=1)
    real = mod_profile.phase_ledger

    def fake_ledger(traces=None):
        return [dict(led_local), dict(led_s0), dict(led_s1)]
    mod_profile.phase_ledger = fake_ledger
    try:
        rec = mod_profile.profile_record(shard=0)
        # Unstamped (process-local) claims count for every shard;
        # other shards' claims do not.
        assert rec['claims'] == 2
        assert rec['shard'] == 0
        assert rec['sampler']['running'] is False
        rec_all = mod_profile.profile_record()
        assert rec_all['claims'] == 3 and rec_all['shard'] is None
    finally:
        mod_profile.phase_ledger = real


def test_phase_histograms_on_metrics():
    async def t():
        coll = mod_metrics.create_collector({'component': 'cueball'})
        mod_trace.enable_tracing(ring_size=64, sample_rate=1.0,
                                 collector=coll)
        pool, res = build_pool()
        await settle(pool)
        hdl, conn = await pool.claim({'timeout': 1000})
        await asyncio.sleep(0.02)
        hdl.release()
        await asyncio.sleep(0.02)
        # Force the native ring drain (scrape-time path).
        mod_trace.trace_ring()
        text = coll.collect()
        assert '# TYPE cueball_claim_phase_ms histogram' in text
        assert 'cueball_claim_phase_ms_bucket{' in text
        assert 'phase="lease",le="+Inf"' in text
        assert 'cueball_claim_phase_ms_count{' in text
        pool.stop()
    run_async(t())


def test_profile_fleet_thread_backend_and_spawn_refusal():
    from bench import _bench_fixture_pool
    from cueball_tpu.errors import CueBallError
    from cueball_tpu.shard import FleetRouter
    from test_shard_router import _stop_pool_and_router

    async def main():
        mod_trace.enable_tracing(ring_size=128, sample_rate=1.0)
        router = FleetRouter({'shards': 2, 'backend': 'thread'})
        await router.start()
        await router.create_pool('svc.prof',
                                 factory=_bench_fixture_pool)
        for _ in range(5):
            claim = await router.claim('svc.prof')
            await claim.release()
        await asyncio.sleep(0.05)
        merged = await router.profile_fleet()
        assert merged['n_shards'] >= 1
        assert merged['claims'] >= 5
        assert merged['coverage'] >= 0.95
        assert set(merged['phase_ms']) == set(mod_profile.PHASES)
        for rec in merged['shards']:
            assert rec['shard'] is not None
            assert 'sampler' in rec
        await _stop_pool_and_router(router, 'svc.prof')
    run_async(main())

    async def spawn_refuses():
        router = FleetRouter({'shards': 1, 'backend': 'spawn'})
        with pytest.raises(CueBallError):
            await router.profile_fleet()
    run_async(spawn_refuses())


def test_dump_profile_absent_then_present():
    # Nothing profiled, no tracing: the section is absent (empty
    # string), so the SIGUSR2 dump stays well-formed without it.
    assert mod_profile.dump_profile() == ''

    async def t():
        mod_trace.enable_tracing(ring_size=64, sample_rate=1.0)
        pool, res = build_pool()
        await settle(pool)
        hdl, conn = await pool.claim({'timeout': 1000})
        hdl.release()
        await asyncio.sleep(0.02)
        text = mod_profile.dump_profile()
        assert text.startswith('-- claim-path profiler --')
        assert 'ledger:' in text and 'coverage=' in text
        pool.stop()
    run_async(t())


def test_flamegraph_empty_without_data():
    assert mod_profile.flamegraph(traces=[]) == ''

"""Shared scaffolding for the randomized FSM soaks
(test_soak.py / test_soak_cset.py): backend topology churn and
connection-fate injection over the DummyConnection protocol."""


class TopoChaos:
    """Drives a DummyInner resolver's backend set and picks connection
    fates. One instance per scenario; all randomness via the seeded rng
    so failures reproduce."""

    def __init__(self, rng, ctx, inner, max_backends=4):
        self.rng = rng
        self.ctx = ctx
        self.inner = inner
        self.max_backends = max_backends
        self.live = []
        self._counter = 0

    # -- topology --------------------------------------------------------

    def add_backend(self):
        if len(self.live) >= self.max_backends:
            return
        self._counter += 1
        k = 'b%d' % self._counter
        self.live.append(k)
        self.inner.emit('added', k, {})

    def remove_backend(self):
        if len(self.live) > 1:
            self.inner.emit(
                'removed', self.live.pop(
                    self.rng.randrange(len(self.live))))

    # -- connection fates ------------------------------------------------

    def connectable(self):
        return [c for c in self.ctx.connections
                if not c.connected and not c.dead]

    def connected(self):
        return [c for c in self.ctx.connections if c.connected]

    def connect_random(self):
        conns = self.connectable()
        if conns:
            self.rng.choice(conns).connect()

    def error_random(self, tag):
        conns = self.connected()
        if conns:
            self.rng.choice(conns).emit(
                'error', RuntimeError('soak-%s' % tag))

    def close_random(self):
        conns = self.connected()
        if conns:
            c = self.rng.choice(conns)
            # The DummyConnection close protocol: mark disconnected
            # before emitting so a subsequent reconnect is legal.
            c.connected = False
            c.emit('close')

    def connect_stragglers(self):
        for c in self.connectable():
            c.connect()

"""Claim-path span tracing (trace.py) and the canonical metric surface
(metrics.py): the end-to-end acceptance test drives a real pool claim
and asserts the SAME trace is visible on all three export surfaces —
GET /kang/traces (OTLP-field NDJSON), the SIGUSR2 dump, and /metrics
histograms + per-pool gauges — plus unit coverage for sampling, the
ring bound, CoDel shed accounting, DNS spans, exposition-format
escaping and metric-type-mismatch errors."""

import asyncio
import json
import re

import pytest

import cueball_tpu as cb
from cueball_tpu import metrics as mod_metrics
from cueball_tpu import trace as mod_trace
from cueball_tpu.http_server import serve_monitor

from conftest import run_async
from test_debug import build_pool, settle
from test_monitor import _get


@pytest.fixture(autouse=True)
def _tracing_off():
    """Tracing is process-global state: never leak it across tests."""
    yield
    mod_trace.disable_tracing()


class DummyPool:
    p_uuid = 'pool-uuid'
    p_domain = 'dummy.example'


class DummyHandle:
    ch_trace = None
    ch_started = None


def test_claim_trace_end_to_end():
    async def t():
        coll = mod_metrics.create_collector({'component': 'cueball'})
        mod_trace.enable_tracing(ring_size=64, sample_rate=1.0,
                                 collector=coll)
        pool, res = build_pool()
        await settle(pool)
        server = await serve_monitor(collector=coll)
        port = server.sockets[0].getsockname()[1]

        hdl, conn = await pool.claim({'timeout': 1000})
        await asyncio.sleep(0.02)    # hold the lease a measurable time
        hdl.release()
        await asyncio.sleep(0.02)

        # (1) The ring holds the completed ClaimTrace with every span
        # of the claim's life.
        claims = [tr for tr in cb.trace_ring()
                  if tr.root.name == 'claim']
        assert claims
        tr = claims[-1]
        assert tr.root.attrs['outcome'] == 'released'
        assert tr.root.attrs['domain'] == 'debug.test'
        names = [s.name for s in tr.spans]
        for want in ('claim', 'queue_wait', 'slot_select', 'connect',
                     'handshake', 'lease', 'release'):
            assert want in names, names
        assert tr.span_totals()['lease'] >= 10.0

        # (2) GET /kang/traces serves the ring as NDJSON with
        # OTLP-compatible field names.
        status, text = await _get(port, '/kang/traces')
        assert status == 200
        spans = [json.loads(line) for line in text.splitlines()]
        assert spans
        for s in spans:
            assert set(s) == {'trace_id', 'span_id', 'parent_span_id',
                              'name', 'start', 'end', 'attrs'}
        assert re.fullmatch(r'[0-9a-f]{32}', tr.trace_id)
        mine = [s for s in spans if s['trace_id'] == tr.trace_id]
        roots = [s for s in mine if s['parent_span_id'] is None]
        assert len(roots) == 1 and roots[0]['name'] == 'claim'
        children = {s['name'] for s in mine
                    if s['parent_span_id'] == roots[0]['span_id']}
        assert {'queue_wait', 'handshake', 'lease'} <= children

        # (3) The SIGUSR2 dump folds in the slowest claims.
        report = cb.dump_fsm_histories()
        assert '-- claim traces' in report
        assert tr.trace_id[:8] in report

        # (4) /metrics carries nonzero histogram observations and the
        # per-pool gauges (refreshed by the scrape-time hook).
        status, text = await _get(port, '/metrics')
        assert status == 200
        assert '# TYPE cueball_claim_wait_ms histogram' in text
        for name in ('cueball_claim_wait_ms', 'cueball_connect_ms',
                     'cueball_handshake_ms', 'cueball_lease_held_ms'):
            m = re.search(r'%s_count(?:{[^}]*})? (\d+)' % name, text)
            assert m and int(m.group(1)) >= 1, name
        m = re.search(r'cueball_open_slots{[^}]*pool="%s"[^}]*} (\d+)'
                      % pool.p_uuid, text)
        assert m and int(m.group(1)) >= 1
        assert 'cueball_queue_depth{' in text
        assert 'cueball_idle_slots{' in text

        # (5) The kang snapshot summarizes the ring.
        status, snap = await _get(port, '/kang/snapshot')
        assert snap['traces']['enabled'] is True
        assert snap['traces']['ring'] >= 1
        assert snap['traces']['sampled'] >= 1

        server.close()
        pool.stop()
    run_async(t())


def test_sampling_zero_records_nothing():
    rt = mod_trace.enable_tracing(ring_size=4, sample_rate=0.0)
    h = DummyHandle()
    rt.claim_begin(h, DummyPool())
    assert h.ch_trace is None
    assert rt.tr_seen == 1 and rt.tr_sampled == 0
    assert mod_trace.export_ndjson() == ''
    s = mod_trace.summary()
    assert s['enabled'] is True
    assert s['seen'] == 1 and s['sampled'] == 0 and s['ring'] == 0


def test_ring_is_bounded_oldest_dropped():
    rt = mod_trace.enable_tracing(ring_size=4, sample_rate=1.0)
    ids = []
    for _ in range(7):
        tr = mod_trace.ClaimTrace(rt, DummyPool())
        tr.claimed()
        tr.released('release')
        ids.append(tr.trace_id)
    ring = mod_trace.trace_ring()
    assert len(ring) == 4
    assert [tr.trace_id for tr in ring] == ids[-4:]


def test_bad_knobs_rejected():
    with pytest.raises(ValueError):
        mod_trace._TraceRuntime(ring_size=0)
    with pytest.raises(ValueError):
        mod_trace._TraceRuntime(sample_rate=1.5)
    with pytest.raises(ValueError):
        mod_trace._TraceRuntime(sample_rate=-0.1)


def test_disabled_surfaces_are_empty():
    mod_trace.disable_tracing()
    assert not mod_trace.tracing_enabled()
    assert mod_trace.trace_ring() == []
    assert mod_trace.export_ndjson() == ''
    assert mod_trace.dump_traces() == ''
    assert mod_trace.summary() == {'enabled': False}
    assert mod_trace.active_collector() is None


def test_ndjson_structure_and_idempotent_finish():
    rt = mod_trace.enable_tracing(ring_size=8)
    tr = mod_trace.ClaimTrace(rt, DummyPool())
    tr.claiming(object())      # slot without a socket manager: fine
    tr.claimed()
    tr.released('close')
    tr.released('release')     # terminal states can chain: first wins
    assert tr.root.attrs['outcome'] == 'closed'
    assert len(mod_trace.trace_ring()) == 1
    out = mod_trace.export_ndjson()
    assert out.endswith('\n')
    spans = [json.loads(line) for line in out.splitlines()]
    root = spans[0]
    assert root['parent_span_id'] is None
    assert re.fullmatch(r'[0-9a-f]{32}', root['trace_id'])
    assert re.fullmatch(r'[0-9a-f]{16}', root['span_id'])
    for s in spans[1:]:
        assert s['trace_id'] == root['trace_id']
        assert s['parent_span_id'] == root['span_id']
        assert s['end'] >= s['start']


def test_codel_paced_shed_counted_and_traced():
    """White-box pacer drive: put the pacer in established shave mode
    with a live dequeue clock and a far-over-target head waiter, then
    run one pacer tick — the shed must increment
    cueball_codel_shed_total{reason="paced"} and stamp the waiter's
    trace with the decision."""
    async def t():
        from cueball_tpu.utils import current_millis
        coll = mod_metrics.create_collector()
        mod_trace.enable_tracing(collector=coll)
        pool, res = build_pool(targetClaimDelay=40, spares=1, maximum=1)
        await settle(pool)
        hdl, conn = await pool.claim()   # occupy the only slot
        shed = []
        pool.claim_cb({}, lambda err, h=None, c=None: shed.append(err))
        await asyncio.sleep(0.01)
        assert len(pool.p_waiters) == 1
        waiter = pool.p_waiters.peek()
        assert waiter.ch_trace is not None
        now = current_millis()
        waiter.ch_started = now - 500
        pool.p_last_dequeue = now - 5       # service looks live
        pool.p_pace_above_since = now - 200  # over target > interval
        pool.p_pace_shaving = True
        pool._codel_pace()
        await asyncio.sleep(0.02)
        assert shed and shed[0] is not None
        c = coll.counter(mod_trace.SHED_COUNTER)
        assert c.value({'reason': 'paced'}) == 1
        events = [s for tr in cb.trace_ring() for s in tr.spans
                  if s.name == 'codel']
        assert any(s.attrs['decision'] == 'shed-paced' for s in events)
        hdl.release()
        pool.stop()
    run_async(t())


def test_dns_resolver_traces_lookups():
    async def t():
        import sys
        sys.path.insert(0, 'tests')
        from fake_dns import FakeDnsClient
        from cueball_tpu import dns_resolver as mod_dns
        from conftest import wait_for_state
        coll = mod_metrics.create_collector()
        mod_trace.enable_tracing(collector=coll)
        orig = mod_dns.have_global_v6
        mod_dns.have_global_v6 = lambda: False
        try:
            res = cb.DNSResolver({
                'domain': 'a.ok', 'service': '_foo._tcp',
                'resolvers': ['1.2.3.4'],
                'recovery': {'default': {'timeout': 1000, 'retries': 2,
                                         'delay': 100}},
                'dnsClient': FakeDnsClient()})
            res.start()
            await wait_for_state(res, 'running')
            lookups = [tr for tr in cb.trace_ring()
                       if tr.root.name == 'dns_lookup']
            assert lookups
            assert any(tr.root.attrs.get('outcome') == 'ok'
                       for tr in lookups)
            assert {'kind', 'domain', 'type'} <= set(lookups[0].root.attrs)
            assert coll.histogram('cueball_dns_lookup_ms').count() >= 1
            res.stop()
            await wait_for_state(res, 'stopped')
        finally:
            mod_dns.have_global_v6 = orig
    run_async(t())


def test_dns_client_per_resolver_query_spans(monkeypatch):
    """Each resolver attempt inside DnsClient becomes one 'dns_query'
    child span carrying the attempt's outcome (ok / exception name)."""
    async def t():
        from cueball_tpu import dns_client as mod_dc
        rt = mod_trace.enable_tracing()
        tr = mod_trace.DnsTrace(rt, 'x.example', 'A')

        async def fake_wire(self, resolver, domain, qtype, timeout_s):
            if resolver == 'bad':
                raise mod_dc.DnsTimeoutError(domain)
            await asyncio.sleep(0.01)
            return mod_dc.DnsMessage(1, 'NOERROR', False, [
                {'name': domain, 'type': 'A', 'ttl': 60,
                 'target': '1.2.3.4', 'port': None}], [], [])

        monkeypatch.setattr(mod_dc.DnsClient, '_query_wire', fake_wire)
        client = mod_dc.DnsClient(concurrency=2)
        done = asyncio.Event()
        out = []

        def cb_(err, msg):
            out.append((err, msg))
            done.set()

        client.lookup({'domain': 'x.example', 'type': 'A',
                       'resolvers': ['bad', 'good'], 'timeout': 1000,
                       'trace': tr}, cb_)
        await done.wait()
        tr.done('ok')
        assert out[0][0] is None
        spans = {s.attrs['resolver']: s for s in tr.spans
                 if s.name == 'dns_query'}
        assert set(spans) == {'bad', 'good'}
        assert spans['good'].attrs['outcome'] == 'ok'
        assert spans['bad'].attrs['outcome'] == 'DnsTimeoutError'
        assert all(s.end is not None for s in spans.values())
    run_async(t())


def test_disable_tracing_detaches_gauge_rows():
    async def t():
        coll = mod_metrics.create_collector()
        mod_trace.enable_tracing(collector=coll)
        pool, res = build_pool()
        await settle(pool)
        text = coll.collect()    # first scrape attaches the row
        assert 'pool="%s"' % pool.p_uuid in text
        rt = mod_trace._runtime
        row = rt.tr_rows[pool.p_uuid]
        assert row in pool.p_telemetry
        mod_trace.disable_tracing()
        assert row not in pool.p_telemetry
        # The rows' samples are dropped too: a later scrape of the same
        # collector must not keep exporting the dead pool's gauges.
        assert 'pool="%s"' % pool.p_uuid not in coll.collect()
        pool.stop()
    run_async(t())


# -- metrics.py exposition-format units ------------------------------------


def test_label_values_escaped_per_text_format():
    c = mod_metrics.Counter('evil', help='h')
    c.increment({'msg': 'a"b\\c\nd'})
    text = c.serialize()
    assert 'msg="a\\"b\\\\c\\nd"' in text


def test_empty_label_set_renders_without_braces():
    g = mod_metrics.Gauge('plain', help='h')
    g.set(3)
    lines = g.serialize().splitlines()
    assert 'plain 3' in lines
    assert all('{}' not in line for line in lines)


def test_metric_type_mismatch_raises_typeerror():
    coll = mod_metrics.create_collector()
    c = coll.counter('x', help='h')
    assert coll.counter('x') is c       # same-type re-declare: idempotent
    with pytest.raises(TypeError, match='already registered'):
        coll.gauge('x')
    with pytest.raises(TypeError, match='histogram'):
        coll.histogram('x')
    coll.gauge('y')
    with pytest.raises(TypeError, match='gauge'):
        coll.counter('y')
    coll.histogram('z')
    with pytest.raises(TypeError, match='already registered'):
        coll.gauge('z')


def test_histogram_exposition_format():
    h = mod_metrics.Histogram('lat_ms', help='h', buckets=(1, 5, 10))
    h.observe(0.5)
    h.observe(4)
    h.observe(100)
    lines = h.serialize().splitlines()
    assert lines[0] == '# HELP lat_ms h'
    assert lines[1] == '# TYPE lat_ms histogram'
    assert 'lat_ms_bucket{le="1"} 1' in lines
    assert 'lat_ms_bucket{le="5"} 2' in lines
    assert 'lat_ms_bucket{le="10"} 2' in lines
    assert 'lat_ms_bucket{le="+Inf"} 3' in lines
    assert 'lat_ms_sum 104.5' in lines
    assert 'lat_ms_count 3' in lines
    assert h.count() == 3 and h.sum() == 104.5
    h.remove()
    assert h.count() == 0


# -- native/pure recorder parity (engine matrix) --------------------------
#
# The native recorder's whole contract is that lazy ring replay through
# the REAL pure trace classes produces byte-identical NDJSON to running
# those classes eagerly — same RNG draws, same span-id derivation, same
# clock reads. These tests run one seeded netsim scenario under each
# recorder and diff the full export. `make ci` runs the suite under
# both engines; the native arms skip themselves on the pure engine.


def _run_seeded_trace_scenario(native, seed=1234, claims=5,
                               ring_size=64, concurrent=False):
    """One deterministic virtual-time pool run with full-rate tracing
    under the chosen recorder; returns (ndjson, summary)."""
    from cueball_tpu import netsim
    from cueball_tpu.pool import ConnectionPool
    from cueball_tpu.resolver import StaticIpResolver

    fabric = netsim.Fabric()

    async def main():
        mod_trace.enable_tracing(ring_size=ring_size, sample_rate=1.0,
                                 native=native)
        res = StaticIpResolver({'backends': [
            {'address': '10.0.0.1', 'port': 80},
            {'address': '10.0.0.2', 'port': 80}]})
        pool = ConnectionPool({
            'domain': 'svc.sim',
            'constructor': fabric.constructor,
            'resolver': res,
            'spares': 2,
            'maximum': 4,
            'recovery': {'default': {'retries': 2, 'timeout': 500,
                                     'delay': 100, 'maxDelay': 400}},
        })
        res.start()
        while not pool.is_in_state('running'):
            await asyncio.sleep(0.05)
        loop = asyncio.get_running_loop()

        async def one(i):
            fut = loop.create_future()

            def cb(err, hdl=None, conn=None):
                if not fut.done():
                    fut.set_result((err, hdl))
            pool.claim_cb({'timeout': 1000.0}, cb)
            err, hdl = await fut
            assert err is None
            # Distinct virtual hold times so concurrent lifecycles
            # interleave their ring events rather than nesting.
            await asyncio.sleep(0.005 * (i % 4 + 1))
            hdl.release()

        if concurrent:
            await asyncio.gather(*[one(i) for i in range(claims)])
        else:
            for i in range(claims):
                await one(i)
        await asyncio.sleep(0.1)
        out = mod_trace.export_ndjson()
        summ = mod_trace.summary()
        pool.stop()
        while not pool.is_in_state('stopped'):
            await asyncio.sleep(0.05)
        res.stop()
        mod_trace.disable_tracing()
        return out, summ

    return netsim.run(main(), seed=seed)


@pytest.mark.skipif(not mod_trace._NATIVE_TRACE_OK,
                    reason='C engine not loaded')
def test_engine_matrix_ndjson_parity():
    a, sa = _run_seeded_trace_scenario(native=True)
    b, sb = _run_seeded_trace_scenario(native=False)
    assert sa['native'] is True and sb['native'] is False
    assert len(a.splitlines()) > 20
    assert a == b
    assert sa['native_ring']['dropped'] == 0
    assert sa['truncated'] == 0


@pytest.mark.skipif(not mod_trace._NATIVE_TRACE_OK,
                    reason='C engine not loaded')
def test_engine_matrix_parity_across_ring_wrap():
    # ring_size=4 traces -> a 64-slot native event ring; 30 claims at
    # ~5 events each wrap it several times. Both recorders must agree
    # on the surviving (newest) completions byte-for-byte.
    a, sa = _run_seeded_trace_scenario(native=True, claims=30,
                                       ring_size=4)
    b, _sb = _run_seeded_trace_scenario(native=False, claims=30,
                                        ring_size=4)
    assert sa['native_ring']['dropped'] > 0   # the wrap really happened
    assert a == b


@pytest.mark.skipif(not mod_trace._NATIVE_TRACE_OK,
                    reason='C engine not loaded')
def test_engine_matrix_parity_concurrent_claims():
    # 8 claims against maximum=4: half park in the wait queue, so
    # begin/slot/claiming/released events from different claims
    # interleave in the ring and the lazy replay has to demultiplex
    # them by serial.
    a, _sa = _run_seeded_trace_scenario(native=True, claims=8,
                                        concurrent=True)
    b, _sb = _run_seeded_trace_scenario(native=False, claims=8,
                                        concurrent=True)
    assert len(a.splitlines()) > 40
    assert a == b


def test_kang_traces_rejects_malformed_query():
    """Bad ?limit / ?backend inputs must come back as 400 with a JSON
    error body naming the offending value — not as a 500, not as a
    silently-empty 200 (a filter naming a backend that never existed
    is almost always an operator typo)."""
    async def t():
        mod_trace.enable_tracing(ring_size=16, sample_rate=1.0)
        pool, res = build_pool()
        await settle(pool)
        server = await serve_monitor()
        port = server.sockets[0].getsockname()[1]
        hdl, conn = await pool.claim({'timeout': 1000})
        hdl.release()
        await asyncio.sleep(0.02)

        status, body = await _get(port, '/kang/traces?limit=-1')
        assert status == 400
        assert body == {'error': 'limit must be >= 0, got -1'}
        status, body = await _get(port, '/kang/traces?limit=abc')
        assert status == 400
        assert body == {'error': "limit must be an integer, got 'abc'"}
        status, body = await _get(port, '/kang/traces?backend=no.such')
        assert status == 400
        assert body == {'error': "unknown backend 'no.such'"}
        # One bad parameter rejects even when the other is fine.
        status, body = await _get(port,
                                  '/kang/traces?limit=1&backend=no.such')
        assert status == 400 and 'unknown backend' in body['error']

        # Valid inputs (including the limit=0 edge) still serve.
        status, text = await _get(port, '/kang/traces?limit=1')
        assert status == 200 and text.strip()
        status, text = await _get(port, '/kang/traces?limit=0')
        assert status == 200 and text == ''
        claims = [tr for tr in cb.trace_ring()
                  if tr.root.attrs.get('kind') == 'claim']
        key = claims[-1].ct_backend
        status, text = await _get(
            port, '/kang/traces?backend=%s' % key)
        assert status == 200 and text.strip()

        server.close()
        pool.stop()
    run_async(t())

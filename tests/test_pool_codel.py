"""CoDel overload-shedding statistical test (scaled port of reference
test/codel.test.js:186-297): saturate a 2-connection pool with a claim
load generator and assert the average claim sojourn tracks
targetClaimDelay, with some successes AND some shed claims, and no other
failure modes."""

import asyncio

import pytest

from cueball_tpu import errors as mod_errors
from cueball_tpu.utils import current_millis

from conftest import run_async, settle, wait_for_state
from test_pool import Ctx, make_pool


HOLD_MS = 50          # claim hold time (reference: 50ms)
CLAIMS_PER_TICK = 5   # 5 claims every 10ms (reference)
TICK_MS = 10
RUN_S = 5.0           # reference run length (test/codel.test.js:251)
TOLERANCE = 175       # reference asserts avg within +/-175ms of target


async def run_load(pool):
    stats = {'ok': 0, 'timeouts': 0, 'other': 0, 'delays': []}
    pending = [0]
    drained = asyncio.Event()

    def make_claim():
        start = current_millis()
        pending[0] += 1

        def cb(err, hdl=None, conn=None):
            # The reference records EVERY resolution's sojourn, not just
            # successes (test/codel.test.js:227).
            stats['delays'].append(current_millis() - start)
            if err is None:
                stats['ok'] += 1
                loop = asyncio.get_running_loop()
                loop.call_later(HOLD_MS / 1000.0, hdl.release)
            elif isinstance(err, mod_errors.ClaimTimeoutError):
                stats['timeouts'] += 1
            else:
                stats['other'] += 1
            pending[0] -= 1
            if pending[0] == 0:
                drained.set()
        pool.claim_cb({}, cb)

    loop = asyncio.get_running_loop()
    deadline = loop.time() + RUN_S
    while loop.time() < deadline:
        for _ in range(CLAIMS_PER_TICK):
            make_claim()
        await asyncio.sleep(TICK_MS / 1000.0)
    # Wait for the queue to fully drain (reference uses a vasync
    # barrier keyed on every claim, test/codel.test.js:225-256).
    await drained.wait()
    return stats


def _run_target(target):
    async def t():
        ctx = Ctx()
        pool, inner = make_pool(ctx, spares=2, maximum=2,
                                targetClaimDelay=target)
        inner.emit('added', 'b1', {})
        await settle()
        for c in list(ctx.connections):
            c.connect()
        await settle()
        assert pool.is_in_state('running')

        stats = await run_load(pool)

        assert stats['ok'] > 0, 'expected some successful claims'
        assert stats['timeouts'] > 0, 'expected some shed claims'
        assert stats['other'] == 0, 'unexpected failure modes'
        avg = sum(stats['delays']) / len(stats['delays'])
        assert abs(avg - target) < TOLERANCE, (
            'avg claim delay %.1fms not within %dms of target %dms '
            '(ok=%d shed=%d)' % (avg, TOLERANCE, target, stats['ok'],
                                 stats['timeouts']))
        # The continuous-evaluation pacer must have engaged under this
        # sustained overload (it is what keeps the tracking tight).
        assert pool.get_stats()['counters'].get('codel-paced-drop', 0) > 0
        pool.stop()
        await wait_for_state(pool, 'stopped')
    # The 5000 ms target needs ~13 s (5 s load + sheds pace the drain).
    run_async(t(), timeout=60)


# The FULL reference envelope: all seven targets asserted in-suite,
# exactly as reference test/codel.test.js:285-297 does. The
# mean-tracking pacer compensation (pool._pace_comp) is what holds
# the long targets: without it the 5000 ms target undershoots by
# ~-240 ms (ramp-up claims resolve below target structurally) and
# fails the reference's own +/-175 ms assertion.
@pytest.mark.parametrize('target',
                         [300, 500, 1000, 1500, 2000, 2500, 5000])
def test_codel_tracks_target(target):
    _run_target(target)


def test_pace_deficit_clamped_to_queue_worth():
    """A healthy-but-never-empty stretch must not bank an unbounded
    deficit: _pace_account clamps at +/- target * (queue_len + 1), so
    the next real overload's shed threshold starts at most one
    queue-repayment above target (pool._pace_account)."""
    async def t():
        ctx = Ctx()
        pool, inner = make_pool(ctx, spares=2, maximum=2,
                                targetClaimDelay=300)
        # Simulate 30 minutes of below-target resolutions with an
        # empty-but-armed queue: the deficit stays pinned at one
        # queue's worth, not -9000 * 300ms.
        for _ in range(9000):
            pool._pace_account(-290.0)
        assert pool.p_pace_sum_err == -300.0 * (len(pool.p_waiters) + 1)
        comp = pool._pace_comp()
        assert comp == 0.0          # no waiters -> no compensation
        for _ in range(9000):
            pool._pace_account(290.0)
        assert pool.p_pace_sum_err == 300.0 * (len(pool.p_waiters) + 1)
        pool.stop()
        await settle()
    run_async(t())


def test_timeout_option_forbidden_with_codel():
    async def t():
        ctx = Ctx()
        pool, inner = make_pool(ctx, targetClaimDelay=300)
        try:
            pool.claim_cb({'timeout': 100}, lambda *a: None)
            raise AssertionError('expected RuntimeError')
        except RuntimeError as e:
            assert 'not allowed' in str(e)
        pool.stop()
        await settle()
    run_async(t())


def test_codel_implicit_high_timeout():
    """Reference 'implicit high timeout' (test/codel.test.js:114-181):
    with targetClaimDelay set and no explicit claim timeout, a claim
    against a pool whose connections never finished connecting times
    out at CoDel's maxIdle (10x target); once connections are up the
    pool is immediately usable."""
    async def t():
        target = 100
        ctx = Ctx()
        pool, inner = make_pool(ctx, spares=2, maximum=2,
                                retries=1, timeout=target * 11,
                                targetClaimDelay=target)
        inner.emit('added', 'b1', {})
        await settle()
        assert len(ctx.connections) == 2
        assert all(c.backend == 'b1' for c in ctx.connections)

        # Connections exist but never emitted 'connect'.
        t0 = current_millis()
        err = None
        try:
            await pool.claim()
        except mod_errors.ClaimTimeoutError as e:
            err = e
        waited = current_millis() - t0
        assert err is not None and 'timed out' in str(err).lower()
        # maxIdle = 10x target in a healthy (never-overloaded) pool.
        assert target * 8 <= waited <= target * 14

        for c in list(ctx.connections):
            assert c.refd
            c.connect()
        await settle()
        hdl, conn = await pool.claim()
        assert conn is not None
        hdl.release()
        pool.stop()
        await wait_for_state(pool, 'stopped')
    run_async(t())


def test_pacer_disarms_and_purges_on_stalled_pool():
    """A stalled pool (connections never connect) must not busy-tick
    the pacer forever nor pin timed-out claim handles in the wait
    queue; shedding is left to the reference's getMaxIdle bound."""
    async def t():
        target = 100
        ctx = Ctx()
        pool, inner = make_pool(ctx, spares=2, maximum=2, retries=1,
                                timeout=target * 12,
                                targetClaimDelay=target)
        inner.emit('added', 'b1', {})
        await settle()
        errs = []
        for _ in range(5):
            pool.claim_cb({}, lambda err, h=None, c=None:
                          errs.append(err))
        # Claims resolve at maxIdle (10x target), far above target: the
        # pacer must not have shed them early.
        await asyncio.sleep(target * 10 / 1000.0 + 0.5)
        assert len(errs) == 5
        assert all(isinstance(e, mod_errors.ClaimTimeoutError)
                   for e in errs)
        assert pool.get_stats()['counters'].get('codel-paced-drop',
                                                0) == 0
        # Resolved handles were unlinked from the wait queue and the
        # pacer disarmed despite no dequeue ever happening.
        assert len(pool.p_waiters) == 0
        assert pool.p_codel_pacer is None
        pool.stop()
        await wait_for_state(pool, 'stopped')
    run_async(t())

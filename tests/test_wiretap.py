"""Transport wire ledger (cueball_tpu/wiretap.py): seam registry,
enable/disable lifecycle, connect decomposition arithmetic (clamping,
exact-sum identity, breakdown retention), the loop-lag sampler
(refusal under a non-system clock, collection on a real loop), metrics
publication + merge_expositions folding, the fleet merge shapes, the
SIGUSR2 dump section, and the FleetSampler loop_lag_p99_us column."""

import asyncio

import pytest

from cueball_tpu import metrics as mod_metrics
from cueball_tpu import profile as mod_profile
from cueball_tpu import trace as mod_trace
from cueball_tpu import transport as mod_transport
from cueball_tpu import utils as mod_utils
from cueball_tpu import wiretap as mod_wiretap
from cueball_tpu.pool import ConnectionPool
from cueball_tpu.resolver import StaticIpResolver

from conftest import run_async


@pytest.fixture(autouse=True)
def _clean_wiretap():
    yield
    mod_wiretap.disable_wiretap()
    mod_wiretap.stop_loop_lag_sampler()
    mod_wiretap._lag_samplers.clear()
    mod_wiretap._lag_disabled_reason = None


# ---------------------------------------------------------------------------
# Registry and lifecycle

def test_seams_mirror_transport_seam_methods():
    # The cross-module contract cbflow A006 pins statically, asserted
    # at runtime too: same names, same order irrelevant, and every
    # seam is a real method on the Transport base class.
    assert set(mod_wiretap.SEAMS) == set(mod_transport.SEAM_METHODS)
    for seam in mod_wiretap.SEAMS:
        assert callable(getattr(mod_transport.Transport, seam))


def test_enable_disable_lifecycle():
    assert not mod_wiretap.wiretap_enabled()
    assert mod_wiretap.seam_stats('asyncio', 'connector') is None
    led = mod_wiretap.enable_wiretap()
    assert mod_wiretap.enable_wiretap() is led       # idempotent
    assert mod_wiretap.wiretap_enabled()
    st = mod_wiretap.seam_stats('asyncio', 'connector')
    assert st is mod_wiretap.seam_stats('asyncio', 'connector')
    assert mod_wiretap.disable_wiretap() is True
    assert mod_wiretap.disable_wiretap() is False
    assert mod_wiretap.seam_stats('asyncio', 'connector') is None


def test_unknown_seam_rejected():
    led = mod_wiretap.enable_wiretap()
    with pytest.raises(ValueError):
        led.seam('asyncio', 'sendfile')


def test_snapshot_shape():
    led = mod_wiretap.enable_wiretap()
    st = led.seam('fabric', 'dns_udp')
    st.events += 2
    st.bytes_out += 64
    snap = mod_wiretap.snapshot()
    assert snap == {'fabric': {'dns_udp': st.as_dict()}}
    assert snap['fabric']['dns_udp']['events'] == 2
    assert set(st.as_dict()) == set(mod_wiretap.SeamStats.__slots__)
    assert set(mod_wiretap.PARITY_FIELDS) < set(st.as_dict())


# ---------------------------------------------------------------------------
# Connect decomposition

def test_record_connect_splits_span_by_marks():
    mod_wiretap.enable_wiretap()
    # start=100, ready=106, dispatched=108, end=110.
    mod_wiretap.record_connect('asyncio', 100.0, 110.0, (106.0, 108.0))
    tot = mod_wiretap.wire_totals()['asyncio']
    assert tot == {'kernel_wait': 6.0, 'loop_dispatch': 2.0,
                   'proto_parse': 2.0}
    assert mod_wiretap.connect_breakdown(100.0, 110.0) \
        == (6.0, 2.0, 2.0)
    # Unknown span -> None.
    assert mod_wiretap.connect_breakdown(1.0, 2.0) is None


def test_record_connect_clamps_marks_into_span():
    mod_wiretap.enable_wiretap()
    # Marks outside [start, end] (clock skew between the protocol
    # stamp and the FSM span) clamp rather than going negative.
    mod_wiretap.record_connect('asyncio', 100.0, 110.0, (90.0, 200.0))
    tot = mod_wiretap.wire_totals()['asyncio']
    assert tot['kernel_wait'] == 0.0
    assert tot['loop_dispatch'] == 10.0
    assert tot['proto_parse'] == 0.0
    assert sum(tot.values()) == 10.0


def test_record_connect_without_marks_is_all_kernel():
    mod_wiretap.enable_wiretap()
    mod_wiretap.record_connect('fabric', 50.0, 57.5, None)
    assert mod_wiretap.wire_totals()['fabric'] \
        == {'kernel_wait': 7.5, 'loop_dispatch': 0.0,
            'proto_parse': 0.0}


def test_wire_wait_accumulates_kernel_only():
    mod_wiretap.enable_wiretap()
    mod_wiretap.wire_wait('fabric', 12.5)
    mod_wiretap.wire_wait('fabric', 0.0)       # no-op
    mod_wiretap.wire_wait('fabric', -1.0)      # no-op
    assert mod_wiretap.wire_totals()['fabric']['kernel_wait'] == 12.5


def test_breakdown_retention_evicts_oldest(monkeypatch):
    monkeypatch.setattr(mod_wiretap, '_BREAKDOWN_CAP', 3)
    mod_wiretap.enable_wiretap()
    for i in range(5):
        mod_wiretap.record_connect('asyncio', float(i), float(i) + 1.0,
                                   None)
    assert mod_wiretap.connect_breakdown(0.0, 1.0) is None
    assert mod_wiretap.connect_breakdown(1.0, 2.0) is None
    for i in (2, 3, 4):
        assert mod_wiretap.connect_breakdown(float(i), float(i) + 1.0) \
            == (1.0, 0.0, 0.0)


def test_disabled_forwarders_are_noops():
    mod_wiretap.record_connect('asyncio', 0.0, 1.0, None)
    mod_wiretap.wire_wait('asyncio', 5.0)
    assert mod_wiretap.connect_breakdown(0.0, 1.0) is None
    assert mod_wiretap.snapshot() == {}
    assert mod_wiretap.wire_totals() == {}


# ---------------------------------------------------------------------------
# watch() and instrument_writer()

class _FakeEmitter:
    def __init__(self):
        self.listeners = {}

    def on(self, event, fn):
        self.listeners.setdefault(event, []).append(fn)
        return fn

    def emit(self, event, *args):
        for fn in list(self.listeners.get(event, [])):
            fn(*args)


def test_watch_counts_outcomes_with_internal_listeners():
    led = mod_wiretap.enable_wiretap()
    st = led.seam('asyncio', 'connector')
    conn = _FakeEmitter()
    mod_wiretap.watch(st, conn)
    # Framework-internal marking: the claim-handle leak detector and
    # the listener mutation epoch must ignore these.
    for fns in conn.listeners.values():
        assert all(getattr(fn, '_cueball_internal', False)
                   for fn in fns)
    conn.emit('connect')
    conn.emit('error', RuntimeError('x'))
    conn.emit('close')
    conn.emit('close')
    assert (st.connects, st.errors, st.closes) == (1, 1, 2)


class _FakeTransport:
    def __init__(self):
        self.depth = 0

    def get_write_buffer_size(self):
        return self.depth


class _FakeWriter:
    def __init__(self):
        self.transport = _FakeTransport()
        self.chunks = []

    def write(self, data):
        self.chunks.append(data)
        self.transport.depth += len(data)


def test_instrument_writer_counts_and_highwater():
    led = mod_wiretap.enable_wiretap()
    st = led.seam('asyncio', 'connector')
    writer = _FakeWriter()
    mod_wiretap.instrument_writer(st, writer)
    writer.write(b'abcd')
    writer.write(b'ef')
    assert writer.chunks == [b'abcd', b'ef']   # bytes still flow
    assert st.writes == 2
    assert st.bytes_out == 6
    assert st.buf_highwater == 6


# ---------------------------------------------------------------------------
# Loop-lag sampler

def test_lag_sampler_refuses_non_system_clock():
    class _FrozenClock:
        def monotonic(self):
            return 0.0

        def wall(self):
            return 0.0

    old = mod_utils.set_clock(_FrozenClock())
    try:
        async def main():
            return mod_wiretap.start_loop_lag_sampler()
        assert run_async(main(), timeout=10) is False
    finally:
        mod_utils.set_clock(old)
    stats = mod_wiretap.loop_lag_stats()
    assert stats['disabled_reason'] \
        == 'non-system clock installed (netsim?)'
    assert stats['running'] is False


def test_lag_sampler_refuses_without_running_loop():
    assert mod_wiretap.start_loop_lag_sampler() is False
    assert mod_wiretap.loop_lag_stats()['disabled_reason'] \
        == 'no running event loop'


def test_lag_sampler_collects_on_real_loop():
    async def main():
        assert mod_wiretap.start_loop_lag_sampler(interval_ms=5.0)
        assert mod_wiretap.start_loop_lag_sampler()   # idempotent
        await asyncio.sleep(0.1)
        stats = mod_wiretap.loop_lag_stats()
        p99 = mod_wiretap.loop_lag_p99_us()
        assert mod_wiretap.stop_loop_lag_sampler() is True
        return stats, p99

    stats, p99 = run_async(main(), timeout=30)
    assert stats['running'] is True
    assert stats['disabled_reason'] is None
    assert stats['samples'] >= 3
    assert stats['max_us'] >= stats['p99_us'] >= stats['p50_us'] >= 0.0
    assert p99 >= 0.0
    assert mod_wiretap.stop_loop_lag_sampler() is False


def test_loop_lag_p99_zero_when_unarmed():
    assert mod_wiretap.loop_lag_p99_us() == 0.0


# ---------------------------------------------------------------------------
# Metrics publication

def test_metrics_publish_and_merge():
    coll = mod_metrics.create_collector()
    led = mod_wiretap.enable_wiretap(collector=coll)
    st = led.seam('asyncio', 'connector')
    st.events += 3
    st.bytes_in += 10
    st.bytes_out += 20
    mod_wiretap.record_connect('asyncio', 0.0, 4.0, (1.0, 3.0))
    text = coll.collect()
    assert 'cueball_transport_events{seam="connector",' \
           'transport="asyncio"} 3' in text
    assert 'direction="in"' in text and 'direction="out"' in text
    assert 'cueball_transport_dispatch_lag_ms_count' in text
    # Fleet scrape: two children's payloads fold — histogram counts
    # sum, gauge rows concatenate without duplicate family headers.
    merged = mod_metrics.merge_expositions([text, text])
    assert merged.count('# TYPE cueball_transport_dispatch_lag_ms') == 1
    for line in merged.splitlines():
        if line.startswith('cueball_transport_dispatch_lag_ms_count'):
            assert line.rsplit(' ', 1)[1] == '2'
    # Disable unhooks the publisher: a fresh scrape stops refreshing.
    mod_wiretap.disable_wiretap()
    assert led._publish not in coll._hooks


# ---------------------------------------------------------------------------
# claim_ledger decomposition on a real asyncio loopback pool

def test_claim_ledger_decomposes_socket_wait_on_real_pool():
    mod_wiretap.enable_wiretap()
    mod_trace.enable_tracing(ring_size=64, sample_rate=1.0)
    try:
        async def main():
            server = await asyncio.start_server(
                lambda r, w: None, '127.0.0.1', 0)
            res = StaticIpResolver({'backends': [{
                'address': '127.0.0.1',
                'port': server.sockets[0].getsockname()[1]}]})
            pool = ConnectionPool({
                'domain': 'wiretap.test',
                'transport': 'asyncio',
                'resolver': res,
                'spares': 1,
                'maximum': 1,
                'recovery': {'default': {
                    'retries': 1, 'timeout': 2000, 'delay': 10,
                    'maxDelay': 50, 'delaySpread': 0}},
            })
            res.start()
            fut = asyncio.get_running_loop().create_future()
            pool.claim_cb({'timeout': 30000.0},
                          lambda e, h=None, c=None:
                          fut.done() or fut.set_result((e, h)))
            err, hdl = await fut
            assert err is None
            hdl.release()
            pool.stop()
            while not pool.is_in_state('stopped'):
                await asyncio.sleep(0.005)
            res.stop()
            await asyncio.sleep(0.05)

        run_async(main(), timeout=30)
        ledgers = mod_profile.phase_ledger()
    finally:
        mod_trace.disable_tracing()
        mod_wiretap.disable_wiretap()
    assert ledgers
    # The cold-pool claim waited out the slot's connect: its
    # socket_wait is decomposed from real wire marks, exactly.
    decomposed = [led for led in ledgers if led['wire_decomposed']]
    assert decomposed, ledgers
    for led in ledgers:
        assert set(led['wire']) == set(mod_wiretap.SUB_PHASES)
        assert sum(led['wire'].values()) \
            == led['phases']['socket_wait'], led
        assert all(v >= 0.0 for v in led['wire'].values())
    summary = mod_profile.ledger_summary(ledgers)
    assert summary['wire_claims'] == len(decomposed)
    assert summary['wire_ms']['kernel_wait'] >= 0.0


# ---------------------------------------------------------------------------
# Fleet merge + dump

def test_wiretap_record_and_reduce_shapes():
    mod_wiretap.enable_wiretap()
    rec = mod_wiretap.wiretap_record(shard=3)
    assert rec['shard'] == 3 and rec['enabled'] is True
    assert 'p99_us' in rec['loop_lag']
    rec2 = dict(rec, shard=4)
    rec2['loop_lag'] = dict(rec['loop_lag'], p99_us=120.0, samples=7)
    out = mod_wiretap.reduce_wiretap([rec, rec2, None])
    assert out['n_shards'] == 2
    assert out['loop_lag_p99_us'] == 120.0
    assert out['loop_lag_samples'] == rec['loop_lag']['samples'] + 7
    assert out['shards'] == [rec, rec2]
    assert out['transports'] == mod_wiretap.snapshot()


def test_reduce_wiretap_empty():
    out = mod_wiretap.reduce_wiretap([])
    assert out['n_shards'] == 0
    assert out['loop_lag_p99_us'] == 0.0


def test_dump_wiretap_absent_then_sectioned():
    assert mod_wiretap.dump_wiretap() == ''
    led = mod_wiretap.enable_wiretap()
    st = led.seam('fabric', 'connector')
    st.events += 1
    st.connects += 1
    mod_wiretap.wire_wait('fabric', 3.25)
    text = mod_wiretap.dump_wiretap()
    assert text.startswith('-- transport wire ledger --')
    assert 'wiretap: enabled' in text
    assert 'fabric/connector: events=1 connects=1' in text
    assert 'wire fabric: kernel_wait=3.2ms' in text


# ---------------------------------------------------------------------------
# FleetSampler column

def test_fleet_gauges_include_loop_lag_column():
    sampler = pytest.importorskip('cueball_tpu.parallel.sampler')
    assert 'loop_lag_p99_us' in sampler._FLEET_GAUGES


def test_reduce_fleet_takes_worst_shard_loop_lag():
    sampler = pytest.importorskip('cueball_tpu.parallel.sampler')
    base = {name: 0.0 for name in sampler._FLEET_GAUGES}
    a = dict(base, n_pools=2.0, loop_lag_p99_us=50.0)
    b = dict(base, n_pools=1.0, loop_lag_p99_us=900.0)
    out = sampler.reduce_fleet([a, b])
    # Worst shard wins: a fleet-weighted mean would bury the one
    # saturated loop (2/3 weight on the healthy shard).
    assert out['loop_lag_p99_us'] == 900.0
    assert out['n_pools'] == 3.0

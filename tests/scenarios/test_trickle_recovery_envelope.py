"""Fabric-vs-native recovery envelope for the trickle-handshake fault.

The parity suite proves the fabric and native transports agree
byte-for-byte on FSM traces over a healthy soak; this scenario asserts
they agree on the *recovery envelope* of a fault. The fault is the
trickle-handshake middlebox: the backend answers, but dribbles the
claim-time bytes out segment by segment, then heals.

The two arms cannot share a clock — netsim runs virtual time, the
native data plane runs a real epoll/io_uring thread against real
loopback sockets — so the comparison is envelope-level, not
trace-level: each arm reduces its run to the same ordered tuple of
observables (pool states seen at op boundaries, per-op outcome and
stalled/fast classification, ops needed after the heal before the
first fast op), and the envelopes must be EQUAL. On the fabric arm
the dribble rides the LinkModel trickle through SimConnection's
cb_claim_ready probe; on the native arm a loopback echo server
dribbles its response in the same segment schedule, stalling the
claim's echo roundtrip instead (real transports expose no claim-time
probe — the bytes stall in the C plane's read path). Same fault
shape, same envelope, different layer: that equivalence is exactly
what the scenario pins.
"""

import asyncio

from cueball_tpu import netsim
from cueball_tpu.pool import ConnectionPool
from cueball_tpu.resolver import StaticIpResolver
from cueball_tpu.transport import FabricTransport, get_transport

import pytest

import scenario_common as sco

SEGMENTS = 4
TRICKLE_MS = 25.0
STALL_MS = SEGMENTS * TRICKLE_MS
FAULT_OPS = 4
HEAL_OPS = 4
REQ_BYTES = 32


def _classify(dur_ms):
    """Envelope bucket for one op. The gap between the buckets is
    deliberate: an op landing in neither (stall half-eaten) breaks
    envelope equality loudly instead of rounding either way."""
    if dur_ms >= STALL_MS - 1.0:
        return 'stalled'
    if dur_ms < STALL_MS / 2.0:
        return 'fast'
    return 'ambiguous(%.1fms)' % dur_ms


def _envelope(op_log, states):
    ops = tuple(op_log)
    healed = ops[FAULT_OPS:]
    to_recover = 0
    for _outcome, speed in healed:
        if speed == 'fast':
            break
        to_recover += 1
    return {'pool_states': tuple(sorted(set(states))),
            'ops': ops, 'ops_to_recover': to_recover}


def _fabric_envelope(seed):
    """The virtual-time arm: LinkModel trickle on the claim-readiness
    probe, toggled off for the heal ops."""
    fabric = netsim.Fabric()
    sc = netsim.Scenario('trickle-recovery-envelope', seed=seed)
    result = {}

    async def main():
        loop = asyncio.get_running_loop()
        backends = [{'address': '10.0.0.1', 'port': 80}]
        fabric.set_link('10.0.0.1:80', latency_ms=1.0,
                        trickle_segments=SEGMENTS,
                        trickle_ms=TRICKLE_MS)
        pool, res = sco.make_sim_pool(
            fabric, backends, spares=1, maximum=1,
            constructor=None, transport=FabricTransport(fabric))
        await sco.wait_state(pool, 'running', timeout_s=20.0)

        op_log, states = [], []
        for i in range(FAULT_OPS + HEAL_OPS):
            if i == FAULT_OPS:
                # The heal: the middlebox stops dribbling.
                fabric.set_link('10.0.0.1:80', latency_ms=1.0,
                                trickle_segments=0,
                                trickle_ms=TRICKLE_MS)
            states.append(pool.get_state())
            t0 = loop.time()
            ok = await sco.claim_release(pool, timeout_ms=5000.0)
            dur_ms = (loop.time() - t0) * 1000.0
            op_log.append(('released' if ok else 'error',
                           _classify(dur_ms)))
        states.append(pool.get_state())
        result['envelope'] = _envelope(op_log, states)
        await sco.stop_pool(pool, res)

    sc.run(lambda: main())
    return result['envelope']


def _native_envelope():
    """The real-time arm: the C data plane against a loopback echo
    server that dribbles its response on the same segment schedule
    while faulted. No Scenario harness — there is no virtual clock to
    replay; the envelope itself is the deterministic artifact."""
    from cueball_tpu import native_transport as mod_nt

    async def main():
        loop = asyncio.get_running_loop()
        faulted = [True]

        async def handler(reader, writer):
            try:
                while True:
                    req = await reader.readexactly(REQ_BYTES)
                    if faulted[0]:
                        seg = REQ_BYTES // SEGMENTS
                        for s in range(SEGMENTS):
                            await asyncio.sleep(TRICKLE_MS / 1000.0)
                            writer.write(req[s * seg:(s + 1) * seg])
                            await writer.drain()
                    else:
                        writer.write(req)
                        await writer.drain()
            except (asyncio.IncompleteReadError, ConnectionError):
                pass
            finally:
                writer.close()

        server = await asyncio.start_server(handler, '127.0.0.1', 0)
        port = server.sockets[0].getsockname()[1]
        res = StaticIpResolver({'backends': [
            {'address': '127.0.0.1', 'port': port}]})
        pool = ConnectionPool({
            'domain': 'envelope.native',
            'transport': get_transport('native'),
            'resolver': res, 'spares': 1, 'maximum': 1,
            'recovery': sco.RECOVERY})
        res.start()
        await sco.wait_state(pool, 'running', timeout_s=20.0)

        payload = bytes(range(REQ_BYTES))
        op_log, states = [], []
        for i in range(FAULT_OPS + HEAL_OPS):
            if i == FAULT_OPS:
                faulted[0] = False
            states.append(pool.get_state())
            t0 = loop.time()
            err, hdl, conn = await sco.claim_once(
                pool, timeout_ms=5000.0)
            outcome = 'error'
            if err is None:
                conn.write(payload)
                got = await conn.read_exactly(REQ_BYTES, 5000.0)
                assert got == payload
                hdl.release()
                outcome = 'released'
            dur_ms = (loop.time() - t0) * 1000.0
            op_log.append((outcome, _classify(dur_ms)))
        states.append(pool.get_state())

        # Anti-vacuity: the op bytes really moved through the C plane.
        plane = mod_nt.peek_plane(loop)
        assert plane is not None
        assert plane.tx.stats()['drains'] > 0

        envelope = _envelope(op_log, states)
        await sco.stop_pool(pool, res)
        mod_nt.close_plane(loop)
        server.close()
        await server.wait_closed()
        return envelope

    return asyncio.run(main())


def _native_unavailable():
    from cueball_tpu import native_transport as mod_nt
    return not mod_nt.native_available()


@pytest.mark.skipif(
    _native_unavailable(),
    reason='extension not built with transport symbols')
def test_fabric_and_native_share_the_recovery_envelope():
    fab = _fabric_envelope(seed=11)
    nat = _native_envelope()
    assert fab == nat, (fab, nat)
    # And the shared envelope says what the fault story requires: the
    # pool rode out the dribble without leaving 'running', every op
    # during the fault stalled for the full dribble yet RELEASED, and
    # the very first post-heal op was already fast.
    assert fab['pool_states'] == ('running',)
    assert fab['ops'][:FAULT_OPS] == (('released', 'stalled'),) \
        * FAULT_OPS
    assert fab['ops'][FAULT_OPS:] == (('released', 'fast'),) * HEAL_OPS
    assert fab['ops_to_recover'] == 0


@pytest.mark.parametrize('seed', [11, 22, 33])
def test_fabric_envelope_is_seed_stable(seed):
    """The virtual arm's envelope must not depend on the seed — the
    envelope is a property of the fault, not of the schedule jitter
    the seed perturbs. (The native arm has no seed; its stability is
    the equality test above.)"""
    assert _fabric_envelope(seed) == _fabric_envelope(11)

"""Scenario: sharded-vs-plain byte parity through regional failover.

The shard router's core promise is that routing adds NO policy: a
claim routed through an inline-backend ``FleetRouter`` is a dict
lookup plus a direct ``pool.claim_cb`` call on the same loop. This
scenario proves it the strong way — run the SAME seeded hostile
schedule (region 1 partitions at t=5s, heals at t=25s, then a CoDel
overload burst) twice, once against a plain pool and once against the
identical pool owned by shard of a K=4 router, and assert:

- the FSM transition traces are IDENTICAL once the router's own
  ``ShardFSM`` entries are filtered out (the router adds lifecycle
  machines, never pool behavior);
- the CoDel shed counters are equal AND nonzero (the overload burst
  actually bit, and bit identically);
- the recovery envelope matches between arms.

Both arms anchor pool creation at the same virtual instant so every
pool-side timer shares one epoch; from there the runs must not
diverge by a single transition.
"""

import asyncio

import pytest

from cueball_tpu import netsim
from cueball_tpu.shard import FleetRouter

import scenario_common as sco

POOL_NAME = 'svc.sim'
TARGET_DELAY_MS = 150.0
# Open-loop overload (test_pool_codel's shape): 4 claims every 10ms
# against maximum=9 slots holding 50ms each — arrivals ~400/s vs
# service ~180/s, so the queue grows, sojourns pin over the 150ms
# target while dequeues keep flowing, and the CoDel pacer must shed.
BURST_PER_TICK = 4
BURST_TICK_S = 0.01
BURST_RUN_S = 3.0
BURST_HOLD_S = 0.05


async def _claim_once(claim_fn):
    """sco.claim_once, but through an injectable claim path so the
    sharded arm exercises router.claim_cb and the plain arm the bare
    pool — the two paths this scenario proves equivalent. No per-claim
    timeout: CoDel pools forbid one (the shed policy IS the timeout)."""
    fut = asyncio.get_running_loop().create_future()

    def cb(err, hdl=None, conn=None):
        if not fut.done():
            fut.set_result((err, hdl, conn))
    claim_fn({}, cb)
    return await fut


async def _claim_release(claim_fn):
    err, hdl, conn = await _claim_once(claim_fn)
    if err is not None:
        return False
    hdl.release()
    return True


async def _measure_recovery_s(claim_fn, needed_ok=3,
                              give_up_s=60.0):
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    streak = 0
    while True:
        if loop.time() - t0 > give_up_s:
            raise AssertionError('no recovery within %.1fs' % give_up_s)
        ok = await _claim_release(claim_fn)
        streak = streak + 1 if ok else 0
        if streak >= needed_ok:
            return loop.time() - t0
        await asyncio.sleep(0.1)


async def _overload_burst(claim_fn):
    """Sustained overload for BURST_RUN_S virtual seconds, then a full
    drain. Entirely virtual-clock driven — identical in both arms."""
    loop = asyncio.get_running_loop()
    pending = [0]

    def make_claim():
        pending[0] += 1

        def cb(err, hdl=None, conn=None):
            if err is None:
                loop.call_later(BURST_HOLD_S, hdl.release)
            pending[0] -= 1
        claim_fn({}, cb)

    deadline = loop.time() + BURST_RUN_S
    while loop.time() < deadline:
        for _ in range(BURST_PER_TICK):
            make_claim()
        await asyncio.sleep(BURST_TICK_S)
    while pending[0] > 0:
        await asyncio.sleep(0.05)


def _run_arm(seed: int, sharded: bool) -> dict:
    fabric = netsim.Fabric()
    sc = netsim.Scenario('sharded-failover', seed=seed)
    result = {}

    async def main():
        loop = asyncio.get_running_loop()
        backends = sco.region_backends(regions=3, per_region=3)
        router = None

        def build():
            # Same construction in both arms; the router arm runs it
            # inside the owning shard (same loop for inline workers).
            return sco.make_sim_pool(fabric, backends, spares=3,
                                     maximum=9,
                                     targetClaimDelay=TARGET_DELAY_MS)

        try:
            if sharded:
                router = FleetRouter({'shards': 4, 'backend': 'inline'})
                await router.start()
            # Anchor pool creation at the same virtual instant in both
            # arms: router startup consumes a few virtual milliseconds
            # of state polling, and every pool-side timer must share
            # one epoch for the traces to be comparable at all.
            await asyncio.sleep(1.0 - loop.time())
            if sharded:
                rec = await router.create_pool(POOL_NAME, factory=build)
                pool, res = rec.pool, rec.aux[0]
                result['shard_id'] = rec.shard_id

                def claim_fn(opts, cb):
                    return router.claim_cb(POOL_NAME, opts, cb)
            else:
                pool, res = build()
                claim_fn = pool.claim_cb
            await sco.wait_state(pool, 'running', timeout_s=10.0)

            sc.at(5.0, 'partition-r1',
                  lambda: fabric.partition(sco.region_keys(pool, 1)))
            sc.at(25.0, 'heal-r1', lambda: fabric.heal())

            # Warm traffic before the fault.
            while loop.time() < 4.5:
                assert await _claim_release(claim_fn)
                await asyncio.sleep(0.25)

            while loop.time() < 5.01:
                await asyncio.sleep(0.05)
            result['recovery_s'] = await _measure_recovery_s(claim_fn)

            failures = 0
            while loop.time() < 24.5:
                if not await _claim_release(claim_fn):
                    failures += 1
                await asyncio.sleep(0.25)
            result['mid_partition_failures'] = failures

            deadline = loop.time() + 30.0
            while loop.time() < deadline and pool.p_dead:
                await asyncio.sleep(0.5)
            result['dead_after_heal'] = sorted(pool.p_dead)

            # Overload burst from a fixed anchor, fully healed.
            while loop.time() < 58.0:
                await asyncio.sleep(0.1)
            await _overload_burst(claim_fn)
            result['codel_sheds'] = pool.get_stats()['counters'].get(
                'codel-paced-drop', 0)

            await sco.stop_pool(pool, res)
        finally:
            if router is not None:
                await router.stop()

    sc.run(lambda: main())
    result['fired'] = [label for _, label in sc.fired]
    result['shard_fsm_transitions'] = sum(
        1 for cls, _, _ in sc.trace if cls == 'ShardFSM')
    result['trace'] = [t for t in sc.trace if t[0] != 'ShardFSM']
    return result


@pytest.mark.parametrize('seed', [7, 1234])
def test_sharded_routing_is_byte_identical_to_plain(seed):
    plain = _run_arm(seed, sharded=False)
    routed = _run_arm(seed, sharded=True)

    # Each arm individually behaves like the regional-failover
    # scenario: bounded recovery, no mid-partition outage, full heal,
    # the schedule actually fired, and the burst actually shed.
    for arm in (plain, routed):
        assert arm['recovery_s'] < 2.5, arm
        assert arm['mid_partition_failures'] <= 1, arm
        assert arm['dead_after_heal'] == [], arm
        assert arm['fired'] == ['partition-r1', 'heal-r1'], arm
        assert arm['codel_sheds'] > 0, arm
        assert len(arm['trace']) > 100, arm

    # The routed arm ran real shard lifecycle machines...
    assert plain['shard_fsm_transitions'] == 0
    assert routed['shard_fsm_transitions'] > 0

    # ...and yet, with those filtered out, the two runs are the SAME
    # run: identical FSM transition sequence, identical shed count,
    # identical recovery clock.
    assert routed['trace'] == plain['trace']
    assert routed['codel_sheds'] == plain['codel_sheds']
    assert routed['recovery_s'] == plain['recovery_s']
    assert routed['mid_partition_failures'] == \
        plain['mid_partition_failures']

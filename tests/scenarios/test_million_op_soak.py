"""Million-operation virtual-time soak.

The whole point of the virtual loop: a workload that would take hours
of wall clock against real sockets — one million pool operations
(each claim and each release counts as one), with a backend flapped
every 50k cycles — runs in well under a minute because timers cost
nothing and only the Python work is real.

The fast variant (not marked slow) rides in tier-1 as the smoke test
for the same machinery; the full million-op run carries the
ISSUE-level wall-clock budget assert and is ``-m slow``.
"""

import asyncio
import time

import pytest

from cueball_tpu import netsim

import scenario_common as sco


def _soak(seed: int, cycles: int, flap_every: int | None = None):
    """Run claim/release cycles; returns stats. ops == 2 * cycles."""
    fabric = netsim.Fabric()
    stats = {'ok': 0, 'errors': 0, 'flaps': 0}

    async def main():
        backends = sco.region_backends(regions=1, per_region=4)
        pool, res = sco.make_sim_pool(fabric, backends, spares=4,
                                      maximum=4)
        await sco.wait_state(pool, 'running', timeout_s=10.0)
        keys = [sco.fabric_key(b) for b in backends]
        loop = asyncio.get_running_loop()

        flapped = None
        for i in range(cycles):
            if flap_every and i % flap_every == flap_every - 1:
                # Restart one backend mid-soak; 3 healthy ones keep
                # serving, the 4th reconnects behind our back.
                if flapped is not None:
                    fabric.up(flapped)
                flapped = keys[stats['flaps'] % len(keys)]
                fabric.down(flapped)
                stats['flaps'] += 1
            err, hdl, conn = await sco.claim_once(pool, 2000)
            if err is not None:
                stats['errors'] += 1
                continue
            hdl.release()
            stats['ok'] += 1
        if flapped is not None:
            fabric.up(flapped)
        stats['virtual_s'] = loop.time()
        await sco.stop_pool(pool, res)

    netsim.run(main(), seed=seed)
    return stats


def test_soak_fast_smoke():
    stats = _soak(seed=31, cycles=2000, flap_every=500)
    assert stats['ok'] + stats['errors'] == 2000
    assert stats['errors'] <= 2, stats
    assert stats['flaps'] == 4


@pytest.mark.slow
def test_million_op_soak_under_60s_wall():
    t0 = time.perf_counter()
    stats = _soak(seed=137, cycles=500_000, flap_every=50_000)
    wall_s = time.perf_counter() - t0
    ops = 2 * (stats['ok'] + stats['errors'])
    assert ops == 1_000_000
    # Claims may time out in the instant a flap lands; the envelope
    # is that they stay noise, not a failure mode.
    assert stats['errors'] < 100, stats
    assert stats['flaps'] == 10
    assert wall_s < 60.0, 'soak took %.1fs wall' % wall_s

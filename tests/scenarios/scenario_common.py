"""Shared plumbing for the adversarial scenario corpus.

Every scenario builds the same shape: a ConnectionPool wired over a
netsim Fabric via the constructor seam, backends named by region
(``r<region>-b<n>``), recovery tuned so fault->recover cycles complete
in seconds of VIRTUAL time. Helpers here issue claims through the real
claim_cb path and wait for pool states on virtual sleeps.

Scenario files import this module directly (pytest puts this directory
on sys.path); they deliberately do NOT import tests/conftest.py
helpers, which assume the real loop."""

import asyncio

from cueball_tpu import netsim
from cueball_tpu.pool import ConnectionPool
from cueball_tpu.resolver import StaticIpResolver

RECOVERY = {'default': {'retries': 2, 'timeout': 500, 'delay': 100,
                        'maxDelay': 400, 'delaySpread': 0.2}}


def region_backends(regions: int = 3, per_region: int = 3,
                    port: int = 80) -> list[dict]:
    out = []
    for r in range(1, regions + 1):
        for b in range(1, per_region + 1):
            out.append({'key': 'r%d-b%d' % (r, b),
                        'address': '10.%d.0.%d' % (r, b),
                        'port': port})
    return out


def backend_keys(pool) -> list[str]:
    return list(pool.p_keys)


def fabric_key(backend: dict) -> str:
    """The 'address:port' alias the fabric resolves alongside the
    pool's opaque hashed backend key — how scenarios name backends
    when driving faults."""
    return '%s:%s' % (backend['address'], backend['port'])


def make_sim_pool(fabric: netsim.Fabric, backends: list[dict],
                  spares: int = 2, maximum: int = 8,
                  recovery: dict | None = None, **opts):
    """Pool over the fabric. Returns (pool, resolver); caller runs
    inside a netsim loop."""
    res = StaticIpResolver({'backends': [
        {'address': b['address'], 'port': b['port']}
        for b in backends]})
    options = {
        'domain': 'svc.sim',
        'constructor': fabric.constructor,
        'resolver': res,
        'spares': spares,
        'maximum': maximum,
        'recovery': recovery or RECOVERY,
    }
    options.update(opts)
    pool = ConnectionPool(options)
    res.start()
    return pool, res


def key_for(pool, backend_key_prefix: str) -> list[str]:
    return [k for k in pool.p_keys
            if pool.p_backends[k]['address'].startswith(
                backend_key_prefix)]


def region_keys(pool, region: int) -> list[str]:
    """Pool backend keys whose address is in 10.<region>.0.0/16."""
    return key_for(pool, '10.%d.' % region)


async def claim_once(pool, timeout_ms: float = 1000.0):
    """One claim through the real callback path -> (err, hdl, conn)."""
    fut = asyncio.get_running_loop().create_future()

    def cb(err, hdl=None, conn=None):
        if not fut.done():
            fut.set_result((err, hdl, conn))
    pool.claim_cb({'timeout': timeout_ms}, cb)
    return await fut


async def claim_release(pool, timeout_ms: float = 1000.0,
                        hold_s: float = 0.0) -> bool:
    err, hdl, conn = await claim_once(pool, timeout_ms)
    if err is not None:
        return False
    listener = conn.on('error', lambda e=None: None)
    if hold_s > 0:
        await asyncio.sleep(hold_s)
    conn.remove_listener('error', listener)
    try:
        hdl.release()
    except Exception:
        return False
    return True


async def wait_state(fsm, state: str, timeout_s: float = 30.0) -> None:
    deadline = asyncio.get_running_loop().time() + timeout_s
    while not fsm.is_in_state(state):
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(
                'timed out waiting for %r (in %r)' % (
                    state, fsm.get_state()))
        await asyncio.sleep(0.05)


async def stop_pool(pool, resolver=None) -> None:
    pool.stop()
    await wait_state(pool, 'stopped', timeout_s=60.0)
    if resolver is not None and hasattr(resolver, 'stop'):
        try:
            resolver.stop()
        except Exception:
            pass
        await asyncio.sleep(0.2)


async def measure_recovery_s(pool, timeout_ms: float = 500.0,
                             probe_every_s: float = 0.1,
                             needed_ok: int = 3,
                             give_up_s: float = 60.0) -> float:
    """Virtual seconds until ``needed_ok`` consecutive claims succeed:
    the scenario-level definition of 'recovered'."""
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    streak = 0
    while True:
        if loop.time() - t0 > give_up_s:
            raise AssertionError(
                'pool did not recover within %.1fs virtual'
                % give_up_s)
        ok = await claim_release(pool, timeout_ms)
        streak = streak + 1 if ok else 0
        if streak >= needed_ok:
            return loop.time() - t0
        await asyncio.sleep(probe_every_s)

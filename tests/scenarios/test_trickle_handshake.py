"""Trickled TCP segments mid-claim-handshake (FabricTransport).

The transport seam lets netsim express a fault no socket-level fake
could: a middlebox that accepts the connection but then dribbles the
claim-time handshake out segment by segment. ``LinkModel``'s
``trickle_segments``/``trickle_ms`` drive SimConnection's
``cb_claim_ready`` probe, which the slot FSM consults before handing
the socket to a claim — the handle sits in 'claiming' for the whole
dribble, so ALL of the stall lands in the phase ledger's `handshake`
column while `queue_wait` stays flat (the claim was served an idle
slot immediately; it just couldn't use it yet).

Runs inside the Scenario harness: any assertion failure writes a
.netsim-failures/ replay dump that embeds the phase ledger of the
slowest claims, and the run must replay byte-identically from its
seed (pinned across 5 seeds below).
"""

import asyncio

from cueball_tpu import netsim
from cueball_tpu import profile as mod_profile
from cueball_tpu import trace as mod_trace
from cueball_tpu import wiretap as mod_wiretap
from cueball_tpu.transport import FabricTransport

import pytest

import scenario_common as sco

SEGMENTS = 5
TRICKLE_MS = 10.0
# Virtual milliseconds the dribble adds to every claim: N timer hops.
STALL_MS = SEGMENTS * TRICKLE_MS


def _run(seed, trickle_segments):
    """One seeded run -> (transition trace, per-claim ledgers)."""
    fabric = netsim.Fabric()
    sc = netsim.Scenario('trickle-handshake', seed=seed)
    result = {}

    async def main():
        backends = [{'address': '10.0.0.1', 'port': 80}]
        fabric.set_link('10.0.0.1:80', latency_ms=1.0,
                        trickle_segments=trickle_segments,
                        trickle_ms=TRICKLE_MS)
        pool, res = sco.make_sim_pool(
            fabric, backends, spares=2, maximum=2,
            constructor=None, transport=FabricTransport(fabric))
        await sco.wait_state(pool, 'running', timeout_s=20.0)

        mod_trace.enable_tracing(ring_size=128, sample_rate=1.0)
        try:
            for _ in range(10):
                assert await sco.claim_release(pool, timeout_ms=5000.0)
                await asyncio.sleep(0.01)
            result['ledgers'] = mod_profile.phase_ledger()
        finally:
            mod_trace.disable_tracing()
        await sco.stop_pool(pool, res)

    sc.run(lambda: main())
    return list(sc.trace), result['ledgers']


@pytest.mark.parametrize('seed', [11, 22, 33, 44, 55])
def test_trickle_inflates_handshake_not_queue_wait(seed):
    _trace, ledgers = _run(seed, SEGMENTS)
    _ctrace, control = _run(seed, 0)
    assert len(ledgers) == 10 and len(control) == 10
    for led, base in zip(ledgers, control):
        assert led['outcome'] == base['outcome'] == 'released'
        # Every claim ate the full dribble in the handshake phase
        # (up to float addition across the N timer hops)...
        assert led['phases']['handshake'] >= STALL_MS - 0.001
        # ...the control run's handshake never saw it...
        assert base['phases']['handshake'] < STALL_MS
        # ...and queue_wait stayed flat: the claim was SERVED promptly
        # on both runs; only the post-serve handshake stalled.
        assert led['phases']['queue_wait'] <= \
            base['phases']['queue_wait'] + 1.0


@pytest.mark.parametrize('seed', [11, 22, 33, 44, 55])
def test_trickle_run_is_deterministic(seed):
    """Same seed, same script -> byte-identical transition trace AND
    identical phase ledgers (virtual clock: ledger times are exact)."""
    trace_a, ledgers_a = _run(seed, SEGMENTS)
    trace_b, ledgers_b = _run(seed, SEGMENTS)
    assert len(trace_a) > 50
    assert trace_a == trace_b
    strip = [{k: v for k, v in led.items() if k != 'trace_id'}
             for led in ledgers_a]
    strip_b = [{k: v for k, v in led.items() if k != 'trace_id'}
               for led in ledgers_b]
    assert strip == strip_b


def test_trickle_delay_lands_in_wire_kernel_wait():
    """The wire-ledger view of the same fault: the dribble is time
    spent waiting on segments the peer hasn't sent — in-kernel wait,
    NOT protocol parsing. SimConnection's claim-readiness probe
    attributes it via wiretap.wire_wait, so the fabric's kernel_wait
    total absorbs ~STALL_MS per claim while proto_parse stays flat."""
    mod_wiretap.enable_wiretap()
    try:
        _trace, ledgers = _run(11, SEGMENTS)
        totals = mod_wiretap.wire_totals()
    finally:
        mod_wiretap.disable_wiretap()
    assert len(ledgers) == 10
    fabric_ms = totals.get('fabric')
    assert fabric_ms is not None, totals
    # 10 claims, each dribbled for STALL_MS of virtual time (exact on
    # the virtual clock, up to float addition across timer hops).
    assert fabric_ms['kernel_wait'] >= 10 * STALL_MS - 0.01
    assert fabric_ms['proto_parse'] <= 1.0

"""Scenario: DNS TTL flapping under a mutating zone.

A real DNSResolver polls a SimZone through a ScriptedDnsClient with
1-second TTLs. The zone mutates mid-run — a backend joins, another is
retired — and then the nameserver SERVFAILs for a 2-second window.

Envelope:

- each zone mutation is reflected in the resolver's backend set
  within 3 virtual seconds (TTL + one retry of slack);
- the SERVFAIL window causes NO removals: the resolver must serve
  stale-but-recent data on refresh errors, not dump the backend list;
- after the window the resolver is still 'running' and converged.
"""

import asyncio

import pytest

from cueball_tpu import netsim
from cueball_tpu.dns_resolver import DNSResolver

SRV = '_svc._tcp.svc.flap'
RECOVERY = {'default': {'retries': 2, 'timeout': 400, 'delay': 100,
                        'maxDelay': 300, 'delaySpread': 0.2}}


class ZoneScriptClient(netsim.ScriptedDnsClient):
    """Client-level view of a SimZone, with a SERVFAIL window."""

    def __init__(self, zone):
        super().__init__()
        self.zone = zone
        self.fail_until = None      # virtual time, None = healthy

    def script(self, opts):
        now = asyncio.get_running_loop().time()
        if self.fail_until is not None and now < self.fail_until:
            return netsim.DnsOutcome(rcode='SERVFAIL')
        rcode, answers, authority = self.zone.resolve(
            opts['domain'], opts['type'])
        if rcode != 'NOERROR':
            return netsim.DnsOutcome(rcode=rcode)
        return netsim.DnsOutcome(answers=answers, authority=authority)


async def _converge(addrs, expected, deadline_s):
    loop = asyncio.get_running_loop()
    while loop.time() < deadline_s:
        if set(addrs.values()) == expected:
            return loop.time()
        await asyncio.sleep(0.1)
    raise AssertionError('no convergence to %r by t=%.1fs (have %r)'
                         % (expected, deadline_s, addrs))


@pytest.mark.parametrize('seed', [3, 555])
def test_dns_flap_convergence_and_stale_serving(seed):
    zone = netsim.SimZone()
    zone.add_srv_backend(SRV, 'b1.flap', 8080, '10.9.0.1',
                         ttl=1, addr_ttl=1)
    zone.add_srv_backend(SRV, 'b2.flap', 8080, '10.9.0.2',
                         ttl=1, addr_ttl=1)
    client = ZoneScriptClient(zone)
    sc = netsim.Scenario('dns-flap', seed=seed)
    result = {}

    async def main():
        res = DNSResolver({
            'domain': 'svc.flap', 'service': '_svc._tcp',
            'defaultPort': 8080, 'resolvers': ['9.9.9.1'],
            'recovery': RECOVERY, 'dnsClient': client,
        })
        addrs = {}
        removals = []

        def on_added(k, b):
            addrs[k] = b['address']

        def on_removed(k):
            removals.append((asyncio.get_running_loop().time(), k))
            addrs.pop(k, None)
        res.on('added', on_added)
        res.on('removed', on_removed)
        res.start()

        sc.at(3.0, 'join-b3', lambda: zone.add_srv_backend(
            SRV, 'b3.flap', 8080, '10.9.0.3', ttl=1, addr_ttl=1))

        def retire_b1():
            zone.remove(SRV, 'SRV')
            zone.add(SRV, 'SRV', 'b2.flap', ttl=1, port=8080)
            zone.add(SRV, 'SRV', 'b3.flap', ttl=1, port=8080)
        sc.at(6.0, 'retire-b1', retire_b1)

        def open_window():
            client.fail_until = 11.0
        sc.at(9.0, 'servfail-window', open_window)

        await _converge(addrs, {'10.9.0.1', '10.9.0.2'}, 3.0)
        t_joined = await _converge(
            addrs, {'10.9.0.1', '10.9.0.2', '10.9.0.3'}, 6.0)
        t_retired = await _converge(
            addrs, {'10.9.0.2', '10.9.0.3'}, 9.0)

        # Across the SERVFAIL window: stale data keeps being served.
        loop = asyncio.get_running_loop()
        while loop.time() < 12.0:
            await asyncio.sleep(0.2)
        window_removals = [r for r in removals if 9.0 <= r[0] <= 12.0]
        result.update({
            't_joined': t_joined, 't_retired': t_retired,
            'window_removals': window_removals,
            'final': set(addrs.values()),
            'running': res.is_in_state('running'),
            'queries': len(client.history),
        })
        res.stop()
        deadline = loop.time() + 10.0
        while loop.time() < deadline and \
                not res.is_in_state('stopped'):
            await asyncio.sleep(0.1)

    sc.run(lambda: main())

    assert result['t_joined'] - 3.0 < 3.0, result
    assert result['t_retired'] - 6.0 < 3.0, result
    assert result['window_removals'] == [], result
    assert result['final'] == {'10.9.0.2', '10.9.0.3'}, result
    assert result['running'], result
    # 1-second TTLs over 12 virtual seconds: the resolver re-queried
    # constantly; the scenario cost essentially no wall time.
    assert result['queries'] > 20, result
    assert [l for _, l in sc.fired] == \
        ['join-b3', 'retire-b1', 'servfail-window']

"""Scenario: regional failover.

Three regions x three backends. At t=5s virtual, region 1 partitions
(established connections die, new handshakes blackhole); at t=25s it
heals. Envelope asserts, in the spirit of test_pool_codel's ±175ms
CoDel pin:

- during the partition, claims keep succeeding (the pool fails over
  to regions 2/3) and the RECOVERY TIME — first claim after the
  partition lands until 3 consecutive claims succeed — stays under
  the explicit bound derivable from the recovery policy (connect
  timeout 500ms x retries + backoff);
- after heal, region-1 backends rejoin the preference list and carry
  connections again within the re-probe envelope.
"""

import asyncio

import pytest

from cueball_tpu import netsim
from cueball_tpu import trace as mod_trace

import scenario_common as sco


@pytest.mark.parametrize('seed', [7, 1234])
def test_regional_failover_recovery_envelope(seed):
    fabric = netsim.Fabric()
    sc = netsim.Scenario('regional-failover', seed=seed)
    result = {}

    async def main():
        # Full-rate tracing rides along (the native recorder under
        # virtual time when the C engine is loaded), so the recovery
        # envelope below can be re-derived from span timestamps alone.
        mod_trace.enable_tracing(ring_size=1024, sample_rate=1.0)
        backends = sco.region_backends(regions=3, per_region=3)
        pool, res = sco.make_sim_pool(fabric, backends, spares=3,
                                      maximum=9)
        await sco.wait_state(pool, 'running', timeout_s=10.0)
        loop = asyncio.get_running_loop()

        sc.at(5.0, 'partition-r1',
              lambda: fabric.partition(sco.region_keys(pool, 1)))
        sc.at(25.0, 'heal-r1', lambda: fabric.heal())

        # Warm traffic before the fault.
        for _ in range(10):
            assert await sco.claim_release(pool, timeout_ms=1000)
            await asyncio.sleep(0.1)

        # Ride through the partition instant, then measure recovery.
        while loop.time() < 5.01:
            await asyncio.sleep(0.05)
        result['recovery_s'] = await sco.measure_recovery_s(
            pool, timeout_ms=1000, needed_ok=3)

        # Claims keep working for the remainder of the partition.
        failures = 0
        while loop.time() < 24.5:
            if not await sco.claim_release(pool, timeout_ms=1000):
                failures += 1
            await asyncio.sleep(0.25)
        result['mid_partition_failures'] = failures

        # After heal, the monitor probes must revive region 1: every
        # backend leaves the dead set. (Whether r1 then CARRIES
        # connections depends only on preference order — spares=3
        # keeps 3 of 9 backends warm — so the dead set, not the
        # connection count, is the recovery signal.)
        deadline = loop.time() + 30.0
        while loop.time() < deadline and pool.p_dead:
            await asyncio.sleep(0.5)
        result['dead_after_heal'] = sorted(pool.p_dead)
        result['healed_at_s'] = loop.time()
        result['claim_traces'] = [
            t for t in mod_trace.trace_ring()
            if t.root.name == 'claim' and t.root.end is not None]
        await sco.stop_pool(pool, res)

    try:
        sc.run(lambda: main())
    finally:
        mod_trace.disable_tracing()

    # Envelopes. Recovery: one failed claim consumes at most its
    # 1000ms claim timeout; with 2 healthy regions the pool's spare
    # slots serve immediately afterwards, so 3 consecutive successes
    # land within 2.5s of the partition — generous only against
    # scheduling noise, not against a broken failover.
    assert result['recovery_s'] < 2.5, result
    assert result['mid_partition_failures'] <= 1, result
    assert result['dead_after_heal'] == [], result
    assert result['healed_at_s'] < 55.0, result
    # The faults actually fired (guard against a vacuous pass) and
    # the scenario exercised real machines end to end.
    assert [l for _, l in sc.fired] == ['partition-r1', 'heal-r1']
    assert len(sc.trace) > 100

    # Trace envelope: the recovery bound must be re-derivable from the
    # span record alone. Root starts/ends are virtual-clock millis, so
    # the partition instant is t=5000ms; recovery is the end of the
    # third consecutive successful claim begun after it.
    claims = sorted(result['claim_traces'], key=lambda t: t.root.start)
    assert claims, 'tracing recorded no completed claim traces'
    assert all(t.spans[1].name == 'queue_wait' for t in claims), \
        'claim trace missing its queue_wait span'
    post = [t for t in claims if t.root.start >= 5000.0]
    assert post, 'no claim traces recorded after the partition'
    streak, recovered_at = 0, None
    for t in post:
        if t.root.attrs.get('outcome') in ('released', 'closed'):
            streak += 1
            if streak == 3:
                recovered_at = t.root.end
                break
        else:
            streak = 0
    assert recovered_at is not None, \
        'spans never show 3 consecutive post-partition successes'
    result['recovery_from_spans_s'] = (recovered_at - 5000.0) / 1000.0
    assert result['recovery_from_spans_s'] < 2.5, result[
        'recovery_from_spans_s']

    # Phase-ledger envelope (the claim-path profiler over the same
    # span record): the ledger partitions every claim's wall time —
    # phase_sum == wall, coverage >= 0.95 under virtual time. During
    # the partition the pool serves from warm spares in the healthy
    # regions, so the ledger must show NO inflation at all: every
    # window claim stays under the single-claim-timeout bound, and any
    # claim that does go slow owes it to waiting (queue_wait plus the
    # carved-out socket_wait of blackholed handshakes), never to
    # service time — the inverse of the gray-failure signature.
    from cueball_tpu import profile as mod_profile
    ledgers = mod_profile.phase_ledger(claims)
    assert len(ledgers) == len(claims)
    for led in ledgers:
        assert abs(sum(led['phases'].values()) - led['wall_ms']) <= \
            max(1e-6, 1e-9 * led['wall_ms'])
        assert led['coverage'] >= 0.95, led
    window = [led for t, led in zip(claims, ledgers)
              if 5000.0 <= t.root.start < 25000.0]
    assert window, 'no ledgered claims inside the partition window'
    for led in window:
        assert led['wall_ms'] <= 1100.0, led
        if led['wall_ms'] > 100.0:
            waiting = led['phases']['queue_wait'] + \
                led['phases']['socket_wait']
            assert waiting >= 0.5 * led['wall_ms'], led
            assert led['phases']['lease'] <= 0.5 * led['wall_ms'], led

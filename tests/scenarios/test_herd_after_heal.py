"""Scenario: thundering herd after a partition heals.

Every backend partitions at t=2s; the pool exhausts its retries and
declares 'failed'. At t=10s the fabric heals; once the pool claws its
way back to 'running', 300 clients from three equal cohorts arrive in
a single burst — far more work than 6 connections can serve inside
the claim timeout, so the pool MUST shed. What matters is how.

Envelope:

- the pool recovers from 'failed' to 'running' within 3 virtual
  seconds of the heal (retry backoff is capped at 400ms);
- shed fairness: per-cohort success rates have a Jain index >= 0.98 —
  the queue must shed by arrival order, not starve a cohort;
- the shed is real but bounded: overall success rate lands in the
  capacity-derived band (6 conns x 50ms holds x 1s timeout serves
  roughly 120 of 300), and every failure is a claim timeout, not a
  pool error;
- post-herd steady state: a fresh claim succeeds immediately.
"""

import asyncio

import pytest

from cueball_tpu import netsim
from cueball_tpu.errors import ClaimTimeoutError

import scenario_common as sco


@pytest.mark.parametrize('seed', [21, 777])
def test_herd_after_heal_shed_fairness(seed):
    fabric = netsim.Fabric()
    sc = netsim.Scenario('herd-after-heal', seed=seed)
    result = {}

    async def main():
        backends = sco.region_backends(regions=1, per_region=6)
        pool, res = sco.make_sim_pool(fabric, backends, spares=4,
                                      maximum=6)
        await sco.wait_state(pool, 'running', timeout_s=10.0)
        loop = asyncio.get_running_loop()

        all_keys = [sco.fabric_key(b) for b in backends]
        sc.at(2.0, 'partition-all',
              lambda: fabric.partition(all_keys))
        sc.at(10.0, 'heal-all', lambda: fabric.heal())

        # The full partition must drive the pool to 'failed'.
        await sco.wait_state(pool, 'failed', timeout_s=9.0)
        result['went_failed'] = True

        while loop.time() < 10.0:
            await asyncio.sleep(0.05)
        await sco.wait_state(pool, 'running', timeout_s=10.0)
        result['recovered_at_s'] = loop.time()

        # The herd hits while the pool is barely back on its feet.
        outcomes = await netsim.herd(
            pool, 300, timeout_ms=1000, hold_s=0.05,
            cohort=lambda i: 'c%d' % (i % 3))
        result['outcomes'] = outcomes
        result['steady_claim'] = await sco.claim_release(pool, 1000)
        await sco.stop_pool(pool, res)

    sc.run(lambda: main())

    outcomes = result['outcomes']
    rates = netsim.success_rates(outcomes)
    fairness = netsim.jain_index(rates.values())
    ok_rate = sum(1 for r in outcomes if r['ok']) / len(outcomes)
    errs = {r['err'] for r in outcomes if not r['ok']}

    assert result['went_failed']
    assert result['recovered_at_s'] - 10.0 < 3.0, result
    assert set(rates) == {'c0', 'c1', 'c2'}
    assert fairness >= 0.98, (fairness, rates)
    # Capacity math: 6 conns x ~20 claims/s each x 1s timeout ~ 120
    # served; the rest shed by timeout. Band is generous on both
    # sides but rules out 'served everything' and 'served nothing'.
    assert 0.20 <= ok_rate <= 0.80, (ok_rate, rates)
    assert errs == {ClaimTimeoutError.__name__}, errs
    assert result['steady_claim']
    assert [l for _, l in sc.fired] == ['partition-all', 'heal-all']
    assert len(sc.trace) > 100

"""Replay determinism: the corpus's foundational guarantee.

Two runs of the same scenario with the same seed must produce a
byte-identical FSM transition trace — the same
``fsm.add_transition_tracer`` tuple stream that
tests/test_runq_conformance.py pins across engines — plus identical
fault firing times and identical herd outcomes. A different seed must
diverge. This is what makes every failure dump's one-command replay
actually reproduce the failure.
"""

import hashlib

import asyncio

from cueball_tpu import netsim

import scenario_common as sco


def _run_once(seed):
    """One fixed hostile run: jittery lossy links, a mid-run
    partition and heal, Poisson herd traffic. Returns everything a
    replay must reproduce."""
    fabric = netsim.Fabric()
    sc = netsim.Scenario('replay-probe', seed=seed)
    result = {}

    async def main():
        backends = sco.region_backends(regions=2, per_region=3)
        for b in backends:
            fabric.set_link(sco.fabric_key(b), latency_ms=2.0,
                            jitter_ms=8.0, loss=0.05)
        pool, res = sco.make_sim_pool(fabric, backends, spares=3,
                                      maximum=6)
        await sco.wait_state(pool, 'running', timeout_s=20.0)

        r1 = [sco.fabric_key(b) for b in backends[:3]]
        sc.at(2.0, 'partition-r1', lambda: fabric.partition(r1))
        sc.at(6.0, 'heal-r1', lambda: fabric.heal())

        outcomes = await netsim.herd(
            pool, 60, rate_per_s=10.0, timeout_ms=1500)
        result['outcomes'] = [
            (r['idx'], r['ok'], r['err'], r['t_arrive_s'],
             r['latency_ms']) for r in outcomes]
        await sco.stop_pool(pool, res)

    sc.run(lambda: main())
    digest = hashlib.sha256(
        '\n'.join(repr(t) for t in sc.trace).encode()).hexdigest()
    return {'digest': digest, 'n': len(sc.trace),
            'fired': list(sc.fired), 'outcomes': result['outcomes'],
            'trace': list(sc.trace)}


def test_same_seed_replays_byte_identically():
    a = _run_once(424242)
    b = _run_once(424242)
    assert a['n'] > 100
    assert a['trace'] == b['trace']
    assert a['digest'] == b['digest']
    assert a['fired'] == b['fired']
    assert a['outcomes'] == b['outcomes']


def test_different_seed_diverges():
    a = _run_once(424242)
    c = _run_once(424243)
    # Jitter, loss draws and Poisson arrivals all flow from the seed:
    # a different seed must visibly change the run.
    assert a['outcomes'] != c['outcomes'] or \
        a['digest'] != c['digest']


def test_wall_clock_independence():
    """Virtual runs may not read the host clock: the trace is a pure
    function of (script, seed), so an identical back-to-back rerun —
    executed at a different wall time by construction — matching
    byte-for-byte is the proof. This test additionally pins that the
    virtual epoch is a constant, not derived from the host."""
    assert netsim.VIRTUAL_EPOCH == 1_700_000_000.0
    t = netsim.run(_read_times(), seed=9)
    assert t == (0.0, netsim.VIRTUAL_EPOCH)


async def _read_times():
    loop = asyncio.get_running_loop()
    from cueball_tpu import utils as mod_utils
    return (loop.time(), mod_utils.wall_time())

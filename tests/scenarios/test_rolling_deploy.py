"""Scenario: rolling deploy.

Six backends restart one at a time, 4 virtual seconds apart: each goes
down (RST on connect, established connections reset) and comes back
2 seconds later. Steady claim traffic rides through the whole roll.

Envelope: a one-backend-at-a-time roll must be nearly invisible —
claim success rate over the roll stays >= 98%, the pool never leaves
'running', and after the roll every backend is alive (dead set empty)
and claims succeed immediately.
"""

import asyncio

import pytest

from cueball_tpu import netsim

import scenario_common as sco


@pytest.mark.parametrize('seed', [11, 4242])
def test_rolling_deploy_is_nearly_invisible(seed):
    fabric = netsim.Fabric()
    sc = netsim.Scenario('rolling-deploy', seed=seed)
    result = {}

    async def main():
        backends = sco.region_backends(regions=1, per_region=6)
        pool, res = sco.make_sim_pool(fabric, backends, spares=4,
                                      maximum=8)
        await sco.wait_state(pool, 'running', timeout_s=10.0)
        loop = asyncio.get_running_loop()

        keys = [sco.fabric_key(b) for b in backends]
        for i, key in enumerate(keys):
            t_down = 4.0 * (i + 1)
            sc.at(t_down, 'down-%s' % key,
                  lambda k=key: fabric.down(k))
            sc.at(t_down + 2.0, 'up-%s' % key,
                  lambda k=key: fabric.up(k))

        ok = 0
        total = 0
        not_running = 0
        while loop.time() < 30.0:
            total += 1
            if await sco.claim_release(pool, timeout_ms=1000):
                ok += 1
            if not pool.is_in_state('running'):
                not_running += 1
            await asyncio.sleep(0.1)

        # Roll is over; everything must come back.
        deadline = loop.time() + 20.0
        while loop.time() < deadline and pool.p_dead:
            await asyncio.sleep(0.5)
        result.update({
            'ok': ok, 'total': total, 'not_running': not_running,
            'dead_after_roll': sorted(pool.p_dead),
            'final_claim': await sco.claim_release(pool, 1000),
        })
        await sco.stop_pool(pool, res)

    sc.run(lambda: main())

    assert result['total'] >= 200, result
    assert result['ok'] / result['total'] >= 0.98, result
    assert result['not_running'] == 0, result
    assert result['dead_after_roll'] == [], result
    assert result['final_claim'], result
    # All 6 down/up pairs actually fired (guard against vacuity).
    assert len(sc.fired) == 12, sc.fired
    assert len(sc.trace) > 100

"""Scenario: gray failure.

Ten backends, each serving at 2ms; at t=2s, 20% of them silently turn
100x slower (200ms per request) WITHOUT failing — the classic gray
failure no health check catches. Poisson claim traffic holds each
lease for one simulated request on the claimed backend.

Envelope, p99-style like test_pool_codel's ±175ms pin:

- p50 claim latency stays sub-10ms (healthy capacity dominates);
- p99 claim latency stays bounded by the gray service time plus a
  scheduling allowance — gray backends slow SOME claims (a claim that
  queued behind a gray lease waits for it) but must not collapse the
  pool;
- overall success rate stays >= 99%: gray is slow, not down.
"""

import pytest

from cueball_tpu import netsim

import scenario_common as sco


@pytest.mark.parametrize('seed', [5, 909])
def test_gray_failure_p99_claim_latency_envelope(seed):
    fabric = netsim.Fabric()
    sc = netsim.Scenario('gray-failure', seed=seed)
    result = {}

    async def main():
        backends = sco.region_backends(regions=1, per_region=10)
        for b in backends:
            fabric.set_link(sco.fabric_key(b), service_ms=2.0)
        pool, res = sco.make_sim_pool(fabric, backends, spares=6,
                                      maximum=10)
        await sco.wait_state(pool, 'running', timeout_s=10.0)

        sc.at(2.0, 'gray-20pct',
              lambda: result.__setitem__(
                  'gray_keys', fabric.set_gray(0.2, mult=100.0)))

        outcomes = await netsim.herd(
            pool, 400, rate_per_s=40.0, timeout_ms=2000)
        result['outcomes'] = outcomes
        await sco.stop_pool(pool, res)

    sc.run(lambda: main())

    outcomes = result['outcomes']
    lats = [r['latency_ms'] for r in outcomes
            if r['latency_ms'] is not None]
    ok_rate = sum(1 for r in outcomes if r['ok']) / len(outcomes)
    p50 = netsim.quantile(lats, 0.50)
    p99 = netsim.quantile(lats, 0.99)

    assert len(result['gray_keys']) == 2
    assert ok_rate >= 0.99, (ok_rate, p50, p99)
    assert p50 < 10.0, (ok_rate, p50, p99)
    # One gray service time (200ms) + one healthy-queue drain
    # allowance; a pool that piles claims onto gray backends blows
    # straight through this.
    assert p99 < 450.0, (ok_rate, p50, p99)
    assert len(sc.trace) > 100

"""Scenario: gray failure.

Ten backends, each serving at 2ms; at t=2s, 20% of them silently turn
100x slower (200ms per request) WITHOUT failing — the classic gray
failure no health check catches. Poisson claim traffic holds each
lease for one simulated request on the claimed backend.

Envelope, p99-style like test_pool_codel's ±175ms pin:

- p50 claim latency stays sub-10ms (healthy capacity dominates);
- p99 claim latency stays bounded by the gray service time plus a
  scheduling allowance — gray backends slow SOME claims (a claim that
  queued behind a gray lease waits for it) but must not collapse the
  pool;
- overall success rate stays >= 99%: gray is slow, not down.

The detector arm (parallel.health) rides the same scenario: claim
traces attribute per backend, a HealthMonitor ticks on the virtual
clock, and the envelope is that it NAMES exactly the seeded gray
backends — zero false positives across seeds — while every other
control surface still reads healthy (no dead set, no failed claims:
the whole point of gray failure).
"""

import asyncio
import json

import pytest

from cueball_tpu import netsim
from cueball_tpu import trace as mod_trace
from cueball_tpu.netsim import scenario as mod_scenario

import scenario_common as sco


class _ClaimCounts:
    """Backend-sink that only counts attributed claims (picks the
    traffic carriers to turn gray, so the detector has signal)."""

    def __init__(self):
        self.counts = {}

    def observe(self, key, service_ms, claim_ms, ok):
        self.counts[key] = self.counts.get(key, 0) + 1

    def observe_shed(self, key):
        pass


@pytest.mark.parametrize('seed', [5, 909])
def test_gray_failure_p99_claim_latency_envelope(seed):
    fabric = netsim.Fabric()
    sc = netsim.Scenario('gray-failure', seed=seed)
    result = {}

    async def main():
        backends = sco.region_backends(regions=1, per_region=10)
        for b in backends:
            fabric.set_link(sco.fabric_key(b), service_ms=2.0)
        pool, res = sco.make_sim_pool(fabric, backends, spares=6,
                                      maximum=10)
        await sco.wait_state(pool, 'running', timeout_s=10.0)

        sc.at(2.0, 'gray-20pct',
              lambda: result.__setitem__(
                  'gray_keys', fabric.set_gray(0.2, mult=100.0)))

        outcomes = await netsim.herd(
            pool, 400, rate_per_s=40.0, timeout_ms=2000)
        result['outcomes'] = outcomes
        await sco.stop_pool(pool, res)

    sc.run(lambda: main())

    outcomes = result['outcomes']
    lats = [r['latency_ms'] for r in outcomes
            if r['latency_ms'] is not None]
    ok_rate = sum(1 for r in outcomes if r['ok']) / len(outcomes)
    p50 = netsim.quantile(lats, 0.50)
    p99 = netsim.quantile(lats, 0.99)

    assert len(result['gray_keys']) == 2
    assert ok_rate >= 0.99, (ok_rate, p50, p99)
    assert p50 < 10.0, (ok_rate, p50, p99)
    # One gray service time (200ms) + one healthy-queue drain
    # allowance; a pool that piles claims onto gray backends blows
    # straight through this.
    assert p99 < 450.0, (ok_rate, p50, p99)
    assert len(sc.trace) > 100


@pytest.mark.parametrize('seed', [5, 17, 23, 42, 909])
def test_gray_detector_names_seeded_backends_zero_false_positives(seed):
    """The health detector names exactly the seeded gray backends.

    Gray selection is informed: at t=2s the two busiest backends (by
    attributed claim count) turn 100x slow, so the detector is
    guaranteed observable signal. The envelope:

    - every backend the detector EVER flags is a seeded one (zero
      false positives, all ticks, all seeds);
    - both seeded backends are flagged within 5s virtual of onset;
    - at first detection the classic control surfaces still read
      healthy — empty dead set, no failed claims — i.e. the detector
      reacts before any other arm can.
    """
    from cueball_tpu.parallel import health as H

    fabric = netsim.Fabric()
    sc = netsim.Scenario('gray-detector', seed=seed)
    result = {'ticks': [], 'gray_keys': None, 'detected_at': None,
              'dead_at_detect': None}
    counts = _ClaimCounts()

    async def tick_loop(monitor, pool, loop):
        while True:
            rec = monitor.tick()
            result['ticks'].append((loop.time(), tuple(rec['gray'])))
            if rec['gray'] and result['detected_at'] is None:
                result['detected_at'] = loop.time()
                result['dead_at_detect'] = sorted(pool.p_dead)
            await asyncio.sleep(0.25)

    def go_gray(pool):
        # The two busiest attributed backends turn gray; remember
        # their pool keys (what the detector reports) and drive the
        # fabric by alias (address:port).
        if mod_trace._runtime is not None:
            mod_trace._runtime._drain_native()
        busiest = sorted(counts.counts, key=counts.counts.get,
                         reverse=True)[:2]
        aliases = ['%s:%s' % (pool.p_backends[k]['address'],
                              pool.p_backends[k]['port'])
                   for k in busiest]
        fabric.set_gray(aliases, mult=100.0)
        result['gray_keys'] = sorted(busiest)

    async def main():
        mod_trace.enable_tracing(ring_size=2048, sample_rate=1.0)
        mod_trace.add_backend_sink(counts)
        backends = sco.region_backends(regions=1, per_region=10)
        for b in backends:
            fabric.set_link(sco.fabric_key(b), service_ms=2.0)
        pool, res = sco.make_sim_pool(fabric, backends, spares=6,
                                      maximum=10)
        await sco.wait_state(pool, 'running', timeout_s=10.0)
        loop = asyncio.get_running_loop()

        monitor = H.HealthMonitor({'interval': 250}).start()
        ticker = asyncio.ensure_future(tick_loop(monitor, pool, loop))
        sc.at(2.0, 'gray-busiest-2', lambda: go_gray(pool))
        try:
            outcomes = await netsim.herd(
                pool, 400, rate_per_s=40.0, timeout_ms=2000)
            result['outcomes'] = outcomes
        finally:
            ticker.cancel()
            monitor.stop()
            mod_trace.remove_backend_sink(counts)
        # Phase ledgers of the post-onset claims, while the ring is
        # still live (pure replay arithmetic: no clock reads, so the
        # seeded schedule replays byte-identically with or without
        # this read).
        from cueball_tpu import profile as mod_profile
        result['ledgers'] = mod_profile.phase_ledger(
            [t for t in mod_trace.trace_ring()
             if t.root.end is not None and t.root.start >= 2000.0])
        await sco.stop_pool(pool, res)

    try:
        sc.run(lambda: main())
    finally:
        mod_trace.disable_tracing()

    seeded = set(result['gray_keys'])
    assert len(seeded) == 2
    flagged_ever = set()
    for t, gray in result['ticks']:
        flagged_ever.update(gray)
        # Zero false positives: nothing outside the seeded set, ever
        # (in particular: nothing at all before the fault fires).
        assert set(gray) <= seeded, (t, gray, sorted(seeded))
    assert flagged_ever == seeded, (sorted(flagged_ever),
                                    sorted(seeded))
    # Detection envelope: named within 5s virtual of onset, with >= 3
    # judged ticks of hysteresis in between (streak gate).
    assert result['detected_at'] is not None
    assert 2.0 < result['detected_at'] <= 7.0, result['detected_at']
    # The detector fired while every other arm still read healthy.
    assert result['dead_at_detect'] == []
    pre_detect = [r for r in result['outcomes']
                  if r['t_arrive_s'] <= result['detected_at']]
    assert pre_detect and all(r['ok'] for r in pre_detect)
    ok_rate = (sum(1 for r in result['outcomes'] if r['ok'])
               / len(result['outcomes']))
    assert ok_rate >= 0.99, ok_rate

    # Phase-ledger envelope (the claim-path profiler over the same
    # ring the detector read): gray failure is SERVICE-TIME inflation.
    # Claims attributed to the seeded backends show it in the lease
    # phase — the simulated request served 100x slower under the held
    # claim — while their queue_wait stays a minority share (healthy
    # capacity keeps absorbing the queue; a pool that piled claims
    # into the queue behind gray leases would show the inverse).
    ledgers = result['ledgers']
    assert len(ledgers) > 50
    for led in ledgers:
        assert abs(sum(led['phases'].values()) - led['wall_ms']) <= \
            max(1e-6, 1e-9 * led['wall_ms'])
        assert led['coverage'] >= 0.95, led
    gray_leds = [led for led in ledgers if led['backend'] in seeded]
    healthy_leds = [led for led in ledgers
                    if led['backend'] not in seeded]
    assert gray_leds and healthy_leds
    gray_lease = netsim.quantile(
        [led['phases']['lease'] for led in gray_leds], 0.50)
    healthy_lease = netsim.quantile(
        [led['phases']['lease'] for led in healthy_leds], 0.50)
    gray_queue = netsim.quantile(
        [led['phases']['queue_wait'] for led in gray_leds], 0.50)
    assert gray_lease >= 10.0 * max(healthy_lease, 1.0), (
        gray_lease, healthy_lease)
    assert gray_queue < gray_lease, (gray_queue, gray_lease)


def test_failure_dump_embeds_health_verdict_history(
        tmp_path, monkeypatch):
    """A scenario that breaks its envelope while a HealthMonitor is
    live writes the verdict history into the replay dump."""
    from cueball_tpu.parallel import health as H

    monkeypatch.setenv(mod_scenario.DUMP_DIR_ENV, str(tmp_path))
    fabric = netsim.Fabric()
    sc = netsim.Scenario('gray-dump', seed=5)
    held = {}

    async def main():
        mod_trace.enable_tracing(ring_size=256, sample_rate=1.0)
        backends = sco.region_backends(regions=1, per_region=4)
        for b in backends:
            fabric.set_link(sco.fabric_key(b), service_ms=2.0)
        pool, res = sco.make_sim_pool(fabric, backends, spares=2,
                                      maximum=4)
        await sco.wait_state(pool, 'running', timeout_s=10.0)
        # Deliberately NOT stopped before the raise: the monitor must
        # still be active when _dump_failure runs, exactly as in a
        # real envelope break mid-scenario.
        held['monitor'] = H.HealthMonitor().start()
        try:
            for _ in range(5):
                assert await sco.claim_release(pool, timeout_ms=1000)
                await asyncio.sleep(0.1)
            held['monitor'].tick()
            raise AssertionError('forced envelope break')
        finally:
            await sco.stop_pool(pool, res)

    try:
        with pytest.raises(AssertionError, match='forced envelope'):
            sc.run(lambda: main())
    finally:
        if 'monitor' in held:
            held['monitor'].stop()
        mod_trace.disable_tracing()

    with open(tmp_path / 'gray-dump-seed5.json') as f:
        dump = json.load(f)
    assert 'health' in dump, sorted(dump)
    history = dump['health']['history']
    assert history and history[0], history
    entry = history[0][-1]
    for field in ('epoch', 'gray', 'burn_fast', 'burn_slow',
                  'alert_page'):
        assert field in entry, entry
    assert dump['health']['fleet'] is not None
    # The replay dump embeds the claims' phase ledgers too (ISSUE 13):
    # the summary cost attribution plus the slowest claims, so a
    # failure dump answers "where did the wall time go" offline.
    ledger = dump['phase_ledger']
    assert ledger['summary']['claims'] >= 5
    assert ledger['summary']['coverage'] >= 0.95
    assert ledger['slowest_claims']
    led = ledger['slowest_claims'][0]
    for field in ('trace_id', 'wall_ms', 'phases', 'coverage'):
        assert field in led, sorted(led)

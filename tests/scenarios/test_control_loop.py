"""Scenario: the closed control loop under gray failure.

Four backends serving at 20ms behind a pool sized exactly to them
(spares = maximum = 4), driven at an offered rate that keeps every
connection busy; at t=1s half the fabric silently turns 25x slower
without failing, so gray leases pin their connections for ~500ms and
a real claim queue forms. Two arms, same seed, same fabric shape:

- static: the pool runs the operator-configured CoDel target (400 ms)
  untouched — the policy every round before PR 9 ran;
- control: the SAME pool shape opts into controlActuation and a
  control loop drives the real jitted control step
  (parallel.control.control_step) off the sampler's own gather
  signals, applying each step's decision columns through
  apply_decisions -> ConnectionPool.apply_control_decision.

Under sustained over-target sojourns the AIMD law multiplicatively
tightens the CoDel target, and with it the claim deadline
(get_max_idle tracks the target), so queued claims stop waiting out
the full operator envelope behind gray leases. The steady-state
claim-latency p99 — claims arriving after the loop has had a few
periods to adapt — must come in MEASURABLY below the static arm's,
and the whole-run tail must improve too, while the pool keeps
serving (the healthy-capacity success floor). Seeded and
byte-replayable like the rest of the corpus: a failure dumps its
replay under .netsim-failures/ with the exact seed."""

import asyncio

import pytest

from cueball_tpu import netsim

import scenario_common as sco

jax = pytest.importorskip('jax')

OPERATOR_TARGET_MS = 400.0
CONTROL_PERIOD_S = 0.15
# Arrivals after this point see the adapted target (the AIMD law has
# run ~20 periods past the t=1s gray onset): the steady-state window.
STEADY_FROM_S = 4.0


async def control_loop(pool, stop, record):
    """Drive the real control step off the pool's live gather signals.

    One-row fleet: ControlInputs built from FleetSampler.gather_pool
    (the same signal path the fleet sampler publishes), one donated
    jitted control_step per period, decisions applied through the
    guarded actuation API. Runs entirely in virtual time."""
    import jax.numpy as jnp

    from cueball_tpu.parallel import control as ctl
    from cueball_tpu.parallel.sampler import FleetSampler
    from cueball_tpu.utils import current_millis

    step = ctl.make_control_step()
    state = ctl.control_init(1)
    while not stop.is_set():
        now = float(current_millis())
        g = FleetSampler.gather_pool(pool, now)
        inp = ctl.control_inputs(
            1,
            samples=jnp.asarray([g['sample']], jnp.float32),
            sojourns=jnp.asarray([g['sojourn']], jnp.float32),
            filtered=jnp.asarray([g['sample']], jnp.float32),
            target_delay=jnp.asarray([g['target_delay']], jnp.float32),
            spares=jnp.asarray([g['spares']], jnp.float32),
            maximum=jnp.asarray([g['maximum']], jnp.float32),
            active=jnp.asarray([True]),
            now_ms=jnp.float32(now % 1e6))
        state, dec, _fleet = step(state, inp)
        res = ctl.apply_decisions({0: pool}, dec, at_ms=now)
        record['applied'] = record.get('applied', 0) + res['applied']
        record['min_target'] = min(
            record.get('min_target', OPERATOR_TARGET_MS),
            float(pool.p_codel.cd_targdelay))
        await asyncio.sleep(CONTROL_PERIOD_S)


def run_arm(seed: int, control: bool) -> dict:
    fabric = netsim.Fabric()
    sc = netsim.Scenario(
        'closed-loop-%s' % ('control' if control else 'static'),
        seed=seed)
    result = {'ctrl': {}}

    async def main():
        backends = sco.region_backends(regions=1, per_region=4)
        for b in backends:
            fabric.set_link(sco.fabric_key(b), service_ms=20.0)
        pool, res = sco.make_sim_pool(
            fabric, backends, spares=4, maximum=4,
            targetClaimDelay=OPERATOR_TARGET_MS,
            controlActuation=control)
        await sco.wait_state(pool, 'running', timeout_s=10.0)

        sc.at(1.0, 'gray-50pct',
              lambda: result.__setitem__(
                  'gray_keys', fabric.set_gray(0.5, mult=25.0)))

        stop = asyncio.Event()
        task = None
        if control:
            task = asyncio.ensure_future(
                control_loop(pool, stop, result['ctrl']))
        # CoDel pools refuse per-claim timeouts (reference semantics):
        # the claim deadline is the pool's own maxIdleTime.
        outcomes = await netsim.herd(
            pool, 1200, rate_per_s=140.0, timeout_ms=None)
        stop.set()
        if task is not None:
            await task
        result['outcomes'] = outcomes
        await sco.stop_pool(pool, res)

    sc.run(lambda: main())
    lats = [r['latency_ms'] for r in result['outcomes']
            if r['latency_ms'] is not None]
    late = [r['latency_ms'] for r in result['outcomes']
            if r['latency_ms'] is not None
            and r['t_arrive_s'] >= STEADY_FROM_S]
    result['p99'] = netsim.quantile(lats, 0.99)
    result['steady_p99'] = netsim.quantile(late, 0.99)
    result['ok_rate'] = (sum(1 for r in result['outcomes'] if r['ok'])
                         / len(result['outcomes']))
    return result


@pytest.mark.parametrize('seed', [17, 404])
def test_control_loop_tightens_p99_under_gray_failure(seed):
    static = run_arm(seed, control=False)
    ctrl = run_arm(seed, control=True)

    # The loop actually ran: decisions were accepted through the
    # guarded API and the CoDel target was multiplicatively tightened
    # below the operator setting.
    assert ctrl['ctrl'].get('applied', 0) > 0, ctrl['ctrl']
    assert ctrl['ctrl']['min_target'] < OPERATOR_TARGET_MS, ctrl['ctrl']

    # The headline: once the adapted target bites, steady-state
    # arrivals stop riding the full 400 ms operator envelope behind
    # gray leases. The margin is wide (measured ~0.35x) because the
    # tightened target drags the claim deadline down with it.
    assert ctrl['steady_p99'] <= 0.6 * static['steady_p99'], (
        static['steady_p99'], ctrl['steady_p99'], ctrl['ctrl'])

    # The whole-run tail (including the pre-adaptation ramp the two
    # arms share) must improve too, not just the filtered window.
    assert ctrl['p99'] <= 0.95 * static['p99'], (
        static['p99'], ctrl['p99'], ctrl['ctrl'])

    # Tightening must shed the queue, not the service: the healthy
    # half keeps the pool well above a 60% success floor, and the
    # static arm stays comparable so the arms are a fair pair.
    assert ctrl['ok_rate'] >= 0.6, (ctrl['ok_rate'], ctrl['p99'])
    assert static['ok_rate'] >= 0.6, (static['ok_rate'], static['p99'])
